"""Admission control: weighted-fair, priority-aware query scheduling.

The reference's GpuSemaphore answers "how many TASKS may hold device
memory"; a serving tier must also answer "WHICH query runs next" when
more sessions arrive than the device can admit.  The
:class:`QueryScheduler` is that answer: start-time weighted fair
queuing (WFQ) across tenants — each tenant carries a virtual clock that
advances by ``1/priority`` per admitted query, and the waiting entry
with the smallest virtual start time is granted next — so a
priority-4 tenant receives 4x the admission share of a priority-1
tenant under contention, while every tenant keeps making progress (no
starvation: virtual clocks are monotone, so a light tenant's entry is
always eventually the minimum).

Coupling to the device (the "gates on TpuSemaphore" contract): the
effective concurrency limit is ``min(serving.maxConcurrent,
TpuSemaphore permits)``.  Admitted queries still acquire per-task
semaphore permits inside execs exactly as before — the scheduler never
HOLDS device permits across a query (doing so would deadlock against
the per-task acquisitions of the queries it admitted); it bounds how
many queries compete for them, and a
:meth:`~spark_rapids_tpu.memory.semaphore.TpuSemaphore.resize` (via its
sync_conf) re-sizes admission on the next grant decision.

Load shedding: a query arriving with the queue at
``serving.queueDepth`` is rejected immediately
(:class:`AdmissionRejected`) — bounded latency beats unbounded queues.

Observability: every admission records its wait in the scheduler stats
(p50/p99 come from a bounded ring of recent waits) and — when tracing
is on — as a ``serve.admit`` span on the correlated timeline; the wait
also lands in the query's event-log record as the
``serve.admit_wait_ms`` counter (the HC009 health-rule input).

Process-global, LAST-WRITER-WINS configuration: the scheduler is one
per process (like the tracer), and :func:`get_scheduler` applies the
admitting conf's ``maxConcurrent``/``queueDepth``/``defaultPriority``
whenever they differ from the live values — a serving fleet is
expected to share one serving-conf epoch, and two sessions admitting
with different explicit limits will flip the shared limits back and
forth (deliberately simple; the admission COUNTS stay consistent
either way).  :func:`reset` tears the instance down for tests.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Optional

from spark_rapids_tpu import trace as _tr
from spark_rapids_tpu.robustness.lock_tracker import tracked_lock
from spark_rapids_tpu.serving import (
    BATCHING_ENABLED,
    DEFAULT_PRIORITY,
    MAX_CONCURRENT,
    QUEUE_DEPTH,
    clear_serving_context,
    current_serving_context,
    update_serving_context,
)


class AdmissionRejected(RuntimeError):
    """The admission queue is full (serving.queueDepth): the serving
    tier sheds this query instead of queuing it unboundedly.  Callers
    should retry with backoff or route to another replica."""


class _Tenant:
    __slots__ = ("name", "vtime")

    def __init__(self, name: str):
        self.name = name
        self.vtime = 0.0


class _Entry:
    __slots__ = ("tenant", "priority", "vtime", "seq", "granted",
                 "group")

    def __init__(self, tenant: str, priority: int, vtime: float,
                 seq: int, group: Optional[str] = None):
        self.tenant = tenant
        self.priority = priority
        self.vtime = vtime
        self.seq = seq
        self.granted = False
        #: template-group key for admission-aware batching
        #: (docs/work_sharing.md): queued entries sharing a group with
        #: a RUNNING query are granted preferentially, so compatible
        #: plans overlap and their scans dedup in flight
        self.group = group


class QueryScheduler:
    """One device's admission scheduler (see module doc)."""

    def __init__(self, max_concurrent: int, queue_depth: int,
                 default_priority: int = 1, batching: bool = True):
        self.max_concurrent = int(max_concurrent)
        self.queue_depth = int(queue_depth)
        self.default_priority = int(default_priority)
        self.batching = bool(batching)
        self._cv = threading.Condition()
        self._running = 0                        # guard: _cv
        self._waiting: list[_Entry] = []         # guard: _cv
        self._tenants: dict[str, _Tenant] = {}   # guard: _cv
        #: group -> count of RUNNING queries carrying it (the
        #: batching preference's membership test)
        self._running_groups: dict[str, int] = {}  # guard: _cv
        self._vclock = 0.0                       # guard: _cv
        self._seq = 0                            # guard: _cv
        # stats (under _cv): totals + a bounded ring of recent waits so
        # p50/p99 stay O(1) memory on a long-lived server
        self._admitted = 0                       # guard: _cv
        self._rejected = 0                       # guard: _cv
        self._coalesced = 0                      # guard: _cv
        #: queued entries unwound by cancellation/deadline before grant
        #: (or after an unconsumed grant) — the admission queue's share
        #: of the cancellation story (docs/robustness.md)
        self._shed = 0                           # guard: _cv
        self._total_wait_ms = 0.0                # guard: _cv
        self._waits_ms: deque = deque(maxlen=4096)  # guard: _cv
        #: per-tenant wait rings + admit totals (the ops plane's
        #: tenant-labelled /metrics families; same bound as the
        #: global ring so a long-lived server stays O(1) memory)
        self._tenant_waits: dict[str, deque] = {}   # guard: _cv
        self._tenant_admitted: dict[str, int] = {}  # guard: _cv

    # -- limit ------------------------------------------------------- #

    def _limit(self) -> int:
        """Effective concurrency: serving.maxConcurrent clamped to the
        device semaphore's permit count — admission control rides the
        same budget that caps device batch residency, so resizing the
        semaphore (its sync_conf) re-sizes admission too.

        Under mesh serving (spark.rapids.tpu.serving.mesh.enabled with
        an active mesh) the semaphore budget generalizes to PER-DEVICE
        budgets: the pump grants mesh residency, and a pod slice of n
        devices admits n x serving.mesh.deviceBudget times the
        single-device clamp — N compatible tenants share one
        mesh-resident partitioned program set instead of serializing
        behind a single-device limit (docs/pod_serving.md)."""
        from spark_rapids_tpu.memory.semaphore import TpuSemaphore

        base = max(1, min(self.max_concurrent,
                          TpuSemaphore.get().permits))
        from spark_rapids_tpu.serving import (
            MESH_DEVICE_BUDGET,
            mesh_serving_enabled,
        )
        if mesh_serving_enabled():
            from spark_rapids_tpu.config import get_conf
            from spark_rapids_tpu.parallel.mesh import active_mesh

            mesh = active_mesh()
            if mesh is not None:
                n = int(mesh.devices.size)
                per_dev = int(get_conf().get(MESH_DEVICE_BUDGET))
                base = base * max(1, n) * max(1, per_dev)
        return base

    # -- core -------------------------------------------------------- #

    def _pump_locked(self) -> None:
        """Grant waiting entries while capacity remains: smallest
        virtual start time first (WFQ), FIFO within ties.  Virtual
        times were assigned at ENQUEUE (each tenant's clock advances
        1/priority per queued request), so a burst from one tenant
        interleaves with other tenants' queued work instead of
        draining FIFO.

        Admission-aware batching (serving.batching.enabled): a
        waiting entry whose template group is already RUNNING is
        granted ahead of strict WFQ order — compatible plans then
        execute together and the work-sharing tier dedups their scans
        in flight (docs/work_sharing.md).  Bounded unfairness: the
        preference only ever reorders against live groups, and each
        coalesced grant still consumes a slot, so ungrouped tenants
        advance as slots free."""
        limit = self._limit()
        while self._running < limit and self._waiting:
            nxt = None
            if self.batching and self._running_groups:
                cands = [e for e in self._waiting
                         if e.group and e.group in self._running_groups]
                if cands:
                    nxt = min(cands, key=lambda e: (e.vtime, e.seq))
                    self._coalesced += 1
            if nxt is None:
                nxt = min(self._waiting,
                          key=lambda e: (e.vtime, e.seq))
            self._waiting.remove(nxt)
            nxt.granted = True
            self._running += 1
            if nxt.group:
                self._running_groups[nxt.group] = \
                    self._running_groups.get(nxt.group, 0) + 1
            self._vclock = max(self._vclock, nxt.vtime)
        self._cv.notify_all()

    def _drop_running_locked(self, entry: _Entry) -> None:
        self._running -= 1
        if entry.group:
            n = self._running_groups.get(entry.group, 0) - 1
            if n <= 0:
                self._running_groups.pop(entry.group, None)
            else:
                self._running_groups[entry.group] = n

    def admit(self, tenant: str = "default",
              priority: Optional[int] = None,
              group: Optional[str] = None, token=None) -> _Entry:
        """Block until this query is admitted (or raise
        :class:`AdmissionRejected` when the queue is full).  Returns
        the ticket to hand back to :meth:`release`.  `group` is the
        optional template-group key batching coalesces on.

        ``token`` (a serving/cancel CancelToken) makes the admission
        wait INTERRUPTIBLE: the wait polls on the cancel cadence
        bounded by the token's remaining deadline, so a query whose
        deadline expires (or that is cancelled) WHILE QUEUED is shed
        here — entry removed, no device work ever dispatched — with
        QueryCancelled raised to the caller.  An already-expired
        deadline sheds before the entry is even enqueued."""
        from spark_rapids_tpu.serving.cancel import poll_timeout

        prio = int(priority) if priority is not None \
            else self.default_priority
        t0 = time.perf_counter_ns()
        with self._cv:
            if token is not None:
                # expired-before-admission: shed with zero queue time
                # (the zero-device-work contract starts here)
                token.check()
            te = self._tenants.get(tenant)
            if te is None:
                te = self._tenants[tenant] = _Tenant(tenant)
                # a brand-new tenant starts at the current virtual
                # clock, not 0 — joining late must not grant it a
                # catch-up burst over tenants that queued all along
                te.vtime = self._vclock
            if self._running >= self._limit() \
                    and len(self._waiting) >= self.queue_depth:
                self._rejected += 1
                raise AdmissionRejected(
                    f"admission queue full ({len(self._waiting)} "
                    f"waiting >= serving.queueDepth="
                    f"{self.queue_depth}, {self._running} running); "
                    f"tenant={tenant!r}")
            self._seq += 1
            entry = _Entry(tenant, prio,
                           max(te.vtime, self._vclock), self._seq,
                           group=group)
            # advance the tenant clock AT ENQUEUE: its next request
            # starts 1/priority later in virtual time, which is what
            # spaces a burst out against other tenants' queued work
            te.vtime = entry.vtime + 1.0 / max(1, prio)
            self._waiting.append(entry)
            self._pump_locked()
            waited = not entry.granted
            try:
                while not entry.granted:
                    # bounded wait (tpulint SRC012: every serving-path
                    # wait is interruptible): grants still arrive via
                    # notify; the timeout only bounds cancel/deadline
                    # response latency
                    self._cv.wait(poll_timeout(token))
                    if token is not None and not entry.granted:
                        token.check()
            except BaseException:
                # interrupted wait (cancellation/deadline shed,
                # KeyboardInterrupt, injected test abort): unwind the
                # entry, or the pump would later grant a slot nobody
                # will ever release and admission wedges for the
                # process lifetime
                self._shed += 1
                if entry in self._waiting:
                    self._waiting.remove(entry)
                elif entry.granted:
                    self._drop_running_locked(entry)
                    self._pump_locked()
                raise
            dt_ns = (time.perf_counter_ns() - t0) if waited else 0
            wait_ms = dt_ns / 1e6
            self._admitted += 1
            self._total_wait_ms += wait_ms
            self._waits_ms.append(wait_ms)
            ring = self._tenant_waits.get(tenant)
            if ring is None:
                ring = self._tenant_waits[tenant] = deque(maxlen=512)
            ring.append(wait_ms)
            self._tenant_admitted[tenant] = \
                self._tenant_admitted.get(tenant, 0) + 1
        if _tr.TRACER.enabled:
            # the admission wait as a first-class span on the
            # correlated timeline (zero-length for immediate grants)
            _tr.record_complete("serve.admit", t0, dt_ns,
                                tenant=tenant, priority=prio)
        update_serving_context(tenant=tenant, priority=prio,
                               admit_wait_ms=round(wait_ms, 3))
        return entry

    def release(self, entry: _Entry) -> None:
        with self._cv:
            self._drop_running_locked(entry)
            self._pump_locked()

    # -- stats ------------------------------------------------------- #

    @staticmethod
    def _quantile(xs: list, q: float) -> float:
        if not xs:
            return 0.0
        xs = sorted(xs)
        i = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
        return xs[i]

    def stats(self) -> dict:
        with self._cv:
            waits = list(self._waits_ms)
            out = {
                "admitted": self._admitted,
                "rejected": self._rejected,
                "coalesced": self._coalesced,
                "shed": self._shed,
                "running": self._running,
                "waiting": len(self._waiting),
                "total_wait_ms": round(self._total_wait_ms, 3),
            }
        out["wait_p50_ms"] = round(self._quantile(waits, 0.50), 3)
        out["wait_p99_ms"] = round(self._quantile(waits, 0.99), 3)
        return out

    def tenant_stats(self) -> dict:
        """Per-tenant admission waits: {tenant: {wait_p50_ms,
        wait_p99_ms, admitted}} — the ops plane's tenant-labelled
        ``tpu_serving_tenant_*`` metric families."""
        with self._cv:
            rings = {t: list(r) for t, r in self._tenant_waits.items()}
            admitted = dict(self._tenant_admitted)
        return {t: {
            "wait_p50_ms": round(self._quantile(w, 0.50), 3),
            "wait_p99_ms": round(self._quantile(w, 0.99), 3),
            "admitted": admitted.get(t, 0),
        } for t, w in rings.items()}

    def reset_stats(self) -> None:
        with self._cv:
            self._admitted = 0
            self._rejected = 0
            self._coalesced = 0
            self._total_wait_ms = 0.0
            self._waits_ms.clear()
            self._tenant_waits.clear()
            self._tenant_admitted.clear()


# ------------------------------------------------------------------ #
# Process-global instance (tracer/faults ownership discipline)
# ------------------------------------------------------------------ #

_SCHED: Optional[QueryScheduler] = None
_LOCK = tracked_lock("scheduler.registry")


def get_scheduler(conf=None) -> QueryScheduler:
    """The process scheduler, created (and re-configured) from the
    given conf.  Conf changes apply in place — live waiters see the new
    limits at the next grant decision."""
    from spark_rapids_tpu.config import get_conf

    global _SCHED
    conf = conf or get_conf()
    want_max = int(conf.get(MAX_CONCURRENT))
    want_depth = int(conf.get(QUEUE_DEPTH))
    want_prio = int(conf.get(DEFAULT_PRIORITY))
    want_batch = bool(conf.get(BATCHING_ENABLED))
    with _LOCK:
        if _SCHED is None:
            _SCHED = QueryScheduler(want_max, want_depth, want_prio,
                                    batching=want_batch)
            return _SCHED
        s = _SCHED
    if (s.max_concurrent, s.queue_depth, s.default_priority,
            s.batching) != (want_max, want_depth, want_prio,
                            want_batch):
        with s._cv:
            s.max_concurrent = want_max
            s.queue_depth = want_depth
            s.default_priority = want_prio
            s.batching = want_batch
            s._pump_locked()
    return s


def queue_gauges() -> dict:
    """Point-in-time admission occupancy without creating a scheduler
    (the telemetry sampler's serving-tier gauge: queries running under
    admission + queue depth right now)."""
    with _LOCK:
        s = _SCHED
    if s is None:
        return {"running": 0, "waiting": 0}
    with s._cv:
        return {"running": s._running, "waiting": len(s._waiting)}


def scheduler_stats() -> dict:
    with _LOCK:
        s = _SCHED
    return s.stats() if s is not None else {
        "admitted": 0, "rejected": 0, "coalesced": 0, "shed": 0,
        "running": 0, "waiting": 0, "total_wait_ms": 0.0,
        "wait_p50_ms": 0.0, "wait_p99_ms": 0.0}


def tenant_wait_stats() -> dict:
    """Per-tenant admission-wait stats without creating a scheduler
    (the ops plane's /metrics adapter; {} while the tier is dormant)."""
    with _LOCK:
        s = _SCHED
    return s.tenant_stats() if s is not None else {}


def reset() -> None:
    """Drop the process scheduler (tests).  In-flight tickets release
    against the old instance harmlessly."""
    global _SCHED
    with _LOCK:
        _SCHED = None


@contextmanager
def admission(conf, tenant: str = "default",
              priority: Optional[int] = None,
              group: Optional[str] = None, token=None):
    """The query-boundary hook: a no-op single conf read when serving
    admission is disabled (maxConcurrent <= 0); otherwise admit through
    the process scheduler for the duration of the block.  Re-entrant
    per thread — a nested collect on an admitted thread (scalar
    subquery prepass, CPU-compare runs inside an admitted bench driver)
    passes straight through instead of deadlocking against itself.
    `group` (optional, the prepared template's binding-independent
    key) feeds admission-aware batching.

    ``token`` threads the query's CancelToken into the admission wait
    (deadline/cancel shed while queued — serving/cancel.py) and this
    block reports the ADMITTED query's outcome to the tenant's circuit
    breaker: success heals, a crash or deadline_exceeded counts toward
    serving.breaker.failureThreshold, an explicit user cancel is
    neutral.  A quarantined tenant is shed BEFORE taking a WFQ slot
    (TenantQuarantined)."""
    if int(conf.get(MAX_CONCURRENT)) <= 0:
        try:
            yield None
        finally:
            # a prepared query's plan-cache verdict was deposited (and
            # consumed by query_end) inside this block; drop it so it
            # cannot leak into a later query's record.  Conditional:
            # the common plain-collect path never touched the context
            if current_serving_context() is not None:
                clear_serving_context()
        return
    tl = _ADMITTED_TL
    if getattr(tl, "depth", 0) > 0:
        # nested query on an admitted thread: pass through, but stash
        # the OUTER query's serving context for the duration — the
        # nested query's event-log capture must not report the outer
        # admission wait / tenant / plan-cache verdict as its own
        outer_ctx = current_serving_context()
        clear_serving_context()
        tl.depth += 1
        try:
            yield None
        finally:
            tl.depth -= 1
            clear_serving_context()
            if outer_ctx:
                update_serving_context(**outer_ctx)
        return
    from spark_rapids_tpu.serving import cancel as _cancel

    _cancel.breaker_admit(conf, tenant)  # may raise TenantQuarantined
    sched = get_scheduler(conf)
    try:
        ticket = sched.admit(tenant, priority, group=group,
                             token=token)
    except BaseException:
        # shed before admission (queue full, deadline expired while
        # queued, interrupt): if breaker_admit claimed the half-open
        # probe for this query, release it — a lost probe must not
        # leave the tenant quarantined forever
        _cancel.breaker_release(conf, tenant)
        raise
    tl.depth = 1
    outcome = "failure"
    try:
        yield ticket
        outcome = "success"
    except _cancel.QueryCancelled as e:
        # deadline mid-flight = the hang signature (counts toward the
        # breaker); an explicit user cancel says nothing about the
        # query's health
        outcome = "failure" if e.reason == "deadline_exceeded" \
            else "neutral"
        raise
    except GeneratorExit:
        # a stream consumer closing early (the documented early-close
        # pattern) is not a query failure — breaker-neutral
        outcome = "neutral"
        raise
    finally:
        tl.depth = 0
        sched.release(ticket)
        clear_serving_context()
        if outcome != "neutral":
            _cancel.breaker_result(conf, tenant,
                                   ok=outcome == "success")
        else:
            # neutral exits still release a claimed half-open probe
            _cancel.breaker_release(conf, tenant)


_ADMITTED_TL = threading.local()
