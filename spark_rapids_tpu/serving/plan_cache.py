"""Prepared-plan cache: lowered exec trees keyed by structural plan
identity, so a repeated query template never re-pays parse/plan/tag/
lower.

The reference never needs this layer — its per-batch kernels are
pre-compiled native code and Spark re-plans cheaply — but this engine's
query setup is real work (plan tagging, runtime-filter injection,
pipeline planning) and its programs key on structural expression trees
(execs/jit_cache.py).  The cache extends that idea one level up: the
whole LOWERED exec tree is the cached object, keyed by

- the **structural plan key**: a deterministic serialization of the
  logical plan — node class names plus every attribute, expressions via
  ``jit_cache.expr_key`` (the same ordinal/dtype/literal-complete
  serialization compiled programs key on), in-memory tables via their
  content digest (an id-based key could alias a recycled address to a
  DIFFERENT table — a stale hit that answers the wrong query);
- the **conf fingerprint** (eventlog.conf_fingerprint): lowering reads
  conf (pipeline depth, runtime filters, shuffle partitions), so two
  conf epochs must never share a lowered tree;
- the **parameter binding** for SQL templates: literal values are burned
  into the lowered programs (that IS the jit key design), so each bound
  value set is its own entry — repeats of a binding hit, new bindings
  lower once.

Exec trees are re-drainable by construction (close() returns join
builds / shuffle registrations to their pre-execute state — asserted by
tests/test_serving.py), so a hit simply re-drains the cached tree.
Operator metrics on the LIVE cached tree accumulate across executions
(the tree is the long-lived object), but every record derived from a
re-drain — explain("analyze"), the history event, the event-log
operator tree — reports per-EXECUTION deltas: the collect paths
snapshot the settled pre-drain totals and subtract
(session._collect_tpu_admitted / tools.profiling.snapshot_delta).

Eviction: LRU bounded by ``spark.rapids.tpu.serving.planCache.capacity``
— entries pin their source data (ArrowSourceExec tables), so the bound
is also a memory bound.  Hit/miss/evict counters are process-global
(:func:`stats`), surfaced in ``explain("analyze")``'s counter footer
and (per query, via the serving context) in the event-log record.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Any, Optional

from spark_rapids_tpu.robustness.lock_tracker import tracked_lock
from spark_rapids_tpu.serving import PLAN_CACHE_CAPACITY

# ------------------------------------------------------------------ #
# Structural keys
# ------------------------------------------------------------------ #


def _value_key(v: Any, seen: dict) -> str:
    """Serialize one logical-plan attribute value deterministically.
    Correctness rule: two plans that could EXECUTE differently must
    never share a key — when in doubt, serialize more, not less."""
    from spark_rapids_tpu.exprs.base import Expression
    from spark_rapids_tpu.plan.logical import LogicalPlan

    import pyarrow as pa

    if isinstance(v, Expression):
        from spark_rapids_tpu.execs.jit_cache import expr_key

        try:
            return expr_key(v)
        except TypeError:
            return repr(v)
    from spark_rapids_tpu.exprs.aggregates import (
        AggregateFunction,
        NamedAgg,
    )

    if isinstance(v, NamedAgg):
        return (f"NamedAgg({_value_key(v.fn, seen)},"
                f"{v.out_name!r})")
    if isinstance(v, AggregateFunction):
        # no custom __repr__: the default falls back to the object
        # address, which would mint a fresh key per plan INSTANCE and
        # defeat every structural-identity consumer (prepared-plan
        # cache across template objects, the cross-tenant result
        # cache) — serialize class + attributes instead
        parts = [f"{k}={_value_key(x, seen)}"
                 for k, x in sorted(vars(v).items())]
        return f"{type(v).__name__}({','.join(parts)})"
    if isinstance(v, LogicalPlan):
        return plan_structural_key(v, seen)
    if isinstance(v, pa.Table):
        # content digest, not id(): a recycled address must not alias
        # a dead table's key onto different data
        from spark_rapids_tpu.eventlog import table_digest

        return f"table:{table_digest(v)}"
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_value_key(x, seen) for x in v) + "]"
    if isinstance(v, (set, frozenset)):
        return "{" + ",".join(sorted(_value_key(x, seen)
                                     for x in v)) + "}"
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{_value_key(k, seen)}:{_value_key(x, seen)}"
            for k, x in sorted(v.items(), key=lambda kv: repr(kv[0]))
        ) + "}"
    if callable(v):
        # UDFs and pandas functions have no structural form; identity
        # keys them (the PreparedQuery holds the plan alive, so the id
        # cannot be recycled while the entry is reachable via its key
        # holder — see PreparedQuery, which keeps the DataFrame)
        return f"fn:{getattr(v, '__qualname__', '?')}@{id(v)}"
    return repr(v)


def plan_structural_key(plan, seen: Optional[dict] = None) -> str:
    """Deterministic structural serialization of a LOGICAL plan tree:
    class names + every instance attribute (expressions via the
    jit_cache structural serialization), recursing into children.
    A node visited twice (a DAG: `a.union(b).union(a)` shares `a`)
    serializes as ``ref:N`` — its first-visit ordinal, assigned in
    deterministic traversal order — so WHICH node repeats is part of
    the key; a class-name-only marker would collide plans that share
    different subtrees of one class."""
    if seen is None:
        seen = {}
    import pyarrow as pa

    ref = seen.get(id(plan))
    if ref is not None:
        return f"ref:{ref}"
    seen[id(plan)] = len(seen)
    digester = getattr(plan, "content_digest", None)
    parts = [type(plan).__name__]
    for k, v in sorted(vars(plan).items()):
        if k.startswith("_") and k != "_schema":
            continue
        if isinstance(v, pa.Table) and digester is not None:
            # the node memoizes its own content digest
            # (InMemoryRelation.content_digest): same digest-keyed
            # identity as _value_key's table branch, hashed once per
            # relation instead of once per prepare()
            parts.append(f"{k}=table:{digester()}")
            continue
        parts.append(f"{k}={_value_key(v, seen)}")
    return f"{parts[0]}[{','.join(parts[1:])}]"


def template_key(plan, conf) -> str:
    """The cache key for a native (DataFrame) template: structural plan
    key x conf fingerprint (x the active mesh identity under mesh
    serving — a plan lowered against an 8-device mesh must re-key, not
    rehit, when the pod reshapes to 4; docs/pod_serving.md), hashed."""
    from spark_rapids_tpu.eventlog import conf_fingerprint
    from spark_rapids_tpu.serving import mesh_cache_suffix

    payload = (plan_structural_key(plan) + "|" + conf_fingerprint(conf)
               + mesh_cache_suffix(conf))
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


def _normalize_sql(text: str) -> str:
    """Whitespace-normalize a SQL template WITHOUT reaching inside
    string literals: token texts joined by one space.  A naive
    ``" ".join(text.split())`` would collapse ``'a  b'`` and ``'a b'``
    onto one key — a stale hit answering the wrong query.  Templates
    the tokenizer rejects key on their raw text (the parse error
    surfaces at lowering, never as a wrong cache hit)."""
    from spark_rapids_tpu.frontends.sql import SqlError, _tokenize

    try:
        return " ".join(tok[1] for tok in _tokenize(text))
    except SqlError:
        return text


def binding_key(params: Optional[dict]) -> str:
    """Canonical serialization of one parameter binding — THE single
    definition (the PreparedQuery key memo and sql_template_key must
    agree to the bit, or a memoized key could alias a different
    binding onto one entry)."""
    if not params:
        return ""
    return repr(sorted((str(k), repr(v)) for k, v in params.items()))


def sql_template_key(text: str, conf,
                     params: Optional[dict] = None) -> str:
    """The cache key for a SQL template: normalized text x conf
    fingerprint x the parameter BINDING (values are burned into the
    lowered programs, so each binding is its own entry) x the active
    mesh identity under mesh serving."""
    from spark_rapids_tpu.eventlog import conf_fingerprint
    from spark_rapids_tpu.serving import mesh_cache_suffix

    payload = (_normalize_sql(text) + "|" + conf_fingerprint(conf)
               + "|" + binding_key(params) + mesh_cache_suffix(conf))
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


# ------------------------------------------------------------------ #
# Process-global counters (per-cache caches, one counter surface)
# ------------------------------------------------------------------ #

_STATS_LOCK = threading.Lock()
_HITS = 0
_MISSES = 0
_EVICTIONS = 0


def stats() -> dict:
    """Cumulative process-wide plan-cache counters {hits, misses,
    evictions, hit_rate} (every session's cache ticks the same surface;
    bench and the analyze footer diff before/after for windows)."""
    with _STATS_LOCK:
        total = _HITS + _MISSES
        return {
            "hits": _HITS,
            "misses": _MISSES,
            "evictions": _EVICTIONS,
            "hit_rate": round(_HITS / total, 3) if total else 0.0,
        }


def reset_stats() -> None:
    global _HITS, _MISSES, _EVICTIONS
    with _STATS_LOCK:
        _HITS = 0
        _MISSES = 0
        _EVICTIONS = 0


# ------------------------------------------------------------------ #
# The cache
# ------------------------------------------------------------------ #


class DrainLock:
    """Non-reentrant drain mutex with same-thread deadlock DETECTION.

    A partially consumed ``execute_stream()`` holds its entry's drain
    lock across yields ON THE CONSUMER THREAD; if that thread then
    re-executes the same template, a plain Lock would block forever
    with no diagnostic.  Re-entry by the owning thread raises
    immediately instead — drain or close the open stream first.
    Cross-thread acquisition blocks normally (that is the serializing
    contract).  The owner check is race-free: another thread's ident
    never equals ours, and our own owner writes happen-before our own
    reads."""

    __slots__ = ("_lock", "_owner")

    def __init__(self):
        self._lock = threading.Lock()
        self._owner = None

    def acquire(self, blocking: bool = True) -> bool:
        if self._owner == threading.get_ident():
            raise RuntimeError(
                "this prepared template is still draining on this "
                "thread (an execute_stream() not yet drained or "
                "closed?); finish or close() the open stream before "
                "re-executing it")
        ok = self._lock.acquire(blocking)
        if ok:
            self._owner = threading.get_ident()
        return ok

    def release(self) -> None:
        self._owner = None
        self._lock.release()

    def __enter__(self) -> "DrainLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


class CacheEntry:
    """One cached lowered plan (plus the DataFrame that lowered to it —
    the CPU-degrade ladder and the structural key's identity-keyed
    parts need the logical plan kept alive).  ``lock`` serializes
    re-drains of the shared exec tree — a single session re-executing
    one template from two threads must not interleave two drains of one
    tree."""

    __slots__ = ("exec_", "meta", "plan_hash", "df", "lock",
                 "rehydrated")

    def __init__(self, exec_, meta, plan_hash: str, df=None):
        self.exec_ = exec_
        self.meta = meta
        self.plan_hash = plan_hash
        self.df = df
        self.lock = DrainLock()
        #: metadata restored from the warm-start disk tier
        #: (spark_rapids_tpu/persist.py) for this key, when a prior
        #: process prepared the same template — None otherwise.  The
        #: lowered exec tree itself is LIVE state (closures, device
        #: buffers) and is rebuilt, immediately hitting the persisted
        #: AOT program tier; this slot carries the cross-process
        #: prepare lineage (docs/warm_start.md).
        self.rehydrated: Optional[dict] = None


class PlanCache:
    """Per-session LRU of :class:`CacheEntry` (see module doc)."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            from spark_rapids_tpu.config import get_conf

            capacity = int(get_conf().get(PLAN_CACHE_CAPACITY))
        self.capacity = max(1, int(capacity))
        # guard: _mu
        self._entries: "collections.OrderedDict[str, CacheEntry]" = \
            collections.OrderedDict()
        # guard: _mu — persisted-plan metadata restored on a miss,
        # consumed by the insert() that follows it (prepared._resolve
        # always inserts after a miss)
        self._rehydrated: dict[str, dict] = {}
        self._mu = tracked_lock("planCache.mu")

    def lookup(self, key: str) -> Optional[CacheEntry]:
        """Get-and-touch; ticks the global hit/miss counters.  An
        in-memory miss additionally probes the warm-start disk tier
        (one conf read when persistence is off): a valid persisted
        entry for this (structural plan key x conf fingerprint) stashes
        its metadata for the insert() that follows the re-lowering."""
        global _HITS, _MISSES
        with self._mu:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
        with _STATS_LOCK:
            if e is None:
                _MISSES += 1
            else:
                _HITS += 1
        if e is None:
            from spark_rapids_tpu import persist as _persist

            store = _persist.active()
            if store is not None:
                meta = store.load_plan(key)
                if meta is not None:
                    with self._mu:
                        self._rehydrated[key] = meta
        return e

    def insert(self, key: str, entry: CacheEntry) -> CacheEntry:
        """Insert (first writer wins under a race) and evict past
        capacity; evicted exec trees are close()d so they release any
        held resources."""
        global _EVICTIONS
        evicted: list[CacheEntry] = []
        with self._mu:
            cur = self._entries.get(key)
            if cur is not None:
                self._entries.move_to_end(key)
                return cur
            self._entries[key] = entry
            entry.rehydrated = self._rehydrated.pop(key, None)
            while len(self._entries) > self.capacity:
                _k, old = self._entries.popitem(last=False)
                evicted.append(old)
        from spark_rapids_tpu import persist as _persist

        store = _persist.active()
        if store is not None:
            # write-back (async, off the prepare path): next process's
            # lookup() rehydrates this metadata instead of starting its
            # prepare lineage from zero
            prev = int((entry.rehydrated or {}).get("prepares", 0))
            store.save_plan_async(
                key, {"plan_hash": entry.plan_hash,
                      "prepares": prev + 1},
                _persist.max_bytes())
        if evicted:
            with _STATS_LOCK:
                _EVICTIONS += len(evicted)
            for old in evicted:
                self._close_entry(old)
        return entry

    @staticmethod
    def _close_entry(old: CacheEntry) -> None:
        """Best-effort teardown of an evicted entry: only under its
        drain lock (closing DURING a drain tears state out from under
        the iterator), and only if the lock is free — an in-flight
        drain closes its own tree when it finishes (stream_exec /
        collect_exec close in their finally), so a busy entry needs no
        close from here, and blocking (or raising, if the evicting
        thread itself holds the lock via an open stream) would stall
        an innocent prepare()."""
        try:
            if not old.lock.acquire(blocking=False):
                return
        except RuntimeError:
            return  # this thread's own open stream owns the drain
        try:
            old.exec_.close()
        except Exception:
            pass
        finally:
            old.lock.release()

    def invalidate(self) -> None:
        """Drop every entry (conf epoch changes key entries out
        naturally; this is the explicit hammer for tests/operators)."""
        with self._mu:
            entries = list(self._entries.values())
            self._entries.clear()
        for e in entries:
            self._close_entry(e)

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)
