"""Multi-tenant query-serving tier: concurrent sessions, one device.

Everything below this package was one-session-one-query; this is the
production front the ROADMAP's "heavy traffic from millions of users"
north star asks for (open item #4), built from four pieces:

- **admission control** (:mod:`serving.scheduler`): a
  :class:`~spark_rapids_tpu.serving.scheduler.QueryScheduler` gating
  query execution on the device's concurrency budget — the same permit
  count :class:`~spark_rapids_tpu.memory.semaphore.TpuSemaphore` guards
  batch residency with — using per-tenant weighted-fair + priority
  queues, a bounded admission queue with rejection, and the admission
  wait recorded as a ``serve.admit`` span plus per-query event-log
  counters;
- a **prepared-statement / plan cache** (:mod:`serving.plan_cache`,
  :mod:`serving.prepared`): ``session.prepare(df)`` /
  ``SqlSession.prepare(sql)`` return a
  :class:`~spark_rapids_tpu.serving.prepared.PreparedQuery` keyed on
  the event log's plan-fingerprint idea + the jit_cache structural
  expression keys, so a repeated template with bound parameters skips
  parse -> plan -> tag -> lower entirely and re-drains the cached
  lowered exec tree;
- **streaming result fetch**
  (:meth:`~spark_rapids_tpu.serving.prepared.PreparedQuery.execute_stream`):
  Arrow record batches yielded incrementally off the pipelined collect
  path, with backpressure tied to the prefetch stage depth
  (parallel/pipeline.py);
- a **concurrency bench** (``bench.py --sessions N --tenants K``)
  emitting ``serving_qps`` / ``serving_p50_ms`` / ``serving_p99_ms`` /
  ``admission_wait_p99_ms`` / ``plan_cache_hit_rate``.

Cost discipline: with ``spark.rapids.tpu.serving.maxConcurrent`` at its
default of 0 the whole tier is dormant — a collect performs one conf
lookup and nothing else; no scheduler exists, no lock is taken.
Docs: ``docs/serving.md``.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from spark_rapids_tpu.config import register

MAX_CONCURRENT = register(
    "spark.rapids.tpu.serving.maxConcurrent", 0,
    "Maximum queries executing concurrently under the serving tier's "
    "admission control (0 = serving admission disabled; collects run "
    "unscheduled).  The effective limit is additionally clamped to the "
    "device semaphore's permit count "
    "(spark.rapids.tpu.sql.concurrentTpuTasks) — admission rides the "
    "same budget that caps device batch residency (docs/serving.md).")

QUEUE_DEPTH = register(
    "spark.rapids.tpu.serving.queueDepth", 32,
    "Bounded admission-queue depth: a query arriving while maxConcurrent "
    "queries run and this many already wait is REJECTED with "
    "AdmissionRejected instead of queuing unboundedly (load shedding; "
    "the rejection is counted in the scheduler stats).",
    check=lambda v: v >= 0)

DEFAULT_PRIORITY = register(
    "spark.rapids.tpu.serving.defaultPriority", 1,
    "Weighted-fair share for tenants that do not set an explicit "
    "priority: a tenant with priority P receives P times the admission "
    "share of a priority-1 tenant under contention (start-time fair "
    "queuing; docs/serving.md).",
    check=lambda v: v >= 1)

PLAN_CACHE_CAPACITY = register(
    "spark.rapids.tpu.serving.planCache.capacity", 32,
    "Per-session LRU capacity of the prepared-plan cache (lowered exec "
    "trees keyed by structural plan key + conf fingerprint + parameter "
    "binding).  Cached entries pin their plan's source data (e.g. "
    "in-memory tables), so the bound is a memory bound too.",
    check=lambda v: v >= 1)

BATCHING_ENABLED = register(
    "spark.rapids.tpu.serving.batching.enabled", True,
    "Admission-aware batching (docs/work_sharing.md): when granting "
    "slots, the scheduler prefers queued queries whose template group "
    "(the prepared-statement identity, independent of parameter "
    "bindings) matches one already running — compatible plans run "
    "together, so the work-sharing tier's in-flight scan dedup and "
    "result cache engage instead of the same scan being paid once per "
    "slot generation.  A deliberate, bounded throughput-over-strict-"
    "WFQ-order tradeoff; disable for strict weighted-fair order.  "
    "Inert unless serving.maxConcurrent > 0.")

ADMIT_WAIT_BUDGET_MS = register(
    "spark.rapids.tpu.serving.health.admitWaitBudgetMs", 250.0,
    "Admission-wait budget per query for the HC009 health rule "
    "(tools/history): a recorded query whose serve.admit_wait_ms "
    "counter exceeds this is flagged — the serving tier is saturated "
    "for its traffic (docs/serving.md).")

MESH_ENABLED = register(
    "spark.rapids.tpu.serving.mesh.enabled", False,
    "Pod-scale serving (docs/pod_serving.md): fuse the serving tier "
    "with the SPMD tier.  Admission grants MESH residency (the "
    "concurrency budget scales per device and batching groups by "
    "mesh_key x template), the prepared-plan / result / persisted-AOT "
    "caches fold parallel/mesh.mesh_key into their keys so same-mesh "
    "tenants share one compiled partitioned program set, exchange and "
    "scan output partitions adopt per-shard device placement at the "
    "producer (stage inputs are born on their mesh device instead of "
    "host device_put round-trips — the reference's UCX shuffle "
    "locality, PAPER.md 2.10/5.8), and SPMD sort runs its bounded-"
    "residency bucketed sampling.  Default off = the single-device "
    "serving tier, bit-for-bit.")

MESH_DEVICE_BUDGET = register(
    "spark.rapids.tpu.serving.mesh.deviceBudget", 1,
    "Admitted queries per mesh device under mesh serving: the WFQ "
    "pump's concurrency limit becomes "
    "min(maxConcurrent, semaphore permits) x n_devices x this.  A pod "
    "slice admits proportionally to its width — N tenants cost one "
    "mesh-resident program set, not N serialized turns "
    "(docs/pod_serving.md).",
    check=lambda v: v >= 1)


def mesh_serving_enabled(conf=None) -> bool:
    """One conf read; the whole pod-serving tier is dormant when off."""
    from spark_rapids_tpu.config import get_conf
    conf = conf or get_conf()
    return bool(conf.get(MESH_ENABLED))


def mesh_cache_suffix(conf=None) -> str:
    """The mesh-identity component of every serving-tier cache key
    under mesh serving: a short digest of ``mesh_key(active_mesh())``,
    or '' when mesh serving is off / no mesh is active.  Folding this
    into template / result / prepared keys is what makes a cache entry
    safe to share between tenants (same mesh => same partitioned
    executables) and what re-keys everything when the mesh SHAPE
    changes (an 8-device entry must never serve a 4-device pod)."""
    if not mesh_serving_enabled(conf):
        return ""
    from spark_rapids_tpu.parallel import mesh as _mesh
    m = _mesh.active_mesh()
    if m is None:
        return ""
    import hashlib
    digest = hashlib.sha256(
        repr(_mesh.mesh_key(m)).encode()).hexdigest()[:12]
    return "|mesh:" + digest


# ------------------------------------------------------------------ #
# Per-query serving context (thread-local)
# ------------------------------------------------------------------ #
#
# Admission happens BEFORE the event-log writer's query_begin counter
# snapshot and plan-cache lookups happen before plan_query — so neither
# is attributable through the monotonic-counter delta mechanism.  The
# scheduler and PreparedQuery instead deposit their per-query facts
# here, and EventLogWriter.query_end (which runs on the calling thread,
# inside the admitted region) folds them into the query record.

_TL = threading.local()


def update_serving_context(**kv: Any) -> None:
    ctx = getattr(_TL, "ctx", None)
    if ctx is None:
        ctx = _TL.ctx = {}
    ctx.update(kv)


def current_serving_context() -> Optional[dict]:
    """The calling thread's serving facts for the query in flight
    (tenant, priority, admit_wait_ms, plan_cache hit/miss), or None."""
    ctx = getattr(_TL, "ctx", None)
    return dict(ctx) if ctx else None


def clear_serving_context() -> None:
    _TL.ctx = None
