"""Cross-tenant work sharing: one fleet, shared computation.

ROADMAP item #4's serving-scale layer (docs/work_sharing.md): N tenants
issuing the same dashboard query should cost ~1x device work, not Nx.
Three mechanisms, all process-wide, all dormant behind ONE conf read
when ``spark.rapids.tpu.serving.sharing.enabled`` is false (the
default — sharing is a serving-tier posture, opted into by the fleet):

- **result cache** (:class:`ResultCache`): completed query results
  keyed by ``plan structural identity x conf fingerprint``
  (plan/share_key.py) and invalidated by input-content digests.  A
  hit returns the cached Arrow result with ZERO plan/tag/lower/
  compile/scan work.  Entries hold their batches as Arrow-IPC frames
  registered with the process :class:`~spark_rapids_tpu.memory.store.
  BufferStore` at HOST tier (priority ``SHARED_RESULT``), so under
  memory pressure cached results spill to disk and restore
  transparently instead of pinning memory — the tiered-store
  economics of the reference applied to whole results.  Byte-budget
  LRU (``resultCache.budgetBytes``); oversized results are simply not
  cached.
- **shared scans** (:class:`ScanShareRegistry`): concurrent queries
  over the same file set + pushed filters ride ONE decode pass.  The
  first arrival is the LEADER and publishes each upload unit (the
  decoded host tables io/scan.py accumulates) as it produces them;
  later arrivals SUBSCRIBE and replay the buffered units, then follow
  live.  While consumers overlap, the leader's uploaded device batch
  is shared too (plain decoded batches only — wire-form EncodedBatch
  carries donation bookkeeping and is never shared); once every
  consumer finishes, device memos drop (host HBM must not stay
  pinned) and the completed entry's HOST tables stay in a bounded LRU
  so a later identical scan still skips the decode.  A leader that
  dies or abandons mid-scan aborts the entry; subscribers fall back
  to their own decode, skipping the units they already consumed
  (unit streams are deterministic by key construction).
- **admission-aware batching** lives in serving/scheduler.py: queued
  plans carrying the same template group are granted together so
  their scans overlap and the in-flight dedup above engages
  (``serving.batching.enabled``).

Sharing is bit-for-bit by construction: keys are structural and
content-complete (plan/share_key.py), results are stored as the exact
Arrow-IPC bytes of the first execution, and anything not provably
deterministic (nondeterministic expressions, UDFs, runtime-filtered
scans) never shares.  Shared objects are IMMUTABLE by contract —
consumers copy-on-write or re-materialize; tpulint SRC011 (error)
enforces this over serving//execs/ source.
"""

from __future__ import annotations

import collections
import threading
import weakref
from typing import Iterator, Optional

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.config import register
from spark_rapids_tpu.robustness.lock_tracker import tracked_lock

SHARING_ENABLED = register(
    "spark.rapids.tpu.serving.sharing.enabled", False,
    "Master switch for cross-tenant work sharing (docs/"
    "work_sharing.md): the process-wide result cache and shared-scan "
    "dedup.  Off (default) = one conf read per query, no cache "
    "exists.  bench.py --sessions rounds turn it on (--no-sharing "
    "opts out).")

RESULT_CACHE_BUDGET = register(
    "spark.rapids.tpu.serving.resultCache.budgetBytes", 256 << 20,
    "Byte budget of the process-wide result cache (LRU past it; a "
    "single result larger than a quarter of this is not cached).  "
    "Entries are registered with the spillable buffer store at HOST "
    "tier, so the budget bounds cache IDENTITY, while residency "
    "follows the store's host/disk spill policy "
    "(docs/work_sharing.md).",
    check=lambda v: v >= 0)

RESULT_MIN_HIT_RATE = register(
    "spark.rapids.tpu.serving.resultCache.health.minHitRate", 0.25,
    "HC012 (tools/history) flags a query window whose result-cache "
    "evictions exceed its hits while the hit rate sits under this "
    "floor — the cache is thrashing: its budget is too small for the "
    "fleet's working set (docs/work_sharing.md).")

SCAN_SHARE_ENABLED = register(
    "spark.rapids.tpu.serving.sharing.scans", True,
    "Shared scans under the sharing master switch: concurrent (and "
    "repeated) queries over one file set + pushed filters ride one "
    "decode pass via in-flight dedup (docs/work_sharing.md).")

SCAN_CACHE_BUDGET = register(
    "spark.rapids.tpu.serving.sharing.scanCache.budgetBytes", 128 << 20,
    "Byte budget for COMPLETED shared-scan entries retained (decoded "
    "host tables) so later identical scans skip the decode; in-flight "
    "entries are never evicted.  Device batches are shared only while "
    "consumers overlap and are dropped when the last one finishes "
    "(shared scans must not pin HBM).",
    check=lambda v: v >= 0)


def enabled(conf=None) -> bool:
    from spark_rapids_tpu.config import get_conf

    return bool((conf or get_conf()).get(SHARING_ENABLED))


def scan_sharing_enabled(conf=None) -> bool:
    from spark_rapids_tpu.config import get_conf

    conf = conf or get_conf()
    return bool(conf.get(SHARING_ENABLED)) \
        and bool(conf.get(SCAN_SHARE_ENABLED))


# ------------------------------------------------------------------ #
# Process-global counters (the `share.*` event-log surface)
# ------------------------------------------------------------------ #

_STATS_LOCK = threading.Lock()
_STATS = collections.Counter()


def tick(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[key] += n


def stats() -> dict:
    """Cumulative process-wide sharing counters.  Monotonic except the
    two gauges (``result_bytes``, ``result_entries``), which report
    the cache's CURRENT footprint."""
    with _STATS_LOCK:
        out = {k: _STATS.get(k, 0) for k in (
            "result_hits", "result_misses", "result_evictions",
            "result_invalidations", "result_inserts",
            "scan_leads", "scan_subscribes", "scan_units_shared",
            "scan_upload_shared", "scan_units_decoded",
            "scan_rows_decoded", "scan_overflows")}
    out["result_bytes"] = RESULT_CACHE.bytes_used()
    out["result_entries"] = len(RESULT_CACHE)
    total = out["result_hits"] + out["result_misses"]
    out["result_hit_rate"] = round(out["result_hits"] / total, 3) \
        if total else 0.0
    return out


def reset_stats() -> None:
    with _STATS_LOCK:
        _STATS.clear()


# ------------------------------------------------------------------ #
# Result cache
# ------------------------------------------------------------------ #


def _table_ipc(tbl: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        for b in tbl.combine_chunks().to_batches():
            w.write_batch(b)
    return sink.getvalue().to_pybytes()


def _ipc_table(buf: bytes) -> pa.Table:
    return pa.ipc.open_stream(pa.py_buffer(buf)).read_all()


class _ResultEntry:
    """One cached result: the Arrow-IPC frame of the exact first
    execution, registered with the buffer store at HOST tier (it
    spills to disk under pressure and restores on read), plus the
    input-content digests that invalidate it."""

    __slots__ = ("key", "digests", "handle", "nbytes", "rows")

    def __init__(self, key: str, digests: list, handle, nbytes: int,
                 rows: int):
        self.key = key
        self.digests = digests
        self.handle = handle
        self.nbytes = nbytes
        self.rows = rows


class ResultCache:
    """Process-wide byte-budget LRU over :class:`_ResultEntry` (see
    module doc).  All methods are lock-protected; the store handles
    entries hold close() on removal so evicted results release their
    host/disk footprint immediately."""

    def __init__(self):
        # guard: _mu
        self._entries: "collections.OrderedDict[str, _ResultEntry]" = \
            collections.OrderedDict()
        self._mu = tracked_lock("resultCache.mu")

    def bytes_used(self) -> int:
        with self._mu:
            return sum(e.nbytes for e in self._entries.values())

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def lookup(self, key: str, digests: list) -> Optional[pa.Table]:
        """Get-and-touch.  The entry's stored input digests are
        verified against the CURRENT digests first: a mismatch (an
        input file changed content) invalidates the entry — counted,
        and observable to the mutation probes — and reads as a
        miss."""
        stale = None
        with self._mu:
            e = self._entries.get(key)
            if e is not None and e.digests != digests:
                stale = self._entries.pop(key)
                e = None
            elif e is not None:
                self._entries.move_to_end(key)
        if stale is not None:
            tick("result_invalidations")
            self._close(stale)
        if e is None:
            tbl = self._restore_persisted(key, digests)
            if tbl is not None:
                tick("result_hits")
                return tbl
            tick("result_misses")
            return None
        try:
            arrays = e.handle.get_host()  # HOST or DISK: restores
            try:
                tbl = _ipc_table(arrays["ipc"].tobytes())
            finally:
                e.handle.unpin()
        except Exception:
            # the backing entry died (store reset between phases, a
            # torn spill file): drop it and answer honestly with a
            # miss — never a broken hit
            with self._mu:
                self._entries.pop(key, None)
            tick("result_misses")
            return None
        tick("result_hits")
        return tbl

    def _restore_persisted(self, key: str,
                           digests: list) -> Optional[pa.Table]:
        """Lazy restore from the warm-start disk tier
        (spark_rapids_tpu/persist.py) on an in-memory miss — one conf
        read when persistence is off.  The persisted frame carries its
        own `plan_source_digests` stat triples; a mismatch against the
        CURRENT digests (a source file changed since the frame was
        written) deletes the entry and reads as an honest miss.  A
        valid restore re-enters the normal in-memory tier via
        insert(), so it re-registers with the buffer store and ages
        under the same LRU as a fresh result."""
        from spark_rapids_tpu import persist as _persist

        store = _persist.active()
        if store is None:
            return None
        rec = store.load_result(key)
        if rec is None:
            return None
        meta, payload = rec
        if meta.get("digests") != [list(t) for t in digests]:
            store.delete_result(key)
            tick("result_invalidations")
            return None
        try:
            tbl = _ipc_table(payload)
        except Exception:
            store.delete_result(key)
            _persist.tick("errors")
            return None
        _persist.tick("result_hits")
        self.insert(key, digests, tbl)
        return tbl

    def insert(self, key: str, digests: list, tbl: pa.Table) -> bool:
        """Cache one result (first writer wins); False when the result
        is too large for the budget.  The IPC frame is registered with
        the process store at HOST tier under the SHARED_RESULT spill
        priority, so pressure moves it host->disk through the normal
        spill machinery instead of pinning memory."""
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.config import get_conf
        from spark_rapids_tpu.memory.store import (
            SpillPriorities,
            get_store,
        )

        budget = int(get_conf().get(RESULT_CACHE_BUDGET))
        # cheap rejections BEFORE paying the IPC copy (insert runs on
        # the collect critical path): tbl.nbytes over-approximates the
        # compact frame, so a table bigger than the whole budget can
        # never pass the quarter rule; a present key never re-inserts
        if budget <= 0 or tbl.nbytes > budget:
            return False
        with self._mu:
            if key in self._entries:
                return False
        buf = _table_ipc(tbl)
        nbytes = len(buf)
        if nbytes > max(1, budget // 4):
            return False
        arrays = {"ipc": np.frombuffer(buf, np.uint8),
                  "__num_rows": np.asarray(tbl.num_rows, np.int64)}
        handle = get_store().register_host(
            arrays, T.Schema([]), SpillPriorities.SHARED_RESULT)
        entry = _ResultEntry(key, digests, handle, nbytes,
                             tbl.num_rows)
        evicted: list[_ResultEntry] = []
        with self._mu:
            if key in self._entries:
                handle.close()
                return False
            self._entries[key] = entry
            used = sum(e.nbytes for e in self._entries.values())
            while used > budget and len(self._entries) > 1:
                _k, old = self._entries.popitem(last=False)
                if old is entry:  # never evict the fresh insert
                    self._entries[_k] = old
                    self._entries.move_to_end(_k, last=False)
                    break
                evicted.append(old)
                used -= old.nbytes
        for old in evicted:
            tick("result_evictions")
            self._close(old)
        from spark_rapids_tpu import persist as _persist

        store = _persist.active()
        if store is not None:
            # reuse the IPC frame already computed for the store
            # registration; the write itself runs on the persist
            # writer thread, off the collect critical path, and a
            # restore-triggered re-insert skips it (file exists)
            store.save_result_async(
                key, {"digests": [list(t) for t in digests],
                      "rows": tbl.num_rows},
                buf, _persist.max_bytes())
        tick("result_inserts")
        return True

    @staticmethod
    def _close(e: _ResultEntry) -> None:
        try:
            e.handle.close()
        except Exception:
            pass

    def reset(self) -> None:
        with self._mu:
            entries = list(self._entries.values())
            self._entries.clear()
        for e in entries:
            self._close(e)


RESULT_CACHE = ResultCache()

#: bounded (id(plan) -> (weakref, conf_fp, key)) memo so a prepared
#: template's repeat executions never re-hash in-memory table content;
#: the weakref guards against a recycled id aliasing a DEAD plan's key
#: onto different work
_KEY_MEMO: dict[int, tuple] = {}
_KEY_MEMO_LOCK = threading.Lock()


def _plan_key(plan, conf) -> Optional[str]:
    from spark_rapids_tpu.eventlog import conf_fingerprint
    from spark_rapids_tpu.plan.share_key import plan_share_key
    from spark_rapids_tpu.serving import mesh_cache_suffix

    # mesh suffix in BOTH the memo key and the result key: a cached
    # result's row ORDER is execution-shaped (mesh width changes
    # partition interleaving), so a result minted on one mesh must not
    # serve another (docs/pod_serving.md)
    mesh_sfx = mesh_cache_suffix(conf)
    fp = conf_fingerprint(conf) + mesh_sfx
    pid = id(plan)
    with _KEY_MEMO_LOCK:
        memo = _KEY_MEMO.get(pid)
        if memo is not None and memo[0]() is plan and memo[1] == fp:
            return memo[2]
    key = plan_share_key(plan, conf)
    if key is not None and mesh_sfx:
        key = key + mesh_sfx
    try:
        ref = weakref.ref(plan)
    except TypeError:
        return key
    with _KEY_MEMO_LOCK:
        if len(_KEY_MEMO) > 256:
            _KEY_MEMO.clear()
        _KEY_MEMO[pid] = (ref, fp, key)
    return key


def lookup_result(plan, conf) -> tuple[Optional[pa.Table],
                                       Optional[str]]:
    """(cached result | None, verdict): verdict is ``"hit"`` /
    ``"miss"`` for shareable plans and None for plans the determinism
    gate excludes (those never consult the cache)."""
    key = _plan_key(plan, conf)
    if key is None:
        return None, None
    from spark_rapids_tpu.plan.share_key import plan_source_digests

    try:
        digests = plan_source_digests(plan)
    except OSError:
        return None, None  # a source vanished: let execution raise
    tbl = RESULT_CACHE.lookup(key, digests)
    return tbl, ("hit" if tbl is not None else "miss")


def offer_result(plan, conf, tbl: pa.Table) -> None:
    """Population hook for a just-completed collect: cache the result
    when the plan is shareable (misses and unshareable plans are both
    silent — offering is always safe)."""
    key = _plan_key(plan, conf)
    if key is None:
        return
    from spark_rapids_tpu.plan.share_key import plan_source_digests

    try:
        digests = plan_source_digests(plan)
    except OSError:
        return
    RESULT_CACHE.insert(key, digests, tbl)


# ------------------------------------------------------------------ #
# Shared scans: in-flight dedup + completed-entry reuse
# ------------------------------------------------------------------ #


class ScanShareAborted(RuntimeError):
    """The leader abandoned or failed the shared scan mid-stream;
    subscribers fall back to their own decode (skipping the units
    they already consumed — unit streams are deterministic)."""


def _unit_bytes(unit) -> int:
    if isinstance(unit, int):
        return 8
    return sum(t.nbytes for t in unit)


class ScanShareEntry:
    """One shared scan partition's published unit stream (see module
    doc).  Units are (host_unit, device_batch|None) pairs; host units
    are immutable Arrow tables (or bare int counts), device batches
    are shared only while consumers overlap."""

    def __init__(self, key: str, cap: int = 0):
        self.key = key
        self._cv = threading.Condition()
        self._units: list = []      # guard: _cv (publish order)
        self._device: dict = {}     # guard: _cv (idx -> shared batch)
        self._done = False          # guard: _cv
        self._aborted = False       # guard: _cv
        self.leader_thread = threading.get_ident()
        self._consumers = 1         # guard: _cv (starts at the leader)
        self.nbytes = 0             # guard: _cv
        #: in-flight footprint cap (scanCache.budgetBytes, 0 = none):
        #: an entry buffers its host units for the scan's LIFETIME, so
        #: without a cap one huge scan would materialize its whole
        #: decoded table set in host memory — past the cap the entry
        #: self-aborts (dropping the buffer; subscribers fall back to
        #: their own decode) rather than trade a decode for an OOM
        self._cap = int(cap)

    # -- leader side ------------------------------------------------ #

    def publish(self, unit, device_batch=None) -> None:
        overflowed = False
        with self._cv:
            if self._aborted:
                return
            if device_batch is not None:
                _mark_batch_shared(device_batch)
                self._device[len(self._units)] = device_batch
            self._units.append(unit)
            self.nbytes += _unit_bytes(unit)
            if self._cap and self.nbytes > self._cap:
                self._abort_locked()
                overflowed = True
            self._cv.notify_all()
        if overflowed:
            tick("scan_overflows")

    def complete(self) -> None:
        with self._cv:
            self._done = True
            self._cv.notify_all()

    def _abort_locked(self) -> None:
        self._aborted = True
        self._done = True
        # free the buffered footprint NOW — subscribers mid-replay
        # observe done+aborted and fall back on their consumed count,
        # never on the dropped buffer
        self._units.clear()
        self._device.clear()
        self._cv.notify_all()

    def abort(self) -> None:
        with self._cv:
            self._abort_locked()

    @property
    def done(self) -> bool:
        with self._cv:
            return self._done and not self._aborted

    # -- subscriber side -------------------------------------------- #

    def subscribe_units(self) -> Iterator[tuple]:
        """Yield (host_unit, shared_device_batch|None) in publish
        order: buffered units first, then live as the leader produces
        them.  Raises :class:`ScanShareAborted` when the leader
        abandons mid-stream (the consumer's fallback skips what it
        already received), or QueryCancelled when the SUBSCRIBER's own
        query is cancelled while waiting for the leader (the wait is
        bounded and cancel-aware — SRC012; the subscriber's release
        path runs normally, and a cancelled LEADER aborts the entry
        through its drain finally, waking everyone here)."""
        from spark_rapids_tpu.serving import cancel as _cancel

        i = 0
        while True:
            with self._cv:
                tok = _cancel.current_token()
                while i >= len(self._units) and not self._done:
                    self._cv.wait(_cancel.poll_timeout(tok))
                    if tok is not None:
                        tok.check()
                if i < len(self._units):
                    unit = self._units[i]
                    dev = self._device.get(i)
                else:
                    if self._aborted:
                        raise ScanShareAborted(self.key)
                    return
            yield unit, dev
            i += 1

    def _drop_device(self) -> None:
        with self._cv:
            self._device.clear()


def _mark_batch_shared(batch) -> None:
    """Register every device array of a shared batch with the
    shared-array registry: a consumer that parks it in the buffer
    store and spills it must copy, never ``.delete()`` — the other
    consumers still compute over the same HBM."""
    from spark_rapids_tpu.columnar.column import mark_shared_array
    from spark_rapids_tpu.memory.store import _col_leaves

    for i, c in enumerate(batch.columns):
        for _name, a in _col_leaves(c, f"c{i}"):
            mark_shared_array(a)
    n = batch.num_rows
    if not isinstance(n, int):
        mark_shared_array(n)


class ScanShareRegistry:
    """Process-wide registry of shared scan entries: in-flight dedup
    plus a byte-bounded LRU of completed entries (host units only —
    device memos drop with the last overlapping consumer)."""

    def __init__(self):
        # guard: _mu
        self._entries: "collections.OrderedDict[str, ScanShareEntry]" \
            = collections.OrderedDict()
        self._mu = tracked_lock("scanShare.mu")

    def begin(self, key: str) -> tuple[Optional[ScanShareEntry], bool]:
        """(entry, is_leader).  (None, False) means "do not share":
        the live entry's leader is THIS thread (a same-thread
        subscribe would deadlock — e.g. a self-join interleaving two
        scans of one table on one task thread)."""
        with self._mu:
            e = self._entries.get(key)
            if e is not None:
                with e._cv:
                    aborted = e._aborted
                if aborted:
                    e = None
                elif e.leader_thread == threading.get_ident() \
                        and not e.done:
                    return None, False
                else:
                    self._entries.move_to_end(key)
                    with e._cv:
                        e._consumers += 1
                    return e, False
            from spark_rapids_tpu.config import get_conf

            e = ScanShareEntry(
                key, cap=int(get_conf().get(SCAN_CACHE_BUDGET)))
            self._entries[key] = e
            tick("scan_leads")
            return e, True

    def release(self, entry: ScanShareEntry) -> None:
        """A consumer (leader or subscriber) finished with the entry;
        the last one out drops the shared device batches — HBM must
        not stay pinned by a cache — and aborted entries leave the
        registry entirely."""
        drop_key = None
        with entry._cv:
            entry._consumers -= 1
            last = entry._consumers <= 0
            aborted = entry._aborted
        if last:
            entry._drop_device()
            if aborted:
                drop_key = entry.key
        if drop_key is not None:
            with self._mu:
                cur = self._entries.get(drop_key)
                if cur is entry:
                    del self._entries[drop_key]
        self._enforce_budget()

    def _enforce_budget(self) -> None:
        from spark_rapids_tpu.config import get_conf

        budget = int(get_conf().get(SCAN_CACHE_BUDGET))
        with self._mu:
            # snapshot size + liveness per entry under ITS lock (a
            # leader thread grows nbytes under _cv concurrently; the
            # old unlocked sum could tear against publish and evict
            # on a stale total), then evict from the locked snapshot.
            # _mu -> _cv nesting matches begin()'s acquisition order.
            sizes: dict[str, int] = {}
            busy: dict[str, bool] = {}
            used = 0
            for key in list(self._entries):
                e = self._entries[key]
                with e._cv:
                    sizes[key] = e.nbytes
                    busy[key] = e._consumers > 0 or not e._done
                used += sizes[key]
            for key in list(self._entries):
                if used <= budget:
                    break
                if busy[key]:
                    continue  # in-flight entries are never evicted
                e = self._entries.pop(key)
                used -= sizes[key]
                e._drop_device()

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def inflight(self) -> int:
        # _done is _cv-guarded state a leader flips concurrently;
        # snapshot the registry under _mu, then read each entry's
        # flag under its own lock instead of racing complete()/abort()
        with self._mu:
            entries = list(self._entries.values())
        n = 0
        for e in entries:
            with e._cv:
                if not e._done:
                    n += 1
        return n

    def reset(self) -> None:
        with self._mu:
            entries = list(self._entries.values())
            self._entries.clear()
        for e in entries:
            e.abort()
            e._drop_device()


SCAN_REGISTRY = ScanShareRegistry()


def record_scan_decode(rows: int) -> None:
    """Tapped decode counter (io/scan.py ticks it per decoded table):
    THE sub-linearity evidence — shared/cached executions leave it
    flat while unshared ones grow it linearly in sessions."""
    with _STATS_LOCK:
        _STATS["scan_units_decoded"] += 1
        _STATS["scan_rows_decoded"] += rows


def reset() -> None:
    """Tests / bench phase boundaries: drop every cache and counter."""
    RESULT_CACHE.reset()
    SCAN_REGISTRY.reset()
    with _KEY_MEMO_LOCK:
        _KEY_MEMO.clear()
    reset_stats()
