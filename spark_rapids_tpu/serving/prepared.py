"""PreparedQuery: the prepared-statement handle over the plan cache.

``TpuSession.prepare(df)`` / ``SqlSession.prepare(sql)`` return one of
these.  ``execute()`` resolves the template against the session's
:class:`~spark_rapids_tpu.serving.plan_cache.PlanCache` — a hit
re-drains the cached lowered exec tree with ZERO parse/plan/tag/lower
work (the acceptance contract: no ``query.plan``/``query.tag``/
``query.lower`` spans and no jit-cache misses on a hit) — and runs it
through the exact collect machinery plain DataFrames use (admission,
tracing, history, event log, CPU-degrade ladder), so a prepared query
is indistinguishable from an ad-hoc one everywhere downstream.

``execute_stream()`` is the serving-shaped fetch: Arrow record batches
yielded incrementally off the pipelined collect path — the device keeps
at most ``pipeline.depth`` result batches in flight and the producer
blocks when the consumer lags (backpressure comes from the prefetch
stage's bounded queue, parallel/pipeline.py), instead of one giant
table materialization per request.

Concurrency: re-drains of ONE cached exec tree serialize on the entry
lock (the tree is stateful while draining); different templates — and
the same template across different sessions' caches — run concurrently
under the admission scheduler.
"""

from __future__ import annotations

from typing import Iterator, Optional

from spark_rapids_tpu.serving.plan_cache import (
    CacheEntry,
    binding_key,
    sql_template_key,
    template_key,
)


class PreparedQuery:
    """A prepared template: either a native DataFrame plan or a SQL
    text with named parameters (``:name``) bound at execute time."""

    def __init__(self, session, df=None, sql_text: Optional[str] = None,
                 sql_session=None,
                 param_names: Optional[frozenset] = None):
        assert (df is None) != (sql_text is None)
        self._session = session
        self._df = df
        self._sql_text = sql_text
        self._sql_session = sql_session
        self.param_names = param_names or frozenset()
        #: (conf_fingerprint, binding_repr) -> key memo: the structural
        #: key digests in-memory tables; recomputing it per execute
        #: would re-hash the data every time
        self._key_memo: dict = {}
        #: conf_fingerprint -> binding-INDEPENDENT template key (the
        #: admission-batching group: same template, different
        #: bindings, one group)
        self._group_memo: dict = {}
        self.last_plan_hash: Optional[str] = None
        #: in-flight CancelTokens of THIS template's executions (the
        #: PreparedQuery.cancel() scope; serving/cancel.py)
        from spark_rapids_tpu.serving.cancel import TokenSet

        self._inflight = TokenSet()

    # -- resolution -------------------------------------------------- #

    def _key(self, conf, params: Optional[dict]) -> str:
        from spark_rapids_tpu.eventlog import conf_fingerprint
        from spark_rapids_tpu.serving import mesh_cache_suffix

        # the mesh suffix is part of the memo key, not just the hashed
        # payload: a pod reshape changes the template key under an
        # UNCHANGED conf fingerprint, and a memo keyed on fp alone
        # would keep serving the old mesh's entry
        fp = conf_fingerprint(conf) + mesh_cache_suffix(conf)
        binding = binding_key(params)
        memo = self._key_memo.get((fp, binding))
        if memo is not None:
            return memo
        if self._sql_text is not None:
            key = sql_template_key(self._sql_text, conf, params)
        else:
            key = template_key(self._df._plan, conf)
        # bound memo: conf epochs and bindings are few per template
        if len(self._key_memo) > 64:
            self._key_memo.clear()
        self._key_memo[(fp, binding)] = key
        return key

    def _group_key(self, conf) -> str:
        """The binding-independent template identity this query admits
        under: admission-aware batching (serving/scheduler.py) grants
        queued queries sharing it together, so their scans overlap and
        dedup in flight (docs/work_sharing.md).  SQL templates key on
        normalized text x conf (bindings excluded — 'same template,
        different bindings' is exactly the compatible-plan class);
        DataFrame templates on their structural plan key x conf.
        Under mesh serving the group folds the mesh identity too
        (mesh_key x template — the ISSUE's batching contract): tenants
        batch together only when they would share the same
        mesh-resident program set."""
        from spark_rapids_tpu.eventlog import conf_fingerprint
        from spark_rapids_tpu.serving import mesh_cache_suffix

        fp = conf_fingerprint(conf) + mesh_cache_suffix(conf)
        memo = self._group_memo.get(fp)
        if memo is not None:
            return memo
        if self._sql_text is not None:
            key = sql_template_key(self._sql_text, conf, None)
        else:
            key = template_key(self._df._plan, conf)
        if len(self._group_memo) > 64:
            self._group_memo.clear()
        self._group_memo[fp] = key
        return key

    def _resolve(self, params: Optional[dict]) -> tuple:
        """(entry, hit): look the template up in the session plan
        cache; on a miss, parse (SQL) + lower ONCE and insert.  The
        hit/miss verdict is returned, NOT written to the serving
        context here — execute() hands it to _collect_tpu, which
        deposits it inside the query's admission scope (a nested
        query's facts must land in its own record, never the outer
        query's)."""
        from spark_rapids_tpu.eventlog import plan_fingerprint
        from spark_rapids_tpu.plan.planner import plan_query

        conf = self._session.conf
        if params and self._sql_text is None:
            raise ValueError(
                "params are only valid for SQL templates "
                "(prepare(sql) with :name placeholders)")
        key = self._key(conf, params)
        cache = self._session.plan_cache
        entry = cache.lookup(key)
        hit = entry is not None
        if entry is None:
            if self._sql_text is not None:
                df = self._sql_session.sql(self._sql_text,
                                           params=params or {})
            else:
                df = self._df
            exec_, meta = plan_query(df._plan, conf)
            mine = CacheEntry(exec_, meta,
                              plan_fingerprint(meta.explain()), df)
            entry = cache.insert(key, mine)
            if entry is not mine:
                # another thread of this session lowered the same
                # template first; drop the duplicate tree
                exec_.close()
        self.last_plan_hash = entry.plan_hash
        return entry, hit

    # -- execution --------------------------------------------------- #

    def _facts(self, hit: bool,
               extra_facts: Optional[dict]) -> dict:
        """The per-query serving facts deposited inside the admission
        scope.  ``extra_facts`` lets an ingress layer attach its own
        record section (the connect server's peer/wire_bytes/
        translate_ms — docs/connect.md) without a second deposit
        path."""
        facts = {"plan_cache": "hit" if hit else "miss",
                 "admission_group":
                     self._group_key(self._session.conf)}
        if extra_facts:
            facts.update(extra_facts)
        return facts

    def execute(self, params: Optional[dict] = None,
                extra_facts: Optional[dict] = None):
        """Run the template (binding ``params`` for SQL templates) and
        return the full Arrow result table.  Cache hits skip straight
        to draining the cached lowered plan.  The entry's re-drain
        lock is taken INSIDE admission (by _collect_tpu) — taking it
        here would deadlock against an admitted query that
        nested-executes this same template."""
        entry, hit = self._resolve(params)
        out, _qid = entry.df._collect_tpu(
            exec_=entry.exec_, meta=entry.meta,
            drain_lock=entry.lock,
            serving_facts=self._facts(hit, extra_facts),
            token_sink=self._inflight)
        return out

    def cancel(self, reason: str = "cancelled") -> int:
        """Cooperatively cancel every in-flight execution of THIS
        template (narrower than ``session.cancel()``): each raises
        QueryCancelled at its next checkpoint and unwinds cleanly —
        admission slot released, the entry's re-drain lock freed, the
        cached exec tree closed back to its re-drainable state.
        Returns the number of executions newly cancelled.  Requires
        spark.rapids.tpu.serving.cancellation.enabled (the default)."""
        return self._inflight.cancel(reason=reason)

    def execute_stream(self, params: Optional[dict] = None,
                       batch_rows: Optional[int] = None,
                       extra_facts: Optional[dict] = None) -> Iterator:
        """Run the template and yield the result INCREMENTALLY as
        Arrow record batches (optionally re-chunked to ``batch_rows``).
        Backpressure: the device-side producer runs at most the
        pipeline fetch depth ahead of the consumer; a slow consumer
        stalls the producer, not the process.  The admission slot and
        the template's entry lock are held until the stream is drained
        or closed — an abandoned stream must be ``close()``d (or left
        to GC) to release them."""
        entry, hit = self._resolve(params)
        yield from entry.df._stream_tpu(
            exec_=entry.exec_, meta=entry.meta,
            batch_rows=batch_rows, drain_lock=entry.lock,
            serving_facts=self._facts(hit, extra_facts),
            token_sink=self._inflight)

    # -- introspection ----------------------------------------------- #

    def explain(self, params: Optional[dict] = None) -> str:
        """The (cached) lowered plan's annotated report — what
        ``DataFrame.explain()`` would show for this template."""
        from spark_rapids_tpu.eventlog import render_plan_report

        entry, _hit = self._resolve(params)
        return render_plan_report(entry.exec_, entry.meta)

    def __repr__(self) -> str:
        what = ("sql" if self._sql_text is not None
                else type(self._df._plan).__name__)
        return (f"PreparedQuery[{what}, "
                f"params={sorted(self.param_names) or '-'}]")
