"""Logical plans and the TPU plan-rewriting engine.

The reference operates on Spark Catalyst physical plans; this framework
ships its own small logical plan + DataFrame frontend (SURVEY.md §7:
"put the data plane behind a narrow columnar FFI"), and this package is
the counterpart of the reference's L4 rewrite layer: GpuOverrides-style
per-node tagging with reasons, conf kill-switches, explain output, and
per-subtree CPU fallback (ref: GpuOverrides.scala, RapidsMeta.scala).
"""
