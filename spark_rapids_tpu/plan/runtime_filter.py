"""Runtime join filters: build-side key pruning pushed into the scan.

The sideways-information-passing / Bloom-join idea (Spark's
InSubqueryExec-based DPP and the reference family's later
GpuBloomFilterAggregate work) re-designed for the TPU deployment shape:
here the scarce resource is the host->device WIRE (BENCH_r05 measured a
~13 MB/s, ~114 ms-RTT tunnel under q3), so the selective side of a join
must reduce the expensive side *before it moves* — the filter is built
ON DEVICE from the build side's join keys (a few fused scatter
programs), fetched ONCE as a small bitset + min/max pair, and applied
ON HOST inside the probe side's scan at three successively cheaper
points:

1. row-group pruning: the filter's [min, max] range joins the pushed
   predicate's footer-statistics checks (io/pushdown.py) — pruned row
   groups are never even decoded;
2. dictionary-LUT pruning in the fast native decoder (io/fastpar.py):
   the Bloom/range probe evaluates on the Parquet DICTIONARY (tens..
   thousands of values) and row filtering becomes one numpy gather;
3. a post-decode numpy mask in the host-prefilter path
   (io/pa_filter.py / io/scan.py) for everything else —
   non-reachable rows are dropped before encode+upload.

Soundness: a filter only ever DROPS probe rows whose key provably (min/
max) or probabilistically-never (Bloom: no-means-no, yes-means-maybe)
matches any build key.  For the eligible join types (inner, left_semi)
such rows contribute nothing to the output, so pruning — including NULL
keys, which never equi-match — is a pure IO optimization.  Outer and
anti joins preserve non-matching rows and are never filtered (tpulint
PL005 hard-errors if such a plan is ever built by hand).

The host and device Bloom share one bit layout — ``k`` double-hashed
murmur3 probes ``(h1 + i*h2) mod m`` over a little-endian uint32 word
array — with the host side running the numpy murmur3 mirrors in
exprs/hashing.py (parity pinned by test_runtime_filter.py).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import register

RF_ENABLED = register(
    "spark.rapids.tpu.sql.runtimeFilter.enabled", True,
    "Build Bloom + min-max filters from the build side of eligible "
    "joins (inner, left_semi; equi-keys) and apply them host-side "
    "inside the probe side's scan, so probe rows whose join key cannot "
    "match any build key never cross the host->device link (the "
    "sideways-information-passing / Bloom-join analog of Spark's "
    "runtime filters).  Disabled, plans are bit-for-bit identical to "
    "the un-filtered shape.")

RF_MINMAX_ENABLED = register(
    "spark.rapids.tpu.sql.runtimeFilter.minMaxEnabled", True,
    "Include the build keys' [min, max] range in runtime filters: "
    "applied to Parquet row-group footer statistics (whole row groups "
    "skipped before decode) and as a host row mask.")

RF_BLOOM_ENABLED = register(
    "spark.rapids.tpu.sql.runtimeFilter.bloomEnabled", True,
    "Include a murmur3 double-hashed Bloom filter of the build keys in "
    "runtime filters (built on device, fetched once, probed on host).")

RF_MAX_BUILD_ROWS = register(
    "spark.rapids.tpu.sql.runtimeFilter.maxBuildRows", 1 << 22,
    "Skip runtime-filter creation when the build side's estimated row "
    "count exceeds this (an unselective build side prunes little and "
    "its Bloom bitset grows with it).")

RF_FPP = register(
    "spark.rapids.tpu.sql.runtimeFilter.fpp", 0.01,
    "Target Bloom false-positive probability; sizes the bitset from "
    "the build side's estimated rows.  False positives only reduce "
    "pruning, never correctness.",
    check=lambda v: 0.0 < v < 1.0)

#: join types whose probe side may be pruned by build-side keys
ELIGIBLE_JOIN_TYPES = ("inner", "left_semi")

#: key dtypes with a host/device hash-parity story (fixed-width
#: integral lanes; floats are excluded — NaN/-0.0 normalization has no
#: pruning payoff on join keys)
_SUPPORTED_32 = (T.ByteType, T.ShortType, T.IntegerType, T.DateType)
_SUPPORTED_64 = (T.LongType, T.TimestampType)

#: murmur3 seeds for the double-hash scheme (h_i = h1 + i*h2 mod m);
#: seed 1 is Spark's default hash seed, seed 2 is the classic Murmur3
#: test seed — any fixed pair works as long as host and device agree
BLOOM_SEED1 = 42
BLOOM_SEED2 = 0x9747B28C

_INT64_MAX = (1 << 63) - 1
_INT64_MIN = -(1 << 63)


def supported_key_dtype(dt: T.DataType) -> bool:
    return isinstance(dt, _SUPPORTED_32 + _SUPPORTED_64)


def bloom_params(n_est: int, fpp: float) -> tuple[int, int]:
    """(n_bits, n_hashes) for an expected key count at the target fpp;
    n_bits is a power of two so the device/host index math is one AND."""
    n_est = max(int(n_est), 1)
    bits = -n_est * math.log(fpp) / (math.log(2.0) ** 2)
    m = 1 << max(6, math.ceil(math.log2(max(bits, 64.0))))
    k = max(1, min(6, round(math.log(2.0) * m / n_est)))
    return m, k


# --------------------------------------------------------------------- #
# Process-global stats (the bench/tests observation surface, like
# parallel.speculation's registry)
# --------------------------------------------------------------------- #

_STATS_LOCK = threading.Lock()
_STATS = {"filters_built": 0, "build_rows": 0, "build_ms": 0.0,
          "pruned_rows": 0, "row_groups_pruned": 0}


def stats() -> dict:
    with _STATS_LOCK:
        return dict(_STATS)


def reset_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0.0 if k == "build_ms" else 0


def _record(key: str, v) -> None:
    with _STATS_LOCK:
        _STATS[key] += v


def record_pruned_rows(n: int) -> None:
    if n:
        _record("pruned_rows", int(n))


def record_row_groups_pruned(n: int) -> None:
    if n:
        _record("row_groups_pruned", int(n))


# --------------------------------------------------------------------- #
# The filter object
# --------------------------------------------------------------------- #

_NEXT_ID = [0]
_ID_LOCK = threading.Lock()


class RuntimeFilter:
    """One published (or pending) runtime filter for a single join key.

    Built by the build side's TpuRuntimeFilterBuildExec, consumed by
    probe-side scans.  Consumers never block on it: an unpublished
    filter simply applies nothing (pruning is an optimization, the join
    itself stays the source of truth)."""

    def __init__(self, key_name: str, dtype: T.DataType, join_type: str,
                 n_bits: int, n_hashes: int, use_minmax: bool,
                 use_bloom: bool, build_desc: str = ""):
        with _ID_LOCK:
            _NEXT_ID[0] += 1
            self.rf_id = _NEXT_ID[0]
        self.key_name = key_name
        self.dtype = dtype
        self.join_type = join_type
        self.n_bits = n_bits
        self.n_hashes = n_hashes
        self.use_minmax = use_minmax
        self.use_bloom = use_bloom
        self.build_desc = build_desc
        self.is64 = isinstance(dtype, _SUPPORTED_64)
        self._ready = threading.Event()
        self.min_val: Optional[int] = None
        self.max_val: Optional[int] = None
        self.bloom_words = None  # np.uint32[n_bits/32] when published
        self.n_keys = 0
        self.build_ms = 0.0

    # -- publication (build side) ------------------------------------- #

    @property
    def ready(self) -> bool:
        return self._ready.is_set()

    def publish(self, min_val: int, max_val: int, n_keys: int,
                bloom_words, build_ms: float) -> None:
        self.min_val = int(min_val)
        self.max_val = int(max_val)
        self.n_keys = int(n_keys)
        self.bloom_words = bloom_words
        self.build_ms = build_ms
        self._ready.set()
        _record("filters_built", 1)
        _record("build_rows", int(n_keys))
        _record("build_ms", build_ms)

    # -- probing (host side) ------------------------------------------ #

    def range_may_match(self, lo, hi) -> bool:
        """Could any key in [lo, hi] (ints) survive this filter's
        min/max?  Conservative: unknown stats keep the row group."""
        if not self.ready:
            return True
        if self.n_keys == 0:
            return False  # empty build side: nothing can match
        if not self.use_minmax or lo is None or hi is None:
            return True
        return not (hi < self.min_val or lo > self.max_val)

    def probe_host(self, values, validity=None):
        """bool[n] keep-mask for int64 numpy key values.  NULL slots
        (validity False) are dropped: NULL keys never equi-match, and
        the eligible join types emit nothing for them."""
        import numpy as np

        values = np.asarray(values, np.int64)
        mask = np.ones(len(values), bool) if validity is None \
            else np.asarray(validity, bool).copy()
        if not self.ready:
            return np.ones(len(values), bool)
        if self.n_keys == 0:
            return np.zeros(len(values), bool)
        if self.use_minmax:
            mask &= (values >= self.min_val) & (values <= self.max_val)
        if self.use_bloom and self.bloom_words is not None:
            from spark_rapids_tpu.exprs.hashing import (
                np_hash_int32_block,
                np_hash_int64_blocks,
            )

            if self.is64:
                h1 = np_hash_int64_blocks(values, BLOOM_SEED1)
                h2 = np_hash_int64_blocks(values, BLOOM_SEED2)
            else:
                w = values.astype(np.int32)
                h1 = np_hash_int32_block(w, BLOOM_SEED1)
                h2 = np_hash_int32_block(w, BLOOM_SEED2)
            m_mask = np.uint32(self.n_bits - 1)
            words = self.bloom_words
            for i in range(self.n_hashes):
                idx = (h1 + np.uint32(i) * h2) & m_mask
                bit = (words[idx >> np.uint32(5)]
                       >> (idx & np.uint32(31))) & np.uint32(1)
                mask &= bit.astype(bool)
        return mask

    def describe(self) -> str:
        parts = []
        if self.use_minmax:
            parts.append("minmax")
        if self.use_bloom:
            parts.append(f"bloom[{self.n_bits}b x{self.n_hashes}]")
        state = f"ready n={self.n_keys}" if self.ready else "pending"
        return (f"rf#{self.rf_id} key={self.key_name} "
                f"({'+'.join(parts) or 'none'}, {self.join_type}, "
                f"{state})")


# --------------------------------------------------------------------- #
# Device-side build helpers (traced inside the build exec's jitted
# per-batch update; see execs/join.py TpuRuntimeFilterBuildExec)
# --------------------------------------------------------------------- #


def device_key_hashes(col, is64: bool):
    """(h1, h2) uint32 hash lanes of a device key Column — the traced
    twin of the numpy pair in probe_host."""
    import jax.numpy as jnp

    from spark_rapids_tpu.exprs.hashing import (
        hash_int32_block,
        hash_int64_blocks,
    )

    if is64:
        v = col.data.astype(jnp.int64)
        return (hash_int64_blocks(v, BLOOM_SEED1),
                hash_int64_blocks(v, BLOOM_SEED2))
    w = col.data.astype(jnp.int32)
    return (hash_int32_block(w, BLOOM_SEED1),
            hash_int32_block(w, BLOOM_SEED2))


def device_update(state, col, contrib, n_bits: int, n_hashes: int,
                  is64: bool, use_bloom: bool):
    """Fold one batch's key column into (bits_u8, lo, hi, count).

    ``bits_u8`` is a byte-per-bit scatter target (scatter-max of 0/1 is
    OR; XLA has no scatter-or) packed to uint32 words only at finalize.
    ``contrib`` masks live, non-NULL rows; dead rows scatter 0 (no
    bit)."""
    import jax.numpy as jnp

    bits, lo, hi, count = state
    v = col.data.astype(jnp.int64)
    if v.shape[0] == 0:  # zero-capacity batch: nothing to fold
        return state
    lo = jnp.minimum(lo, jnp.min(
        jnp.where(contrib, v, jnp.int64(_INT64_MAX))))
    hi = jnp.maximum(hi, jnp.max(
        jnp.where(contrib, v, jnp.int64(_INT64_MIN))))
    count = count + jnp.sum(contrib.astype(jnp.int64))
    if use_bloom:
        h1, h2 = device_key_hashes(col, is64)
        one = contrib.astype(jnp.uint8)
        mask = jnp.uint32(n_bits - 1)
        for i in range(n_hashes):
            idx = (h1 + jnp.uint32(i) * h2) & mask
            bits = bits.at[idx.astype(jnp.int32)].max(one)
    return bits, lo, hi, count


def device_init_state(n_bits: int, use_bloom: bool):
    import jax.numpy as jnp

    bits = jnp.zeros((n_bits if use_bloom else 1,), jnp.uint8)
    return (bits, jnp.int64(_INT64_MAX), jnp.int64(_INT64_MIN),
            jnp.int64(0))


def device_merge_states(a, b):
    import jax.numpy as jnp

    return (jnp.maximum(a[0], b[0]), jnp.minimum(a[1], b[1]),
            jnp.maximum(a[2], b[2]), a[3] + b[3])


def device_pack_bits(bits_u8):
    """byte-per-bit uint8[m] -> little-endian uint32[m/32] words (the
    wire form the host probe indexes)."""
    import jax.numpy as jnp

    m = bits_u8.shape[0]
    b = bits_u8.reshape(m // 32, 32).astype(jnp.uint32)
    return jnp.sum(b << jnp.arange(32, dtype=jnp.uint32)[None, :],
                   axis=1, dtype=jnp.uint32)


def finalize(rf: RuntimeFilter, state) -> None:
    """Fetch the accumulated filter state (ONE small transfer) and
    publish.  Lives here — not in execs/ — so the blocking readback
    routes through the sanctioned pipeline API in one audited place.
    ``build_ms`` records THIS step's wall time (bit packing + the D2H
    fetch): the synchronous cost the filter adds to the critical path —
    the per-batch update dispatches ride the build stream asynchronously
    and land in the build exec's totalTime."""
    import numpy as np

    from spark_rapids_tpu import trace as _trace
    from spark_rapids_tpu.parallel.pipeline import device_read_many

    bits, lo, hi, count = state
    t0 = time.perf_counter()
    with _trace.span("rf.build", rf=rf.rf_id, key=rf.key_name):
        packed = device_pack_bits(bits) if rf.use_bloom else None
        fetch = [lo, hi, count] + ([packed] if packed is not None else [])
        host = device_read_many(fetch, tag="rf.build")
        words = np.asarray(host[3], np.uint32) if rf.use_bloom else None
        build_ms = (time.perf_counter() - t0) * 1e3
        rf.publish(int(host[0]), int(host[1]), int(host[2]), words,
                   build_ms)


# --------------------------------------------------------------------- #
# Planner pass: filter injection over the lowered physical plan
# --------------------------------------------------------------------- #


def _probe_scan_targets(node, ordinal: int):
    """Scans reachable from the probe subtree through schema-preserving
    execs, with the probe key ordinal stable at every hop.  Returns
    [(scan_exec, column_name)]; an unmodeled node kind ends that branch
    (no target — never a wrong one)."""
    from spark_rapids_tpu.execs.adaptive import CoalescedShuffleReaderExec
    from spark_rapids_tpu.execs.basic import (
        TpuCoalesceBatchesExec,
        TpuFilterExec,
    )
    from spark_rapids_tpu.execs.coalesce import TpuCoalescePartitionsExec
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.execs.join import TpuRuntimeFilterBuildExec
    from spark_rapids_tpu.io.scan import OrcScanExec, ParquetScanExec

    passthrough = (TpuShuffleExchangeExec, TpuFilterExec,
                   TpuCoalesceBatchesExec, TpuCoalescePartitionsExec,
                   CoalescedShuffleReaderExec, TpuRuntimeFilterBuildExec)
    out = []
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ParquetScanExec, OrcScanExec)):
            fields = n.schema.fields
            if ordinal < len(fields):
                name = fields[ordinal].name
                file_cols = n.columns
                part = {f.name for f in n.partition_fields}
                readable = (name in part or file_cols is None
                            or name in file_cols)
                if readable:
                    out.append((n, name))
        elif isinstance(n, passthrough):
            stack.extend(n.children)
    return out


def _eligible_key_pairs(left_keys, right_keys, build_is_right: bool):
    """[(key_index, build_key_expr, probe_key_ordinal, dtype)] for key
    columns a filter can be built+pushed for: matching supported
    dtypes, probe side a plain bound column."""
    from spark_rapids_tpu.exprs.base import BoundReference

    build_keys = right_keys if build_is_right else left_keys
    probe_keys = left_keys if build_is_right else right_keys
    out = []
    for i, (bk, pk) in enumerate(zip(build_keys, probe_keys)):
        if not isinstance(pk, BoundReference):
            continue
        try:
            bdt, pdt = bk.dtype, pk.dtype
        except Exception:
            continue
        if bdt != pdt or not supported_key_dtype(pdt):
            continue
        out.append((i, bk, pk.ordinal, pdt))
    return out


def inject_runtime_filters(root, conf) -> list[RuntimeFilter]:
    """Walk the lowered plan; for each eligible join, wrap the build
    side with a key-collecting pass-through exec and register the
    resulting filters on every probe-side scan they can reach.  Also
    flips the adaptive join's stage order to build-before-probe so the
    filter is published before the probe side's map stage scans."""
    use_minmax = conf.get(RF_MINMAX_ENABLED)
    use_bloom = conf.get(RF_BLOOM_ENABLED)
    if not conf.get(RF_ENABLED) or not (use_minmax or use_bloom):
        return []
    from spark_rapids_tpu.execs.adaptive import TpuAdaptiveJoinExec
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.execs.join import (
        TpuRuntimeFilterBuildExec,
        _HashJoinBase,
    )
    from spark_rapids_tpu.plan.cost import exec_estimated_rows

    max_rows = conf.get(RF_MAX_BUILD_ROWS)
    fpp = conf.get(RF_FPP)
    filters: list[RuntimeFilter] = []

    for node in list(root._walk()):
        if isinstance(node, TpuAdaptiveJoinExec):
            jt = node.join_type
            # the adaptive template always builds right for eligible
            # types (only right_outer flips, and it is ineligible)
            build_idx = 1
            left_keys, right_keys = node.left_keys, node.right_keys
            build_is_right = True
        elif isinstance(node, _HashJoinBase) and node.condition is None:
            jt = node.join_type
            build_is_right = node.build_is_right
            build_idx = 1 if build_is_right else 0
            left_keys, right_keys = node.left_keys, node.right_keys
        else:
            continue
        if jt not in ELIGIBLE_JOIN_TYPES:
            continue
        pairs = _eligible_key_pairs(left_keys, right_keys,
                                    build_is_right)
        if not pairs:
            continue
        build_child = node.children[build_idx]
        probe_child = node.children[1 - build_idx]
        # build-side selectivity gate (the cost.py posture: never act
        # on an unknown estimate)
        est = exec_estimated_rows(build_child)
        if est is None or est > max_rows:
            continue
        n_bits, n_hashes = bloom_params(est, fpp)

        entries = []
        for _i, bk, probe_ord, dt in pairs:
            targets = _probe_scan_targets(probe_child, probe_ord)
            if not targets:
                continue
            rf = RuntimeFilter(
                targets[0][1], dt, jt, n_bits, n_hashes,
                use_minmax, use_bloom,
                build_desc=f"{node.name}[{jt}]")
            for scan, col_name in targets:
                scan.runtime_filters.append((col_name, rf))
            entries.append((bk, rf))
            filters.append(rf)
        if not entries:
            continue
        # wrap the build side BELOW its exchange (the whole build input
        # streams through the map stage exactly once) or directly when
        # there is no exchange (wide/broadcast joins collect build
        # first by construction)
        if isinstance(build_child, TpuShuffleExchangeExec):
            build_child.children[0] = TpuRuntimeFilterBuildExec(
                build_child.children[0], entries)
        else:
            node.children[build_idx] = TpuRuntimeFilterBuildExec(
                build_child, entries)
        if isinstance(node, TpuAdaptiveJoinExec):
            node.rf_build_first = "right"
    if filters:
        root._runtime_filters = filters
    return filters


def render_runtime_filters(root) -> list[str]:
    """explain() lines: one per build site and one per probe scan
    application, with pruned-row counts once executed."""
    from spark_rapids_tpu.execs.join import TpuRuntimeFilterBuildExec

    lines: list[str] = []
    for node in root._walk():
        if isinstance(node, TpuRuntimeFilterBuildExec):
            for _k, rf in node.entries:
                lines.append(
                    f"build {rf.describe()} <- {rf.build_desc} "
                    f"[{node.children[0].name}]")
        rfs = getattr(node, "runtime_filters", None)
        if rfs:
            for col_name, rf in rfs:
                pruned = node.metrics["rfPrunedRows"].value \
                    if "rfPrunedRows" in node.metrics else 0
                lines.append(
                    f"apply rf#{rf.rf_id} on {node.name}.{col_name} "
                    f"(rfPrunedRows={pruned})")
    return lines
