"""The plan-rewriting engine: tagging, conversion, fallback, explain.

TPU re-design of the reference's L4 layer:
- per-node meta wrappers carrying will-not-work reasons
  (ref: RapidsMeta.scala:162 willNotWorkOnGpu, :197 canThisBeReplaced);
- a replacement-rule registry with auto-registered per-exec and
  per-expression conf kill-switches
  (ref: GpuOverrides.scala:679-748 expr/exec rules,
  RapidsMeta.scala:35-46 DataFromReplacementRule.confKey);
- explain output listing every node kept off the accelerator and why
  (ref: GpuOverrides.scala:3113-3122, the plugin's single most important
  observability feature);
- per-subtree CPU fallback with explicit transition execs at the
  boundary (ref: GpuTransitionOverrides.scala inserts
  HostColumnarToGpu/GpuBringBackToHost the same way).
"""

from __future__ import annotations

import copy
from typing import Iterator, Optional

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import SQL_ENABLED, get_conf, register
from spark_rapids_tpu.columnar.arrow import schema_to_arrow, to_arrow
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.exprs import arithmetic as A
from spark_rapids_tpu.exprs import base as B
from spark_rapids_tpu.exprs import predicates as P
from spark_rapids_tpu.exprs import decimal as DEC
from spark_rapids_tpu.exprs.hashing import Md5, Murmur3Hash
from spark_rapids_tpu.plan import logical as L

# ---------------------------------------------------------------------- #
# Supported-expression registry (ref: GpuOverrides.scala expr rules)
# ---------------------------------------------------------------------- #

from spark_rapids_tpu.plan import typesig as TS

SUPPORTED_EXPRS: dict[type, object] = {}
#: declarative input-type signatures per expression rule
#: (ref: TypeChecks.scala — tagging checks declarations, not op code)
EXPR_SIGS: dict[type, TS.ExprSig] = {}


def register_expr(cls: type, sig: TS.ExprSig = None) -> None:
    key = f"spark.rapids.tpu.sql.expression.{cls.__name__}"
    entry = register(key, True,
                     f"Enable TPU execution of expression {cls.__name__}.")
    SUPPORTED_EXPRS[cls] = entry
    if sig is not None:
        EXPR_SIGS[cls] = sig


from spark_rapids_tpu.exprs import bitwise as BW  # noqa: E402
from spark_rapids_tpu.exprs import datetime as DT  # noqa: E402
from spark_rapids_tpu.exprs import math as M  # noqa: E402
from spark_rapids_tpu.exprs import strings as S  # noqa: E402
from spark_rapids_tpu.exprs.cast import Cast  # noqa: E402

_PASSTHROUGH = TS.ExprSig(TS.ALL)
_ARITH = TS.ExprSig(
    TS.NUMERIC + TS.NULLSIG,
    "decimal arithmetic falls back (unscaled-value math would be wrong)")
_COMPARE = TS.ExprSig(TS.ORDERABLE)
_LOGIC = TS.ExprSig(TS.BOOLEAN + TS.NULLSIG)
_MATH = TS.ExprSig(TS.NUMERIC + TS.NULLSIG)
_BITS = TS.ExprSig(TS.INTEGRAL + TS.NULLSIG)
_DT = TS.ExprSig(TS.DATETIME + TS.INTEGRAL + TS.NULLSIG)
_STR = TS.ExprSig(TS.STRING + TS.INTEGRAL + TS.NULLSIG,
                  "needle/length parameters must be literals")
_COND = TS.ExprSig(TS.ORDERABLE)

for _sig, _classes in (
    (_PASSTHROUGH, (B.Alias, B.BoundReference, B.ColumnReference,
                    B.Literal)),
    (TS.ExprSig(TS.NUMERIC + TS.DECIMAL + TS.NULLSIG,
                "decimal results wider than precision 18 fall back"),
     (A.Add, A.Subtract)),
    (_ARITH, (A.Multiply, A.Divide, A.IntegralDivide,
              A.Remainder, A.Pmod, A.UnaryMinus, A.UnaryPositive, A.Abs,
              A.Least, A.Greatest)),
    (_COMPARE, (P.EqualTo, P.LessThan, P.LessThanOrEqual, P.GreaterThan,
                P.GreaterThanOrEqual, P.EqualNullSafe, P.In)),
    (_LOGIC, (P.And, P.Or, P.Not)),
    (_PASSTHROUGH, (P.IsNull, P.IsNotNull, P.AtLeastNNonNulls)),
    (TS.ExprSig(TS.NUMERIC + TS.NULLSIG), (P.IsNaN,)),
    (_COND, (P.Coalesce, P.If, P.CaseWhen)),
    (TS.ExprSig(TS.COMMON_N), (Murmur3Hash,)),
    (TS.ExprSig(TS.STRING + TS.NULLSIG), (Md5,)),
    (TS.ExprSig(TS.DECIMAL + TS.NULLSIG),
     (DEC.PromotePrecision, DEC.CheckOverflow, DEC.UnscaledValue)),
    (TS.ExprSig(TS.INTEGRAL + TS.DECIMAL + TS.NULLSIG),
     (DEC.MakeDecimal,)),
    (_MATH, (M.Sqrt, M.Cbrt, M.Exp, M.Expm1, M.Sin, M.Cos, M.Tan, M.Cot,
             M.Asin, M.Acos, M.Atan, M.Sinh, M.Cosh, M.Tanh, M.Asinh,
             M.Acosh, M.Atanh, M.Rint, M.Signum, M.ToDegrees,
             M.ToRadians, M.Log, M.Log10, M.Log2, M.Log1p, M.Logarithm,
             M.Pow, M.Ceil, M.Floor, M.Round, M.BRound,
             M.KnownFloatingPointNormalized)),
    (TS.ExprSig(TS.TypeSig.of("float", "double") + TS.NULLSIG,
                "NaN semantics need floating inputs"),
     (M.NaNvl, M.NormalizeNaNAndZero)),
    (_BITS, (BW.BitwiseAnd, BW.BitwiseOr, BW.BitwiseXor, BW.BitwiseNot,
             BW.ShiftLeft, BW.ShiftRight, BW.ShiftRightUnsigned)),
    (_DT, (DT.Year, DT.Month, DT.DayOfMonth, DT.DayOfWeek, DT.WeekDay,
           DT.DayOfYear, DT.Quarter, DT.LastDay, DT.Hour, DT.Minute,
           DT.Second, DT.DateAdd, DT.DateSub, DT.AddMonths, DT.DateDiff,
           DT.UnixTimestampFromTs, DT.DateFormatClass, DT.TimeAdd,
           DT.TimeSub, DT.DateAddInterval)),
    (TS.ExprSig(TS.INTEGRAL + TS.NULLSIG,
                "epoch seconds input"), (DT.FromUnixTime,)),
    (_STR, (S.Length, S.Upper, S.Lower, S.StartsWith, S.EndsWith,
            S.Contains, S.Like, S.Substring, S.StringTrim,
            S.StringTrimLeft, S.StringTrimRight, S.Concat,
            S.StringReplace, S.RegExpReplace, S.StringLPad, S.StringRPad,
            S.StringLocate, S.SubstringIndex, S.InitCap, S.ConcatWs,
            S.StringSplit, S.SplitPart, S.GetJsonObject)),
    (TS.ExprSig(TS.ALL, "per-pair support matrix in check_supported"),
     (Cast,)),
):
    for _cls in _classes:
        register_expr(_cls, _sig)

from spark_rapids_tpu.exprs import collections as COLL  # noqa: E402

for _cls in (COLL.Size, COLL.GetArrayItem, COLL.ArrayContains):
    register_expr(_cls, TS.ExprSig(TS.ALL, "array input required"))

register_expr(COLL.CreateArray, TS.ExprSig(
    TS.NUMERIC + TS.BOOLEAN + TS.DATETIME + TS.NULLSIG,
    "fixed-width elements only"))

from spark_rapids_tpu.exprs import complex as CX  # noqa: E402

for _cls in (CX.GetStructField, CX.CreateNamedStruct, CX.GetMapValue,
             CX.ElementAt):
    register_expr(_cls, TS.ExprSig(
        TS.ALL + TS.NESTED, "struct/map input; fixed-width map "
        "key/value on device (check_supported)"))

# partition-context / nondeterministic expressions
from spark_rapids_tpu.exprs import nondeterministic as ND  # noqa: E402

register_expr(ND.SparkPartitionID, TS.ExprSig(TS.ALL, "no inputs"))
for _cls in (ND.InputFileName, ND.InputFileBlockStart,
             ND.InputFileBlockLength):
    register_expr(_cls, TS.ExprSig(
        TS.ALL, "rewritten to hidden scan columns above file scans; "
        "other positions fall back (Spark default values)"))
register_expr(ND.MonotonicallyIncreasingID,
              TS.ExprSig(TS.ALL, "no inputs"))
register_expr(ND.Rand, TS.ExprSig(TS.ALL, "no inputs"))

# columnar jax UDFs trace into the fused program like built-ins
# (OpaquePythonUDF deliberately stays unregistered -> CPU fallback)
from spark_rapids_tpu.udf.exprs import JaxScalarUDF  # noqa: E402

register_expr(JaxScalarUDF, TS.ExprSig(
    TS.NUMERIC + TS.BOOLEAN + TS.DATETIME + TS.NULLSIG,
    "user columnar function over fixed-width device arrays"))

# aggregate functions are checked by their own registry
from spark_rapids_tpu.exprs import aggregates as AG  # noqa: E402

SUPPORTED_AGGS = (AG.Sum, AG.Count, AG.CountStar, AG.Min, AG.Max,
                  AG.Average, AG.First, AG.Last, AG.CollectList,
                  AG.CollectSet, AG.PivotFirst)

#: per-aggregate input signatures (ref: TypeChecks on AggExprMeta)
AGG_SIGS: dict[type, TS.ExprSig] = {
    AG.CollectList: TS.ExprSig(
        TS.NUMERIC + TS.DATETIME + TS.BOOLEAN + TS.NULLSIG,
        "fixed-width elements only"),
    AG.CollectSet: TS.ExprSig(
        TS.NUMERIC + TS.DATETIME + TS.BOOLEAN + TS.NULLSIG,
        "fixed-width elements only"),
    AG.Sum: TS.ExprSig(TS.NUMERIC + TS.DECIMAL + TS.NULLSIG),
    AG.Average: TS.ExprSig(TS.NUMERIC + TS.NULLSIG,
                           "decimal avg needs scale-aware division"),
    AG.Count: TS.ExprSig(TS.ALL),
    AG.CountStar: TS.ExprSig(TS.ALL),
    AG.Min: TS.ExprSig(TS.NUMERIC + TS.DECIMAL + TS.DATETIME
                       + TS.BOOLEAN + TS.NULLSIG,
                       "string min/max falls back"),
    AG.Max: TS.ExprSig(TS.NUMERIC + TS.DECIMAL + TS.DATETIME
                       + TS.BOOLEAN + TS.NULLSIG,
                       "string min/max falls back"),
    AG.First: TS.ExprSig(TS.NUMERIC + TS.DECIMAL + TS.DATETIME
                         + TS.BOOLEAN + TS.NULLSIG),
    AG.Last: TS.ExprSig(TS.NUMERIC + TS.DECIMAL + TS.DATETIME
                        + TS.BOOLEAN + TS.NULLSIG),
    AG.PivotFirst: TS.ExprSig(
        TS.NUMERIC + TS.DECIMAL + TS.DATETIME + TS.BOOLEAN + TS.NULLSIG,
        "expanded into one masked First per pivot value"),
}


def _check_agg(fn, conf, reasons: set[str]) -> None:
    sig = AGG_SIGS.get(type(fn))
    if sig is None or fn.child is None:
        return
    try:
        dt = fn.child.dtype
    except Exception:
        return
    if not sig.inputs.supports(dt):
        reasons.add(
            f"aggregate {fn.name} does not support input type "
            f"{dt.name} on TPU (supported: {sig.inputs.describe()})")
    # data-dependent capability checks (the AggExprMeta.tagAggForGpu
    # hook): a raise becomes a fallback reason
    check = getattr(fn, "check_supported", None)
    if check is not None:
        try:
            check()
        except TypeError as exc:
            reasons.add(str(exc))

# per-exec kill switches (ref: spark.rapids.sql.exec.*)
_EXEC_CONFS = {
    cls: register(f"spark.rapids.tpu.sql.exec.{cls.__name__}", True,
                  f"Enable TPU execution of {cls.__name__}.")
    for cls in (L.InMemoryRelation, L.ParquetRelation, L.CsvRelation,
                L.OrcRelation, L.RangeRel, L.Project, L.Filter,
                L.Aggregate, L.Sort, L.Limit, L.Join, L.Union, L.Window,
                L.Expand, L.Generate, L.MapInArrow, L.GroupedPandas,
                L.CoGroupedPandas, L.Cached)
}


def _check_expr(e: B.Expression, conf, reasons: set[str]) -> None:
    entry = SUPPORTED_EXPRS.get(type(e))
    if entry is None:
        reasons.add(f"expression {type(e).__name__} is not supported on TPU")
    elif not conf.get(entry):
        reasons.add(
            f"expression {type(e).__name__} disabled by {entry.key}")
    # declarative input-type signature (ref: TypeChecks.tagExprForGpu)
    TS.check_inputs(e, EXPR_SIGS.get(type(e)), reasons)
    # expressions with data-dependent support (Cast matrix, Like
    # patterns) expose check_supported(); a raise becomes a reason
    check = getattr(e, "check_supported", None)
    if check is not None:
        try:
            check()
        except TypeError as exc:
            reasons.add(str(exc))
    for c in e.children:
        _check_expr(c, conf, reasons)


# ---------------------------------------------------------------------- #
# Meta wrapper
# ---------------------------------------------------------------------- #

class PlanMeta:
    """Wrapper tree over a logical plan carrying tagging state
    (ref: RapidsMeta.scala SparkPlanMeta)."""

    def __init__(self, plan: L.LogicalPlan, conf):
        self.plan = plan
        self.conf = conf
        self.children = [PlanMeta(c, conf) for c in plan.children]
        self.reasons: set[str] = set()

    @property
    def can_replace(self) -> bool:
        return not self.reasons

    def will_not_work(self, reason: str) -> None:
        self.reasons.add(reason)

    def _forbid_partition_aware(self, e, where: str) -> None:
        """Partition-context expressions (Rand, MID, ...) only get their
        context in the fused Project/Filter/Expand/Generate pipeline;
        anywhere else they would silently evaluate with partition 0 /
        offset 0 per batch, so route those plans to the CPU engine."""
        from spark_rapids_tpu.exprs.nondeterministic import (
            tree_is_partition_aware,
        )

        if tree_is_partition_aware(e):
            self.will_not_work(
                f"nondeterministic expression as {where} is only "
                "supported in project/filter on TPU")

    def tag(self) -> None:
        conf = self.conf
        entry = _EXEC_CONFS.get(type(self.plan))
        if entry is None:
            self.will_not_work(
                f"operator {self.plan.name} is not supported on TPU")
        elif not conf.get(entry):
            self.will_not_work(f"disabled by {entry.key}")
        if not self.children and not _schema_device_representable(
                self.plan.schema):
            # a LEAF producing unrepresentable columns can never
            # upload (list<string>, map<string,*>, ...): CPU source
            self.will_not_work(
                "source output type has no device layout")
        self._tag_exprs()
        for c in self.children:
            c.tag()

    def _forbid_ansi_risky(self, e, where: str) -> None:
        """ANSI error flags are captured only by the FUSED
        project/filter/expand/generate pipelines; an overflow-capable
        expression in any other position would silently keep legacy
        semantics while the CPU engine raises — route those plans to
        the CPU engine instead (the reference's partial-ANSI fallback
        posture)."""
        from spark_rapids_tpu.exprs.base import ansi_enabled

        if not ansi_enabled():
            return
        if _tree_has_ansi_risk(e):
            self.will_not_work(
                f"ANSI-checked expression as {where} only runs on TPU "
                "inside project/filter — CPU fallback")

    def _tag_exprs(self) -> None:
        p = self.plan
        conf = self.conf
        if isinstance(p, L.Project):
            for e in p.exprs:
                _check_expr(e, conf, self.reasons)
        elif isinstance(p, L.Expand):
            for proj in p.projections:
                for e in proj:
                    _check_expr(e, conf, self.reasons)
        elif isinstance(p, L.Generate):
            _check_expr(p.generator.child, conf, self.reasons)
            try:
                p.generator.check_supported()
            except TypeError as exc:
                self.will_not_work(str(exc))
        elif isinstance(p, L.Filter):
            _check_expr(p.condition, conf, self.reasons)
        elif isinstance(p, L.Aggregate):
            for g in p.groups:
                _check_expr(g, conf, self.reasons)
                self._forbid_partition_aware(g, "grouping key")
                self._forbid_ansi_risky(g, "grouping key")
            for na in p.aggs:
                for e in na.fn.inputs():
                    self._forbid_partition_aware(e, "aggregate input")
                if not isinstance(na.fn, SUPPORTED_AGGS):
                    self.will_not_work(
                        f"aggregate {na.fn.name} is not supported on TPU")
                else:
                    _check_agg(na.fn, conf, self.reasons)
                for e in na.fn.inputs():
                    _check_expr(e, conf, self.reasons)
                    self._forbid_ansi_risky(e, "aggregate input")
        elif isinstance(p, L.Sort):
            for k in p.keys:
                _check_expr(k.expr, conf, self.reasons)
                self._forbid_partition_aware(k.expr, "sort key")
                self._forbid_ansi_risky(k.expr, "sort key")
        elif isinstance(p, L.Window):
            for we, _name in p.window_exprs:
                for e in we.children:
                    _check_expr(e, conf, self.reasons)
                    self._forbid_partition_aware(e, "window input")
                    self._forbid_ansi_risky(e, "window input")
                try:
                    we.check_supported()
                except TypeError as exc:
                    self.will_not_work(str(exc))
        elif isinstance(p, L.Join):
            for e in list(p.left_keys) + list(p.right_keys):
                _check_expr(e, conf, self.reasons)
                self._forbid_partition_aware(e, "join key")
                self._forbid_ansi_risky(e, "join key")
            if p.condition is not None:
                if p.join_type != "inner":
                    self.will_not_work(
                        "non-inner join with residual condition")
                else:
                    _check_expr(p.condition, conf, self.reasons)
                    self._forbid_ansi_risky(p.condition,
                                            "join condition")
            if not p.left_keys and p.join_type not in ("cross", "inner"):
                # keyless inner joins run as conditional nested loops
                # (constant-key cross); keyless outer joins fall back
                self.will_not_work("non-equi join without keys")

    # -- explain -------------------------------------------------------- #

    def explain(self, indent: int = 0) -> str:
        mark = "*" if self.can_replace else "!"
        s = "  " * indent + f"{mark} {self.plan.node_desc()}"
        if self.reasons:
            s += "  <-- cannot run on TPU because " + "; ".join(
                sorted(self.reasons))
        s += "\n"
        for c in self.children:
            s += c.explain(indent + 1)
        return s


# ---------------------------------------------------------------------- #
# Conversion (ref: RapidsMeta convertIfNeeded)
# ---------------------------------------------------------------------- #

class CpuFallbackExec(TpuExec):
    """Runs one logical node on the CPU engine; exec children are
    materialized to Arrow at the boundary (the device->host transition,
    ref: GpuBringBackToHost + ColumnarToRow) and the result re-enters the
    device path through ArrowSourceExec slicing on the parent side."""

    def __init__(self, plan: L.LogicalPlan, *children: TpuExec):
        super().__init__(*children)
        self.plan = plan

    @property
    def schema(self) -> T.Schema:
        return self.plan.schema

    def node_desc(self) -> str:
        return f"CpuFallbackExec [{self.plan.node_desc()}]"

    def cpu_table(self) -> pa.Table:
        from spark_rapids_tpu.cpu.engine import execute_cpu

        new_children = []
        for c in self.children:
            if isinstance(c, CpuFallbackExec):
                # fuse adjacent CPU nodes: no device round-trip
                new_children.append(L.InMemoryRelation(c.cpu_table()))
            else:
                new_children.append(L.InMemoryRelation(collect_exec(c)))
        plan = copy.copy(self.plan)
        plan.children = new_children
        return execute_cpu(plan)

    #: logical nodes whose CPU evaluation is per-row: they can run on one
    #: batch at a time, so the fallback boundary streams batch-wise
    #: instead of materializing the whole child as a single Arrow table
    #: (the reference's fallback is row-iterator streaming throughout)
    _STREAMABLE = (L.Filter, L.Project, L.Generate)

    def _execute_streaming(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.columnar.arrow import to_arrow
        from spark_rapids_tpu.cpu.engine import execute_cpu
        from spark_rapids_tpu.columnar.arrow import from_arrow

        for b in self.children[0].execute():
            tbl = to_arrow(b)
            plan = copy.copy(self.plan)
            plan.children = [L.InMemoryRelation(tbl)]
            out = execute_cpu(plan)
            yield self._count_output(from_arrow(out))

    def execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.io.scan import ArrowSourceExec

        if isinstance(self.plan, self._STREAMABLE) \
                and len(self.children) == 1 \
                and not isinstance(self.children[0], CpuFallbackExec):
            # adjacent CPU nodes keep the fusing cpu_table() path — the
            # streaming boundary would bounce each batch through the
            # device (from_arrow -> to_arrow) for nothing
            yield from self._execute_streaming()
            return
        src = ArrowSourceExec(self.cpu_table(), self.schema)
        for b in src.execute():
            yield self._count_output(b)


def convert_meta(meta: PlanMeta) -> TpuExec:
    p = meta.plan
    if not meta.can_replace:
        kids = [convert_meta(c) for c in meta.children]
        _maybe_push_filter(p, kids)
        return CpuFallbackExec(p, *kids)
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.execs.basic import (
        TpuFilterExec,
        TpuProjectExec,
        TpuRangeExec,
        TpuUnionExec,
    )
    from spark_rapids_tpu.execs.join import TpuShuffledHashJoinExec
    from spark_rapids_tpu.execs.limit import TpuGlobalLimitExec
    from spark_rapids_tpu.execs.sort import TpuSortExec
    from spark_rapids_tpu.io.scan import (
        ArrowSourceExec,
        CsvScanExec,
        ParquetScanExec,
    )

    kids = [convert_meta(c) for c in meta.children]
    if isinstance(p, L.InMemoryRelation):
        return ArrowSourceExec(p.table, p.schema)
    if isinstance(p, L.ParquetRelation):
        return ParquetScanExec(p.paths, p.schema, p.columns,
                               partition_values=p.partition_values,
                               partition_fields=p.partition_fields)
    if isinstance(p, L.OrcRelation):
        from spark_rapids_tpu.io.scan import OrcScanExec

        return OrcScanExec(p.paths, p.schema, p.columns,
                           partition_values=p.partition_values,
                           partition_fields=p.partition_fields)
    if isinstance(p, L.CsvRelation):
        return CsvScanExec(p.paths, p.schema,
                           partition_values=p.partition_values,
                           partition_fields=p.partition_fields)
    if isinstance(p, L.RangeRel):
        return TpuRangeExec(p.start, p.end, p.step)
    if isinstance(p, L.Cached):
        from spark_rapids_tpu.execs.cache import TpuCacheExec

        return TpuCacheExec(p.slot, kids[0])
    if isinstance(p, L.Project):
        return TpuProjectExec(p.exprs, kids[0])
    if isinstance(p, L.Filter):
        _maybe_push_filter(p, kids)
        if _can_elide_device_filter(p, kids):
            # the host prefilter applies the FULL condition exactly
            # (exact mode raises instead of silently disabling), so the
            # device Filter would re-verify already-filtered rows — on
            # a per-program-cost link that is a whole program execution
            # per batch for nothing
            kids[0].exact_prefilter = True
            refs = getattr(p, "upload_refs", None)
            if refs is not None:
                # columns referenced ONLY by the (now host-applied)
                # condition ship as zero-byte all-NULL placeholders
                scan = kids[0]
                keep = {scan.schema.fields[i].name for i in refs}
                part = {f.name for f in getattr(
                    scan, "partition_fields", [])}
                drop = {f.name for f in scan.schema.fields} - keep - part
                if drop:
                    scan.null_upload_cols = drop
            return kids[0]
        return TpuFilterExec(p.condition, kids[0])
    if isinstance(p, L.Expand):
        from spark_rapids_tpu.execs.expand import TpuExpandExec

        return TpuExpandExec(p.projections, p.schema, kids[0])
    if isinstance(p, L.Generate):
        from spark_rapids_tpu.execs.generate import TpuGenerateExec

        return TpuGenerateExec(p.generator, p.schema, kids[0])
    if isinstance(p, L.MapInArrow):
        from spark_rapids_tpu.execs.python_exec import (
            TpuMapInArrowExec,
            TpuMapInPandasExec,
        )

        if getattr(p, "pandas", False):
            return TpuMapInPandasExec(p.fn, p.schema, kids[0])
        return TpuMapInArrowExec(p.fn, p.schema, kids[0])
    if isinstance(p, L.CoGroupedPandas):
        from spark_rapids_tpu.execs.exchange import (
            SHUFFLE_PARTITIONS,
            TpuShuffleExchangeExec,
        )
        from spark_rapids_tpu.execs.python_exec import (
            TpuFlatMapCoGroupsInPandasExec,
        )
        from spark_rapids_tpu.ops.partition import HashPartitioning

        n = get_conf().get(SHUFFLE_PARTITIONS)
        sides = []
        for kid, keys in ((kids[0], p.left_key_names),
                          (kids[1], p.right_key_names)):
            kexprs = [B.ColumnReference(k) for k in keys]
            sides.append(TpuShuffleExchangeExec(
                HashPartitioning(kexprs, n), kid))
        return TpuFlatMapCoGroupsInPandasExec(
            p.left_key_names, p.right_key_names, p.fn, p.schema,
            sides[0], sides[1])
    if isinstance(p, L.GroupedPandas):
        from spark_rapids_tpu.execs.exchange import (
            SHUFFLE_PARTITIONS,
            TpuShuffleExchangeExec,
        )
        from spark_rapids_tpu.execs.python_exec import (
            TpuAggregateInPandasExec,
            TpuFlatMapGroupsInPandasExec,
            TpuWindowInPandasExec,
        )
        from spark_rapids_tpu.ops.partition import HashPartitioning

        source = kids[0]
        keys = [B.ColumnReference(k) for k in p.key_names]
        if source.num_partitions > 1 and p.key_names \
                and _hash_satisfies(source, [
                    B.BoundReference(
                        source.schema.index_of(k),
                        source.schema.field(k).dtype,
                        source.schema.field(k).nullable, k)
                    for k in p.key_names]) is None:
            n = get_conf().get(SHUFFLE_PARTITIONS)
            source = TpuShuffleExchangeExec(
                HashPartitioning(keys, n), source)
        elif source.num_partitions > 1 and not p.key_names:
            from spark_rapids_tpu.execs.coalesce import (
                TpuCoalescePartitionsExec,
            )

            source = TpuCoalescePartitionsExec(source)
        if p.kind == "flatmap":
            return TpuFlatMapGroupsInPandasExec(
                p.key_names, p.payload, p.schema, source)
        if p.kind == "agg":
            return TpuAggregateInPandasExec(
                p.key_names, p.payload, p.schema, source)
        return TpuWindowInPandasExec(
            p.key_names, p.payload, p.schema, source)
    if isinstance(p, L.Aggregate):
        return _plan_aggregate(p, kids[0])
    if isinstance(p, L.Sort):
        return _plan_sort(p, kids[0])
    if isinstance(p, L.Window):
        from spark_rapids_tpu.execs.window import TpuWindowExec

        part_by = p.window_exprs[0][0].spec.partition_by
        if part_by and kids[0].num_partitions > 1:
            # out-of-core: hash exchange on the partition keys makes
            # window groups partition-local, each reduce partition
            # windows independently (ref: GpuWindowExec's required
            # child distribution = ClusteredDistribution(partitionBy));
            # EnsureRequirements: an already-satisfying distribution
            # (e.g. a final aggregate keyed the same) skips the shuffle
            from spark_rapids_tpu.execs.exchange import (
                SHUFFLE_PARTITIONS,
                TpuShuffleExchangeExec,
            )
            from spark_rapids_tpu.ops.partition import HashPartitioning

            source = kids[0]
            if _hash_satisfies(source, list(part_by)) is None:
                n = get_conf().get(SHUFFLE_PARTITIONS)
                source = TpuShuffleExchangeExec(
                    HashPartitioning(list(part_by), n), source)
            w = TpuWindowExec(p.window_exprs, source)
            w.partitioned = True
            return w
        return TpuWindowExec(p.window_exprs, kids[0])
    if isinstance(p, L.Limit):
        topn = _maybe_topn(p, kids)
        if topn is not None:
            return topn
        if kids[0].num_partitions > 1:
            # collect-limit shape: prune each partition locally before
            # the single-partition drain (ref: GpuCollectLimitExec)
            from spark_rapids_tpu.execs.limit import TpuCollectLimitExec

            return TpuCollectLimitExec(p.n, kids[0])
        return TpuGlobalLimitExec(p.n, kids[0])
    if isinstance(p, L.Union):
        return TpuUnionExec(*kids)
    if isinstance(p, L.Join):
        return _plan_join(p, kids)
    raise AssertionError(f"tagged-replaceable node unconvertible: {p.name}")


ELIDE_DEVICE_FILTER = register(
    "spark.rapids.tpu.sql.scan.elideDeviceFilter", True,
    "Drop the device Filter above a Parquet scan when the host "
    "prefilter provably applies the full condition (deterministic, "
    "non-ANSI, prefilter enabled): the prefilter then runs in EXACT "
    "mode — any host evaluation failure raises instead of shipping "
    "unfiltered rows.")


def _can_elide_device_filter(p: L.LogicalPlan,
                             kids: list[TpuExec]) -> bool:
    from spark_rapids_tpu.exprs.base import ansi_enabled
    from spark_rapids_tpu.exprs.nondeterministic import (
        tree_is_partition_aware,
    )
    from spark_rapids_tpu.io.scan import HOST_PREFILTER, ParquetScanExec

    conf = get_conf()
    if not (conf.get(ELIDE_DEVICE_FILTER) and conf.get(HOST_PREFILTER)):
        return False
    if not (kids and type(kids[0]) in (ParquetScanExec,)
            and kids[0].pushed_filter is p.condition):
        return False
    if ansi_enabled() or tree_is_partition_aware(p.condition):
        return False
    # count-only scans never run the row-wise prefilter: the condition
    # must read at least one column so rows flow as tables
    refs = [e for e in _walk_expr(p.condition)
            if isinstance(e, (B.BoundReference, B.ColumnReference))]
    if not refs:
        return False
    # only elide when the compiled pyarrow prefilter subset covers the
    # whole condition: a condition only the DEVICE expression engine
    # supports must keep its device Filter (before elision the host
    # prefilter would just disable itself; with elision the exact-mode
    # prefilter would hard-fail the query instead)
    from spark_rapids_tpu.io.pa_filter import compile_filter

    return compile_filter(p.condition) is not None


def _walk_expr(e):
    yield e
    for c in getattr(e, "children", ()):
        yield from _walk_expr(c)


def _annotate_filter_upload(root: L.LogicalPlan) -> None:
    """Column-pruning-through-Filter analysis (the interplay of Spark's
    ColumnPruning and PushDownPredicates): for every Filter sitting
    directly on a file relation, record which relation ordinals any
    operator ABOVE the filter reads.  If the device filter is later
    elided (exact host prefilter), columns referenced ONLY by the
    filter condition need not cross the host->device wire at all —
    the scan ships them as zero-byte all-NULL placeholders, keeping
    the schema (and every bound ordinal above) intact.

    Conservative by construction: the walk ends at the nearest
    'bounding' ancestor whose output drops the relation's columns
    (Project/Aggregate/semi-anti-join's dropped side); any node kind
    outside the modeled set, or reaching the root with the columns
    still in the output, yields no annotation (upload everything)."""
    from spark_rapids_tpu.exprs import aggregates as AG
    from spark_rapids_tpu.plan.logical import OrcRelation, ParquetRelation

    def collect(e, pos: int, n: int, req: set) -> None:
        for x in _walk_expr(e):
            if isinstance(x, B.BoundReference) \
                    and pos <= x.ordinal < pos + n:
                req.add(x.ordinal - pos)
            elif isinstance(x, AG.AggregateFunction):
                for c in x.inputs():
                    collect(c, pos, n, req)

    def required_above(path: list, f: L.Filter):
        """`path` is [(ancestor, child_slot), ...] from root to the
        filter's parent; slots disambiguate self-joins where both
        children are the same object."""
        n = len(f.schema.fields)
        pos = 0
        req: set = set()
        for anc, ci in reversed(path):
            if isinstance(anc, L.Filter):
                collect(anc.condition, pos, n, req)
            elif isinstance(anc, L.Sort):
                for k in anc.keys:
                    collect(k.expr, pos, n, req)
            elif isinstance(anc, L.Limit):
                pass
            elif isinstance(anc, L.Project):
                for e in anc.exprs:
                    collect(e, pos, n, req)
                return req  # bounding: output drops pass-through cols
            elif isinstance(anc, L.Aggregate):
                for g in anc.groups:
                    collect(g, pos, n, req)
                for na in anc.aggs:
                    for e in na.fn.inputs():
                        collect(e, pos, n, req)
                return req  # bounding
            elif isinstance(anc, L.Window):
                for we, _name in anc.window_exprs:
                    for e in we.children:
                        collect(e, pos, n, req)
                # output = child ++ window cols: position unchanged
            elif isinstance(anc, L.Join):
                n_left = len(anc.children[0].schema.fields)
                if ci == 0:
                    for k in anc.left_keys:
                        collect(k, pos, n, req)
                    if anc.condition is not None:
                        collect(anc.condition, pos, n, req)
                    # output keeps the left side first (or alone, for
                    # semi/anti): position unchanged
                else:
                    for k in anc.right_keys:
                        collect(k, pos, n, req)
                    if anc.condition is not None:
                        collect(anc.condition, pos + n_left, n, req)
                    if anc.join_type in ("left_semi", "left_anti"):
                        # the right side never reaches the output (the
                        # condition above still reads it)
                        return req
                    pos += n_left
            else:
                return None  # unmodeled shape: no pruning
        return None  # columns reach the final output

    # plans are DAGs (DataFrame reuse, self-joins): gather EVERY path
    # to each filter-over-relation and union the requirements — a
    # column any consumer path reads must upload
    targets: dict[int, tuple[L.Filter, list]] = {}
    budget = [4096]  # visit cap: degenerate shared DAGs bail out

    def visit(node: L.LogicalPlan, path: list) -> None:
        budget[0] -= 1
        if budget[0] < 0:
            return
        for i, c in enumerate(node.children):
            visit(c, path + [(node, i)])
        if isinstance(node, L.Filter) and isinstance(
                node.children[0], (ParquetRelation, OrcRelation)):
            targets.setdefault(id(node), (node, []))[1].append(path)

    visit(root, [])
    if budget[0] < 0:
        return
    for node, paths in targets.values():
        reqs = [required_above(p, node) for p in paths]
        node.upload_refs = (None if any(r is None for r in reqs)
                            else set().union(*reqs))


def _maybe_push_filter(p: L.LogicalPlan, kids: list[TpuExec]) -> None:
    """Attach a scan-adjacent Filter's condition to the Parquet scan for
    row-group/partition pruning (ref: GpuParquetScan.scala:263-306).
    Pure IO optimization on the fresh exec instance — the Filter still
    evaluates exactly, whichever engine it runs on."""
    from spark_rapids_tpu.io.scan import ParquetScanExec

    if isinstance(p, L.Filter) and kids \
            and isinstance(kids[0], ParquetScanExec):
        kids[0].pushed_filter = p.condition


TOPN_MAX_ROWS = register(
    "spark.rapids.tpu.sql.topn.maxRows", 1 << 14,
    "LIMIT values up to this use the streaming top-n rewrite of "
    "ORDER BY + LIMIT (GpuTopN / TakeOrderedAndProject analog) instead "
    "of a full global sort.")


def _maybe_topn(p: "L.Limit", kids: list[TpuExec]) -> Optional[TpuExec]:
    """LIMIT over a just-planned global Sort with a fixed-width primary
    key -> streaming top-n (per-batch candidate pruning; the full
    multi-key sort runs only over the candidates)."""
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.execs.sort import TpuSortExec, TpuTopNExec
    from spark_rapids_tpu.ops.partition import RangePartitioning

    sort = kids[0]
    if not (isinstance(sort, TpuSortExec)
            and 0 < p.n <= get_conf().get(TOPN_MAX_ROWS)
            and sort.keys):
        return None
    child = sort.children[0]
    if sort.scope == "partition" and isinstance(
            child, TpuShuffleExchangeExec) and isinstance(
            child.partitioning, RangePartitioning):
        # distributed ORDER BY shape (range exchange + per-partition
        # sort): top-n needs no exchange at all — consume the
        # pre-exchange child directly
        child = child.children[0]
    elif sort.scope != "global":
        return None
    primary = sort.keys[0].expr.dtype
    if not isinstance(primary, (T.ByteType, T.ShortType, T.IntegerType,
                                T.LongType, T.FloatType, T.DoubleType,
                                T.DateType, T.TimestampType,
                                T.BooleanType)):
        return None
    return TpuTopNExec(p.n, sort.keys, child)


BROADCAST_THRESHOLD = register(
    "spark.rapids.tpu.sql.autoBroadcastJoinThresholdBytes", 10 << 20,
    "Maximum estimated build-side size for a join to use the broadcast "
    "strategy (the spark.sql.autoBroadcastJoinThreshold analog); -1 "
    "disables broadcast joins.")


def broadcast_candidates(join_type: str, lbytes, rbytes,
                         thr: int) -> list[tuple[str, int]]:
    """Legal (build_side, bytes) pairs for a broadcast hash join — ONE
    legality table shared by static planning and the adaptive join's
    runtime re-decision (which feeds measured instead of estimated
    bytes)."""
    out: list[tuple[str, int]] = []
    if thr < 0 or join_type == "full_outer":
        return out
    if join_type in ("inner", "cross", "left_outer", "left_semi",
                     "left_anti") and rbytes is not None and rbytes <= thr:
        out.append(("right", rbytes))
    if join_type in ("inner", "cross", "right_outer") \
            and lbytes is not None and lbytes <= thr:
        out.append(("left", lbytes))
    return out


def _plan_join(p: L.Join, kids: list[TpuExec]) -> TpuExec:
    """Physical join strategy (the role GpuOverrides plays when Spark has
    already chosen; here the planner chooses, like Spark's
    JoinSelection): broadcast the small side when an estimate proves it
    fits; otherwise co-hash-partition both sides for a partition-wise
    join; otherwise a single wide local join."""
    from spark_rapids_tpu.execs.exchange import (
        SHUFFLE_PARTITIONS,
        TpuShuffleExchangeExec,
    )
    from spark_rapids_tpu.execs.join import (
        TpuBroadcastHashJoinExec,
        TpuShuffledHashJoinExec,
    )
    from spark_rapids_tpu.ops.partition import HashPartitioning

    conf = get_conf()
    thr = conf.get(BROADCAST_THRESHOLD)
    jt = p.join_type
    lbytes = p.children[0].estimated_bytes()
    rbytes = p.children[1].estimated_bytes()

    candidates = broadcast_candidates(jt, lbytes, rbytes, thr)
    if candidates:
        side = min(candidates, key=lambda c: c[1])[0]
        return TpuBroadcastHashJoinExec(
            p.left_keys, p.right_keys, jt, kids[0], kids[1],
            condition=p.condition, build_side=side)

    # partition-wise shuffled join: only for real equi-keys with equal
    # key dtypes on both sides (hash-parity requires identical physical
    # hashing) and a genuinely partitioned input
    key_dtypes_match = p.left_keys and all(
        lk.dtype == rk.dtype
        for lk, rk in zip(p.left_keys, p.right_keys))

    # tier-2 lowering: with the collective transport active, the whole
    # exchange+exchange+join pipeline becomes fused SPMD programs over
    # the mesh — the route-everything-through-shuffle architecture of
    # GpuShuffleExchangeExec applied to joins (SURVEY.md §5.8)
    if key_dtypes_match and p.condition is None:
        from spark_rapids_tpu.execs.collective import (
            TpuCollectiveHashJoinExec,
            stage_config,
        )
        from spark_rapids_tpu.shuffle.transport import get_transport

        transport = get_transport()
        if (transport.kind == "collective"
                and jt in TpuCollectiveHashJoinExec.SUPPORTED_TYPES
                and transport.supports_schema(kids[0].schema)
                and transport.supports_schema(kids[1].schema)):
            # stage boundary decided HERE at plan time: SPMD
            # whole-stage vs legacy host-loop, pinned into the exec
            spmd, bucket = stage_config(conf)
            return TpuCollectiveHashJoinExec(
                p.left_keys, p.right_keys, jt, kids[0], kids[1],
                transport.mesh, spmd=spmd, bucket_rounds=bucket)
    if key_dtypes_match and (kids[0].num_partitions > 1
                             or kids[1].num_partitions > 1):
        # EnsureRequirements: a child already hash-partitioned on these
        # keys (e.g. a final aggregate over an exchange) is not
        # re-shuffled
        lsat = _hash_satisfies(kids[0], p.left_keys)
        rsat = _hash_satisfies(kids[1], p.right_keys)
        if lsat is not None:
            n = lsat.num_partitions
            if rsat is not None and rsat.num_partitions != n:
                rsat = None  # mismatched widths: re-shuffle right
        elif rsat is not None:
            n = rsat.num_partitions
        else:
            n = conf.get(SHUFFLE_PARTITIONS)
        lex = kids[0] if lsat is not None else TpuShuffleExchangeExec(
            HashPartitioning(p.left_keys, n), kids[0])
        rex = kids[1] if rsat is not None else TpuShuffleExchangeExec(
            HashPartitioning(p.right_keys, n), kids[1])
        from spark_rapids_tpu.execs.adaptive import (
            ADAPTIVE_ENABLED,
            TpuAdaptiveJoinExec,
        )

        if conf.get(ADAPTIVE_ENABLED) and lsat is None and rsat is None:
            # both sides are fresh exchanges: defer shuffled-vs-broadcast
            # and reduce-partition grouping to measured map-output sizes
            # (reused child distributions can't re-group: their
            # partitioning is fixed by the producing stage)
            return TpuAdaptiveJoinExec(
                p.left_keys, p.right_keys, jt, lex, rex,
                condition=p.condition)
        return TpuShuffledHashJoinExec(
            p.left_keys, p.right_keys, jt, lex, rex,
            condition=p.condition, partition_wise=True)

    return TpuShuffledHashJoinExec(
        p.left_keys, p.right_keys, jt, kids[0], kids[1],
        condition=p.condition)


def _hash_satisfies(exec_: TpuExec, keys):
    """The child's output HashPartitioning when it already distributes by
    exactly these key expressions (value-identical hashing), else None."""
    from spark_rapids_tpu.execs.jit_cache import expr_key
    from spark_rapids_tpu.ops.partition import HashPartitioning

    part = exec_.output_partitioning
    if not isinstance(part, HashPartitioning) \
            or len(part.exprs) != len(keys):
        return None
    for pe, jk in zip(part.exprs, keys):
        if isinstance(pe, B.BoundReference) \
                and isinstance(jk, B.BoundReference):
            if pe.ordinal != jk.ordinal or pe.dtype != jk.dtype:
                return None
        elif expr_key(pe) != expr_key(jk):
            return None
    return part


RANGE_SORT = register(
    "spark.rapids.tpu.sql.sort.rangeExchange", True,
    "Plan multi-partition ORDER BY as a range-partitioned exchange plus "
    "per-partition sorts (the Spark physical shape, ref: "
    "GpuRangePartitioning.scala); disabled, the sort runs as one "
    "wide out-of-core operator.")


def _plan_sort(p: L.Sort, child_exec: TpuExec) -> TpuExec:
    """Distributed ORDER BY (ref: Spark planning SortExec under a
    RangePartitioning exchange): sample-bounded range exchange, then
    each reduce partition sorts independently; partition index order
    equals total order.  Single-partition children sort locally (with
    the out-of-core sample-split path above the size threshold)."""
    from spark_rapids_tpu.execs.exchange import (
        SHUFFLE_PARTITIONS,
        TpuShuffleExchangeExec,
    )
    from spark_rapids_tpu.execs.sort import TpuSortExec
    from spark_rapids_tpu.ops.partition import RangePartitioning

    conf = get_conf()
    # tier-2: distributed ORDER BY as a fused range-routed all_to_all
    # plus per-shard local sorts (SURVEY.md §5.8)
    from spark_rapids_tpu.shuffle.transport import get_transport

    transport = get_transport()
    if transport.kind == "collective" \
            and transport.supports_schema(child_exec.schema):
        from spark_rapids_tpu.execs.collective import (
            TpuCollectiveSortExec,
            stage_config,
        )

        # stage boundary decided at plan time (docs/spmd.md)
        spmd, bucket = stage_config(conf)
        return TpuCollectiveSortExec(p.keys, child_exec,
                                     transport.mesh, spmd=spmd,
                                     bucket_rounds=bucket)
    if child_exec.num_partitions > 1 and conf.get(RANGE_SORT):
        n = conf.get(SHUFFLE_PARTITIONS)
        ex = TpuShuffleExchangeExec(
            RangePartitioning(p.keys, n), child_exec)
        return TpuSortExec(p.keys, ex, scope="partition")
    return TpuSortExec(p.keys, child_exec)


def _plan_aggregate(p: L.Aggregate, child_exec: TpuExec) -> TpuExec:
    """Multi-partition input: partial agg (narrow) -> hash exchange on
    the group keys -> final agg (narrow over key-disjoint partitions) —
    the Spark/reference physical shape (aggregate.scala mode handling
    around ShuffleExchange).  Grand aggregates skip the shuffle manager:
    their "exchange" has a single destination, so the partials are pulled
    straight into the final aggregate through a coalesce-partitions exec
    (prefetching worker pool) with no partitioned-block storage at all.
    Single-partition input: one complete aggregation, no shuffle."""
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.execs.coalesce import TpuCoalescePartitionsExec
    from spark_rapids_tpu.execs.exchange import (
        SHUFFLE_PARTITIONS,
        TpuShuffleExchangeExec,
    )
    from spark_rapids_tpu.ops.partition import HashPartitioning

    has_collect = any(isinstance(na.fn, AG.CollectList)
                      for na in p.aggs)
    if has_collect:
        # ragged results need the dedicated two-phase dense-list exec;
        # mixed collect+scalar aggregate lists still fall back
        if not all(isinstance(na.fn, AG.CollectList) for na in p.aggs):
            return CpuFallbackExec(p, child_exec)
        from spark_rapids_tpu.execs.collect_agg import TpuCollectAggExec

        if child_exec.num_partitions > 1:
            if p.groups:
                # hash exchange on the group keys makes partitions
                # KEY-DISJOINT: each reduce partition collects
                # independently, outputs union (ref: the reference's
                # shuffle-then-aggregate shape for GpuCollectList);
                # a child already distributed by the keys skips it
                source = child_exec
                if _hash_satisfies(source, list(p.groups)) is None:
                    n = get_conf().get(SHUFFLE_PARTITIONS)
                    source = TpuShuffleExchangeExec(
                        HashPartitioning(p.groups, n), source)
                agg = TpuCollectAggExec(p.groups, p.aggs, source)
                agg.partitioned = True
                return agg
            child_exec = TpuCoalescePartitionsExec(child_exec)
        return TpuCollectAggExec(p.groups, p.aggs, child_exec)
    if p.groups:
        # tier-2 lowering: with the collective transport active, the
        # whole partial->exchange->final pipeline becomes ONE fused
        # all_to_all SPMD program over the mesh (SURVEY.md §5.8)
        from spark_rapids_tpu.shuffle.transport import get_transport

        transport = get_transport()
        if transport.kind == "collective" \
                and transport.supports_schema(child_exec.schema):
            from spark_rapids_tpu.execs.collective import (
                TpuCollectiveHashAggregateExec,
                stage_config,
            )

            # stage boundary decided at plan time (docs/spmd.md)
            spmd, bucket = stage_config()
            return TpuCollectiveHashAggregateExec(
                p.groups, p.aggs, child_exec, transport.mesh,
                spmd=spmd, bucket_rounds=bucket)
    if child_exec.num_partitions <= 1:
        return TpuHashAggregateExec(p.groups, p.aggs, child_exec)
    partial = TpuHashAggregateExec(p.groups, p.aggs, child_exec,
                                   mode="partial")
    if p.groups:
        n = get_conf().get(SHUFFLE_PARTITIONS)
        keys = [B.BoundReference(i, f.dtype, f.nullable, f.name)
                for i, f in enumerate(
                    partial.schema.fields[: len(p.groups)])]
        source: TpuExec = TpuShuffleExchangeExec(
            HashPartitioning(keys, n), partial)
    else:
        source = TpuCoalescePartitionsExec(partial)
    return TpuHashAggregateExec(p.groups, p.aggs, source, mode="final",
                                input_schema=child_exec.schema)


def _tree_has_ansi_risk(e) -> bool:
    """True when the tree contains an expression whose ANSI error
    checks only fire inside fused pipelines (integral
    Add/Subtract/Multiply, division family, Cast)."""
    from spark_rapids_tpu.exprs.cast import Cast as _Cast

    stack = [e]
    while stack:
        x = stack.pop()
        if isinstance(x, _Cast):
            return True
        if isinstance(x, (A.Add, A.Subtract, A.Multiply, A.Divide,
                          A.IntegralDivide, A.Remainder, A.Pmod)):
            return True
        stack.extend(x.children)
    return False


# ---------------------------------------------------------------------- #
# Entry points
# ---------------------------------------------------------------------- #

def _rewrite_split_extracts(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Prepass: split(s, d)[i] (GetArrayItem over StringSplit with a
    plain literal delimiter and non-negative literal index) fuses into
    the device SplitPart kernel — the dominant consumption pattern of
    GpuStringSplit; other split uses stay and fall back to the CPU
    engine via StringSplit.check_supported."""

    def xform(e):
        kids = [xform(c) for c in e.children]
        if kids != list(e.children):
            e = e.with_children(kids)
        if isinstance(e, COLL.GetArrayItem) \
                and isinstance(e.child, S.StringSplit) \
                and isinstance(e.index, B.Literal) \
                and e.index.value is not None \
                and int(e.index.value) >= 0:
            sp = e.child
            if isinstance(sp.delim, B.Literal) and sp.delim.value \
                    and not any(ch in S.StringSplit._META
                                for ch in sp.delim.value) \
                    and sp.limit == -1:
                return S.SplitPart(sp.child, sp.delim,
                                   int(e.index.value))
        return e

    def walk(p: L.LogicalPlan) -> None:
        if isinstance(p, L.Project):
            p.exprs = [xform(e) for e in p.exprs]
        elif isinstance(p, L.Filter):
            p.condition = xform(p.condition)
        for c in p.children:
            walk(c)

    walk(plan)
    return plan


def _rewrite_input_file_exprs(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Prepass: InputFileName/BlockStart/BlockLength become hidden
    per-file constant columns appended by the scan (the reference's
    ColumnarPartitionReaderWithPartitionValues mechanism), provided the
    path from the expression down to a file relation crosses only
    Project/Filter nodes.  Anything else is left in place: the
    expression's check_supported then routes the subtree to the CPU
    engine, which evaluates Spark's no-file-context defaults."""
    import copy as _copy
    import os

    from spark_rapids_tpu.exprs.nondeterministic import InputFileName

    def tree_has(e) -> bool:
        stack = [e]
        while stack:
            x = stack.pop()
            if isinstance(x, InputFileName):
                return True
            stack.extend(x.children)
        return False

    def node_exprs(p):
        if isinstance(p, L.Project):
            return p.exprs
        if isinstance(p, L.Filter):
            return [p.condition]
        return []

    from spark_rapids_tpu.exprs.nondeterministic import (
        InputFileBlockLength,
        InputFileBlockStart,
    )

    def augment_relation(rel: L.LogicalPlan) -> L.LogicalPlan:
        rel2 = _copy.copy(rel)
        hidden = [T.Field(InputFileName.HIDDEN, T.STRING, False),
                  T.Field(InputFileBlockStart.HIDDEN, T.LONG, False),
                  T.Field(InputFileBlockLength.HIDDEN, T.LONG, False)]
        pvs = []
        for i, path in enumerate(rel.paths):
            pv = dict(rel.partition_values[i]
                      if i < len(rel.partition_values) else {})
            try:
                size = os.path.getsize(path)
            except OSError:
                size = -1
            pv[InputFileName.HIDDEN] = path
            pv[InputFileBlockStart.HIDDEN] = 0
            pv[InputFileBlockLength.HIDDEN] = size
            pvs.append(pv)
        rel2.partition_values = pvs
        rel2.partition_fields = list(rel.partition_fields) + hidden
        rel2._schema = T.Schema(list(rel.schema.fields) + hidden)
        return rel2

    def augment_chain(p: L.LogicalPlan):
        """Rebuild the Project/Filter chain below `p` over an augmented
        relation; returns the new child or None (unsupported shape)."""
        if isinstance(p, (L.ParquetRelation, L.OrcRelation,
                          L.CsvRelation)):
            return augment_relation(p)
        if isinstance(p, L.Project):
            child = augment_chain(p.children[0])
            if child is None:
                return None
            from spark_rapids_tpu.exprs.base import ColumnReference

            exprs = list(p.exprs) + [
                ColumnReference(f.name)
                for f in child.schema.fields[-3:]]
            return L.Project(exprs, child)
        if isinstance(p, L.Filter):
            child = augment_chain(p.children[0])
            if child is None:
                return None
            return L.Filter(p.condition, child)
        return None

    def replace_exprs(e, schema):
        from spark_rapids_tpu.exprs.base import Alias, ColumnReference

        if isinstance(e, InputFileName):
            return Alias(ColumnReference(e.HIDDEN), e.name)
        kids = [replace_exprs(c, schema) for c in e.children]
        return e.with_children(kids) if e.children else e

    def walk(p: L.LogicalPlan) -> L.LogicalPlan:
        new_children = [walk(c) for c in p.children]
        if new_children != p.children:
            p = _copy.copy(p)
            p.children = new_children
        if not any(tree_has(e) for e in node_exprs(p)):
            return p
        child = augment_chain(p.children[0])
        if child is None:
            return p  # leave for check_supported -> CPU fallback
        if isinstance(p, L.Project):
            return L.Project([replace_exprs(e, child.schema)
                              for e in p.exprs], child)
        # Filter: rewrite the condition, then strip the hidden columns
        # so the output schema is unchanged
        cond = replace_exprs(p.condition, child.schema)
        filtered = L.Filter(cond, child)
        keep = [B.BoundReference(i, f.dtype, f.nullable, f.name)
                for i, f in enumerate(p.children[0].schema.fields)]
        return L.Project(keep, filtered)

    return walk(plan)


def _rewrite_scalar_subqueries(plan: L.LogicalPlan,
                               conf) -> L.LogicalPlan:
    """Prepass: run each ScalarSubquery's subplan once and splice its
    value in as a Literal (ref: GpuScalarSubquery's driver-side eager
    evaluation).  Non-mutating: nodes with rewritten expressions are
    shallow-copied."""
    from spark_rapids_tpu.exprs.base import Literal
    from spark_rapids_tpu.exprs.subquery import (
        ScalarSubquery,
        subquery_value,
    )

    new_children = [_rewrite_scalar_subqueries(c, conf)
                    for c in plan.children]

    def rw(e):
        if isinstance(e, ScalarSubquery):
            return Literal.of(subquery_value(e.plan, conf), e.dtype)
        return e

    def has_sq(e) -> bool:
        if isinstance(e, ScalarSubquery):
            return True
        return any(has_sq(c) for c in e.children)

    replaced = False
    out = copy.copy(plan)
    out.children = new_children
    if isinstance(plan, L.Project) and any(has_sq(e) for e in plan.exprs):
        out.exprs = [e.transform_up(rw) for e in plan.exprs]
        replaced = True
    elif isinstance(plan, L.Filter) and has_sq(plan.condition):
        out.condition = plan.condition.transform_up(rw)
        replaced = True
    if not replaced and new_children == plan.children:
        return plan
    return out


def plan_query(plan: L.LogicalPlan, conf=None) -> tuple[TpuExec, PlanMeta]:
    from spark_rapids_tpu import trace as _trace

    conf = conf or get_conf()
    with _trace.span("query.tag"):
        plan = _rewrite_split_extracts(plan)
        plan = _rewrite_input_file_exprs(plan)
        plan = _rewrite_scalar_subqueries(plan, conf)
        _annotate_filter_upload(plan)
        meta = PlanMeta(plan, conf)
        if conf.get(SQL_ENABLED):
            meta.tag()
            from spark_rapids_tpu.plan.cost import optimize_costs

            optimize_costs(meta)
            _demote_unrepresentable_boundaries(meta)
        else:
            meta.will_not_work(f"disabled by {SQL_ENABLED.key}")
    with _trace.span("query.lower"):
        root = convert_meta(meta)
        # runtime join filters must inject BEFORE the encoded-scan
        # marking: the build wrapper changes which exec is a scan's
        # direct parent (plan/runtime_filter.py)
        from spark_rapids_tpu.plan.runtime_filter import (
            inject_runtime_filters,
        )

        inject_runtime_filters(root, conf)
        # coalesce insertion runs BEFORE the encoded-scan marking so
        # the marking can look through the inserted execs
        root = _plan_coalesce(root, conf)
        _mark_encoded_scans(root)
        _plan_pipeline(root, conf)
        _plan_fusion(root)
    return root, meta


def _plan_coalesce(root: TpuExec, conf) -> TpuExec:
    """Insert TpuCoalesceBatchesExec below the operators whose programs
    benefit from dense inputs (spark.rapids.tpu.sql.coalesce.enabled;
    docs/occupancy.md): the bottom link of every fusable chain, hash
    aggregates, hash joins and sorts.  Consecutive small batches from
    the producer below (scans, caches, exchanges, CPU fallbacks) then
    reach the expensive operator concatenated up to the coalesce
    targets.  Off (the default), the plan is untouched — bit-for-bit
    the pre-coalesce engine.  The insertion points are recorded on the
    root (`_coalesce_report`) for DataFrame.explain()."""
    from spark_rapids_tpu.execs.coalesce import (
        TpuCoalesceBatchesExec,
        coalesce_enabled,
    )

    if not coalesce_enabled(conf):
        root._coalesce_report = []
        return root
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.execs.base import FusableExec
    from spark_rapids_tpu.execs.join import _HashJoinBase
    from spark_rapids_tpu.execs.sort import _SortMixin

    def wants_dense_input(node: TpuExec, child: TpuExec) -> bool:
        if isinstance(child, FusableExec):
            # never split a fusable chain (or an aggregate's absorbed
            # chain): the coalesce lands below the chain's BOTTOM link
            # instead, where the chain sources its batches
            return False
        return isinstance(node, (FusableExec, TpuHashAggregateExec,
                                 _HashJoinBase, _SortMixin))

    lines: list[str] = []
    for node in list(root._walk()):
        for i, c in enumerate(list(node.children)):
            if isinstance(c, (TpuCoalesceBatchesExec, CpuFallbackExec)) \
                    or not wants_dense_input(node, c):
                continue
            node.children[i] = TpuCoalesceBatchesExec(c)
            lines.append(f"{node.name} <- coalesce({c.name})")
    root._coalesce_report = lines
    return root


def _mark_encoded_scans(root: TpuExec) -> None:
    """Mark scans whose DIRECT parent fuses the wire decode into its own
    program (fusable chains, hash-aggregate update): those scans emit
    wire-form EncodedBatches, collapsing decode+transform(+update) to
    one program execution per batch (each execution pays a link round
    trip on the tunneled backend)."""
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.execs.base import FusableExec
    from spark_rapids_tpu.execs.coalesce import TpuCoalesceBatchesExec
    from spark_rapids_tpu.io.scan import ParquetScanExec

    from spark_rapids_tpu.execs.base import fusion_enabled

    if not fusion_enabled():
        # unfused baseline (spark.rapids.tpu.sql.fusion.enabled=false):
        # scans upload eagerly-decoded batches and every exec runs its
        # own program — the dispatch-soup configuration the fusion
        # smoke's on/off digest + dispatch-count gates compare against
        return
    for node in root._walk():
        for c in node.children:
            # look through a planner-inserted coalesce: the decode no
            # longer fuses into `node`'s program (the coalesce decodes
            # eagerly before concatenating), but the compressed wire
            # upload is preserved and the decode program is cached
            scan = c.children[0] \
                if isinstance(c, TpuCoalesceBatchesExec) else c
            if not isinstance(scan, ParquetScanExec):
                continue
            if isinstance(node, FusableExec) or (
                    isinstance(node, TpuHashAggregateExec)
                    and node.mode != "final"):
                scan.emit_encoded = True


def _plan_pipeline(root: TpuExec, conf) -> None:
    """Choose the software-pipeline stage insertion points for this plan
    (spark.rapids.tpu.sql.pipeline.*; parallel/pipeline.py): every
    Parquet/ORC scan gets its scan->decode and decode->upload stages,
    and the plan root gets the last-exec->fetch stage that collect_exec
    applies — so compute for batch k+1 dispatches while batch k's
    result is fetched D2H.  The chosen list is recorded on the root for
    DataFrame.explain()'s "Pipeline:" section."""
    from spark_rapids_tpu.io.scan import ParquetScanExec
    from spark_rapids_tpu.parallel.pipeline import stage_depth

    depth = stage_depth(conf)
    stages: list[str] = []
    if depth:
        for node in root._walk():
            if isinstance(node, ParquetScanExec):
                node._pipeline_depth = depth
                stages.append(
                    f"{node.name}: scan->decode + decode->upload "
                    f"stages (depth={depth})")
        if not isinstance(root, CpuFallbackExec):
            root._pipeline_fetch = depth
            stages.append(
                f"{root.name}: last-exec->fetch stage (depth={depth})")
    root._pipeline_stages = stages


def _plan_fusion(root: TpuExec) -> None:
    """Record which per-batch chains fuse into single XLA programs —
    and why others don't — for DataFrame.explain()'s "Fusion:" section
    (mirrors the "Pipeline:"/"RuntimeFilters:" sections; the list is
    stored on the root and rendered by eventlog.render_plan_report so
    the persisted plan matches the in-process view).  Pure
    description: it reads the same fusion_chain()/_absorbed_chain()
    decisions the drivers execute, so the report can never say one
    thing while the engine compiles another (docs/fusion.md)."""
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.execs.base import (
        FusableExec,
        fusion_enabled,
        record_fused_chain,
    )
    from spark_rapids_tpu.execs.jit_cache import donation_enabled
    from spark_rapids_tpu.exprs.base import ansi_enabled

    lines: list[str] = []
    if not fusion_enabled():
        from spark_rapids_tpu.execs.base import _fusion_conf

        root._fusion_report = [
            f"disabled by {_fusion_conf().key}: every exec "
            "dispatches its own per-batch program"]
        return
    donate = donation_enabled()

    def decode_part() -> str:
        return "wire decode fused" + (", inputs donated" if donate
                                      else "")

    absorbed_heads: set[int] = set()
    for node in root._walk():
        if isinstance(node, TpuHashAggregateExec):
            ch = node._absorbed_chain()
            src = node._source_node()
            decode = getattr(src, "emit_encoded", False)
            if ch is not None:
                chain, src, _keys = ch
                absorbed_heads.update(id(e) for e in chain)
                names = "<-".join(e.name for e in reversed(chain))
                parts = [f"update + {len(chain)} exec(s)"]
                if decode:
                    parts.append(decode_part())
                lines.append(
                    f"{node.name}[{node.mode}] absorbs {names}: one "
                    f"program [{', '.join(parts)}] over {src.name}")
                record_fused_chain()
            elif decode:
                # no fusable chain below, but the scan's wire decode
                # still fuses into the update program
                lines.append(
                    f"{node.name}[{node.mode}]: one program "
                    f"[update + {decode_part()}] over {src.name}")
                record_fused_chain()
            elif node.mode != "final" and isinstance(
                    node.children[0], FusableExec):
                why = "ANSI error polling" if ansi_enabled() else \
                    "partition-aware or uncacheable chain"
                lines.append(
                    f"{node.name}[{node.mode}]: child chain NOT "
                    f"absorbed ({why}) — the chain still fuses on "
                    "its own")
    seen: set[int] = set()
    for node in root._walk():
        if not isinstance(node, FusableExec) or id(node) in seen \
                or id(node) in absorbed_heads:
            continue
        chain, src, aware, keys = node.fusion_chain()
        seen.update(id(e) for e in chain)
        decode = getattr(src, "emit_encoded", False) and not aware
        if len(chain) > 1 or decode:
            names = "<-".join(e.name for e in reversed(chain))
            parts = [f"{len(chain)} exec(s)"]
            if decode:
                parts.append(decode_part())
            lines.append(f"{names}: one program "
                         f"[{', '.join(parts)}] over {src.name}")
            record_fused_chain()
            if aware:
                lines[-1] += " (partition-aware: encoded inputs " \
                             "decode eagerly)"
            if not all(k is not None for k in keys):
                lines[-1] += " (uncacheable key: compiled per " \
                             "instance)"
    root._fusion_report = lines


def _schema_device_representable(schema: T.Schema) -> bool:
    """Can a batch of this schema live in device columns?  list<string>
    / list<decimal> exist logically (CPU-engine results) but have no
    dense device layout; map key/value must be fixed-width (the twin
    dense matrices hold physical scalars)."""

    fixed = (T.BooleanType, T.ByteType, T.ShortType, T.IntegerType,
             T.LongType, T.FloatType, T.DoubleType, T.DateType,
             T.TimestampType)

    def ok(dt: T.DataType) -> bool:
        if isinstance(dt, T.ListType):
            # dense element matrix holds physical scalars only
            return isinstance(dt.element, fixed)
        if isinstance(dt, T.StructType):
            return all(ok(f.dtype) for f in dt.fields)
        if isinstance(dt, T.MapType):
            return isinstance(dt.key, fixed) and isinstance(dt.value,
                                                            fixed)
        return True

    return all(ok(f.dtype) for f in schema.fields)


def _demote_unrepresentable_boundaries(meta: PlanMeta) -> None:
    """A TPU node above a CPU child whose output cannot be uploaded
    would crash at the transition — push the CPU region up until every
    host->device boundary carries representable types (iterates because
    each demotion creates a new boundary one level up)."""
    changed = True
    while changed:
        changed = False

        def walk(m: PlanMeta) -> None:
            nonlocal changed
            for c in m.children:
                if m.can_replace and not c.can_replace \
                        and not _schema_device_representable(
                            c.plan.schema):
                    m.will_not_work(
                        "child output type has no device layout "
                        "(list of string/decimal) — runs on CPU")
                    changed = True
                walk(c)

        walk(meta)


def collect_exec(exec_: TpuExec) -> pa.Table:
    """Drain an exec to a host Arrow table (the D2H plan root): the
    materialized form of :func:`stream_exec` — ONE drain loop serves
    both the classic collect and the serving tier's streaming fetch,
    so the drain protocol (prefetch wiring, traced fetches, iterator/
    exec close invariants) can never diverge between them."""
    tables = list(stream_exec(exec_))
    if not tables:
        return schema_to_arrow(exec_.schema).empty_table()
    return pa.concat_tables(tables)


def stream_exec(exec_: TpuExec, stage: str = "result.fetch"):
    """Drain an exec INCREMENTALLY: one host Arrow table per device
    batch (already cast to the output schema), yielded as produced —
    the serving tier's streaming result fetch (docs/serving.md) and
    the single drain loop under :func:`collect_exec`.

    With the software pipeline on, the plan runs on a prefetch
    producer thread whose bounded queue (`pipeline.depth`) holds the
    in-flight result batches — a slow consumer blocks the producer at
    the queue, so backpressure is the stage depth, not unbounded
    buffering; fetch(k) overlaps compute(k+1) exactly as the classic
    collect's last-exec->fetch stage did.  Closing the generator early
    aborts the stage and closes the exec tree (partial drains release
    shuffle blocks).  A fully-CPU root yields its host table directly
    (also the only path for types with no device layout,
    e.g. list<string>)."""
    from spark_rapids_tpu import trace as _trace
    from spark_rapids_tpu.serving.cancel import check_point

    if isinstance(exec_, CpuFallbackExec):
        try:
            yield exec_.cpu_table().cast(schema_to_arrow(exec_.schema))
        finally:
            exec_.close()
        return
    aschema = schema_to_arrow(exec_.schema)
    try:
        it = exec_.execute()
        fetch_depth = getattr(exec_, "_pipeline_fetch", 0)
        if fetch_depth:
            from spark_rapids_tpu.parallel.pipeline import prefetch

            it = prefetch(it, depth=fetch_depth, stage=stage)
        try:
            for b in it:
                # the result-fetch cancellation checkpoint: a
                # cancelled query raises HERE on the consumer thread;
                # the finallys below close the prefetch stage (abort +
                # join) and the exec tree (shuffle blocks, spillables)
                check_point()
                if _trace.TRACER.enabled:
                    with _trace.span("query.fetch.batch"):
                        t = to_arrow(b)
                else:
                    t = to_arrow(b)
                yield t.cast(aschema)
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()
    finally:
        exec_.close()  # release shuffle blocks even on partial drains
