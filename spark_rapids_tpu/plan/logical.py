"""Logical plan nodes.

The role Catalyst's logical/physical plans play for the reference: the
engine-neutral description of a query that both the TPU planner
(plan.planner) and the CPU engine (cpu.engine) consume.  Expressions are
the shared Expression trees (unbound ColumnReferences resolved against
child schemas at construction, so every node knows its output schema)."""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.execs.sort import SortKey
from spark_rapids_tpu.exprs.aggregates import NamedAgg
from spark_rapids_tpu.exprs.base import Expression, bind_references


class LogicalPlan:
    children: list["LogicalPlan"]

    @property
    def schema(self) -> T.Schema:
        raise NotImplementedError

    def estimated_rows(self) -> Optional[int]:
        """Upper-bound row estimate for physical strategy choices (e.g.
        broadcast-vs-shuffle join, ref: CostBasedOptimizer.scala's row
        counts).  None = unknown.  Narrow nodes propagate their child's
        estimate (a filter can only shrink)."""
        if len(self.children) == 1:
            return self.children[0].estimated_rows()
        return None

    def estimated_bytes(self) -> Optional[int]:
        n = self.estimated_rows()
        if n is None:
            return None
        return n * row_width_bytes(self.schema)

    @property
    def name(self) -> str:
        return type(self).__name__

    def node_desc(self) -> str:
        return self.name

    def tree_string(self, indent: int = 0) -> str:
        s = "  " * indent + "+- " + self.node_desc() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s


def row_width_bytes(schema: T.Schema) -> int:
    """Fixed-width physical bytes per row (+1 validity byte per column);
    strings estimated at 32 chars."""
    total = 0
    for f in schema.fields:
        if isinstance(f.dtype, T.StringType):
            total += 32 + 4
        else:
            try:
                total += T.to_numpy_dtype(f.dtype).itemsize
            except TypeError:
                total += 8
        total += 1
    return max(total, 1)


def _output_fields(exprs: Sequence[Expression]) -> T.Schema:
    from spark_rapids_tpu.execs.basic import output_field

    return T.Schema([output_field(e, i) for i, e in enumerate(exprs)])


#: content_digest() computations since process start — the serving
#: test's proof that repeated prepare()s of one in-memory table hash
#: its content once, not once per structural-key build
_DIGESTS_COMPUTED = 0


def digests_computed() -> int:
    return _DIGESTS_COMPUTED


class InMemoryRelation(LogicalPlan):
    """Leaf over a host Arrow table (test sources, fallback boundaries)."""

    def __init__(self, table: pa.Table):
        from spark_rapids_tpu.columnar.arrow import schema_from_arrow

        self.children = []
        self.table = table
        self._schema = schema_from_arrow(table.schema)
        self._content_digest: Optional[str] = None

    def content_digest(self) -> str:
        """Memoized content digest of the wrapped table, for structural
        plan keys (serving/plan_cache).  Arrow tables are immutable, so
        hashing once per RELATION is sound — without the memo every
        prepare() of a large in-memory table re-hashed its buffers on
        the serving hot path.  The underscore slot keeps the memo out
        of the structural key itself."""
        global _DIGESTS_COMPUTED
        if self._content_digest is None:
            from spark_rapids_tpu.eventlog import table_digest

            _DIGESTS_COMPUTED += 1
            self._content_digest = table_digest(self.table)
        return self._content_digest

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def estimated_rows(self) -> Optional[int]:
        return self.table.num_rows

    def node_desc(self) -> str:
        return f"InMemoryRelation [{self.table.num_rows} rows]"


def expand_scan_paths(paths: Sequence[str], ext: str
                      ) -> tuple[list[str], list[dict], list[str]]:
    """Expand directory paths into data files, discovering Hive-style
    key=value partition directories written by the file writers
    (ref: the partition-discovery side of Spark's file index; per-file
    partition values feed ColumnarPartitionReaderWithPartitionValues).

    Returns (files, per-file partition-value dicts, partition col names).
    """
    import os

    files: list[str] = []
    values: list[dict] = []
    part_cols: list[str] = []
    for p in paths:
        if not os.path.isdir(p):
            files.append(p)
            values.append({})
            continue
        for root, dirs, names in sorted(os.walk(p)):
            dirs.sort()
            rel = os.path.relpath(root, p)
            pv: dict = {}
            if rel != ".":
                for seg in rel.split(os.sep):
                    if "=" not in seg:
                        pv = None
                        break
                    k, _, v = seg.partition("=")
                    pv[k] = None if v == "__HIVE_DEFAULT_PARTITION__" \
                        else _unescape_part(v)
                if pv is None:
                    continue
            for name in sorted(names):
                if name.startswith(("_", ".")) or not name.endswith(ext):
                    continue
                files.append(os.path.join(root, name))
                values.append(dict(pv))
                for k in pv:
                    if k not in part_cols:
                        part_cols.append(k)
    return files, values, part_cols


def _unescape_part(v: str) -> str:
    import re

    return re.sub("%([0-9A-Fa-f]{2})",
                  lambda m: chr(int(m.group(1), 16)), v)


def infer_partition_fields(part_cols: Sequence[str],
                           values: Sequence[dict]) -> list:
    """Type each partition column: int64 when every value parses, else
    string (the common subset of Spark's partition-type inference)."""
    from spark_rapids_tpu import types as T

    fields = []
    for c in part_cols:
        vs = [pv.get(c) for pv in values]
        dtype: T.DataType = T.LONG
        for v in vs:
            if v is None:
                continue
            try:
                int(v)
            except (TypeError, ValueError):
                dtype = T.STRING
                break
        fields.append(T.Field(c, dtype, True))
    return fields


class _FileRelation(LogicalPlan):
    """Shared Hive-discovered file-scan leaf: path expansion, column/
    partition projection resolution, lazy footer row estimates.
    Partition columns trail the file columns (Spark's layout)."""

    EXT = ""

    def __init__(self, paths: Sequence[str],
                 columns: Optional[Sequence[str]] = None):
        self.children = []
        self.paths, self.partition_values, part_cols = expand_scan_paths(
            list(paths), self.EXT)
        if not self.paths:
            raise FileNotFoundError(f"no {self.EXT} files under {paths}")
        self.partition_fields = infer_partition_fields(
            part_cols, self.partition_values)
        file_schema = self._file_schema(self.paths[0])
        if columns is not None:
            part_names = {f.name for f in self.partition_fields}
            file_cols = [c for c in columns if c not in part_names]
            by_name = {f.name: f for f in file_schema.fields}
            file_fields = [by_name[c] for c in file_cols]
            self.columns: Optional[list[str]] = file_cols
            self.partition_fields = [f for f in self.partition_fields
                                     if f.name in set(columns)]
        else:
            self.columns = None
            file_fields = list(file_schema.fields)
        self._schema = T.Schema(file_fields + self.partition_fields)
        self._est_rows: Optional[int] = None
        self._est_done = False

    def _file_schema(self, path: str) -> T.Schema:
        raise NotImplementedError

    def _file_rows(self, path: str) -> int:
        raise NotImplementedError

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def estimated_rows(self) -> Optional[int]:
        """Lazy (footer reads cost IO; only joins ever ask), memoized."""
        if not self._est_done:
            self._est_done = True
            try:
                self._est_rows = sum(self._file_rows(p)
                                     for p in self.paths)
            except Exception:
                pass
        return self._est_rows

    def node_desc(self) -> str:
        return f"{type(self).__name__} {self.paths}"


class ParquetRelation(_FileRelation):
    """Parquet scan leaf (ref: GpuParquetScan.scala — here the footer/
    row-group handling is pyarrow's; device decode is a later stage).
    Directory paths are expanded with Hive partition discovery; partition
    values surface as trailing columns."""

    EXT = ".parquet"

    def _file_schema(self, path: str) -> T.Schema:
        import pyarrow.parquet as pq

        from spark_rapids_tpu.columnar.arrow import schema_from_arrow

        return schema_from_arrow(pq.read_schema(path))

    def _file_rows(self, path: str) -> int:
        import pyarrow.parquet as pq

        return pq.read_metadata(path).num_rows


class CsvRelation(LogicalPlan):
    """CSV scan leaf (ref: GpuCSVScan in GpuBatchScanExec.scala:90)."""

    def __init__(self, paths: Sequence[str],
                 schema: Optional[T.Schema] = None):
        import pyarrow.csv as pacsv

        from spark_rapids_tpu.columnar.arrow import schema_from_arrow

        self.children = []
        self.paths, self.partition_values, part_cols = expand_scan_paths(
            list(paths), ".csv")
        if not self.paths:
            raise FileNotFoundError(f"no csv files under {paths}")
        self.partition_fields = infer_partition_fields(
            part_cols, self.partition_values)
        if schema is None:
            head = pacsv.read_csv(self.paths[0])
            schema = schema_from_arrow(head.schema)
        self.file_schema = schema
        self._schema = T.Schema(
            list(schema.fields) + self.partition_fields)

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"CsvRelation {self.paths}"


class OrcRelation(_FileRelation):
    """ORC scan leaf (ref: GpuOrcScan.scala — CPU footer parse + device
    decode; here pyarrow's ORC reader decodes stripes on host and the
    scan exec uploads them like Parquet row groups)."""

    EXT = ".orc"

    def _file_schema(self, path: str) -> T.Schema:
        import pyarrow.orc as paorc

        from spark_rapids_tpu.columnar.arrow import schema_from_arrow

        return schema_from_arrow(paorc.ORCFile(path).schema)

    def _file_rows(self, path: str) -> int:
        import pyarrow.orc as paorc

        return paorc.ORCFile(path).nrows


class RangeRel(LogicalPlan):
    def __init__(self, start: int, end: int, step: int = 1):
        self.children = []
        self.start, self.end, self.step = start, end, step
        self._schema = T.Schema([T.Field("id", T.LONG, False)])

    def estimated_rows(self) -> Optional[int]:
        return max(0, -(-(self.end - self.start) // self.step))

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"Range ({self.start}, {self.end}, step={self.step})"


class CacheSlot:
    """Shared materialization slot behind `df.cache()`: filled once by
    the first TPU collect that drains the cached subtree, then every
    plan referencing the slot re-serves the stored batches instead of
    re-running the subtree (the InMemoryTableScanExec replacement the
    reference installs per shim, Spark311Shims.scala + the cache
    serializer doc).  Device batches live in the BufferStore — spillable
    and pin-counted like every other long-lived buffer."""

    def __init__(self):
        import threading

        self.lock = threading.Lock()
        #: list per partition of SpillableBatch handles (None = empty)
        self.parts = None
        self.cpu_table = None  # CPU-engine materialization

    @property
    def filled(self) -> bool:
        return self.parts is not None

    def publish(self, parts) -> None:
        with self.lock:
            if self.parts is None:
                self.parts = parts
            else:  # lost the race: keep first, drop ours
                for handles in parts:
                    for h in handles:
                        h.close()

    def clear(self) -> None:
        with self.lock:
            parts, self.parts = self.parts, None
            self.cpu_table = None
        if parts:
            for handles in parts:
                for h in handles:
                    h.close()


class Cached(LogicalPlan):
    """df.cache()/persist() marker (ref: SURVEY Appendix A
    InMemoryTableScanExec + docs/additional-functionality/
    cache-serializer.md)."""

    def __init__(self, child: LogicalPlan, slot: Optional[CacheSlot]
                 = None):
        self.children = [child]
        self.slot = slot or CacheSlot()

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        state = "materialized" if self.slot.filled else "pending"
        return f"Cached [{state}]"


class Project(LogicalPlan):
    def __init__(self, exprs: Sequence[Expression], child: LogicalPlan):
        self.children = [child]
        self.exprs = [bind_references(e, child.schema) for e in exprs]
        self._schema = _output_fields(self.exprs)

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"Project [{', '.join(e.name for e in self.exprs)}]"


class Filter(LogicalPlan):
    def __init__(self, condition: Expression, child: LogicalPlan):
        self.children = [child]
        self.condition = bind_references(condition, child.schema)

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        return f"Filter [{self.condition!r}]"


class Aggregate(LogicalPlan):
    def __init__(self, groups: Sequence[Expression],
                 aggs: Sequence[NamedAgg], child: LogicalPlan):
        self.children = [child]
        self.groups = [bind_references(g, child.schema) for g in groups]
        self.aggs = [NamedAgg(na.fn.bind(child.schema), na.out_name)
                     for na in aggs]
        key_fields = list(_output_fields(self.groups).fields)
        self._schema = T.Schema(
            key_fields + [na.output_field() for na in self.aggs])

    def estimated_rows(self) -> Optional[int]:
        if not self.groups:
            return 1  # grand aggregate: exactly one output row
        return self.children[0].estimated_rows()  # upper bound

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        ks = ", ".join(g.name for g in self.groups)
        asr = ", ".join(f"{na.fn.name}->{na.out_name}" for na in self.aggs)
        return f"Aggregate keys=[{ks}] [{asr}]"


class Expand(LogicalPlan):
    """Multiple projection lists over each input row (ref:
    GpuExpandExec.scala:67): one output row per (input row, projection).
    Grouping-set rewrites (rollup/cube) and distinct-aggregate rewrites
    build on this node the way Spark's analyzer does."""

    def __init__(self, projections: Sequence[Sequence[Expression]],
                 names: Sequence[str], child: LogicalPlan):
        assert projections and all(
            len(p) == len(names) for p in projections)
        self.children = [child]
        self.projections = [
            [bind_references(e, child.schema) for e in proj]
            for proj in projections]
        fields = []
        for i, name in enumerate(names):
            dt = None
            for proj in self.projections:
                pdt = proj[i].dtype
                if not isinstance(pdt, T.NullType):
                    dt = pdt
                    break
            fields.append(T.Field(name, dt or T.NULL, True))
        self.names = list(names)
        self._schema = T.Schema(fields)

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return (f"Expand [{len(self.projections)} projections, "
                f"{len(self.names)} cols]")


class Generate(LogicalPlan):
    """Generator over each input row (ref: GpuGenerateExec.scala:378):
    child columns repeated per generated row, generator output columns
    appended ('pos' for posexplode, 'col' for the element)."""

    def __init__(self, generator, child: LogicalPlan,
                 out_name: str = "col"):
        from spark_rapids_tpu.exprs.collections import Explode

        assert isinstance(generator, Explode)
        self.children = [child]
        self.generator = generator.with_children(
            [bind_references(generator.child, child.schema)])
        # analysis error, not a fallback: no engine can explode a
        # non-array (Spark raises AnalysisException the same way)
        self.generator.check_supported()
        self.out_name = out_name
        fields = list(child.schema.fields)
        if self.generator.pos:
            fields.append(T.Field("pos", T.INT, self.generator.outer))
        fields.append(T.Field(out_name, self.generator.dtype, True))
        self._schema = T.Schema(fields)

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"Generate [{self.generator.name}]"


class Sort(LogicalPlan):
    def __init__(self, keys: Sequence[SortKey], child: LogicalPlan):
        self.children = [child]
        self.keys = [SortKey(bind_references(k.expr, child.schema),
                             k.descending, k.nulls_last) for k in keys]

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        ks = ", ".join(
            f"{k.expr.name}{' DESC' if k.descending else ''}"
            for k in self.keys)
        return f"Sort [{ks}]"


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        self.children = [child]
        self.n = n

    def estimated_rows(self) -> Optional[int]:
        c = self.children[0].estimated_rows()
        return self.n if c is None else min(self.n, c)

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        return f"Limit {self.n}"


class Join(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression], join_type: str,
                 condition: Optional[Expression] = None):
        from spark_rapids_tpu.execs.join import JOIN_TYPES, _nullable_fields

        assert join_type in JOIN_TYPES, join_type
        self.children = [left, right]
        self.join_type = join_type
        self.left_keys = [bind_references(k, left.schema) for k in left_keys]
        self.right_keys = [bind_references(k, right.schema)
                           for k in right_keys]
        joined = T.Schema(list(left.schema.fields)
                          + list(right.schema.fields))
        self.condition = (bind_references(condition, joined)
                          if condition is not None else None)
        lf, rf = list(left.schema.fields), list(right.schema.fields)
        if join_type in ("left_outer", "full_outer"):
            rf = _nullable_fields(right.schema)
        if join_type in ("right_outer", "full_outer"):
            lf = _nullable_fields(left.schema)
        if join_type in ("left_semi", "left_anti"):
            self._schema = left.schema
        else:
            self._schema = T.Schema(lf + rf)

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        ks = ", ".join(f"{l.name}={r.name}" for l, r in
                       zip(self.left_keys, self.right_keys))
        c = f" cond={self.condition!r}" if self.condition is not None else ""
        return f"Join {self.join_type} [{ks}]{c}"


class Window(LogicalPlan):
    """One (partition_by, order_by) group of window expressions appended
    to the child's output (ref: Spark's WindowExec contract; the session
    frontend splits mixed specs into a chain of Window nodes)."""

    def __init__(self, window_exprs, child: LogicalPlan):
        self.children = [child]
        self.window_exprs = [(we.bind(child.schema), name)
                             for we, name in window_exprs]
        spec0 = self.window_exprs[0][0].spec
        for we, _ in self.window_exprs[1:]:
            assert (we.spec.partition_by, we.spec.order_by) == \
                (spec0.partition_by, spec0.order_by)
        self._schema = T.Schema(
            list(child.schema.fields)
            + [T.Field(name, we.dtype, we.nullable)
               for we, name in self.window_exprs])

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        fns = ", ".join(f"{we.fn.describe()}->{n}"
                        for we, n in self.window_exprs)
        return f"Window [{fns}] ({self.window_exprs[0][0].spec.describe()})"


class Union(LogicalPlan):
    def __init__(self, children: Sequence[LogicalPlan]):
        assert children
        self.children = list(children)

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def estimated_rows(self) -> Optional[int]:
        total = 0
        for c in self.children:
            n = c.estimated_rows()
            if n is None:
                return None
            total += n
        return total


class GroupedPandas(LogicalPlan):
    """Grouped pandas-UDF nodes (ref: the reference's python exec
    family): kind in {"flatmap", "agg", "window"}; `payload` is the
    user fn (flatmap) or [(out_name, fn, in_col)] (agg/window).
    Requires ClusteredDistribution on `key_names` — the planner
    inserts the hash exchange."""

    def __init__(self, key_names, payload, schema, kind: str,
                 child: LogicalPlan):
        assert kind in ("flatmap", "agg", "window"), kind
        self.children = [child]
        self.key_names = list(key_names)
        self.payload = payload
        self.kind = kind
        self._schema = schema
        for k in self.key_names:
            child.schema.index_of(k)  # raises on unknown key

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"GroupedPandas[{self.kind}] keys={self.key_names}"


class CoGroupedPandas(LogicalPlan):
    """cogroup(...).applyInPandas (ref: GpuFlatMapCoGroupsInPandasExec):
    fn(left group frame, right group frame) -> frame."""

    def __init__(self, left_keys, right_keys, fn, schema,
                 left: LogicalPlan, right: LogicalPlan):
        self.children = [left, right]
        self.left_key_names = list(left_keys)
        self.right_key_names = list(right_keys)
        self.fn = fn
        self._schema = schema
        for k in self.left_key_names:
            left.schema.index_of(k)
        for k in self.right_key_names:
            right.schema.index_of(k)

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"CoGroupedPandas keys={self.left_key_names}"


class MapInArrow(LogicalPlan):
    #: True when `fn` is a pandas-frame function (mapInPandas); the
    #: planner then lowers to the pandas exec variant
    pandas = False

    """Arrow-batch python transform over the child (the
    mapInArrow/mapInPandas family the reference schedules onto GPU
    python workers, ref: GpuArrowEvalPythonExec + python/rapids/
    worker.py).  `fn` runs in a process-isolated worker pool; the
    declared schema is the contract both engines cast results to."""

    def __init__(self, fn, schema: T.Schema, child: LogicalPlan):
        self.children = [child]
        self.fn = fn
        self._schema = schema

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def estimated_rows(self):
        return None  # an arbitrary python transform may grow rows

    def node_desc(self) -> str:
        name = getattr(self.fn, "__name__", "fn")
        return f"MapInArrow [{name}]"
