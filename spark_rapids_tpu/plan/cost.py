"""Cost-based optimizer: demote unprofitable TPU islands to the CPU.

Analog of the reference's optional CBO (ref: CostBasedOptimizer.scala:34
`Optimizer` trait, :62 `optimize` — CpuCostModel vs GpuCostModel per
operator, forcing subtrees back to CPU when acceleration cannot repay
the row/columnar transition cost).  The TPU version reasons about
host<->device transfers instead of row<->columnar conversions, but the
shape is the same:

  island      = a maximal subtree of nodes the tagger left replaceable
  tpu cost    = per-row device op cost * rows, summed over the island,
                plus a per-row transfer cost at every boundary where
                data enters (host-resident child or source leaf) or
                leaves (island root) the device
  cpu cost    = per-row host op cost * rows over the same nodes

If the island's TPU cost (including transfers) exceeds its CPU cost,
every node in it is tagged will-not-work — the planner then builds one
fused CpuFallbackExec and the data never bounces through the device.
Rows come from `LogicalPlan.estimated_rows()` upper bounds; an unknown
estimate aborts demotion (never move unknown — possibly huge — work to
the host on a guess).

Disabled by default, like the reference
(spark.rapids.sql.optimizer.enabled, RapidsConf.scala).
"""

from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.config import register, get_conf
from spark_rapids_tpu.plan import logical as L

CBO_ENABLED = register(
    "spark.rapids.tpu.sql.optimizer.enabled", False,
    "Cost-based demotion of small TPU subtrees whose host<->device "
    "transfer cost exceeds the acceleration win (the "
    "spark.rapids.sql.optimizer.enabled analog).")

CPU_ROW_COST = register(
    "spark.rapids.tpu.sql.optimizer.cpuRowCost", 1.0,
    "Relative per-row cost of one operator on the CPU engine.")

TPU_ROW_COST = register(
    "spark.rapids.tpu.sql.optimizer.tpuRowCost", 0.05,
    "Relative per-row cost of one operator on the TPU (compiled XLA "
    "programs amortize to far below host per-row cost).")

TRANSFER_ROW_COST = register(
    "spark.rapids.tpu.sql.optimizer.transferRowCost", 1.5,
    "Relative per-row cost of moving a boundary's rows across the "
    "host<->device link (decode/pack + transfer latency).")

DEMOTION_REASON = "not cost-effective on TPU (cost-based optimizer)"


def _rows(p: L.LogicalPlan) -> Optional[int]:
    return p.estimated_rows()


def _work_rows(p: L.LogicalPlan) -> Optional[int]:
    """Rows an operator actually processes: its inputs (an aggregate
    reads a million rows to emit ten), falling back to its own output
    estimate for leaves."""
    if p.children:
        total = 0
        for c in p.children:
            r = _rows(c)
            if r is None:
                return None
            total += r
        return total
    return _rows(p)


def exec_estimated_rows(node) -> Optional[int]:
    """Upper-bound row estimate for a lowered PHYSICAL subtree — the
    runtime-filter pass's build-side selectivity gate (the same posture
    as logical `estimated_rows`: narrow nodes propagate, a filter can
    only shrink, unknown shapes return None and the caller never acts
    on a guess).  File scans answer from footer metadata, which the
    logical layer already read for the join-strategy choice (OS page
    cache makes the re-read free)."""
    from spark_rapids_tpu.io.scan import (
        ArrowSourceExec,
        CsvScanExec,
        OrcScanExec,
        ParquetScanExec,
    )

    if isinstance(node, ArrowSourceExec):
        return node.table.num_rows
    if isinstance(node, (ParquetScanExec, OrcScanExec)):
        cached = getattr(node, "_est_rows", None)
        if cached is not None:
            return cached
        try:
            if isinstance(node, OrcScanExec):
                import pyarrow.orc as paorc

                n = sum(paorc.ORCFile(p).nrows for p in node.paths)
            else:
                import pyarrow.parquet as pq

                n = sum(pq.read_metadata(p).num_rows
                        for p in node.paths)
        except Exception:
            return None
        node._est_rows = n
        return n
    if isinstance(node, CsvScanExec):
        return None
    from spark_rapids_tpu.execs.adaptive import CoalescedShuffleReaderExec
    from spark_rapids_tpu.execs.basic import (
        TpuCoalesceBatchesExec,
        TpuFilterExec,
        TpuProjectExec,
    )
    from spark_rapids_tpu.execs.cache import TpuCacheExec
    from spark_rapids_tpu.execs.coalesce import TpuCoalescePartitionsExec
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.execs.join import TpuRuntimeFilterBuildExec

    if isinstance(node, (TpuFilterExec, TpuProjectExec,
                         TpuShuffleExchangeExec, TpuCoalesceBatchesExec,
                         TpuCoalescePartitionsExec, TpuCacheExec,
                         CoalescedShuffleReaderExec,
                         TpuRuntimeFilterBuildExec)):
        return exec_estimated_rows(node.children[0])
    return None


def optimize_costs(meta) -> None:
    """Tag every node of each unprofitable replaceable island with
    DEMOTION_REASON.  Runs after tag(), before conversion."""
    conf = get_conf()
    if not conf.get(CBO_ENABLED):
        return
    cpu_c = conf.get(CPU_ROW_COST)
    tpu_c = conf.get(TPU_ROW_COST)
    xfer_c = conf.get(TRANSFER_ROW_COST)

    def walk(m, parent_replaceable: bool) -> None:
        if m.can_replace and not parent_replaceable:
            _consider_island(m, cpu_c, tpu_c, xfer_c)
            # island internals were visited by _consider_island; recurse
            # only into the non-replaceable frontier below it
            for f in _frontier(m):
                for c in f.children:
                    walk(c, False)
        else:
            for c in m.children:
                walk(c, m.can_replace)

    walk(meta, False)


def _frontier(island_root) -> list:
    """Non-replaceable children hanging below an island (the CPU
    boundary nodes)."""
    out = []

    def rec(m):
        for c in m.children:
            if c.can_replace:
                rec(c)
            else:
                out.append(c)
    rec(island_root)
    return out


def _consider_island(root, cpu_c: float, tpu_c: float,
                     xfer_c: float) -> None:
    nodes = []

    def rec(m):
        nodes.append(m)
        for c in m.children:
            if c.can_replace:
                rec(c)
    rec(root)

    op_rows = []
    entry_rows = []
    for m in nodes:
        w = _work_rows(m.plan)
        if w is None:
            return  # unknown work: never demote on a guess
        op_rows.append(w)
        if not m.plan.children:
            # source leaf: its output must be uploaded
            r = _rows(m.plan)
            if r is None:
                return
            entry_rows.append(r)
        else:
            for c in m.children:
                if not c.can_replace:
                    r = _rows(c.plan)
                    if r is None:
                        return
                    entry_rows.append(r)  # host-resident child
    exit_rows = _rows(root.plan)
    if exit_rows is None:
        return

    tpu_cost = (tpu_c * sum(op_rows)
                + xfer_c * (sum(entry_rows) + exit_rows))
    cpu_cost = cpu_c * sum(op_rows)
    if tpu_cost > cpu_cost:
        for m in nodes:
            m.will_not_work(DEMOTION_REASON)
