"""Subplan-hash extraction: the keying substrate of cross-tenant work
sharing (serving/work_share.py, docs/work_sharing.md).

The reference amortizes device work across a fleet of tasks through
shared, content-addressed storage (the tiered device store keyed by
buffer id, shuffle blocks keyed by (shuffle, map, reduce)).  The
serving-tier mirror needs the same property one level up: a QUERY
(or any subtree of one) must have a deterministic, content-complete
identity so two tenants presenting the same work can share one
execution.  This module mints those identities:

- :func:`plan_share_key` — the result-cache key for a logical plan:
  the structural plan serialization (serving/plan_cache.py — node
  classes + every attribute, expressions via the jit_cache structural
  keys, in-memory tables by CONTENT digest) crossed with the conf
  fingerprint (lowering reads conf, so two conf epochs must never
  share a result), hashed.  ``None`` when the plan is not shareable.
- :func:`plan_is_shareable` — the determinism gate: only plans built
  from pure relational nodes over pure expressions may share results.
  Nondeterministic expressions (rand, monotonically_increasing_id,
  partition ids), opaque host callables (pandas/arrow UDFs — their
  structural key is identity-based and proves nothing about behavior)
  and mutable-state nodes (df.cache slots) are excluded: serving a
  cached result for those could answer a DIFFERENT computation.
- :func:`plan_source_digests` — the file-content fingerprint of every
  file relation in the plan ((path, size, mtime_ns) STAT triples, not
  byte hashes — hashing every input at every lookup would cost the
  scan sharing exists to save; see docs/work_sharing.md for the
  coarse-mtime caveat): the invalidation token.  The structural key
  pins WHICH files a plan reads; the fingerprints pin what was IN
  them when the result was produced, and a mismatch at lookup time
  invalidates the entry (in-memory tables need no token — their
  content digest is already part of the structural key, and Arrow
  tables are immutable).
- :func:`iter_shareable_subplans` — every shareable subtree with its
  key, root first: the subplan enumeration the result cache keys by
  (today the cache serves whole-plan hits — a dashboard fleet issues
  the same full query — and scan-level sharing reuses the relation
  subtree identity through :func:`scan_share_key`).
- :func:`scan_share_key` — the in-flight scan-dedup key for one scan
  exec partition: the relation subtree identity (paths + content
  digests + read columns + partition values) crossed with everything
  that shapes the decoded unit stream (pushed-filter structural key,
  prefilter mode, batch rows, upload-suppression set, wire form) and
  the conf fingerprint.  Two queries holding the same key provably
  produce byte-identical unit streams, so the second may ride the
  first's decode.
"""

from __future__ import annotations

import hashlib
import os
from typing import Iterator, Optional

from spark_rapids_tpu.plan import logical as L


def _source_stat(path: str) -> tuple[str, int, int]:
    st = os.stat(path)
    return (path, st.st_size, st.st_mtime_ns)


def plan_source_digests(plan: L.LogicalPlan) -> list[tuple]:
    """(path, size, mtime_ns) for every file the plan reads, in
    deterministic order — the content token verified at lookup time.
    Raises OSError when a file vanished (callers treat that as
    unshareable)."""
    out: list[tuple] = []

    def walk(p: L.LogicalPlan) -> None:
        if isinstance(p, (L.ParquetRelation, L.OrcRelation,
                          L.CsvRelation)):
            for path in p.paths:
                out.append(_source_stat(path))
        for c in p.children:
            walk(c)

    walk(plan)
    return sorted(out)


#: logical nodes whose execution is a pure function of (inputs, conf).
#: Anything outside this set keeps mutable state (Cached slots) or runs
#: opaque host callables (pandas/arrow UDF nodes) — never shared.
_PURE_NODES = (
    L.InMemoryRelation, L.ParquetRelation, L.OrcRelation,
    L.CsvRelation, L.RangeRel, L.Project, L.Filter, L.Aggregate,
    L.Sort, L.Limit, L.Join, L.Union, L.Window, L.Expand, L.Generate,
)


def _node_exprs(p: L.LogicalPlan) -> list:
    if isinstance(p, L.Project):
        return list(p.exprs)
    if isinstance(p, L.Filter):
        return [p.condition]
    if isinstance(p, L.Aggregate):
        out = list(p.groups)
        for na in p.aggs:
            out.extend(na.fn.inputs())
        return out
    if isinstance(p, L.Sort):
        return [k.expr for k in p.keys]
    if isinstance(p, L.Join):
        out = list(p.left_keys) + list(p.right_keys)
        if p.condition is not None:
            out.append(p.condition)
        return out
    if isinstance(p, L.Window):
        return [e for we, _n in p.window_exprs for e in we.children]
    if isinstance(p, L.Expand):
        return [e for proj in p.projections for e in proj]
    if isinstance(p, L.Generate):
        return [p.generator.child]
    return []


def _expr_is_pure(e) -> bool:
    from spark_rapids_tpu.exprs.nondeterministic import (
        tree_is_partition_aware,
    )
    from spark_rapids_tpu.exprs.subquery import ScalarSubquery

    if tree_is_partition_aware(e):
        return False
    stack = [e]
    while stack:
        x = stack.pop()
        # opaque host callables: their structural key is id()-based
        # (serving/plan_cache._value_key) and says nothing about what
        # the function computes — a recycled id could alias a cached
        # result onto a different function
        if type(x).__module__.endswith("udf.exprs"):
            return False
        if isinstance(x, ScalarSubquery):
            if not plan_is_shareable(x.plan):
                return False
        stack.extend(x.children)
    return True


def plan_is_shareable(plan: L.LogicalPlan) -> bool:
    """True when the plan's RESULT is a pure function of its inputs'
    content and the conf — the precondition for serving a cached
    result (see module doc for what is excluded and why)."""
    if not isinstance(plan, _PURE_NODES):
        return False
    for e in _node_exprs(plan):
        if not _expr_is_pure(e):
            return False
    return all(plan_is_shareable(c) for c in plan.children)


def _digest(payload: str) -> str:
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


def plan_share_key(plan: L.LogicalPlan, conf) -> Optional[str]:
    """The result-cache key: structural plan identity x conf
    fingerprint (None when the plan is not shareable).  File CONTENT
    is deliberately NOT part of the key — it is the invalidation
    token (:func:`plan_source_digests`), verified at lookup, so a
    mutated input observably invalidates the stale entry instead of
    silently orphaning it under a new key."""
    if not plan_is_shareable(plan):
        return None
    from spark_rapids_tpu.eventlog import conf_fingerprint
    from spark_rapids_tpu.serving.plan_cache import plan_structural_key

    try:
        structural = plan_structural_key(plan)
    except Exception:
        return None  # unserializable attribute: never guess a key
    return _digest(structural + "|" + conf_fingerprint(conf))


def iter_shareable_subplans(plan: L.LogicalPlan,
                            conf) -> Iterator[tuple[str,
                                                    L.LogicalPlan]]:
    """(key, subplan) for every shareable subtree, root first in
    pre-order — the subplan enumeration work sharing keys by.  A
    subtree inside an unshareable parent still enumerates: the parent
    cannot share its result, but the subtree's identity remains valid
    (scan-level sharing rides exactly this)."""
    key = plan_share_key(plan, conf)
    if key is not None:
        yield key, plan
    for c in plan.children:
        yield from iter_shareable_subplans(c, conf)


def scan_share_key(scan, partition: int, conf) -> Optional[str]:
    """The in-flight scan-dedup key for one ParquetScanExec/OrcScanExec
    task partition (see module doc).  None when the scan's unit stream
    is not provably deterministic-and-identical across queries:
    runtime filters registered (their publication time is
    query-dependent), or a pushed filter with no structural key."""
    if getattr(scan, "runtime_filters", None):
        return None
    from spark_rapids_tpu.eventlog import conf_fingerprint

    parts: list[str] = [type(scan).__name__, str(partition)]
    try:
        for p in scan.paths:
            parts.append(repr(_source_stat(p)))
    except OSError:
        return None
    parts.append(repr(scan.columns))
    parts.append(repr(scan.batch_rows))
    parts.append(repr(scan.partition_values))
    parts.append(repr([(f.name, f.dtype.name)
                       for f in scan.partition_fields]))
    pushed = getattr(scan, "pushed_filter", None)
    if pushed is not None:
        from spark_rapids_tpu.execs.jit_cache import expr_key

        try:
            parts.append(expr_key(pushed))
        except Exception:
            return None  # no structural form: never guess
    else:
        parts.append("-")
    parts.append(repr(bool(getattr(scan, "exact_prefilter", False))))
    parts.append(repr(sorted(getattr(scan, "null_upload_cols", None)
                             or ())))
    parts.append(repr(bool(getattr(scan, "emit_encoded", False))))
    parts.append(conf_fingerprint(conf))
    return _digest("|".join(parts))
