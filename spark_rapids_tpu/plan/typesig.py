"""Declarative type-support signatures driving plan tagging.

TPU re-design of the reference's TypeSig/TypeChecks
(ref: sql-plugin/.../TypeChecks.scala:129 TypeSig, :483 TypeChecks —
every replacement rule declares which input types it accelerates, the
tagging pass checks declarations instead of trusting operator code, and
the registry generates docs/supported_ops.md).

A signature is a set of type *kinds*; an expression rule carries one
uniform input signature (parameter-position granularity can narrow it
later, as the reference does).  Tagging walks each expression tree and
turns every unsupported child dtype into a will-not-work reason — so a
decimal multiply or an array-typed comparison falls back to the CPU
engine with an explanation instead of silently computing wrong results
or crashing mid-kernel."""

from __future__ import annotations

import dataclasses
from typing import Optional

from spark_rapids_tpu import types as T

_KIND_OF = {
    T.BooleanType: "boolean",
    T.ByteType: "byte",
    T.ShortType: "short",
    T.IntegerType: "int",
    T.LongType: "long",
    T.FloatType: "float",
    T.DoubleType: "double",
    T.StringType: "string",
    T.DateType: "date",
    T.TimestampType: "timestamp",
    T.DecimalType: "decimal",
    T.NullType: "null",
    T.ListType: "array",
    T.StructType: "struct",
    T.MapType: "map",
}

KIND_ORDER = ["boolean", "byte", "short", "int", "long", "float",
              "double", "decimal", "string", "date", "timestamp",
              "null", "array", "struct", "map"]


def kind_of(dtype: T.DataType) -> str:
    return _KIND_OF[type(dtype)]


@dataclasses.dataclass(frozen=True)
class TypeSig:
    kinds: frozenset

    def supports(self, dtype: T.DataType) -> bool:
        return kind_of(dtype) in self.kinds

    def __add__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(self.kinds | other.kinds)

    def describe(self) -> str:
        return ", ".join(k for k in KIND_ORDER if k in self.kinds)

    @staticmethod
    def of(*kinds: str) -> "TypeSig":
        unknown = set(kinds) - set(KIND_ORDER)
        assert not unknown, f"unknown type kinds {unknown}"
        return TypeSig(frozenset(kinds))


BOOLEAN = TypeSig.of("boolean")
INTEGRAL = TypeSig.of("byte", "short", "int", "long")
NUMERIC = INTEGRAL + TypeSig.of("float", "double")
STRING = TypeSig.of("string")
DATETIME = TypeSig.of("date", "timestamp")
DECIMAL = TypeSig.of("decimal")
NULLSIG = TypeSig.of("null")
ARRAY = TypeSig.of("array")
STRUCT = TypeSig.of("struct")
MAP = TypeSig.of("map")
NESTED = ARRAY + STRUCT + MAP

#: the commonCudfTypes analog (ref: TypeSig.commonCudfTypes :427):
#: everything the columnar kernels handle uniformly
COMMON = NUMERIC + BOOLEAN + STRING + DATETIME
COMMON_N = COMMON + NULLSIG
ORDERABLE = COMMON + DECIMAL + NULLSIG  # sort/compare/group keys
ALL = ORDERABLE + ARRAY


@dataclasses.dataclass(frozen=True)
class ExprSig:
    """Input signature of one expression rule: the types its children
    may produce for the TPU version to engage."""

    inputs: TypeSig
    note: str = ""


def check_inputs(expr, sig: Optional[ExprSig], reasons: set) -> None:
    """Tag unsupported child dtypes (the tagging side of TypeChecks)."""
    if sig is None:
        return
    for c in expr.children:
        try:
            dt = c.dtype
        except Exception:
            continue  # unresolved: binding errors surface elsewhere
        if not sig.inputs.supports(dt):
            reasons.add(
                f"expression {type(expr).__name__} does not support "
                f"input type {dt.name} on TPU "
                f"(supported: {sig.inputs.describe()})")
