"""File writers: the framework's durable output path.

TPU re-design of the reference's columnar write stack:
- `GpuParquetFileFormat`/`GpuOrcFileFormat` (ref: sql-plugin/.../
  GpuParquetFileFormat.scala:39,154) — per-format ColumnarOutputWriter;
- `GpuFileFormatWriter`/`GpuFileFormatDataWriter` (ref: sql/rapids/
  GpuFileFormatWriter.scala, GpuFileFormatDataWriter.scala) — the write
  protocol: one task per input partition, part files + _SUCCESS marker,
  dynamic partitioning by splitting each batch on the partition-column
  values;
- write-stats trackers (ref: BasicColumnarWriteStatsTracker.scala) —
  files/rows/bytes accounting surfaced through exec metrics.

The device side produces columnar batches; encoding to the file format
runs on host via Arrow (the reference encodes on device via cudf
`writeParquet` — a device-side Pallas encoder is a later optimization,
the protocol and semantics live here either way).
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import threading
import uuid
from typing import Iterator, Optional, Sequence

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.arrow import to_arrow
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import MetricTimer, TOTAL_TIME, TpuExec


@dataclasses.dataclass
class WriteStats:
    """ref: BasicColumnarWriteStatsTracker's numFiles/numOutputRows/
    numOutputBytes."""

    num_files: int = 0
    num_rows: int = 0
    num_bytes: int = 0
    partitions: int = 0  # dynamic partition directories created


class _FormatWriter:
    """One open output file; append Arrow tables, close, report bytes."""

    def write(self, table: pa.Table) -> None:
        raise NotImplementedError

    def close(self) -> int:
        raise NotImplementedError


class _ParquetWriter(_FormatWriter):
    def __init__(self, path: str, schema: pa.Schema, compression: str):
        import pyarrow.parquet as pq

        self.path = path
        self._w = pq.ParquetWriter(path, schema, compression=compression)

    def write(self, table: pa.Table) -> None:
        self._w.write_table(table)

    def close(self) -> int:
        self._w.close()
        return os.path.getsize(self.path)


class _CsvWriter(_FormatWriter):
    def __init__(self, path: str, schema: pa.Schema):
        import pyarrow.csv as pacsv

        self.path = path
        self._w = pacsv.CSVWriter(path, schema)

    def write(self, table: pa.Table) -> None:
        self._w.write_table(table)

    def close(self) -> int:
        self._w.close()
        return os.path.getsize(self.path)


class FileWriteExec(TpuExec):
    """Writes the child's partitions as part files under a directory.

    One write task per child partition (the Spark task model,
    ref: GpuFileFormatWriter.executeTask); tasks run on the shared task
    thread pool so host encoding overlaps device compute across
    partitions.  With `partition_by`, each batch is split host-side on
    the partition-column values into Hive-style key=value directories
    (ref: GpuFileFormatDataWriter's DynamicPartitionDataWriter).
    """

    FORMAT = ""
    EXT = ""

    def __init__(self, path: str, child: TpuExec,
                 partition_by: Sequence[str] = (),
                 compression: str = "snappy"):
        super().__init__(child)
        self.path = path
        self.partition_by = list(partition_by)
        self.compression = compression
        self.stats = WriteStats()
        self._lock = threading.Lock()
        bad = [c for c in self.partition_by
               if c not in [f.name for f in child.schema.fields]]
        if bad:
            raise ValueError(f"partition columns not in schema: {bad}")

    @property
    def schema(self) -> T.Schema:
        return self.children[0].schema

    def node_desc(self) -> str:
        extra = f" partitioned by {self.partition_by}" \
            if self.partition_by else ""
        return f"{type(self).__name__} {self.path}{extra}"

    def additional_metrics(self):
        return [("numFiles", "ESSENTIAL"), ("numOutputBytes", "ESSENTIAL"),
                ("writeTime", "MODERATE")]

    # -- format hooks --------------------------------------------------- #

    def _open(self, path: str, schema: pa.Schema) -> _FormatWriter:
        raise NotImplementedError

    # -- write protocol -------------------------------------------------- #

    def _task_filename(self, task: int) -> str:
        return f"part-{task:05d}-{uuid.uuid4().hex[:12]}{self.EXT}"

    def _data_schema(self) -> pa.Schema:
        from spark_rapids_tpu.columnar.arrow import schema_to_arrow

        aschema = schema_to_arrow(self.schema)
        if not self.partition_by:
            return aschema
        keep = [f for f in aschema if f.name not in self.partition_by]
        return pa.schema(keep)

    def _write_task(self, p: int) -> None:
        child = self.children[0]
        data_schema = self._data_schema()
        fname = self._task_filename(p)
        writers: dict[tuple, _FormatWriter] = {}

        def writer_for(part_values: tuple) -> _FormatWriter:
            w = writers.get(part_values)
            if w is not None:
                return w
            if part_values:
                sub = "/".join(
                    f"{c}={_part_str(v)}"
                    for c, v in zip(self.partition_by, part_values))
                d = os.path.join(self.path, sub)
                os.makedirs(d, exist_ok=True)
                with self._lock:
                    self.stats.partitions += 1
            else:
                d = self.path
            w = self._open(os.path.join(d, fname), data_schema)
            writers[part_values] = w
            return w

        rows = 0
        try:
            for batch in child.execute_partition(p):
                with MetricTimer(self.metrics["writeTime"]):
                    table = to_arrow(batch)
                    rows += table.num_rows
                    if not self.partition_by:
                        if table.num_rows or p == 0:
                            writer_for(()).write(table)
                        continue
                    for part_values, sub_table in _split_by_partitions(
                            table, self.partition_by):
                        writer_for(part_values).write(
                            sub_table.select(
                                [f.name for f in data_schema]))
            if not self.partition_by and not writers and p == 0:
                writer_for(())  # empty input: schema-only file
        finally:
            nbytes = 0
            for w in writers.values():
                nbytes += w.close()
            with self._lock:
                self.stats.num_files += len(writers)
                self.stats.num_rows += rows
                self.stats.num_bytes += nbytes
            self.metrics["numFiles"].add(len(writers))
            self.metrics["numOutputBytes"].add(nbytes)

    def run(self) -> WriteStats:
        from spark_rapids_tpu.config import get_conf
        from spark_rapids_tpu.execs.exchange import TASK_THREADS

        os.makedirs(self.path, exist_ok=True)
        child = self.children[0]
        n = child.num_partitions
        threads = min(get_conf().get(TASK_THREADS), max(n, 1))
        with MetricTimer(self.metrics[TOTAL_TIME], op=self.name):
            if threads <= 1 or n <= 1:
                for p in range(n):
                    self._write_task(p)
            else:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(max_workers=threads) as pool:
                    futs = [pool.submit(self._write_task, p)
                            for p in range(n)]
                    for f in futs:
                        f.result()
        # commit marker (ref: Spark's HadoopMapReduceCommitProtocol)
        with open(os.path.join(self.path, "_SUCCESS"), "w"):
            pass
        self.children[0].close()
        return self.stats

    def execute(self) -> Iterator[ColumnarBatch]:  # pragma: no cover
        raise TypeError("FileWriteExec is a command; call run()")


class ParquetWriteExec(FileWriteExec):
    """ref: GpuParquetFileFormat.scala:39,154 (ColumnarOutputWriter via
    cudf writeParquet)."""

    FORMAT = "parquet"
    EXT = ".parquet"

    def _open(self, path: str, schema: pa.Schema) -> _FormatWriter:
        return _ParquetWriter(path, schema, self.compression)


class CsvWriteExec(FileWriteExec):
    FORMAT = "csv"
    EXT = ".csv"

    def _open(self, path: str, schema: pa.Schema) -> _FormatWriter:
        return _CsvWriter(path, schema)


class _OrcWriter(_FormatWriter):
    def __init__(self, path: str, schema: pa.Schema, compression: str):
        import pyarrow.orc as paorc

        self.path = path
        # ORC has its own codec set; "snappy" (parquet's default here)
        # is also a valid ORC codec
        self._w = paorc.ORCWriter(path, compression=compression)

    def write(self, table: pa.Table) -> None:
        self._w.write(table)

    def close(self) -> int:
        self._w.close()
        return os.path.getsize(self.path)


class OrcWriteExec(FileWriteExec):
    """ref: GpuOrcFileFormat.scala (ColumnarOutputWriter via cudf
    writeORC)."""

    FORMAT = "orc"
    EXT = ".orc"

    def _open(self, path: str, schema: pa.Schema) -> _FormatWriter:
        return _OrcWriter(path, schema, self.compression)


def _part_str(v) -> str:
    """Hive-style partition value encoding."""
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    s = str(v)
    return "".join("%%%02X" % ord(c) if c in '/\\{}[]#%:=' else c
                   for c in s)


def _split_by_partitions(table: pa.Table, part_cols: Sequence[str]
                         ) -> list[tuple[tuple, pa.Table]]:
    """Split one Arrow table by distinct partition-column tuples."""
    import pyarrow.compute as pc

    if table.num_rows == 0:
        return []
    distinct = pa.table(
        {c: table.column(c) for c in part_cols}).group_by(
        list(part_cols)).aggregate([]).to_pydict()
    out = []
    n_distinct = len(distinct[part_cols[0]])
    for i in range(n_distinct):
        values = tuple(distinct[c][i] for c in part_cols)
        mask = None
        for c, v in zip(part_cols, values):
            col = table.column(c)
            if v is None:
                m = pc.is_null(col)
            elif isinstance(v, float) and v != v:
                # NaN partition value: pc.equal(x, NaN) never matches
                m = pc.is_nan(col)
            else:
                m = pc.equal(col, pa.scalar(v))
            mask = m if mask is None else pc.and_(mask, m)
        out.append((values, table.filter(mask)))
    return out


# ---------------------------------------------------------------------- #
# Mode handling (error/overwrite/append/ignore — Spark SaveMode)
# ---------------------------------------------------------------------- #

def prepare_target(path: str, mode: str) -> bool:
    """Returns False when the write should be skipped (mode=ignore)."""
    exists = os.path.exists(path) and (
        not os.path.isdir(path) or len(os.listdir(path)) > 0)
    if not exists:
        return True
    if mode == "error":
        raise FileExistsError(
            f"path {path} already exists (write mode 'error'; use "
            "mode('overwrite') or mode('append'))")
    if mode == "ignore":
        return False
    if mode == "overwrite":
        if os.path.isdir(path):
            shutil.rmtree(path)
        else:
            os.unlink(path)
        return True
    if mode == "append":
        return True
    raise ValueError(f"unknown save mode {mode!r}")
