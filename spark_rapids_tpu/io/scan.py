"""Scan execs: host-decoded columnar reads uploaded to device.

TPU analog of the reference's scan layer (ref: GpuParquetScan.scala:84 —
CPU footer parse + device decode; GpuCSVScan at GpuBatchScanExec.scala:90).
Stage-5 design from SURVEY.md §7: pyarrow does file decode on host
(multi-threaded C++), and batches are uploaded H2D through the single
arrow seam; device-side Parquet decode (Pallas) is a later optimization.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.arrow import from_arrow, schema_to_arrow
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec


def _conf_batch_rows() -> int:
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS, get_conf

    return get_conf().get(BATCH_SIZE_ROWS)


class ArrowSourceExec(TpuExec):
    """Leaf over a host Arrow table: slices it into device batches (the
    receiving end of every CPU->TPU transition, ref: HostColumnarToGpu)."""

    def __init__(self, table: pa.Table, schema: Optional[T.Schema] = None,
                 batch_rows: Optional[int] = None):
        super().__init__()
        from spark_rapids_tpu.columnar.arrow import schema_from_arrow

        self.table = table
        self._schema = schema or schema_from_arrow(table.schema)
        self.batch_rows = batch_rows or _conf_batch_rows()

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"ArrowSourceExec [{self.table.num_rows} rows]"

    @property
    def num_partitions(self) -> int:
        return max(1, -(-self.table.num_rows // self.batch_rows))

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        t = self.table
        if t.num_rows == 0:
            yield self._count_output(
                from_arrow(t.cast(schema_to_arrow(self._schema))))
            return
        chunk = t.slice(p * self.batch_rows, self.batch_rows)
        yield self._count_output(from_arrow(chunk))


def constant_column(value, dtype: T.DataType, n: int, cap: int):
    """A device column holding one repeated value for n live rows (the
    partition-value appender, ref:
    ColumnarPartitionReaderWithPartitionValues.scala)."""
    import numpy as np

    from spark_rapids_tpu.columnar.column import Column, StringColumn, pad_width

    if isinstance(dtype, T.StringType):
        b = (value or "").encode("utf-8")
        w = pad_width(max(len(b), 1))
        chars = np.zeros((cap, w), np.uint8)
        lengths = np.zeros(cap, np.int32)
        valid = np.zeros(cap, np.bool_)
        if value is not None:
            chars[:n, : len(b)] = np.frombuffer(b, np.uint8)
            lengths[:n] = len(b)
            valid[:n] = True
        import jax.numpy as jnp

        return StringColumn(jnp.asarray(chars), jnp.asarray(lengths),
                            jnp.asarray(valid))
    vals = np.zeros(n, T.to_numpy_dtype(dtype))
    validity = np.zeros(n, np.bool_)
    if value is not None:
        vals[:] = value
        validity[:] = True
    return Column.from_numpy(vals, dtype, validity, capacity=cap)


class ParquetScanExec(TpuExec):
    """Reads row-group-sized record batches per file and uploads them
    (the per-file reader mode; multi-file coalescing/cloud thread pools
    of GpuParquetScan.scala:882 are a later stage).  Per-file Hive
    partition values are appended as trailing constant columns."""

    def __init__(self, paths: Sequence[str], schema: T.Schema,
                 columns: Optional[Sequence[str]] = None,
                 batch_rows: Optional[int] = None,
                 partition_values: Optional[Sequence[dict]] = None,
                 partition_fields: Sequence[T.Field] = ()):
        super().__init__()
        self.paths = list(paths)
        self._schema = schema
        self.columns = list(columns) if columns is not None else None
        self.batch_rows = batch_rows or _conf_batch_rows()
        self.partition_values = list(partition_values or [])
        self.partition_fields = list(partition_fields)

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"ParquetScanExec {self.paths}"

    def additional_metrics(self):
        return [("scanTime", "MODERATE")]

    @property
    def num_partitions(self) -> int:
        return len(self.paths)  # one task per file (row-group splits later)

    def _partition_value(self, p: int, f: T.Field):
        v = self.partition_values[p].get(f.name) \
            if p < len(self.partition_values) else None
        if v is not None and isinstance(f.dtype, T.LongType):
            v = int(v)
        return v

    def _with_partition_cols(self, batch: ColumnarBatch,
                             p: int) -> ColumnarBatch:
        if not self.partition_fields:
            return batch
        n = batch.concrete_num_rows()
        cap = max(batch.capacity, 1)
        cols = list(batch.columns)
        for f in self.partition_fields:
            cols.append(constant_column(
                self._partition_value(p, f), f.dtype, n, cap))
        return ColumnarBatch(cols, batch.num_rows, self._schema)

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        import pyarrow.parquet as pq

        if self.columns is not None and not self.columns:
            # partition-columns-only projection: no file columns to read
            from spark_rapids_tpu.columnar.column import pad_capacity

            n_total = pq.read_metadata(self.paths[p]).num_rows
            offs = range(0, n_total, self.batch_rows) if n_total \
                else ([0] if p == 0 else [])
            for off in offs:
                n = min(self.batch_rows, n_total - off)
                cap = pad_capacity(max(n, 1))
                cols = [constant_column(self._partition_value(p, f),
                                        f.dtype, n, cap)
                        for f in self.partition_fields]
                yield self._count_output(
                    ColumnarBatch(cols, n, self._schema))
            return

        f = pq.ParquetFile(self.paths[p])
        empty = True
        for rb in f.iter_batches(batch_size=self.batch_rows,
                                 columns=self.columns):
            empty = False
            yield self._count_output(self._with_partition_cols(
                from_arrow(pa.Table.from_batches([rb])), p))
        if empty and p == 0:
            aschema = schema_to_arrow(self._schema)
            yield self._count_output(
                from_arrow(pa.Table.from_arrays(
                    [pa.array([], fl.type) for fl in aschema],
                    schema=aschema)))


class CsvScanExec(TpuExec):
    def __init__(self, paths: Sequence[str], schema: T.Schema,
                 batch_rows: Optional[int] = None,
                 partition_values: Optional[Sequence[dict]] = None,
                 partition_fields: Sequence[T.Field] = ()):
        super().__init__()
        self.paths = list(paths)
        self._schema = schema
        self.batch_rows = batch_rows or _conf_batch_rows()
        self.partition_values = list(partition_values or [])
        self.partition_fields = list(partition_fields)
        n_file = len(schema.fields) - len(self.partition_fields)
        self.file_aschema = schema_to_arrow(
            T.Schema(schema.fields[:n_file]))

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"CsvScanExec {self.paths}"

    @property
    def num_partitions(self) -> int:
        return len(self.paths)

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        import pyarrow.csv as pacsv

        t = pacsv.read_csv(self.paths[p]).cast(self.file_aschema)
        for off in range(0, max(t.num_rows, 1), self.batch_rows):
            chunk = t.slice(off, self.batch_rows)
            batch = from_arrow(chunk)
            if self.partition_fields:
                n = batch.concrete_num_rows()
                cap = max(batch.capacity, 1)
                cols = list(batch.columns)
                for f in self.partition_fields:
                    v = self.partition_values[p].get(f.name) \
                        if p < len(self.partition_values) else None
                    if v is not None and isinstance(f.dtype, T.LongType):
                        v = int(v)
                    cols.append(constant_column(v, f.dtype, n, cap))
                batch = ColumnarBatch(cols, batch.num_rows, self._schema)
            yield self._count_output(batch)
