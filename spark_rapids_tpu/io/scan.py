"""Scan execs: host-decoded columnar reads uploaded to device.

TPU analog of the reference's scan layer (ref: GpuParquetScan.scala:84 —
CPU footer parse + device decode; GpuCSVScan at GpuBatchScanExec.scala:90).
Stage-5 design from SURVEY.md §7: pyarrow does file decode on host
(multi-threaded C++), and batches are uploaded H2D through the single
arrow seam; device-side Parquet decode (Pallas) is a later optimization.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import pyarrow as pa

from spark_rapids_tpu import config as _config
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.arrow import from_arrow, schema_to_arrow
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import MetricTimer, TpuExec


def _conf_batch_rows() -> int:
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS, get_conf

    return get_conf().get(BATCH_SIZE_ROWS)


class ArrowSourceExec(TpuExec):
    """Leaf over a host Arrow table: slices it into device batches (the
    receiving end of every CPU->TPU transition, ref: HostColumnarToGpu)."""

    def __init__(self, table: pa.Table, schema: Optional[T.Schema] = None,
                 batch_rows: Optional[int] = None):
        super().__init__()
        from spark_rapids_tpu.columnar.arrow import schema_from_arrow

        self.table = table
        self._schema = schema or schema_from_arrow(table.schema)
        self.batch_rows = batch_rows or _conf_batch_rows()

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"ArrowSourceExec [{self.table.num_rows} rows]"

    @property
    def num_partitions(self) -> int:
        return max(1, -(-self.table.num_rows // self.batch_rows))

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        t = self.table
        if t.num_rows == 0:
            yield self._count_output(
                from_arrow(t.cast(schema_to_arrow(self._schema))))
            return
        chunk = t.slice(p * self.batch_rows, self.batch_rows)
        yield self._count_output(from_arrow(chunk))


def constant_column(value, dtype: T.DataType, n: int, cap: int):
    """A device column holding one repeated value for n live rows (the
    partition-value appender, ref:
    ColumnarPartitionReaderWithPartitionValues.scala)."""
    import numpy as np

    from spark_rapids_tpu.columnar.column import Column, StringColumn, pad_width

    if isinstance(dtype, T.StringType):
        b = (value or "").encode("utf-8")
        w = pad_width(max(len(b), 1))
        chars = np.zeros((cap, w), np.uint8)
        lengths = np.zeros(cap, np.int32)
        valid = np.zeros(cap, np.bool_)
        if value is not None:
            chars[:n, : len(b)] = np.frombuffer(b, np.uint8)
            lengths[:n] = len(b)
            valid[:n] = True
        import jax.numpy as jnp

        return StringColumn(jnp.asarray(chars), jnp.asarray(lengths),
                            jnp.asarray(valid))
    vals = np.zeros(n, T.to_numpy_dtype(dtype))
    validity = np.zeros(n, np.bool_)
    if value is not None:
        vals[:] = value
        validity[:] = True
    return Column.from_numpy(vals, dtype, validity, capacity=cap)


FILES_PER_TASK_BYTES = _config.register(
    "spark.rapids.tpu.sql.scan.taskTargetBytes", 512 << 20,
    "Target total file size per scan task: small files coalesce into one "
    "task up to this size (the multi-file reader analog, ref: "
    "GpuParquetScan.scala:882 MultiFileParquetPartitionReader).")

MAX_READ_BATCH_BYTES = _config.register(
    "spark.rapids.tpu.sql.scan.maxReadBatchSizeBytes", 128 << 20,
    "Target device bytes per scanned batch (ref: "
    "spark.rapids.sql.reader.batchSizeBytes, RapidsConf.scala:446). "
    "Scan batches are sized rows = bytes/estimated-row-width: batches "
    "this size amortize per-dispatch/per-transfer latency while still "
    "pipelining decode -> upload -> compute across batches.")

HOST_PREFILTER = _config.register(
    "spark.rapids.tpu.sql.scan.hostPrefilter", True,
    "Evaluate a scan-adjacent Filter's deterministic condition on the "
    "host right after decode and ship only surviving rows across the "
    "host->device link (the filter-pushdown-into-scan contract of "
    "DataSourceV2; ref: the reference's row-group/page pruning, "
    "GpuParquetScan.scala:263-306, taken to row granularity).  The "
    "exact Filter still runs on device — the prefilter only shrinks "
    "the wire, it never decides semantics.")

SCAN_DECODE_THREADS = _config.register(
    "spark.rapids.tpu.sql.scan.decodeThreads", 4,
    "Host threads decoding a task's files concurrently (the multi-file "
    "cloud reader's pool, ref: GpuParquetScan.scala:882-895 "
    "MultiFileCloudParquetPartitionReader).")

FAST_DECODE = _config.register(
    "spark.rapids.tpu.sql.scan.fastDecode", True,
    "Decode supported Parquet column chunks with the native host codec "
    "and evaluate pushed single-column predicates on dictionary values "
    "(io/fastpar.py) instead of the general pyarrow read path — the "
    "host-side mirror of the reference's device page decode (ref: "
    "GpuParquetScan.scala:495-560).  Files with unsupported encodings, "
    "nulls, or nested types silently use the standard path.")


def _task_target_bytes() -> int:
    return _config.get_conf().get(FILES_PER_TASK_BYTES)


def _scan_batch_rows(schema: T.Schema) -> int:
    """Rows per scanned batch from the byte target; an explicitly set
    global batchSizeRows still caps it exactly (tests and memory-tight
    deployments rely on that), as does maxBatchCapacity."""
    import numpy as np

    from spark_rapids_tpu.config import BATCH_SIZE_ROWS, MAX_CAPACITY
    from spark_rapids_tpu.memory.device_manager import (
        effective_batch_size_rows,
    )

    conf = _config.get_conf()
    rows_cap = effective_batch_size_rows(conf)
    if rows_cap == BATCH_SIZE_ROWS.default:
        rows_cap = 64 << 20  # defer to the byte target
    def _w(dt: T.DataType) -> int:
        if isinstance(dt, T.StringType):
            return 40
        if isinstance(dt, T.ListType):
            return 128
        if isinstance(dt, T.StructType):
            return 1 + sum(_w(f2.dtype) for f2 in dt.fields)
        if isinstance(dt, T.MapType):
            return 192
        return np.dtype(T.to_numpy_dtype(dt)).itemsize

    est = 2  # validity byte + slack
    for f in schema.fields:
        est += _w(f.dtype)
    by_bytes = max(1024, conf.get(MAX_READ_BATCH_BYTES) // est)
    # round down to a power of two: full batches then sit exactly on
    # their capacity bucket — no device padding, no wire padding, and
    # one compiled program shape for every full batch
    by_bytes = 1 << (by_bytes.bit_length() - 1)
    return int(max(1, min(rows_cap, by_bytes, conf.get(MAX_CAPACITY))))


def _prefetched(gen, stage: str = "scan.decode",
                depth: Optional[int] = None):
    """Run a generator on a background pipeline stage so host-side work
    (footer pruning, Parquet decode) overlaps the consumer's upload +
    device compute (the cloud-reader thread-pool idea, ref:
    GpuParquetScan.scala:882-895 MultiFileCloudParquetPartitionReader).
    Items must stay host-side; device residency belongs to the
    consuming task thread.  Thin shim over the shared
    parallel.pipeline stage (clean join-on-abort shutdown, error
    propagation, occupancy metrics)."""
    from spark_rapids_tpu.parallel.pipeline import prefetch

    return prefetch(gen, depth=depth, stage=stage)


class ParquetScanExec(TpuExec):
    """Multi-file coalesced Parquet scan with footer predicate pushdown.

    - files group into tasks up to a byte target (ref:
      MultiFileParquetPartitionReader, GpuParquetScan.scala:882);
    - a scan-adjacent Filter's condition prunes whole files on Hive
      partition values and row groups on footer min/max statistics
      before any byte is read (ref: filterBlocks :263-306) — the exact
      Filter still runs afterwards;
    - each task's decode+upload runs prefetched on a background thread;
    - per-file Hive partition values append as trailing constants."""

    def __init__(self, paths: Sequence[str], schema: T.Schema,
                 columns: Optional[Sequence[str]] = None,
                 batch_rows: Optional[int] = None,
                 partition_values: Optional[Sequence[dict]] = None,
                 partition_fields: Sequence[T.Field] = ()):
        super().__init__()
        self.paths = list(paths)
        self._schema = schema
        self.columns = list(columns) if columns is not None else None
        self.batch_rows = batch_rows or _scan_batch_rows(schema)
        self.partition_values = list(partition_values or [])
        self.partition_fields = list(partition_fields)
        self.pushed_filter = None  # set by the planner (Filter above)
        #: [(column_name, RuntimeFilter)] registered by the
        #: runtime-filter planner pass (plan/runtime_filter.py): build-
        #: side join-key filters applied host-side before encode+upload
        self.runtime_filters: list = []
        self._groups = self._group_files()

    def _group_files(self) -> list[list[int]]:
        import os

        target = _task_target_bytes()
        groups: list[list[int]] = []
        cur: list[int] = []
        cur_bytes = 0
        for i, p in enumerate(self.paths):
            try:
                sz = os.path.getsize(p)
            except OSError:
                sz = target
            if cur and cur_bytes + sz > target:
                groups.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += sz
        if cur:
            groups.append(cur)
        return groups or [[]]

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        pf = ""
        if self.pushed_filter is not None:
            pf = f" pushed=[{self.pushed_filter.name}]"
        return (f"ParquetScanExec [{len(self.paths)} files, "
                f"{len(self._groups)} tasks]{pf}")

    def additional_metrics(self):
        return [("scanTime", "MODERATE"),
                ("filesPruned", "ESSENTIAL"),
                ("rowGroupsPruned", "ESSENTIAL"),
                ("hostFilteredRows", "ESSENTIAL"),
                ("rfPrunedRows", "ESSENTIAL"),
                ("rfRowGroupsPruned", "ESSENTIAL")]

    def _ready_runtime_filters(self) -> list:
        """Published filters only — an unpublished filter applies
        nothing (never block the scan on the build side)."""
        return [(n, rf) for n, rf in self.runtime_filters if rf.ready]

    @property
    def num_partitions(self) -> int:
        return len(self._groups)

    def _partition_value(self, p: int, f: T.Field):
        v = self.partition_values[p].get(f.name) \
            if p < len(self.partition_values) else None
        if v is not None and isinstance(f.dtype, T.LongType):
            v = int(v)
        return v

    def _conjuncts(self):
        if self.pushed_filter is None:
            return None
        from spark_rapids_tpu.io.pushdown import split_conjuncts

        return split_conjuncts(self.pushed_filter)

    def _host_partition_array(self, fi: int, f: T.Field,
                              n: int) -> pa.Array:
        """A host Arrow array repeating file fi's partition value."""
        import numpy as np

        atype = schema_to_arrow(T.Schema([f])).field(0).type
        v = self._partition_value(fi, f)
        if v is None:
            return pa.nulls(n, atype)
        if isinstance(f.dtype, T.StringType):
            one = pa.array([str(v)], atype)
        else:
            one = pa.array([v]).cast(atype)
        return one.take(pa.array(np.zeros(n, np.int32)))

    def _partition_only_tables(self, fi: int, n_total: int):
        """Chunks for a projection with no file columns: bare row counts
        (zero-column schema) or repeated partition values."""
        for off in range(0, n_total, self.batch_rows):
            n = min(self.batch_rows, n_total - off)
            if not self.partition_fields:
                yield n
            else:
                yield pa.Table.from_arrays(
                    [self._host_partition_array(fi, f, n)
                     for f in self.partition_fields],
                    [f.name for f in self.partition_fields])

    def _file_tables(self, fi: int, conjuncts):
        """One file's surviving data as HOST Arrow tables (full output
        schema: file columns + repeated partition values), or bare ints
        (row counts) when the projection has zero columns.

        Pruning and Parquet decode run while this generator is iterated
        (on the prefetch thread); uploads happen later on the consuming
        task thread, which holds the TPU semaphore — prefetched data
        waits on HOST, as in the reference's cloud reader."""
        import pyarrow.parquet as pq

        from spark_rapids_tpu.io.pushdown import (
            partition_may_match,
            row_group_may_match,
        )

        if conjuncts is not None and self.partition_fields:
            pv = self.partition_values[fi] \
                if fi < len(self.partition_values) else {}
            if not partition_may_match(conjuncts, self._schema, pv,
                                       self.partition_fields):
                self.metrics["filesPruned"].add(1)
                return

        if self.columns is not None and not self.columns:
            # no file columns to read: only row counts matter
            yield from self._partition_only_tables(
                fi, pq.read_metadata(self.paths[fi]).num_rows)
            return

        f = pq.ParquetFile(self.paths[fi])
        from spark_rapids_tpu.io.rebase import REBASE_MODE_READ, check_rebase

        read_fields = [fl for fl in self._schema.fields
                       if self.columns is None or fl.name in self.columns]
        check_rebase(self.paths[fi], f.metadata, T.Schema(read_fields),
                     getattr(self, "_rebase_mode", None)
                     or _config.get_conf().get(REBASE_MODE_READ))
        n_rgs = f.metadata.num_row_groups
        if conjuncts is not None:
            keep_rgs = [g for g in range(n_rgs)
                        if row_group_may_match(
                            conjuncts, self._schema,
                            f.metadata.row_group(g))]
            self.metrics["rowGroupsPruned"].add(n_rgs - len(keep_rgs))
            if not keep_rgs:
                return
        else:
            keep_rgs = list(range(n_rgs))

        rfs = self._ready_runtime_filters()
        if rfs:
            # runtime-filter min/max as an extra footer conjunct: the
            # build side's key range decides row-group reachability
            # before any byte is decoded
            from spark_rapids_tpu.io.pushdown import (
                runtime_range_may_match,
            )

            before = len(keep_rgs)
            keep_rgs = [g for g in keep_rgs
                        if all(runtime_range_may_match(
                            n, rf, f.metadata.row_group(g))
                            for n, rf in rfs)]
            if before != len(keep_rgs):
                from spark_rapids_tpu.plan import runtime_filter as _RF

                self.metrics["rfRowGroupsPruned"].add(
                    before - len(keep_rgs))
                _RF.record_row_groups_pruned(before - len(keep_rgs))
            if not keep_rgs:
                return

        fast = self._try_fast_tables(f, fi, keep_rgs, conjuncts)
        if fast is not None:
            tables, fast_rf_complete = fast
            for tbl in tables:
                for f2 in self.partition_fields:
                    tbl = tbl.append_column(
                        f2.name,
                        self._host_partition_array(fi, f2, tbl.num_rows))
                # multi-column conjuncts (not applied by the fast
                # decoder) still prefilter here; survivors are few.
                # Runtime filters the decoder fully applied are NOT
                # re-probed (skip_rf) — the mask is deterministic
                yield self._host_prefilter(tbl,
                                           skip_rf=fast_rf_complete)
            return

        if f.metadata.num_rows <= self.batch_rows:
            # whole file fits one scan batch: single threaded columnar
            # read (iter_batches re-slices row groups and serializes
            # column decode; read_row_groups decodes all columns with
            # the Arrow C++ pool)
            tbl = f.read_row_groups(keep_rgs, columns=self.columns,
                                    use_threads=True)
            for f2 in self.partition_fields:
                tbl = tbl.append_column(
                    f2.name,
                    self._host_partition_array(fi, f2, tbl.num_rows))
            yield self._host_prefilter(tbl)
            return
        for rb in f.iter_batches(batch_size=self.batch_rows,
                                 columns=self.columns,
                                 row_groups=keep_rgs,
                                 use_threads=True):
            tbl = pa.Table.from_batches([rb])
            for f2 in self.partition_fields:
                tbl = tbl.append_column(
                    f2.name,
                    self._host_partition_array(fi, f2, rb.num_rows))
            yield self._host_prefilter(tbl)

    def _try_fast_tables(self, f, fi: int, keep_rgs,
                         conjuncts) -> Optional[tuple]:
        """Native fast-decode path (io/fastpar.py): returns (the
        file's surviving rows as host tables, whether runtime filters
        were FULLY applied inside the decoder — so the prefilter can
        skip its redundant re-probe), or None to use pyarrow."""
        if not getattr(self, "_fast_decode", True):
            return None
        from spark_rapids_tpu.io import fastpar

        file_cols = self.columns
        if file_cols is None:
            pnames = {pf.name for pf in self.partition_fields}
            file_cols = [fl.name for fl in self._schema.fields
                         if fl.name not in pnames]
        if not file_cols:
            return None
        use_conjs = conjuncts if getattr(self, "_prefilter_on", False) \
            else None
        rfs = self._ready_runtime_filters()
        counters: dict = {}
        tables = fastpar.read_file(
            self.paths[fi], keep_rgs, file_cols, use_conjs,
            self._schema, pqfile=f,
            max_decoded_bytes=getattr(self, "_max_batch_bytes",
                                      64 << 20),
            runtime_filters=rfs or None, counters=counters)
        if tables is None:
            return None
        rf_pruned = counters.get("rf_pruned", 0)
        if rf_pruned:
            from spark_rapids_tpu.plan import runtime_filter as _RF

            self.metrics["rfPrunedRows"].add(rf_pruned)
            _RF.record_pruned_rows(rf_pruned)
        if use_conjs:
            kept_rg_rows = sum(f.metadata.row_group(g).num_rows
                               for g in keep_rgs)
            after = sum(t.num_rows for t in tables)
            self.metrics["hostFilteredRows"].add(
                kept_rg_rows - after - rf_pruned)
        return tables, bool(rfs) and counters.get("rf_complete", False)

    @staticmethod
    def _harmonize_dicts(tables: list) -> list:
        """Decode dictionary columns to plain wherever the accumulated
        tables disagree (one file kept its Parquet dict, another came
        back plain) — pa.concat_tables requires identical schemas."""
        if len(tables) <= 1 or len({t.schema for t in tables}) <= 1:
            return tables
        out = []
        for t in tables:
            cols, changed = {}, False
            for name in t.schema.names:
                c = t[name]
                if pa.types.is_dictionary(c.type):
                    c = c.cast(c.type.value_type)
                    changed = True
                cols[name] = c
            out.append(pa.table(cols) if changed else t)
        return out

    def _upload(self, tables: list) -> ColumnarBatch:
        tables = self._harmonize_dicts(tables)
        tbl = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
        if getattr(self, "emit_encoded", False) and tbl.num_rows > 0:
            # planner marked the consumer as decode-fusing: ship the
            # batch in wire form; the consumer's program decodes it
            # (one program execution per batch instead of two)
            from spark_rapids_tpu.columnar.transfer import encode_batch

            tbl = tbl.combine_chunks()
            arrays = []
            for c in tbl.columns:
                a = c.combine_chunks() if isinstance(c, pa.ChunkedArray) \
                    else c
                arrays.append(a.chunk(0) if isinstance(a, pa.ChunkedArray)
                              else a)
            eb = encode_batch(arrays, self._schema, tbl.num_rows)
            if eb is not None:
                return eb
        b = from_arrow(tbl)
        return ColumnarBatch(b.columns, b.num_rows, self._schema)

    def _prefilter_active(self) -> bool:
        if self.pushed_filter is None \
                or not _config.get_conf().get(HOST_PREFILTER):
            return False
        from spark_rapids_tpu.exprs.nondeterministic import (
            tree_is_partition_aware,
        )

        # a nondeterministic predicate must evaluate exactly once, on
        # device, with its partition context — never pre-applied
        return not tree_is_partition_aware(self.pushed_filter)

    def _host_prefilter(self, tbl: pa.Table,
                        skip_rf: bool = False) -> pa.Table:
        """Drop rows the pushed Filter must reject, BEFORE they cross
        the wire.  Prefers the compiled pyarrow.compute form (C++
        multi-threaded, GIL-free — decode-speed); falls back to the CPU
        engine's interpreter for predicates outside that subset.
        Conservative only in failure: any evaluation problem disables
        prefiltering and ships everything; the device Filter is always
        the source of truth."""
        if not skip_rf:
            tbl = self._apply_runtime_filters(tbl)
        if not getattr(self, "_prefilter_on", False) or tbl.num_rows == 0:
            # suppression must still run (accumulated tables are
            # concatenated and need one consistent schema)
            return self._suppress_upload_cols(tbl)
        try:
            import pyarrow.compute as pc

            mask = None
            if self._pa_filter is not None:
                try:
                    mask = self._pa_filter(tbl)
                except Exception:
                    # compiled form hit a kernel gap (e.g. date32 vs
                    # int literal): the CPU engine's interpreter below
                    # is the complete fallback
                    self._pa_filter = None
            if mask is None:
                from spark_rapids_tpu.cpu.engine import cpu_eval

                mask = cpu_eval(self.pushed_filter, tbl)
            kept = tbl.filter(pc.fill_null(mask, False))
        except Exception:
            if getattr(self, "exact_prefilter", False):
                # the planner ELIDED the device Filter on the promise
                # that this prefilter is exact — failing silently here
                # would return unfiltered rows as final results
                raise
            self._prefilter_on = False  # unsupported expr: stop trying
            return tbl
        self.metrics["hostFilteredRows"].add(tbl.num_rows - kept.num_rows)
        return self._suppress_upload_cols(kept)

    def _apply_runtime_filters(self, tbl: pa.Table) -> pa.Table:
        """Application point 3 (plan/runtime_filter.py): drop decoded
        rows whose join key provably/probabilistically matches no build
        key, BEFORE they are encoded and cross the wire.  Dictionary
        columns probe their dictionary once (LUT + gather); anything
        the probe cannot model is skipped — pruning is an IO
        optimization, the join stays the source of truth."""
        rfs = self._ready_runtime_filters()
        if not rfs or tbl.num_rows == 0:
            return tbl
        names = set(tbl.schema.names)
        rfs = [(n, rf) for n, rf in rfs if n in names]
        if not rfs:
            return tbl
        from spark_rapids_tpu import trace as _trace
        from spark_rapids_tpu.io.pa_filter import (
            runtime_filter_column_mask,
        )

        with _trace.span("rf.apply", scan=self.name,
                         rows=tbl.num_rows):
            keep = None
            for name, rf in rfs:
                m = runtime_filter_column_mask(tbl.column(name), rf)
                if m is None:
                    continue
                keep = m if keep is None else (keep & m)
            if keep is None:
                return tbl
            n_keep = int(keep.sum())
            if n_keep == tbl.num_rows:
                return tbl
            kept = tbl.filter(pa.array(keep))
        pruned = tbl.num_rows - kept.num_rows
        from spark_rapids_tpu.plan import runtime_filter as _RF

        self.metrics["rfPrunedRows"].add(pruned)
        _RF.record_pruned_rows(pruned)
        return kept

    def _suppress_upload_cols(self, tbl: pa.Table) -> pa.Table:
        """Replace filter-only columns with all-NULL arrays AFTER the
        host prefilter consumed their values: the planner proved no
        operator above the elided Filter reads them, and the wire
        encoder ships an all-null column as zero bytes (kind 'null').
        Schema and ordinals stay intact, so bound references above are
        unaffected."""
        cols = getattr(self, "null_upload_cols", None)
        if not cols:
            return tbl
        for i, name in enumerate(tbl.schema.names):
            if name in cols:
                ft = tbl.schema.field(i).type
                if pa.types.is_dictionary(ft):
                    ft = ft.value_type
                tbl = tbl.set_column(i, pa.field(name, ft),
                                     pa.nulls(tbl.num_rows, ft))
        return tbl

    def _upload_units(self, items):
        """Accumulate decoded host tables ACROSS row groups and files up
        to batch_rows; yield upload-ready units — int row counts
        (zero-column projections) or lists of host tables summing to at
        most batch_rows.  Pure host work: runs on the decode->upload
        pipeline stage when the planner inserted one."""
        acc: list[pa.Table] = []
        acc_rows = 0
        pending_count = 0  # zero-column case: rows are pure counts
        for item in items:
            if isinstance(item, int):
                pending_count += item
                if pending_count >= self.batch_rows:
                    yield pending_count
                    pending_count = 0
                continue
            acc.append(item)
            acc_rows += item.num_rows
            while acc_rows >= self.batch_rows:
                acc = self._harmonize_dicts(acc)
                tbl = pa.concat_tables(acc) if len(acc) > 1 else acc[0]
                head = tbl.slice(0, self.batch_rows)
                tail = tbl.slice(self.batch_rows)
                yield [head]
                acc = [tail] if tail.num_rows else []
                acc_rows = tail.num_rows
        if pending_count:
            yield pending_count
        if acc_rows:
            yield acc

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        """Accumulates decoded host tables ACROSS row groups and files
        up to batch_rows, then uploads each accumulated chunk in one
        transfer round: few big batches, not many small ones — on TPU
        the per-dispatch/per-transfer latency dominates small batches.

        With cross-tenant sharing on (serving/work_share.py), an
        identical scan task already decoding for another query is
        joined instead of repeated: the first arrival LEADS (decoding
        and publishing its upload units), later arrivals SUBSCRIBE
        and ride the same decode — and, while consumers overlap, the
        same uploaded device batch.  Scans with runtime filters
        registered never share (their pruning is query-dependent)."""
        conjuncts = self._conjuncts()
        self._prefilter_on = self._prefilter_active() \
            or getattr(self, "exact_prefilter", False)
        self._pa_filter = None
        if self._prefilter_on:
            from spark_rapids_tpu.io.pa_filter import compile_filter

            self._pa_filter = compile_filter(self.pushed_filter)
        # conf is THREAD-LOCAL: snapshot on the calling (session) thread
        # — task() runs on the prefetch producer thread, where get_conf()
        # would return a fresh default and silently ignore session
        # settings (decode threads, batch bytes, fastDecode)
        conf = _config.get_conf()
        self._fast_decode = conf.get(FAST_DECODE)
        self._max_batch_bytes = conf.get(MAX_READ_BATCH_BYTES)
        from spark_rapids_tpu.io.rebase import REBASE_MODE_READ

        self._rebase_mode = conf.get(REBASE_MODE_READ)

        from spark_rapids_tpu.parallel import pipeline as P

        depth = getattr(self, "_pipeline_depth", None)
        if depth is None:
            depth = P.stage_depth(conf)

        share = None
        if not self.runtime_filters:
            from spark_rapids_tpu.serving import work_share as _ws

            if _ws.scan_sharing_enabled(conf):
                from spark_rapids_tpu.plan.share_key import (
                    scan_share_key,
                )

                skey = scan_share_key(self, p, conf)
                if skey is not None:
                    share, leader = _ws.SCAN_REGISTRY.begin(skey)
                    if share is not None and not leader:
                        yield from self._subscribe_shared(
                            share, p, conf, conjuncts, depth)
                        return
        yield from self._drain_units(
            self._local_units(conf, conjuncts, p, depth), p,
            share=share)

    def _local_units(self, conf, conjuncts, p: int, depth):
        """The scan's own decode pipeline: prefetched file decode ->
        upload-unit accumulation (optionally on its own pipeline
        stage).  Every decoded item ticks the tapped decode counter —
        THE evidence shared/cached executions decode nothing."""

        def _counted(gen):
            from spark_rapids_tpu.serving.work_share import (
                record_scan_decode,
            )

            for item in gen:
                record_scan_decode(
                    item if isinstance(item, int) else item.num_rows)
                yield item

        def task():
            import os

            files = self._groups[p]
            # the pool materializes each file's decoded tables before
            # yielding, so it is bounded to files that fit one scan
            # batch (threads x batch bytes of host memory); bigger
            # files keep the one-table-at-a-time streaming path.  The
            # gate compares COMPRESSED on-disk size, so it budgets a
            # conservative 4x decode expansion (dict/RLE+snappy)
            def _size_or_big(path: str) -> int:
                # un-stat-able paths (object-store/remote URIs) must count
                # as big: excluding them would let the pool materialize
                # unbounded decoded tables, defeating the memory gate
                try:
                    return os.path.getsize(path)
                except OSError:
                    return 1 << 62

            big = any(
                _size_or_big(self.paths[fi]) >
                self._max_batch_bytes // 4
                for fi in files)
            threads = min(conf.get(SCAN_DECODE_THREADS), len(files))
            if threads <= 1 or big:
                for fi in files:
                    yield from _counted(self._file_tables(fi,
                                                          conjuncts))
                return
            # per-file decode pool with a bounded in-flight window (the
            # MultiFileCloud reader shape): file k+threads starts while
            # file k's tables are being consumed, order preserved
            from concurrent.futures import ThreadPoolExecutor

            def decode(fi):
                return list(_counted(self._file_tables(fi,
                                                       conjuncts)))

            with ThreadPoolExecutor(max_workers=threads) as pool:
                pending = []
                it = iter(files)
                for fi in it:
                    pending.append(pool.submit(decode, fi))
                    if len(pending) >= threads:
                        break
                while pending:
                    done = pending.pop(0)
                    nxt = next(it, None)
                    if nxt is not None:
                        pending.append(pool.submit(decode, nxt))
                    yield from done.result()

        from spark_rapids_tpu.parallel import pipeline as P

        units = self._upload_units(
            _prefetched(task(), stage="scan.decode", depth=depth))
        if depth:
            # decode->upload boundary: accumulation/slicing (host CPU
            # work) runs one stage ahead of the consumer's upload +
            # device compute; units are host tables (no device
            # residency crosses the stage queue)
            units = P.prefetch(units, depth=depth, stage="scan.upload")
        return units

    def _empty_scan_batch(self) -> ColumnarBatch:
        aschema = schema_to_arrow(self._schema)
        return from_arrow(pa.Table.from_arrays(
            [pa.array([], fl.type) for fl in aschema],
            schema=aschema))

    def _drain_units(self, units, p: int, share=None,
                     skip: int = 0) -> Iterator[ColumnarBatch]:
        """Upload-and-yield loop over upload units.  As the LEADER of
        a shared scan (`share` set), every unit is published for
        subscribers — plain decoded device batches ride along so
        overlapping consumers skip their own upload; wire-form
        EncodedBatches never do (donation bookkeeping makes them
        mutable).  `skip` replays a deterministic prefix without
        re-uploading it (the subscriber-fallback path: those batches
        were already served from the aborted share entry)."""
        empty = True
        completed = False
        try:
            for i, unit in enumerate(units):
                empty = False
                if i < skip:
                    continue
                # scanTime: host-unit -> device-batch (encode + upload
                # dispatch, settled when the device work completes) —
                # the reference's GpuScan scan-time metric; the decode
                # wait ahead of it lives on the scan.decode stage
                with MetricTimer(self.metrics["scanTime"],
                                 op=self.name) as t:
                    if isinstance(unit, int):
                        b = ColumnarBatch([], unit, self._schema)
                    else:
                        b = t.observe(self._upload(unit))
                if share is not None:
                    share.publish(
                        unit, b if type(b) is ColumnarBatch else None)
                yield self._count_output(b)
            completed = True
        finally:
            if share is not None:
                from spark_rapids_tpu.serving import work_share as _ws

                if completed:
                    share.complete()
                else:
                    # died or was abandoned mid-stream: wake the
                    # subscribers so they fall back to their own
                    # decode instead of waiting forever
                    share.abort()
                _ws.SCAN_REGISTRY.release(share)
        if empty and skip == 0 and p == 0:
            yield self._count_output(self._empty_scan_batch())

    def _subscribe_shared(self, share, p: int, conf, conjuncts,
                          depth) -> Iterator[ColumnarBatch]:
        """Ride another query's identical scan: replay its buffered
        upload units (and, while in flight, its uploaded device
        batches), then follow live.  If the leader aborts mid-stream,
        fall back to a local decode, skipping the deterministic
        prefix already served."""
        from spark_rapids_tpu.serving import work_share as _ws

        _ws.tick("scan_subscribes")
        consumed = 0
        aborted = False
        try:
            for unit, dev in share.subscribe_units():
                with MetricTimer(self.metrics["scanTime"],
                                 op=self.name) as t:
                    if dev is not None:
                        _ws.tick("scan_upload_shared")
                        b = dev
                    elif isinstance(unit, int):
                        b = ColumnarBatch([], unit, self._schema)
                    else:
                        b = t.observe(self._upload(unit))
                _ws.tick("scan_units_shared")
                consumed += 1
                yield self._count_output(b)
        except _ws.ScanShareAborted:
            aborted = True
        finally:
            _ws.SCAN_REGISTRY.release(share)
        if aborted:
            yield from self._drain_units(
                self._local_units(conf, conjuncts, p, depth), p,
                skip=consumed)
            return
        if consumed == 0 and p == 0:
            yield self._count_output(self._empty_scan_batch())


class OrcScanExec(ParquetScanExec):
    """ORC scan: stripes play the role of row groups (ref:
    GpuOrcScan.scala — stripe-granular reads).  Reuses the Parquet
    exec's task coalescing, host accumulation, partition pruning and
    prefetching; footer min/max stripe pruning is skipped (pyarrow does
    not expose ORC stripe statistics)."""

    def node_desc(self) -> str:
        pf = ""
        if self.pushed_filter is not None:
            pf = f" pushed=[{self.pushed_filter.name}]"
        return (f"OrcScanExec [{len(self.paths)} files, "
                f"{len(self._groups)} tasks]{pf}")

    def _file_tables(self, fi: int, conjuncts):
        import pyarrow.orc as paorc

        from spark_rapids_tpu.io.pushdown import partition_may_match

        if conjuncts is not None and self.partition_fields:
            pv = self.partition_values[fi] \
                if fi < len(self.partition_values) else {}
            if not partition_may_match(conjuncts, self._schema, pv,
                                       self.partition_fields):
                self.metrics["filesPruned"].add(1)
                return

        f = paorc.ORCFile(self.paths[fi])
        if self.columns is not None and not self.columns:
            yield from self._partition_only_tables(fi, f.nrows)
            return

        for si in range(f.nstripes):
            rb = f.read_stripe(si, columns=self.columns)
            tbl = pa.Table.from_batches([rb])
            for f2 in self.partition_fields:
                tbl = tbl.append_column(
                    f2.name,
                    self._host_partition_array(fi, f2, tbl.num_rows))
            yield self._host_prefilter(tbl)


class CsvScanExec(TpuExec):
    def __init__(self, paths: Sequence[str], schema: T.Schema,
                 batch_rows: Optional[int] = None,
                 partition_values: Optional[Sequence[dict]] = None,
                 partition_fields: Sequence[T.Field] = ()):
        super().__init__()
        self.paths = list(paths)
        self._schema = schema
        self.batch_rows = batch_rows or _conf_batch_rows()
        self.partition_values = list(partition_values or [])
        self.partition_fields = list(partition_fields)
        n_file = len(schema.fields) - len(self.partition_fields)
        self.file_aschema = schema_to_arrow(
            T.Schema(schema.fields[:n_file]))

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"CsvScanExec {self.paths}"

    @property
    def num_partitions(self) -> int:
        return len(self.paths)

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        import pyarrow.csv as pacsv

        t = pacsv.read_csv(self.paths[p]).cast(self.file_aschema)
        for off in range(0, max(t.num_rows, 1), self.batch_rows):
            chunk = t.slice(off, self.batch_rows)
            batch = from_arrow(chunk)
            if self.partition_fields:
                n = batch.concrete_num_rows()
                cap = max(batch.capacity, 1)
                cols = list(batch.columns)
                for f in self.partition_fields:
                    v = self.partition_values[p].get(f.name) \
                        if p < len(self.partition_values) else None
                    if v is not None and isinstance(f.dtype, T.LongType):
                        v = int(v)
                    cols.append(constant_column(v, f.dtype, n, cap))
                batch = ColumnarBatch(cols, batch.num_rows, self._schema)
            yield self._count_output(batch)
