"""Scan execs: host-decoded columnar reads uploaded to device.

TPU analog of the reference's scan layer (ref: GpuParquetScan.scala:84 —
CPU footer parse + device decode; GpuCSVScan at GpuBatchScanExec.scala:90).
Stage-5 design from SURVEY.md §7: pyarrow does file decode on host
(multi-threaded C++), and batches are uploaded H2D through the single
arrow seam; device-side Parquet decode (Pallas) is a later optimization.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.arrow import from_arrow, schema_to_arrow
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec


def _conf_batch_rows() -> int:
    from spark_rapids_tpu.config import BATCH_SIZE_ROWS, get_conf

    return get_conf().get(BATCH_SIZE_ROWS)


class ArrowSourceExec(TpuExec):
    """Leaf over a host Arrow table: slices it into device batches (the
    receiving end of every CPU->TPU transition, ref: HostColumnarToGpu)."""

    def __init__(self, table: pa.Table, schema: Optional[T.Schema] = None,
                 batch_rows: Optional[int] = None):
        super().__init__()
        from spark_rapids_tpu.columnar.arrow import schema_from_arrow

        self.table = table
        self._schema = schema or schema_from_arrow(table.schema)
        self.batch_rows = batch_rows or _conf_batch_rows()

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"ArrowSourceExec [{self.table.num_rows} rows]"

    @property
    def num_partitions(self) -> int:
        return max(1, -(-self.table.num_rows // self.batch_rows))

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        t = self.table
        if t.num_rows == 0:
            yield self._count_output(
                from_arrow(t.cast(schema_to_arrow(self._schema))))
            return
        chunk = t.slice(p * self.batch_rows, self.batch_rows)
        yield self._count_output(from_arrow(chunk))


class ParquetScanExec(TpuExec):
    """Reads row-group-sized record batches per file and uploads them
    (the per-file reader mode; multi-file coalescing/cloud thread pools
    of GpuParquetScan.scala:882 are a later stage)."""

    def __init__(self, paths: Sequence[str], schema: T.Schema,
                 columns: Optional[Sequence[str]] = None,
                 batch_rows: Optional[int] = None):
        super().__init__()
        self.paths = list(paths)
        self._schema = schema
        self.columns = list(columns) if columns is not None else None
        self.batch_rows = batch_rows or _conf_batch_rows()

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"ParquetScanExec {self.paths}"

    def additional_metrics(self):
        return [("scanTime", "MODERATE")]

    @property
    def num_partitions(self) -> int:
        return len(self.paths)  # one task per file (row-group splits later)

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        import pyarrow.parquet as pq

        f = pq.ParquetFile(self.paths[p])
        empty = True
        for rb in f.iter_batches(batch_size=self.batch_rows,
                                 columns=self.columns):
            empty = False
            yield self._count_output(
                from_arrow(pa.Table.from_batches([rb])))
        if empty and p == 0:
            aschema = schema_to_arrow(self._schema)
            yield self._count_output(
                from_arrow(pa.Table.from_arrays(
                    [pa.array([], fl.type) for fl in aschema],
                    schema=aschema)))


class CsvScanExec(TpuExec):
    def __init__(self, paths: Sequence[str], schema: T.Schema,
                 batch_rows: Optional[int] = None):
        super().__init__()
        self.paths = list(paths)
        self._schema = schema
        self.batch_rows = batch_rows or _conf_batch_rows()

    @property
    def schema(self) -> T.Schema:
        return self._schema

    def node_desc(self) -> str:
        return f"CsvScanExec {self.paths}"

    @property
    def num_partitions(self) -> int:
        return len(self.paths)

    def execute_partition(self, p: int) -> Iterator[ColumnarBatch]:
        import pyarrow.csv as pacsv

        t = pacsv.read_csv(self.paths[p]).cast(
            schema_to_arrow(self._schema))
        for off in range(0, max(t.num_rows, 1), self.batch_rows):
            chunk = t.slice(off, self.batch_rows)
            yield self._count_output(from_arrow(chunk))
