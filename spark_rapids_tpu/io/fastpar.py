"""Fast Parquet column-chunk decoder with filter-on-dictionary.

The reference sidesteps host decode cost by copying raw column chunks
to the GPU and decoding there with cudf kernels (ref:
GpuParquetScan.scala:495-560).  On this system the host->device link —
not host compute — is the scarce resource, so the idiomatic inversion
is: decode *and filter* on the host at native-code speed, then ship
only surviving rows across the wire (the wire encoder in
columnar/transfer.py re-packs them compactly).

What makes this faster than the general pyarrow read path:

- snappy + RLE/bit-packed decode run in the native host codec
  (native/hostcodec.cpp) with zero allocation churn;
- predicates on dictionary-encoded columns evaluate on the DICTIONARY
  (tens..thousands of values), producing a per-code boolean LUT that
  turns row filtering into one numpy gather — the classic
  late-materialization trick columnar engines use;
- non-filter columns materialize only surviving rows.

Scope (anything else returns None and the caller uses pyarrow):
- physical types INT32/INT64/FLOAT/DOUBLE, plus BYTE_ARRAY when every
  data page is dictionary-encoded;
- SNAPPY, GZIP, ZSTD or UNCOMPRESSED codecs; data page v1/v2; no
  repetition levels; definition levels with real nulls decode into a
  validity mask (null-aware filter evaluation, dict LUT kept for
  all-valid chunks).

Everything degrades per FILE: one unsupported chunk sends the whole
file down the standard path, so results are always exact.
"""

from __future__ import annotations

import struct
from typing import Optional, Sequence

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import native

# -- parquet enums ---------------------------------------------------- #

_DATA_PAGE = 0
_DICT_PAGE = 2
_DATA_PAGE_V2 = 3

_ENC_PLAIN = 0
_ENC_PLAIN_DICT = 2
_ENC_RLE = 3
_ENC_RLE_DICT = 8

_PHYS_NP = {
    "INT32": np.dtype("<i4"),
    "INT64": np.dtype("<i8"),
    "FLOAT": np.dtype("<f4"),
    "DOUBLE": np.dtype("<f8"),
}

# -- thrift compact protocol (just enough for PageHeader) ------------- #


class _Thrift:
    __slots__ = ("buf", "pos")

    def __init__(self, buf, pos: int):
        self.buf = buf
        self.pos = pos

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = int(self.buf[self.pos])  # int(): numpy uint8 would
            self.pos += 1                # wrap in the << below
            out |= (b & 0x7F) << shift
            if not (b & 0x80):
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def skip(self, ftype: int) -> None:
        if ftype in (1, 2):        # bool true/false: value in type
            return
        if ftype == 3:             # i8
            self.pos += 1
        elif ftype in (4, 5, 6):   # i16/i32/i64 zigzag varints
            self.varint()
        elif ftype == 7:           # double
            self.pos += 8
        elif ftype == 8:           # binary
            ln = self.varint()     # NOT `pos += varint()`: the left
            self.pos += ln         # operand would load pre-call pos
        elif ftype in (9, 10):     # list/set
            head = int(self.buf[self.pos])
            self.pos += 1
            size = head >> 4
            if size == 15:
                size = self.varint()
            et = head & 0x0F
            for _ in range(size):
                self.skip(et)
        elif ftype == 12:          # struct
            self.struct_fields(None)
        else:
            raise ValueError(f"thrift type {ftype}")

    def struct_fields(self, out: Optional[dict]) -> None:
        """Walk one struct; when `out` is a dict, record i32 fields."""
        fid = 0
        while True:
            head = int(self.buf[self.pos])
            self.pos += 1
            if head == 0:
                return
            delta = head >> 4
            ftype = head & 0x0F
            fid = fid + delta if delta else self.zigzag()
            if out is not None and ftype in (4, 5, 6):
                out[fid] = self.zigzag()
            elif out is not None and ftype in (1, 2):
                out[fid] = ftype == 1
            elif out is not None and ftype == 12:
                sub: dict = {}
                self.struct_fields(sub)
                out[fid] = sub
            else:
                self.skip(ftype)


def _parse_page_header(buf, pos: int):
    """-> (fields dict, new_pos).  Field ids per parquet.thrift
    PageHeader; nested page-header structs parse recursively."""
    t = _Thrift(buf, pos)
    fields: dict = {}
    t.struct_fields(fields)
    return fields, t.pos


# -- native/portable decode primitives -------------------------------- #


def _snappy_decompress(payload, out_len: int) -> Optional[np.ndarray]:
    arr = np.frombuffer(payload, np.uint8)
    # snappy block format: varint decoded-length preamble, then stream
    pos = 0
    dec_len = 0
    shift = 0
    while True:
        if pos >= len(arr):
            return None
        b = int(arr[pos])
        pos += 1
        dec_len |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    if dec_len != out_len:
        return None
    out = np.empty(out_len, np.uint8)
    lib = native.load()
    if lib is not None:
        rc = lib.snappy_raw_decompress(
            arr[pos:].ctypes.data if pos else arr.ctypes.data,
            len(arr) - pos, out.ctypes.data, out_len)
        return out if rc == 0 else None
    try:
        dec = pa.Codec("snappy").decompress(payload,
                                            decompressed_size=out_len)
    except Exception as e:
        # a RETRYABLE failure (transient resource exhaustion) must
        # reach the recovery ladder, not silently demote this file to
        # the slow pyarrow path; a corrupt/foreign stream stays a
        # clean None (the caller's fallback decodes it properly)
        from spark_rapids_tpu.execs.retry import classify

        if classify(e) == "retryable":
            raise
        return None
    return np.frombuffer(dec, np.uint8)


def _rle_decode(data: np.ndarray, bit_width: int,
                n: int) -> Optional[np.ndarray]:
    """RLE/bit-packed hybrid -> uint32[n] (native or numpy)."""
    out = np.empty(n, np.uint32)
    if n == 0:
        return out
    lib = native.load()
    if lib is not None:
        rc = lib.rle_unpack_u32(data.ctypes.data, len(data), bit_width,
                                out.ctypes.data, n)
        return out if rc == 0 else None
    # numpy fallback: sequential headers, vectorized group unpack
    pos = 0
    op = 0
    if bit_width == 0:
        out[:] = 0
        return out
    byte_w = (bit_width + 7) // 8
    while op < n:
        h = 0
        shift = 0
        while True:
            if pos >= len(data):
                return None
            b = int(data[pos])
            pos += 1
            h |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        if h & 1:
            count = (h >> 1) * 8
            nbytes = count * bit_width // 8
            grp = data[pos:pos + nbytes]
            pos += nbytes
            bits = np.unpackbits(grp, bitorder="little")
            take = min(count, n - op)
            vals = bits[:take * bit_width].reshape(take, bit_width)
            out[op:op + take] = vals @ (1 << np.arange(
                bit_width, dtype=np.uint32))
            op += take
        else:
            count = h >> 1
            v = 0
            for j in range(byte_w):
                v |= int(data[pos + j]) << (8 * j)
            pos += byte_w
            take = min(count, n - op)
            out[op:op + take] = v
            op += take
    return out


# -- column-chunk decode ---------------------------------------------- #


class FastColumn:
    """Decoded chunk: either (dict_values, codes) or plain values.
    `validity` (None = all valid) marks rows whose definition level was
    below max_def; their code/value slots hold zeros."""

    __slots__ = ("dict_values", "codes", "values", "validity")

    def __init__(self, dict_values=None, codes=None, values=None,
                 validity=None):
        self.dict_values = dict_values
        self.codes = codes
        self.values = values
        self.validity = validity

    @property
    def n(self) -> int:
        return len(self.codes) if self.codes is not None \
            else len(self.values)

    def materialize(self) -> np.ndarray:
        if self.values is not None:
            return self.values
        return self.dict_values[self.codes]

    def take(self, idx: np.ndarray) -> np.ndarray:
        if self.values is not None:
            return self.values[idx]
        return self.dict_values[self.codes[idx]]


def _decode_byte_array_dict(buf: np.ndarray, n: int):
    """PLAIN byte-array dictionary page -> numpy unicode array."""
    vals = []
    pos = 0
    mv = buf.tobytes()
    for _ in range(n):
        if pos + 4 > len(mv):
            return None
        ln = struct.unpack_from("<i", mv, pos)[0]
        pos += 4
        if ln < 0 or pos + ln > len(mv):
            return None
        vals.append(mv[pos:pos + ln])
        pos += ln
    try:
        return np.array([v.decode("utf-8") for v in vals])
    except UnicodeDecodeError:
        return None


def _decode_chunk(fh, col_meta, max_def: int,
                  max_rep: int) -> Optional[FastColumn]:
    """One column chunk (seek+read from `fh`) -> FastColumn, or None
    (unsupported)."""
    if max_rep > 0:
        return None
    phys = col_meta.physical_type
    is_ba = phys == "BYTE_ARRAY"
    np_dt = _PHYS_NP.get(phys)
    if np_dt is None and not is_ba:
        return None
    codec = col_meta.compression
    if codec not in ("SNAPPY", "UNCOMPRESSED", "GZIP", "ZSTD"):
        return None
    n_total = col_meta.num_values

    start = col_meta.data_page_offset
    if col_meta.has_dictionary_page \
            and col_meta.dictionary_page_offset is not None:
        start = min(start, col_meta.dictionary_page_offset)
    fh.seek(start)
    seg = np.frombuffer(fh.read(col_meta.total_compressed_size),
                        np.uint8)
    if len(seg) < col_meta.total_compressed_size:
        return None

    pos = 0
    dict_values = None
    code_parts: list = []
    plain_parts: list = []  # (order, np values)
    order: list = []        # 'dict'/'plain' per data page, in order
    valid_parts: list = []  # per data page bool[n_vals] or None
    seen = 0
    def_bw = max(1, (max_def).bit_length()) if max_def > 0 else 0

    while seen < n_total and pos < len(seg):
        hdr, body = _parse_page_header(seg, pos)
        comp_sz = hdr.get(3)
        uncomp_sz = hdr.get(2)
        ptype = hdr.get(1)
        if comp_sz is None or uncomp_sz is None or ptype is None:
            return None
        payload = seg[body:body + comp_sz]
        pos = body + comp_sz
        if ptype == _DICT_PAGE:
            dh = hdr.get(7, {})
            if dh.get(2, _ENC_PLAIN) not in (_ENC_PLAIN,
                                             _ENC_PLAIN_DICT):
                return None
            buf = _page_bytes(payload, uncomp_sz, codec)
            if buf is None:
                return None
            n_dict = dh.get(1, 0)
            if is_ba:
                dict_values = _decode_byte_array_dict(buf, n_dict)
            else:
                dict_values = np.frombuffer(
                    buf, np_dt, count=n_dict).copy()
            if dict_values is None:
                return None
            continue
        if ptype == _DATA_PAGE:
            dh = hdr.get(5)
            if dh is None:
                return None
            n_vals = dh.get(1, 0)
            enc = dh.get(2, _ENC_PLAIN)
            if dh.get(3, _ENC_RLE) != _ENC_RLE and max_def > 0:
                return None
            buf = _page_bytes(payload, uncomp_sz, codec)
            if buf is None:
                return None
            off = 0
            page_valid = None
            if max_def > 0:
                if len(buf) < 4:
                    return None
                dl_len = struct.unpack_from("<i", buf.tobytes()[:4])[0]
                dl = buf[4:4 + dl_len]
                off = 4 + dl_len
                if not _def_levels_all_valid(dl, def_bw, n_vals,
                                             max_def):
                    page_valid = _decode_validity(dl, def_bw, n_vals,
                                                  max_def)
                    if page_valid is None:
                        return None
        elif ptype == _DATA_PAGE_V2:
            dh = hdr.get(8)
            if dh is None:
                return None
            n_vals = dh.get(1, 0)
            n_nulls = dh.get(2, 0)
            enc = dh.get(4, _ENC_PLAIN)
            dl_len = dh.get(5, 0)
            rl_len = dh.get(6, 0)
            if rl_len:
                return None
            # v2: levels are NOT compressed and precede the values
            compressed = dh.get(7, True) and codec != "UNCOMPRESSED"
            if compressed:
                levels = payload[:dl_len]
                vals_part = _page_bytes(payload[dl_len:],
                                        uncomp_sz - dl_len, codec)
                if vals_part is None:
                    return None
            else:
                levels = payload[:dl_len]
                vals_part = payload[dl_len:]
            page_valid = None
            if max_def > 0 and (
                    n_nulls or (dl_len and not _def_levels_all_valid(
                        levels, def_bw, n_vals, max_def))):
                if not dl_len:
                    # nulls recorded but no definition levels: a
                    # nonconforming page — degrade, never misread
                    return None
                page_valid = _decode_validity(levels, def_bw, n_vals,
                                              max_def)
                if page_valid is None:
                    return None
            buf = vals_part
            off = 0
        else:
            return None

        vals = buf[off:]
        # with nulls, the value stream holds PRESENT entries only:
        # decode n_present then scatter into the page's n_vals slots
        n_present = int(page_valid.sum()) if page_valid is not None \
            else n_vals
        if enc in (_ENC_RLE_DICT, _ENC_PLAIN_DICT):
            if len(vals) < 1:
                return None
            bw = int(vals[0])
            codes = _rle_decode(vals[1:], bw, n_present)
            if codes is None:
                return None
            if page_valid is not None:
                full = np.zeros(n_vals, np.uint32)
                full[page_valid] = codes
                codes = full
            code_parts.append(codes)
            order.append("dict")
        elif enc == _ENC_PLAIN and not is_ba:
            arr = np.frombuffer(vals.tobytes(), np_dt, count=n_present)
            if page_valid is not None:
                full = np.zeros(n_vals, np_dt)
                full[page_valid] = arr
                arr = full
            plain_parts.append(arr)
            order.append("plain")
        else:
            return None
        valid_parts.append(page_valid)
        seen += n_vals

    if seen != n_total:
        return None
    validity = None
    if any(v is not None for v in valid_parts):
        counts = []
        di = pi = 0
        for kind in order:
            if kind == "dict":
                counts.append(len(code_parts[di]))
                di += 1
            else:
                counts.append(len(plain_parts[pi]))
                pi += 1
        validity = np.concatenate(
            [v if v is not None else np.ones(c, bool)
             for v, c in zip(valid_parts, counts)])
    if plain_parts and not code_parts:
        return FastColumn(values=np.concatenate(plain_parts)
                          if len(plain_parts) > 1 else
                          np.asarray(plain_parts[0]),
                          validity=validity)
    if code_parts and not plain_parts:
        if dict_values is None:
            return None
        codes = np.concatenate(code_parts) \
            if len(code_parts) > 1 else code_parts[0]
        if codes.size and int(codes.max()) >= len(dict_values):
            return None
        return FastColumn(dict_values=dict_values, codes=codes,
                          validity=validity)
    if not code_parts and not plain_parts:
        return None
    # mixed dict->plain fallback within one chunk: materialize
    if dict_values is None or is_ba:
        return None
    di = pi = 0
    parts = []
    for kind in order:
        if kind == "dict":
            c = code_parts[di]
            di += 1
            if c.size and int(c.max()) >= len(dict_values):
                return None
            parts.append(dict_values[c])
        else:
            parts.append(plain_parts[pi])
            pi += 1
    return FastColumn(values=np.concatenate(parts), validity=validity)


def _page_bytes(payload: np.ndarray, uncomp_sz: int,
                codec: str) -> Optional[np.ndarray]:
    if codec == "UNCOMPRESSED":
        return payload
    if codec == "SNAPPY":
        return _snappy_decompress(payload.tobytes(), uncomp_sz)
    if codec in ("GZIP", "ZSTD"):
        try:
            dec = pa.Codec(codec.lower()).decompress(
                payload.tobytes(), decompressed_size=uncomp_sz)
        except Exception:
            return None
        return np.frombuffer(dec, np.uint8)
    return None


def _decode_validity(levels: np.ndarray, bw: int, n: int,
                     max_def: int) -> Optional[np.ndarray]:
    """Definition levels -> bool[n] validity (True = value present)."""
    dl = _rle_decode(levels, bw, n)
    if dl is None:
        return None
    return dl == max_def


def _def_levels_all_valid(dl: np.ndarray, bw: int, n: int,
                          max_def: int) -> bool:
    """True iff every definition level == max_def (no nulls)."""
    if n == 0:
        return True
    # fast path: a single repeated run covering all n values
    if len(dl) >= 1:
        h = 0
        shift = 0
        pos = 0
        ok = True
        while pos < len(dl):
            b = int(dl[pos])
            pos += 1
            h |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        else:
            ok = False
        if ok and not (h & 1) and (h >> 1) >= n:
            byte_w = (bw + 7) // 8
            if pos + byte_w <= len(dl):
                v = 0
                for j in range(byte_w):
                    v |= int(dl[pos + j]) << (8 * j)
                return v == max_def
    levels = _rle_decode(dl, bw, n)
    if levels is None:
        return False
    return bool((levels == max_def).all())


# -- file-level read + filter ----------------------------------------- #


def read_file(path: str, keep_rgs: Sequence[int],
              columns: Sequence[str], conjuncts,
              engine_schema, pqfile=None,
              max_decoded_bytes: Optional[int] = None,
              runtime_filters=None, counters: Optional[dict] = None
              ) -> Optional[list]:
    """Decode + filter one file -> list of pa.Table (survivor rows,
    one per row group), or None when any part is unsupported.

    `conjuncts` (may be None) are the pushed filter's AND legs; legs
    referencing exactly one decoded column are applied here (on the
    dictionary when possible), the rest are left for the device
    Filter — the result is conservative, never wrong.

    `runtime_filters` ([(column_name, RuntimeFilter)], may be None) are
    build-side join-key filters (plan/runtime_filter.py application
    point 2): probed per DICTIONARY value when the chunk is
    dict-encoded — a per-code LUT turning key-reachability filtering
    into one numpy gather — else per value.  Rows they drop (beyond
    what the conjuncts already dropped) are counted into
    ``counters["rf_pruned"]``.

    Reads each needed column chunk with seek+read (never the whole
    file) and refuses any row group whose decoded size exceeds
    `max_decoded_bytes`, so peak host memory stays bounded by the same
    budget the standard streaming path honors."""
    import pyarrow.parquet as pq

    try:
        f = pqfile if pqfile is not None else pq.ParquetFile(path)
        arrow_types = {fl.name: fl.type for fl in f.schema_arrow}
    except Exception:
        return None
    md = f.metadata
    pq_schema = md.schema
    name_to_idx = {}
    for i in range(len(pq_schema)):
        sc = pq_schema.column(i)
        name_to_idx[sc.path] = i
    needed = list(columns)
    filter_cols = _conjunct_columns(conjuncts, engine_schema) \
        if conjuncts else {}
    for c in filter_cols:
        if c not in needed and c in name_to_idx:
            needed.append(c)
    rfs = [(n, rf) for n, rf in (runtime_filters or [])
           if n in name_to_idx]
    if counters is not None and runtime_filters:
        # True until proven otherwise: a filter column missing from the
        # file (e.g. a partition column) or any per-group application
        # gap flips it, and the caller must then re-probe post-decode
        counters["rf_complete"] = len(rfs) == len(runtime_filters)
    for c, _rf in rfs:
        if c not in needed:
            needed.append(c)
    for c in needed:
        if c not in name_to_idx:
            return None

    out: list = []
    with open(path, "rb") as fh:
        for rg in keep_rgs:
            rg_meta = md.row_group(rg)
            if max_decoded_bytes is not None:
                decoded = sum(
                    rg_meta.column(name_to_idx[c]).total_uncompressed_size
                    for c in needed)
                if decoded > max_decoded_bytes:
                    return None
            cols: dict = {}
            for name in needed:
                ci = name_to_idx[name]
                sc = pq_schema.column(ci)
                fc = _decode_chunk(fh, rg_meta.column(ci),
                                   sc.max_definition_level,
                                   sc.max_repetition_level)
                if fc is None:
                    return None
                cols[name] = fc
            tbl = _filter_project(cols, filter_cols, rg_meta.num_rows,
                                  engine_schema, columns, arrow_types,
                                  runtime_filters=rfs,
                                  counters=counters)
            if tbl is None:
                return None
            out.append(tbl)
    return out


def _eval_runtime_filter_mask(cols: dict, rfs
                              ) -> tuple[Optional[np.ndarray], bool]:
    """(AND of the runtime filters' keep masks over decoded chunks,
    complete) — dict-encoded chunks probe the dictionary once (per-code
    LUT), plain chunks probe values.  ``complete`` is True only when
    EVERY filter produced a mask, letting the scan skip the redundant
    point-3 re-probe of these rows.  mask None = nothing applied."""
    mask = None
    complete = True
    for name, rf in rfs:
        fc = cols.get(name)
        if fc is None:
            return None, False  # partial would miscount pruning
        try:
            if fc.codes is not None:
                dv = np.asarray(fc.dict_values)
                if not np.issubdtype(dv.dtype, np.integer):
                    complete = False
                    continue
                lut = rf.probe_host(dv.astype(np.int64))
                m = lut[fc.codes]
                if fc.validity is not None:
                    m = np.where(fc.validity, m, False)
            else:
                vals = fc.values
                if not np.issubdtype(vals.dtype, np.integer):
                    complete = False
                    continue
                m = rf.probe_host(vals.astype(np.int64), fc.validity)
        except Exception:
            complete = False
            continue
        mask = m if mask is None else (mask & m)
    return mask, complete


def _filter_project(cols, filter_cols, n_rows, engine_schema, columns,
                    arrow_types, runtime_filters=(),
                    counters: Optional[dict] = None
                    ) -> Optional[pa.Table]:
    mask = _eval_filter_mask(cols, filter_cols, n_rows, engine_schema)
    if runtime_filters:
        rf_mask, rf_complete = _eval_runtime_filter_mask(
            cols, runtime_filters)
        if counters is not None and not rf_complete:
            counters["rf_complete"] = False
        if rf_mask is not None:
            base = mask if mask is not None else np.ones(n_rows, bool)
            rf_pruned = int((base & ~rf_mask).sum())
            if counters is not None and rf_pruned:
                counters["rf_pruned"] = counters.get("rf_pruned", 0) \
                    + rf_pruned
            mask = base & rf_mask
    if mask is None:
        idx = None
    else:
        idx = np.flatnonzero(mask)
        if idx.size == n_rows:
            idx = None
    arrays = []
    for name in columns:
        fc = cols[name]
        validity = fc.validity
        if idx is not None and validity is not None:
            validity = validity[idx]
        if fc.codes is not None and len(fc.dict_values) <= 0xFFFF:
            # keep the PARQUET dictionary: ship codes + dict values as a
            # pa.DictionaryArray so the wire encoder maps them straight
            # to its dict entries — no host materialization of the full
            # column and no re-dictionary_encode (the dominant host
            # costs of dict-heavy scans)
            codes = fc.codes if idx is None else fc.codes[idx]
            try:
                dvals = pa.array(fc.dict_values)
                want = arrow_types.get(name)
                if want is not None and dvals.type != want:
                    dvals = dvals.cast(want)  # cast the SMALL dict side
                null_mask = None if validity is None else ~validity
                arrays.append(pa.DictionaryArray.from_arrays(
                    pa.array(codes.astype(np.int32), mask=null_mask),
                    dvals))
                continue
            except Exception:
                pass  # fall through to materialized path
        vals = fc.materialize() if idx is None else fc.take(idx)
        arr = pa.array(vals, mask=None if validity is None
                       else ~validity)
        want = arrow_types.get(name)
        if want is not None and arr.type != want:
            # physical->logical mapping (int32 -> date32,
            # int64 -> timestamp[...], ...): a pure reinterpret
            try:
                arr = arr.cast(want)
            except Exception:
                return None
        arrays.append(arr)
    return pa.Table.from_arrays(arrays, list(columns))


def _conjunct_columns(conjuncts, engine_schema) -> dict:
    """{col_name: [conjunct exprs referencing ONLY that column]}."""
    from spark_rapids_tpu.exprs import base as B

    by_col: dict = {}
    for conj in conjuncts:
        refs = set()
        stack = [conj]
        while stack:
            e = stack.pop()
            if isinstance(e, B.ColumnReference):
                refs.add(e.col_name)
            elif isinstance(e, B.BoundReference):
                refs.add(engine_schema.fields[e.ordinal].name)
            stack.extend(e.children)
        if len(refs) == 1:
            by_col.setdefault(next(iter(refs)), []).append(conj)
    return by_col


def _eval_table(name: str, values, engine_schema) -> pa.Table:
    """A table the compiled filter can evaluate `name`'s conjunct on:
    bound conjuncts index columns by ORDINAL, so the real values sit at
    the column's schema position, nulls elsewhere."""
    if engine_schema is None:
        return pa.table({name: pa.array(values)})
    arr = pa.array(values)
    arrays = []
    names = []
    for f in engine_schema.fields:
        names.append(f.name)
        arrays.append(arr if f.name == name
                      else pa.nulls(len(arr), arr.type))
    return pa.Table.from_arrays(arrays, names)


def _eval_filter_mask(cols: dict, filter_cols: dict, n_rows: int,
                      engine_schema) -> Optional[np.ndarray]:
    """AND of all single-column conjunct masks; None = keep all."""
    from spark_rapids_tpu.io.pa_filter import compile_filter

    mask = None
    for name, conjs in filter_cols.items():
        fc = cols.get(name)
        if fc is None:
            continue
        for conj in conjs:
            fn = compile_filter(conj)
            if fn is None:
                continue  # device filter will handle it
            try:
                if fc.codes is not None:
                    # evaluate on the dictionary -> per-code LUT; null
                    # rows take the conjunct's NULL-INPUT result
                    # (False for ordinary predicates, True for IS NULL)
                    t = _eval_table(name, fc.dict_values, engine_schema)
                    lut = np.asarray(fn(t)).astype(bool)
                    m = lut[fc.codes]
                    if fc.validity is not None:
                        import pyarrow.compute as _pc

                        nt = _eval_table(
                            name,
                            pa.array([None],
                                     type=pa.array(
                                         fc.dict_values).type),
                            engine_schema)
                        res = fn(nt)
                        if isinstance(res, pa.ChunkedArray):
                            res = res.combine_chunks()
                        keep_null = bool(
                            _pc.fill_null(res, False)[0].as_py())
                        m = np.where(fc.validity, m, keep_null)
                else:
                    vals = fc.materialize()
                    arr = pa.array(vals, mask=None
                                   if fc.validity is None
                                   else ~fc.validity)
                    t = _eval_table(name, arr, engine_schema)
                    m = np.asarray(fn(t).fill_null(False)).astype(bool)
            except Exception:
                continue
            mask = m if mask is None else (mask & m)
    return mask
