"""Pushed-filter compilation to pyarrow.compute.

The scan's host prefilter (io/scan.py) must run at decode speed: the
CPU engine's cpu_eval is a per-batch Python/numpy interpreter (built
for oracle fidelity, not throughput), while pyarrow.compute kernels are
multi-threaded C++ that release the GIL — the same division of labor
the reference gets from Arrow-native filtering before device transfer.

`compile_filter` translates the supported predicate subset (column
refs, literals, comparisons, boolean connectives, null checks, IN
lists) into a callable `table -> bool Array`; anything outside the
subset returns None and the caller falls back to cpu_eval.  SQL
semantics note: the caller treats null mask slots as FALSE (rows only
survive a Filter when the condition is TRUE), so kernels here may
propagate nulls freely.
"""

from __future__ import annotations

from typing import Callable, Optional

import pyarrow.compute as pc

from spark_rapids_tpu.exprs import base as B


def compile_filter(e) -> Optional[Callable]:
    """expr -> (table -> pa.BooleanArray), or None when unsupported."""
    try:
        fn = _compile(e)
    except _Unsupported:
        return None
    return fn


class _Unsupported(Exception):
    pass


_CMP = {
    "GreaterThan": pc.greater,
    "GreaterThanOrEqual": pc.greater_equal,
    "LessThan": pc.less,
    "LessThanOrEqual": pc.less_equal,
    "EqualTo": pc.equal,
    "NotEqual": pc.not_equal,
}


def _compile(e) -> Callable:
    name = type(e).__name__
    if isinstance(e, B.BoundReference):
        i = e.ordinal
        return lambda t: t.column(i)
    if isinstance(e, B.Literal):
        v = e.value
        return lambda t: v
    if name in _CMP:
        kids = _children(e)
        if len(kids) != 2:
            raise _Unsupported
        lf, rf = _compile(kids[0]), _compile(kids[1])
        if any(getattr(k, "dtype", None) is not None
               and type(k.dtype).__name__ in ("FloatType", "DoubleType")
               for k in kids):
            # Spark float total order (predicates.py:53): NaN == NaN is
            # true and NaN sorts greater than everything — IEEE kernels
            # would silently drop NaN rows the device Filter keeps
            return _float_cmp(name, lf, rf)
        op = _CMP[name]
        return lambda t: op(lf(t), rf(t))
    if name == "And":
        kids = _children(e)
        lf, rf = _compile(kids[0]), _compile(kids[1])
        return lambda t: pc.and_kleene(lf(t), rf(t))
    if name == "Or":
        kids = _children(e)
        lf, rf = _compile(kids[0]), _compile(kids[1])
        return lambda t: pc.or_kleene(lf(t), rf(t))
    if name == "Not":
        kf = _compile(_children(e)[0])
        return lambda t: pc.invert(kf(t))
    if name == "IsNull":
        kf = _compile(_children(e)[0])
        return lambda t: pc.is_null(kf(t))
    if name == "IsNotNull":
        kf = _compile(_children(e)[0])
        return lambda t: pc.is_valid(kf(t))
    if name == "In":
        kids = _children(e)
        kf = _compile(kids[0])
        vals = getattr(e, "values", None)
        if vals is None or not all(isinstance(v, B.Literal)
                                   for v in vals):
            raise _Unsupported
        import pyarrow as pa

        vset = pa.array([v.value for v in vals])
        return lambda t: pc.is_in(kf(t), value_set=vset)
    raise _Unsupported


def _float_cmp(name: str, lf: Callable, rf: Callable) -> Callable:
    def nan(x):
        try:
            return pc.is_nan(x)
        except Exception:
            return False  # integer literal side: never NaN

    def fn(t):
        l, r = lf(t), rf(t)
        ln, rn = nan(l), nan(r)
        eq = pc.or_kleene(pc.equal(l, r), pc.and_kleene(ln, rn)) \
            if ln is not False and rn is not False \
            else pc.equal(l, r)
        lt = pc.less(l, r)
        if rn is not False:
            not_ln = pc.invert(ln) if ln is not False else True
            lt = pc.or_kleene(lt, pc.and_kleene(not_ln, rn)
                              if not_ln is not True else rn)
        if name == "EqualTo":
            return eq
        if name == "NotEqual":
            return pc.invert(eq)
        if name == "LessThan":
            return lt
        if name == "LessThanOrEqual":
            return pc.or_kleene(lt, eq)
        if name == "GreaterThan":
            return pc.invert(pc.or_kleene(lt, eq))
        return pc.invert(lt)  # GreaterThanOrEqual

    return fn


def _int64_values(arr) -> Optional[tuple]:
    """pa array -> (np.int64 values, np.bool validity) in the engine's
    integer key representation; None when not losslessly convertible."""
    import numpy as np
    import pyarrow as pa

    t = arr.type
    try:
        if pa.types.is_date32(t):
            arr = arr.cast(pa.int32())
        elif pa.types.is_timestamp(t):
            arr = arr.cast(pa.int64())
        elif not (pa.types.is_integer(t)):
            return None
        valid = np.asarray(pc.is_valid(arr))
        vals = np.asarray(arr.fill_null(0).cast(pa.int64()))
        return vals, valid
    except Exception:
        return None


def runtime_filter_column_mask(col, rf):
    """Host-side runtime-filter probe of one table column -> np.bool
    keep mask, or None when the column shape is outside the probe's
    scope (caller then skips this filter — pruning is best-effort,
    never semantics).

    Dictionary-encoded columns probe the DICTIONARY once and gather by
    code (the fastpar LUT trick at the arrow layer); plain columns
    probe values directly.  This is application point 3 of
    plan/runtime_filter.py — the post-decode mask in the hostFilter
    path."""
    import numpy as np
    import pyarrow as pa

    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    if pa.types.is_dictionary(col.type):
        dv = _int64_values(col.dictionary)
        if dv is None:
            return None
        lut = rf.probe_host(dv[0], dv[1])
        codes = col.indices
        code_valid = np.asarray(pc.is_valid(codes))
        code_vals = np.asarray(codes.fill_null(0)).astype(np.int64)
        return np.where(code_valid, lut[code_vals], False)
    v = _int64_values(col)
    if v is None:
        return None
    return rf.probe_host(v[0], v[1])


def _children(e):
    kids = getattr(e, "children", None)
    if kids is None:
        import dataclasses

        if dataclasses.is_dataclass(e):
            kids = [v for v in
                    (getattr(e, f.name)
                     for f in dataclasses.fields(e))
                    if isinstance(v, B.Expression)]
        else:
            raise _Unsupported
    return list(kids)
