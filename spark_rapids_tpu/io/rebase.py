"""Datetime rebase policy for Parquet reads.

Counterpart of the reference's RebaseHelper + GpuParquetScan rebase
gating (ref: com/nvidia/spark/RebaseHelper.scala,
GpuParquetScan.scala:226-241): files written by Spark 2.x — or by
Spark 3.x in LEGACY mode (the `org.apache.spark.legacyDateTime` file
metadata marker) — carry hybrid Julian/Gregorian datetimes that would
silently read shifted for pre-1582 values.  Policy mirrors Spark's
`datetimeRebaseModeInRead`:

- EXCEPTION (default): legacy-calendar files with date/timestamp
  columns are refused with guidance;
- CORRECTED: values are trusted as proleptic Gregorian (correct for
  post-1582 data, the overwhelmingly common case);
- LEGACY rebase arithmetic is not implemented (falls under EXCEPTION).
"""

from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import register

_SPARK_VERSION_KEY = b"org.apache.spark.version"
_SPARK_LEGACY_KEY = b"org.apache.spark.legacyDateTime"

REBASE_MODE_READ = register(
    "spark.rapids.tpu.sql.parquet.datetimeRebaseModeInRead", "EXCEPTION",
    "Handling of Parquet files written under the legacy hybrid "
    "Julian/Gregorian calendar (Spark 2.x, or Spark 3.x LEGACY mode): "
    "EXCEPTION refuses them when the read includes date/timestamp "
    "columns; CORRECTED trusts the stored values as proleptic "
    "Gregorian (the spark.sql.parquet.datetimeRebaseModeInRead "
    "analog; ref: RebaseHelper.scala + GpuParquetScan.scala:226).",
    check=lambda v: v in ("EXCEPTION", "CORRECTED"))


def file_is_legacy_calendar(file_metadata) -> bool:
    """True when the file's key-value metadata marks hybrid-calendar
    datetimes (the isCorrectedRebaseMode logic, inverted)."""
    kv = file_metadata.metadata or {}
    version = kv.get(_SPARK_VERSION_KEY)
    if version is None:
        return False  # not Spark-written: proleptic (pyarrow et al.)
    if kv.get(_SPARK_LEGACY_KEY) is not None:
        return True  # Spark 3.x LEGACY mode marker
    return version.decode(errors="replace") < "3.0.0"


def check_rebase(path: str, file_metadata, schema: T.Schema,
                 mode: str) -> None:
    """Raise under EXCEPTION mode for legacy-calendar files whose read
    touches datetime columns."""
    if mode == "CORRECTED":
        return

    def has_dt(dt: T.DataType) -> bool:
        # recurse like Spark's dataTypeExistsRecursively: nested
        # datetimes (list<timestamp>, struct fields, map values) are
        # just as rebase-sensitive as top-level ones
        if isinstance(dt, (T.DateType, T.TimestampType)):
            return True
        if isinstance(dt, T.ListType):
            return has_dt(dt.element)
        if isinstance(dt, T.StructType):
            return any(has_dt(f.dtype) for f in dt.fields)
        if isinstance(dt, T.MapType):
            return has_dt(dt.key) or has_dt(dt.value)
        return False

    has_datetime = any(has_dt(f.dtype) for f in schema.fields)
    if has_datetime and file_is_legacy_calendar(file_metadata):
        raise ValueError(
            f"Parquet file {path!r} was written with the legacy hybrid "
            "Julian/Gregorian calendar; pre-1582 datetimes would read "
            "shifted. Set "
            "spark.rapids.tpu.sql.parquet.datetimeRebaseModeInRead="
            "CORRECTED to read the stored values as proleptic "
            "Gregorian (ref: Spark's datetimeRebaseModeInRead).")
