"""Scan predicate pushdown: row-group and partition pruning.

TPU analog of the reference's CPU-side Parquet filtering
(ref: GpuParquetScan.scala:263-306 GpuParquetFileFilterHandler.
filterBlocks — footer statistics decide which row groups are read at
all) plus Hive partition pruning on the discovered partition values.

The pushed predicate is the scan-adjacent Filter's condition; pruning is
conservative (a row group is skipped only when its stats PROVE no row
can match), and the Filter still runs exactly afterwards — pushdown is
an IO optimization, never a semantics change."""

from __future__ import annotations

import math
from typing import Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs import base as B
from spark_rapids_tpu.exprs import predicates as P


def split_conjuncts(e: B.Expression) -> list[B.Expression]:
    if isinstance(e, P.And):
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def _col_name(e: B.Expression, schema: T.Schema) -> Optional[str]:
    if isinstance(e, B.BoundReference):
        return schema.fields[e.ordinal].name
    if isinstance(e, B.ColumnReference):
        return e.col_name
    return None


def _lit_value(e: B.Expression):
    if isinstance(e, B.Literal) and e.value is not None:
        return e.value
    return None


_FLIP = {P.LessThan: P.GreaterThan, P.LessThanOrEqual: P.GreaterThanOrEqual,
         P.GreaterThan: P.LessThan, P.GreaterThanOrEqual: P.LessThanOrEqual,
         P.EqualTo: P.EqualTo}


def _as_col_op_lit(conj: B.Expression, schema: T.Schema):
    """Normalize a conjunct to (col_name, op_class, literal) or None."""
    if type(conj) not in (P.LessThan, P.LessThanOrEqual, P.GreaterThan,
                          P.GreaterThanOrEqual, P.EqualTo):
        return None
    name = _col_name(conj.left, schema)
    v = _lit_value(conj.right)
    if name is not None and v is not None:
        return name, type(conj), v
    name = _col_name(conj.right, schema)
    v = _lit_value(conj.left)
    if name is not None and v is not None:
        return name, _FLIP[type(conj)], v
    return None


def _range_may_match(op, v, lo, hi) -> bool:
    """Could any x in [lo, hi] satisfy `x op v`?  Conservative: any
    comparison error (mismatched python types) keeps the range."""
    try:
        # NaN anywhere (literal OR footer stats) -> comparisons are
        # unordered garbage; keep the row group
        for x in (v, lo, hi):
            if isinstance(x, float) and math.isnan(x):
                return True
        if op is P.LessThan:
            return lo < v
        if op is P.LessThanOrEqual:
            return lo <= v
        if op is P.GreaterThan:
            return hi > v
        if op is P.GreaterThanOrEqual:
            return hi >= v
        if op is P.EqualTo:
            return lo <= v <= hi
    except TypeError:
        return True
    return True


def row_group_may_match(conjuncts: Sequence[B.Expression],
                        schema: T.Schema, rg_meta) -> bool:
    """False only when the row group's footer statistics prove no row
    matches every conjunct (ref: filterBlocks' min/max checks)."""
    stats_by_name = {}
    nrows = rg_meta.num_rows
    for ci in range(rg_meta.num_columns):
        col = rg_meta.column(ci)
        name = col.path_in_schema.split(".")[0]
        stats_by_name[name] = col.statistics
    for conj in conjuncts:
        if isinstance(conj, P.IsNull):
            name = _col_name(conj.child, schema)
            st = stats_by_name.get(name)
            if st is not None and st.null_count is not None \
                    and st.null_count == 0:
                return False
            continue
        if isinstance(conj, P.IsNotNull):
            name = _col_name(conj.child, schema)
            st = stats_by_name.get(name)
            if st is not None and st.null_count is not None \
                    and st.null_count >= nrows:
                return False
            continue
        norm = _as_col_op_lit(conj, schema)
        if norm is None:
            continue
        name, op, v = norm
        st = stats_by_name.get(name)
        if st is None or not st.has_min_max:
            continue
        v = _coerce_like(v, st.min)
        if not _range_may_match(op, v, st.min, st.max):
            return False
        # a comparison also implies the column is non-NULL
        if st.null_count is not None and st.null_count >= nrows:
            return False
    return True


def _coerce_like(v, stat_sample):
    """Align literal representation with pyarrow's stat values (dates
    come back as datetime.date; our date literals are epoch days)."""
    import datetime

    if isinstance(stat_sample, datetime.date) \
            and not isinstance(stat_sample, datetime.datetime) \
            and isinstance(v, int):
        return datetime.date(1970, 1, 1) + datetime.timedelta(days=v)
    return v


def _stat_to_int(v) -> Optional[int]:
    """Footer stat value -> the engine's integer key representation
    (epoch days / epoch micros / plain int); None = not convertible
    (conservative: caller keeps the row group)."""
    import datetime

    if isinstance(v, bool):
        return None
    if isinstance(v, int):
        return v
    if isinstance(v, datetime.datetime):
        epoch = datetime.datetime(1970, 1, 1, tzinfo=v.tzinfo)
        d = v - epoch
        # exact integer micros: float total_seconds() rounds at ~0.25us,
        # enough to shift a boundary stat and wrongly prune a row group
        return (d.days * 86_400_000_000 + d.seconds * 1_000_000
                + d.microseconds)
    if isinstance(v, datetime.date):
        return (v - datetime.date(1970, 1, 1)).days
    return None


def runtime_range_may_match(name: str, rf, rg_meta) -> bool:
    """Runtime-filter min/max vs a row group's footer statistics: False
    only when the stats PROVE no row's key can fall in the filter's
    [min, max] (plan/runtime_filter.py application point 1 — pruned
    row groups are never decoded).  An empty build side proves no key
    matches anywhere, stats or not."""
    if not rf.ready:
        return True
    if rf.n_keys == 0:
        return False
    st = None
    for ci in range(rg_meta.num_columns):
        col = rg_meta.column(ci)
        if col.path_in_schema.split(".")[0] == name:
            st = col.statistics
            break
    if st is None or not st.has_min_max:
        return True
    lo, hi = _stat_to_int(st.min), _stat_to_int(st.max)
    if lo is None or hi is None:
        return True
    return rf.range_may_match(lo, hi)


def partition_may_match(conjuncts: Sequence[B.Expression],
                        schema: T.Schema, part_values: dict,
                        part_fields: Sequence[T.Field]) -> bool:
    """Hive partition pruning: partition values are EXACT, so any
    violated conjunct on a partition column eliminates the whole file."""
    typed = {}
    for f in part_fields:
        v = part_values.get(f.name)
        if v is not None and isinstance(f.dtype, T.LongType):
            v = int(v)
        typed[f.name] = v
    for conj in conjuncts:
        if isinstance(conj, P.IsNull):
            name = _col_name(conj.child, schema)
            if name in typed and typed[name] is not None:
                return False
            continue
        if isinstance(conj, P.IsNotNull):
            name = _col_name(conj.child, schema)
            if name in typed and typed[name] is None:
                return False
            continue
        norm = _as_col_op_lit(conj, schema)
        if norm is None:
            continue
        name, op, v = norm
        if name not in typed:
            continue
        pv = typed[name]
        if pv is None:
            return False  # NULL partition value fails any comparison
        if not _range_may_match(op, v, pv, pv):
            return False
    return True
