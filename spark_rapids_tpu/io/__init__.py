"""I/O: scans and writers (ref layer: SURVEY.md §2.8)."""
