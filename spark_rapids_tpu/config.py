"""Self-documenting config registry.

TPU re-design of the reference's RapidsConf
(ref: sql-plugin/src/main/scala/com/nvidia/spark/rapids/RapidsConf.scala:190-270):
a typed ConfBuilder registry where every entry carries a doc string, a
default, an optional value-check, and an `internal` flag; `help_text()`
generates the configs doc the way RapidsConf.help generates docs/configs.md.
Per-operator / per-expression kill-switch keys (spark.rapids.sql.exec.* /
expression.* in the reference, RapidsMeta.scala:35-46) are registered
dynamically by the planner's replacement rules under
`spark.rapids.tpu.sql.exec.*` / `...expression.*`.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Callable, Optional


@dataclasses.dataclass
class ConfEntry:
    key: str
    default: Any
    doc: str
    conv: Callable[[str], Any]
    internal: bool = False
    check: Optional[Callable[[Any], bool]] = None

    def convert(self, raw: Any) -> Any:
        v = self.conv(raw) if isinstance(raw, str) else raw
        if self.check is not None and not self.check(v):
            raise ValueError(f"invalid value {v!r} for {self.key}")
        return v


_REGISTRY: dict[str, ConfEntry] = {}
_REG_LOCK = threading.Lock()


def _to_bool(s: str) -> bool:
    return s.strip().lower() in ("true", "1", "yes")


def register(key: str, default: Any, doc: str, *, internal: bool = False,
             conv: Optional[Callable[[str], Any]] = None,
             check: Optional[Callable[[Any], bool]] = None) -> ConfEntry:
    if conv is None:
        if isinstance(default, bool):
            conv = _to_bool
        elif isinstance(default, int):
            conv = int
        elif isinstance(default, float):
            conv = float
        else:
            conv = str
    with _REG_LOCK:
        if key in _REGISTRY:
            return _REGISTRY[key]
        e = ConfEntry(key, default, doc, conv, internal, check)
        _REGISTRY[key] = e
        return e


# ---------------------------------------------------------------------- #
# Core entries (counterparts of the reference keys noted inline)
# ---------------------------------------------------------------------- #

SQL_ENABLED = register(
    "spark.rapids.tpu.sql.enabled", True,
    "Master enable for plan replacement (ref: spark.rapids.sql.enabled, "
    "RapidsConf.scala:514).")
CONCURRENT_TPU_TASKS = register(
    "spark.rapids.tpu.sql.concurrentTpuTasks", 2,
    "Max concurrent tasks admitted to the accelerator per executor "
    "(ref: spark.rapids.sql.concurrentGpuTasks, RapidsConf.scala:423).")
BATCH_SIZE_ROWS = register(
    "spark.rapids.tpu.sql.batchSizeRows", 1 << 20,
    "Target row count per coalesced batch; the TPU analog of "
    "spark.rapids.sql.batchSizeBytes (RapidsConf.scala:436) — rows, not "
    "bytes, because XLA programs are specialized per capacity bucket.")
MAX_CAPACITY = register(
    "spark.rapids.tpu.sql.maxBatchCapacity", 1 << 22,
    "Hard cap on a single batch's padded capacity.")
HBM_POOL_FRACTION = register(
    "spark.rapids.tpu.memory.hbm.poolFraction", 0.75,
    "Fraction of device HBM the buffer store may occupy before proactive "
    "spill (ref: spark.rapids.memory.gpu.allocFraction).")
HOST_SPILL_SIZE = register(
    "spark.rapids.tpu.memory.host.spillStorageSize", 1 << 30,
    "Bytes of host memory for spilled buffers before they go to disk "
    "(ref: spark.rapids.memory.host.spillStorageSize, RapidsConf.scala:357).")
SPILL_DIR = register(
    "spark.rapids.tpu.memory.spillDir", "/tmp/spark_rapids_tpu_spill",
    "Directory for disk-tier spill files (ref: RapidsDiskBlockManager).")
EXPLAIN = register(
    "spark.rapids.tpu.sql.explain", "NOT_ON_TPU",
    "What to log about plan replacement: NONE, NOT_ON_TPU, ALL "
    "(ref: spark.rapids.sql.explain).")
INCOMPATIBLE_OPS = register(
    "spark.rapids.tpu.sql.incompatibleOps.enabled", True,
    "Allow ops whose results may differ from the CPU engine in documented "
    "ways, e.g. float aggregation order "
    "(ref: spark.rapids.sql.incompatibleOps.enabled).")
HAS_NANS = register(
    "spark.rapids.tpu.sql.hasNans", True,
    "Assume floating point data may contain NaNs (ref: "
    "spark.rapids.sql.hasNans).")
VARIABLE_FLOAT_AGG = register(
    "spark.rapids.tpu.sql.variableFloatAgg.enabled", True,
    "Permit float aggregation whose ordering differs from CPU "
    "(ref: spark.rapids.sql.variableFloatAgg.enabled).")
SHUFFLE_TRANSPORT_ENABLED = register(
    "spark.rapids.tpu.shuffle.transport.enabled", False,
    "Enable the accelerated collective shuffle transport "
    "(ref: spark.rapids.shuffle.transport.enabled, RapidsConf.scala:930).")
SHUFFLE_PARTITIONS = register(
    "spark.rapids.tpu.sql.shuffle.partitions", 8,
    "Default partition count for shuffle exchanges (ref: "
    "spark.sql.shuffle.partitions).")
CBO_ENABLED = register(
    "spark.rapids.tpu.sql.optimizer.enabled", False,
    "Enable the cost-based optimizer that keeps subtrees on CPU when "
    "acceleration is not profitable (ref: CostBasedOptimizer.scala:34).")
METRICS_LEVEL = register(
    "spark.rapids.tpu.sql.metrics.level", "MODERATE",
    "Metric detail level: ESSENTIAL, MODERATE, DEBUG "
    "(ref: GpuExec.scala:40-160 metric levels).",
    check=lambda v: v in ("ESSENTIAL", "MODERATE", "DEBUG"))
TEST_ALLOWED_NONTPU = register(
    "spark.rapids.tpu.sql.test.allowedNonTpu", "",
    "Comma-separated exec names allowed to fall back in strict test mode.",
    internal=True)
STRICT_FALLBACK = register(
    "spark.rapids.tpu.sql.test.strictFallback", False,
    "Raise if any operator falls back to CPU (test aid; analog of the "
    "reference integration tests' allow_non_gpu machinery).",
    internal=True)


class TpuConf:
    """An immutable-ish snapshot of config values, like `new RapidsConf(conf)`
    in the reference (Plugin.scala:179)."""

    def __init__(self, overrides: Optional[dict[str, Any]] = None):
        self._values: dict[str, Any] = {}
        env_prefix = "SPARK_RAPIDS_TPU_"
        for key, entry in _REGISTRY.items():
            raw: Any = entry.default
            env_key = env_prefix + key.split("spark.rapids.tpu.")[-1] \
                .replace(".", "_").upper()
            if env_key in os.environ:
                raw = os.environ[env_key]
            self._values[key] = entry.convert(raw)
        for k, v in (overrides or {}).items():
            self.set(k, v)

    def set(self, key: str, value: Any) -> "TpuConf":
        entry = _REGISTRY.get(key)
        if entry is not None:
            self._values[key] = entry.convert(value)
        else:
            # unknown keys allowed (dynamic per-op keys register lazily)
            self._values[key] = value
        return self

    def get(self, entry_or_key, default: Any = None) -> Any:
        if isinstance(entry_or_key, ConfEntry):
            key = entry_or_key.key
            default = entry_or_key.default
        else:
            key = entry_or_key
            reg = _REGISTRY.get(key)
            if reg is not None and default is None:
                default = reg.default
        return self._values.get(key, default)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key, default)
        return _to_bool(v) if isinstance(v, str) else bool(v)

    # convenient properties
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def explain(self) -> str:
        return self.get(EXPLAIN)

    @property
    def batch_size_rows(self) -> int:
        return self.get(BATCH_SIZE_ROWS)

    @property
    def strict_fallback(self) -> bool:
        return self.get(STRICT_FALLBACK)


def help_text(include_internal: bool = False) -> str:
    """Generate the configs doc, like RapidsConf.help -> docs/configs.md."""
    lines = ["# spark_rapids_tpu configuration", "",
             "| Key | Default | Description |", "|---|---|---|"]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.internal and not include_internal:
            continue
        doc = e.doc.replace("\n", " ")
        lines.append(f"| {key} | {e.default} | {doc} |")
    return "\n".join(lines) + "\n"


_ACTIVE = threading.local()


def get_conf() -> TpuConf:
    conf = getattr(_ACTIVE, "conf", None)
    if conf is None:
        conf = TpuConf()
        _ACTIVE.conf = conf
    return conf


def set_conf(conf: TpuConf) -> None:
    _ACTIVE.conf = conf
