"""Shuffle subsystem (SURVEY.md §2.10).

TPU re-design of the reference's two-tier shuffle: device-resident
partition outputs held in a catalog (ref:
RapidsShuffleInternalManagerBase's RapidsCachingWriter +
ShuffleBufferCatalog) with spill-store backing, behind a transport SPI
(ref: RapidsShuffleTransport.scala:338).  In-process execution uses the
local catalog transport; partitions aligned with a device mesh ride the
collective all_to_all exchange in parallel.exchange instead of N x N
point-to-point pulls.
"""

from spark_rapids_tpu.shuffle.manager import (  # noqa: F401
    ShuffleManager,
    get_shuffle_manager,
    reset_shuffle_manager,
)
from spark_rapids_tpu.shuffle.net import (  # noqa: F401
    FetchFailedError,
    HeartbeatClient,
    HeartbeatManager,
    HeartbeatServer,
    ShuffleBlockServer,
    fetch_blocks,
    read_remote,
)
