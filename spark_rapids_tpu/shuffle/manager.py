"""In-process shuffle manager.

Counterpart of RapidsShuffleInternalManagerBase + ShuffleBufferCatalog
(ref: sql-plugin/.../sql/rapids/RapidsShuffleInternalManagerBase.scala:66
RapidsCachingWriter stores partition slices in the device store instead
of writing files; RapidsCachingReader serves local blocks zero-copy from
the catalog).  Map-task outputs register with the spill store at
OUTPUT_FOR_SHUFFLE priority — the first thing evicted under memory
pressure, exactly the reference's spill ordering — so shuffle data
overflows to host/disk transparently while reduce tasks read
device-resident batches zero-copy when memory allows."""

from __future__ import annotations

import threading
from typing import Iterator, Optional

from spark_rapids_tpu import trace as _trace
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.memory import SpillPriorities, get_store


class ShuffleManager:
    def __init__(self):
        self._lock = threading.Lock()
        #: (shuffle_id, reduce_id) -> list of SpillableBatch handles
        self._blocks: dict[tuple[int, int], list] = {}
        #: (shuffle_id, reduce_id) -> [bytes, rows] written (MapStatus
        #: analog: survives read() so adaptive re-planning can consult
        #: sizes after map stages complete)
        self._stats: dict[tuple[int, int], list] = {}
        self._next_shuffle = 0

    def new_shuffle_id(self) -> int:
        with self._lock:
            sid = self._next_shuffle
            self._next_shuffle += 1
            return sid

    def write(self, shuffle_id: int, reduce_id: int,
              batch: ColumnarBatch) -> None:
        """Map side convenience: register + publish ONE partition slice
        (a single-block commit — bulk task output should buffer and use
        commit_task directly so failed attempts publish nothing)."""
        rows = batch.concrete_num_rows()
        if rows == 0:
            return
        h = get_store().register(batch, SpillPriorities.OUTPUT_FOR_SHUFFLE)
        h.unpin()  # at rest until a reduce task fetches it
        self.commit_task(shuffle_id, [(reduce_id, h, h.nbytes, rows)])

    def read(self, shuffle_id: int, reduce_id: int
             ) -> Iterator[ColumnarBatch]:
        """Reduce side: drain this partition's blocks (consumes them).
        Abandon-safe: if the consumer stops early (limit satisfied,
        generator dropped), GeneratorExit lands in the finally and the
        unread handles are still closed."""
        with self._lock:
            handles = self._blocks.pop((shuffle_id, reduce_id), [])
        try:
            while handles:
                h = handles.pop(0)
                try:
                    if _trace.TRACER.enabled:
                        with _trace.span("shuffle.block.recv",
                                         shuffle=shuffle_id,
                                         reduce=reduce_id,
                                         bytes=h.nbytes):
                            b = h.get()
                    else:
                        b = h.get()
                    yield b
                finally:
                    h.close()
        finally:
            for h in handles:
                h.close()

    def read_keep(self, shuffle_id: int, reduce_id: int
                  ) -> Iterator[ColumnarBatch]:
        """Reduce side, NON-consuming: iterate this partition's blocks
        leaving them registered.  Skew-split join tasks read the same
        reduce partition once per slice (ref: Spark's
        PartialReducerPartitionSpec re-reads map output ranges); the
        blocks are freed when the exchange unregisters the shuffle."""
        with self._lock:
            handles = list(self._blocks.get((shuffle_id, reduce_id), []))
        for h in handles:
            b = h.get()
            try:
                yield b
            finally:
                h.unpin()  # spillable again between readers

    def commit_task(self, shuffle_id: int,
                    outputs: list[tuple[int, object, int, int]]) -> None:
        """Atomically publish one map task's outputs: a list of
        (reduce_id, spillable_handle, nbytes, rows).  Failed/retried
        attempts never call this, so readers only ever observe complete
        task output — the MapStatus commit protocol (Spark publishes a
        task's shuffle blocks only when the task commits)."""
        if outputs and _trace.TRACER.enabled:
            _trace.event(
                "shuffle.block.send", shuffle=shuffle_id,
                blocks=len(outputs),
                bytes=sum(nb for _r, _h, nb, _n in outputs),
                rows=sum(n for _r, _h, _nb, n in outputs))
        with self._lock:
            for rid, h, nbytes, rows in outputs:
                self._blocks.setdefault((shuffle_id, rid), []).append(h)
                st = self._stats.setdefault((shuffle_id, rid), [0, 0])
                st[0] += nbytes
                st[1] += rows

    def knows_shuffle(self, shuffle_id: int) -> bool:
        """True when this manager has EVER seen the shuffle (stats
        survive read(), so a restarted process — fresh manager — says
        False and the network server can distinguish 'lost blocks'
        from 'genuinely empty partition')."""
        with self._lock:
            return any(k[0] == shuffle_id for k in self._stats) \
                or any(k[0] == shuffle_id for k in self._blocks)

    def serve_host(self, shuffle_id: int, reduce_id: int
                   ) -> Iterator[dict]:
        """NON-destructive host-side read for the network block server
        (ref: RapidsShuffleServer serving catalog buffers): blocks stay
        published so a reducer can re-fetch after a failure; each block
        is pinned only while its host arrays are being read."""
        with self._lock:
            handles = list(self._blocks.get((shuffle_id, reduce_id), []))
        for h in handles:
            try:
                arrays = h.get_host()
            except KeyError:
                continue  # unregistered concurrently
            try:
                yield arrays
            finally:
                h.unpin()

    def partition_stats(self, shuffle_id: int,
                        n_partitions: int) -> list[tuple[int, int]]:
        """Per-reduce-partition (bytes, rows) written by the map stage —
        the MapOutputStatistics analog adaptive execution plans against
        (ref: GpuShuffleExchangeExec's mapOutputStatistics via
        ShuffledBatchRDD)."""
        with self._lock:
            return [tuple(self._stats.get((shuffle_id, rid), (0, 0)))
                    for rid in range(n_partitions)]

    def block_counts(self, shuffle_id: int,
                     n_partitions: int) -> list[int]:
        """Committed blocks per reduce partition — the upper bound on
        how many skew slices of a partition can carry any data (slices
        deal blocks round-robin)."""
        with self._lock:
            return [len(self._blocks.get((shuffle_id, rid), []))
                    for rid in range(n_partitions)]

    def unregister(self, shuffle_id: int) -> None:
        with self._lock:
            keys = [k for k in self._blocks if k[0] == shuffle_id]
            for k in keys:
                for h in self._blocks.pop(k):
                    h.close()
            for k in [k for k in self._stats if k[0] == shuffle_id]:
                del self._stats[k]


_MANAGER: Optional[ShuffleManager] = None
_LOCK = threading.Lock()


def get_shuffle_manager() -> ShuffleManager:
    global _MANAGER
    with _LOCK:
        if _MANAGER is None:
            _MANAGER = ShuffleManager()
        return _MANAGER


def reset_shuffle_manager() -> None:
    global _MANAGER
    with _LOCK:
        _MANAGER = None
