"""Shuffle transport SPI: which fabric moves exchange data.

Counterpart of the reference's transport seam
(ref: RapidsShuffleTransport.scala:338 `makeTransport` — the SPI behind
which UCX lives, with the default Spark shuffle as the fallback tier).
Here the two tiers are:

- ``local``      — the in-process spillable shuffle manager
                   (shuffle.manager; the "default Spark shuffle" tier);
- ``collective`` — exchanges lower into ONE fused SPMD program per
                   query stage: map-side work, an XLA ``all_to_all``
                   over the active mesh axis (ICI/DCN,
                   compiler-scheduled), and reduce-side work, with no
                   host round trip between map and reduce
                   (parallel.exchange; SURVEY.md §5.8 tier 2).

The planner consults `get_transport()` when lowering exchange-bearing
operators; the collective tier engages only when a device mesh is
active (parallel.mesh.set_active_mesh) and the data plane supports the
schema (fixed-width + string columns).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import register, get_conf

SHUFFLE_TRANSPORT = register(
    "spark.rapids.tpu.shuffle.transport", "local",
    "Exchange transport tier: 'local' (in-process spillable shuffle "
    "manager) or 'collective' (fused all_to_all SPMD programs over the "
    "active device mesh; requires parallel.mesh.set_active_mesh). "
    "The spark.rapids.shuffle.transport.enabled/class analog "
    "(ref: RapidsConf.scala:930-954).",
    check=lambda v: v in ("local", "collective"))


@dataclasses.dataclass
class ShuffleTransport:
    """Resolved transport choice handed to the planner."""

    kind: str  # "local" | "collective"
    mesh: Optional[object] = None  # jax.sharding.Mesh for collective

    def supports_schema(self, schema: T.Schema) -> bool:
        """The collective data plane stacks leaves across shards; list
        columns are not wired through it yet."""
        if self.kind != "collective":
            return True
        return not any(isinstance(f.dtype, T.ListType)
                       for f in schema.fields)


def get_transport() -> ShuffleTransport:
    from spark_rapids_tpu.parallel.mesh import active_mesh

    kind = get_conf().get(SHUFFLE_TRANSPORT)
    if kind == "collective":
        mesh = active_mesh()
        if mesh is not None:
            return ShuffleTransport("collective", mesh)
        # configured but no mesh: fall back to the local tier (the
        # reference likewise degrades to the default shuffle when the
        # transport cannot initialize)
    return ShuffleTransport("local")
