"""Cross-process shuffle transport: TCP block server/client + peer
registry.

Counterpart of the reference's network shuffle tier (ref:
RapidsShuffleServer.scala:70 serving catalog buffers,
RapidsShuffleClient.scala:96 MetadataRequest/TransferRequest fetch
protocol, RapidsShuffleHeartbeatManager.scala:51-114 driver-side peer
registry).  Re-designed for this engine's substrate:

- blocks travel as the serde frame format (columnar/serde.py) over a
  length-prefixed TCP stream — the host-serialized tier; the
  device-to-device tier is the collective transport (SURVEY.md §5.8);
- the server serves blocks NON-destructively out of the local
  spillable shuffle manager (get_host pins, unpin after send), so a
  reducer can re-fetch after a failure — the reference's
  catalog-backed BufferSendState behavior;
- fetch failures surface as FetchFailedError, classified retryable by
  execs/retry.py so the standard task-retry machinery provides
  elasticity (the FetchFailedException contract).

Everything is stdlib sockets + threads: no external RPC dependency.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time
from typing import Iterator, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.serde import (
    deserialize_arrays,
    serialize_arrays,
)
from spark_rapids_tpu.config import register

HEARTBEAT_INTERVAL_S = register(
    "spark.rapids.tpu.shuffle.heartbeat.intervalSeconds", 5.0,
    "Executor-to-registry heartbeat period (ref: "
    "spark.rapids.shuffle.transport.earlyStart.heartbeatInterval).")

HEARTBEAT_TIMEOUT_S = register(
    "spark.rapids.tpu.shuffle.heartbeat.timeoutSeconds", 30.0,
    "A peer missing heartbeats this long is pruned from the registry "
    "and no longer handed to new executors.")

FETCH_MAX_ATTEMPTS = register(
    "spark.rapids.tpu.shuffle.fetch.maxAttempts", 3,
    "Connection/read attempts per block fetch before FetchFailedError "
    "propagates to the task-retry layer (ref: "
    "spark.shuffle.io.maxRetries).  Between attempts the client backs "
    "off exponentially with jitter; callers that supply a resolver "
    "(net.peer_resolver over the heartbeat registry) get the peer "
    "address re-resolved before every retry after the first (with "
    "only two attempts budgeted, before that sole retry) — a "
    "restarted peer on a fresh port is found early, not only on the "
    "last-ditch attempt.  The query's cancel token is honored between "
    "attempts (a cancelled reducer stops reconnecting immediately).",
    check=lambda v: v >= 1)

FETCH_BACKOFF_S = register(
    "spark.rapids.tpu.shuffle.fetch.retryWaitSeconds", 0.05,
    "Base sleep between fetch attempts (doubles per attempt, +-50% "
    "jitter so reducers hammered off the same dying peer do not "
    "reconnect in lockstep; ref: spark.shuffle.io.retryWait).")

FETCH_TIMEOUT_S = register(
    "spark.rapids.tpu.shuffle.fetch.timeoutSeconds", 30.0,
    "Per-ATTEMPT socket timeout (connect and reads) for block "
    "fetches; a hung peer costs one attempt, not the whole fetch "
    "budget.")


class FetchFailedError(RuntimeError):
    """A remote shuffle block could not be fetched (peer died,
    connection reset, truncated stream).  Retryable: the task retry
    path re-runs the attempt, which re-resolves peers (the
    FetchFailedException -> stage-retry contract of the reference's
    RapidsShuffleIterator)."""


# ------------------------------------------------------------------ #
# Wire helpers: every message is <Q length><payload>
# ------------------------------------------------------------------ #


def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise FetchFailedError(
                f"connection closed mid-message ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return _recv_exact(sock, n)


# ------------------------------------------------------------------ #
# Block server (executor side)
# ------------------------------------------------------------------ #


class _BlockHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # one request per connection
        try:
            req = json.loads(_recv_msg(self.request).decode())
        except Exception:
            return
        if req.get("op") != "fetch":
            _send_msg(self.request, json.dumps(
                {"error": "bad op"}).encode())
            return
        manager = self.server.shuffle_manager  # type: ignore[attr-defined]
        sid, rid = int(req["shuffle_id"]), int(req["reduce_id"])
        if not manager.knows_shuffle(sid):
            # restarted peer / stale address: the blocks are LOST, not
            # empty — the reducer must get a retryable failure, never
            # silently consume zero rows
            _send_msg(self.request, json.dumps(
                {"error": f"unknown shuffle {sid} (blocks lost; "
                          "peer restarted?)"}).encode())
            return
        _send_msg(self.request, json.dumps({"streaming": True}).encode())
        # one block serialized + sent at a time (the bounce-buffer
        # windowing discipline: peak memory is one frame, each block
        # pinned only while its bytes stream out); an EMPTY frame
        # terminates the stream (frames always start with the magic)
        for arrays in manager.serve_host(sid, rid):
            frame = serialize_arrays(arrays, self.server.codec)  # type: ignore
            raw = sum(int(a.nbytes) for a in arrays.values())
            self.server.count_bytes(raw, len(frame))  # type: ignore
            _send_msg(self.request, frame)
        _send_msg(self.request, b"")


class ShuffleBlockServer:
    """Serves this process's shuffle blocks over TCP (ref:
    RapidsShuffleServer — metadata + transfer responses built from the
    catalog, windowed through bounce buffers; here the serde staging
    buffer plays the bounce-buffer role)."""

    def __init__(self, manager=None, host: str = "127.0.0.1",
                 port: int = 0, codec: str = "none"):
        from spark_rapids_tpu.columnar.compression import get_bytes_codec
        from spark_rapids_tpu.shuffle.manager import get_shuffle_manager

        get_bytes_codec(codec)  # fail fast on a typo'd codec conf
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _BlockHandler, bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.shuffle_manager = manager or get_shuffle_manager()
        self._srv.codec = codec
        # bytes accounting (the shuffleWriteBytes/compression-ratio
        # observability the reference surfaces per-codec)
        self._bytes_lock = threading.Lock()
        self._raw_bytes = 0
        self._wire_bytes = 0

        srv_self = self

        def count_bytes(raw: int, wire: int) -> None:
            with srv_self._bytes_lock:
                srv_self._raw_bytes += raw
                srv_self._wire_bytes += wire

        self._srv.count_bytes = count_bytes
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name="tpu-shuffle-server")

    def bytes_stats(self) -> dict:
        """{'raw': bytes before codec, 'wire': framed bytes sent,
        'codec': this server's frame codec, 'codecs': the process-wide
        per-codec registry stats} — the shuffle tier's view of the ONE
        stats surface the H2D tunnel and spill tiers also report
        through (columnar/compression/; docs/wire_compression.md)."""
        from spark_rapids_tpu.columnar import compression as WC

        with self._bytes_lock:
            return {"raw": self._raw_bytes, "wire": self._wire_bytes,
                    "codec": self._srv.codec, "codecs": WC.stats()}

    @property
    def address(self) -> tuple[str, int]:
        return self._srv.server_address[:2]

    def start(self) -> "ShuffleBlockServer":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def _fetch_once(host: str, port: int, shuffle_id: int, reduce_id: int,
                timeout: float) -> list[dict]:
    """One fetch attempt (the previous whole-fetch body): any transport
    problem raises FetchFailedError.  ``timeout`` bounds the connect
    AND every read on this attempt's socket."""
    from spark_rapids_tpu.robustness import faults as _faults

    try:
        _faults.fault_point("shuffle.fetch", shuffle_id=shuffle_id,
                            reduce_id=reduce_id)
        with socket.create_connection((host, port),
                                      timeout=timeout) as sock:
            _send_msg(sock, json.dumps({
                "op": "fetch", "shuffle_id": shuffle_id,
                "reduce_id": reduce_id}).encode())
            head = json.loads(_recv_msg(sock).decode())
            if "error" in head:
                raise FetchFailedError(head["error"])
            out = []
            while True:
                frame = _recv_msg(sock)
                if not frame:  # end-of-stream marker
                    break
                out.append(deserialize_arrays(frame))
            return out
    except FetchFailedError:
        raise
    except (OSError, ValueError, json.JSONDecodeError) as e:
        raise FetchFailedError(
            f"fetch {shuffle_id}/{reduce_id} from {host}:{port} "
            f"failed: {e}") from e
    except RuntimeError as e:
        # the shuffle.fetch fault seam injects RuntimeErrors carrying
        # transport markers; surface them under the same contract a
        # real connection reset would
        from spark_rapids_tpu.execs.retry import classify

        if classify(e) != "retryable":
            raise
        raise FetchFailedError(
            f"fetch {shuffle_id}/{reduce_id} from {host}:{port} "
            f"failed: {e}") from e


def fetch_blocks(host: str, port: int, shuffle_id: int, reduce_id: int,
                 timeout: Optional[float] = None,
                 resolve_peer=None) -> list[dict]:
    """Fetch one reduce partition's blocks from a peer as host-array
    dicts, with BOUNDED RETRIES inside the fetch itself (ref:
    RetryingBlockTransferor / spark.shuffle.io.maxRetries): each
    attempt gets its own socket timeout; between attempts the client
    honors the query's cancel token (a cancelled reducer raises
    QueryCancelled instead of reconnecting) and sleeps a jittered
    doubling backoff; from the SECOND retry on, every attempt first
    re-resolves the peer through ``resolve_peer`` (typically
    HeartbeatManager.live_peers via ``peer_resolver``) — a restarted
    executor re-registers on a fresh port, and finding it early saves
    whole backoff rounds hammering a dead address (the first retry
    skips resolution: transient resets on a LIVE peer are the common
    case and the registry round trip is not free).  Only after the
    budget is spent does FetchFailedError propagate — the task-retry
    layer then provides the coarser elasticity, as before."""
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.robustness import faults as _faults
    from spark_rapids_tpu.serving.cancel import check_point

    conf = get_conf()
    if timeout is None:
        timeout = conf.get(FETCH_TIMEOUT_S)
    attempts = max(1, conf.get(FETCH_MAX_ATTEMPTS))
    backoff = conf.get(FETCH_BACKOFF_S)
    caught: list[BaseException] = []
    for attempt in range(attempts):
        try:
            out = _fetch_once(host, port, shuffle_id, reduce_id,
                              timeout)
        except FetchFailedError as e:
            if attempt == attempts - 1:
                raise
            caught.append(e)
            check_point()  # cancelled mid-fetch: stop reconnecting
            from spark_rapids_tpu.execs.retry import _sleep_backoff

            _sleep_backoff(backoff, attempt)
            if resolve_peer is not None \
                    and attempt >= min(1, attempts - 2):
                # persistent failure (two attempts on this address
                # died): re-resolve before EVERY further attempt — a
                # restarted peer re-registers with a fresh endpoint
                # and is found as early as the registry knows it,
                # not only before the last-ditch attempt.  With only
                # two attempts budgeted the sole retry IS the final
                # attempt, so resolution fires before it (min clamp)
                # rather than never
                try:
                    fresh = resolve_peer()
                except Exception as re_exc:  # noqa: BLE001 — resolver is best-effort
                    from spark_rapids_tpu.execs.retry import classify

                    classify(re_exc)
                    fresh = None
                if fresh is not None:
                    host, port = fresh
            continue
        for e in caught:
            _faults.note_recovered(e, action="fetch_retry")
        return out
    raise caught[-1]  # unreachable; keeps type checkers honest


def peer_resolver(registry, executor_id: str):
    """A ``resolve_peer`` callback over a HeartbeatManager (or any
    object with ``live_peers()``): the freshest (host, port) the
    registry knows for ``executor_id``, else None."""
    def resolve() -> Optional[tuple[str, int]]:
        for eid, h, p in registry.live_peers():
            if eid == executor_id:
                return h, p
        return None

    return resolve


def read_remote(host: str, port: int, shuffle_id: int, reduce_id: int,
                schema, timeout: Optional[float] = None,
                resolve_peer=None) -> Iterator[ColumnarBatch]:
    """Fetch + upload: remote blocks as device batches."""
    from spark_rapids_tpu.memory.store import _host_to_batch

    for arrays in fetch_blocks(host, port, shuffle_id, reduce_id,
                               timeout=timeout,
                               resolve_peer=resolve_peer):
        yield _host_to_batch(arrays, schema)


# ------------------------------------------------------------------ #
# Peer registry (driver side) + executor heartbeat client
# ------------------------------------------------------------------ #


class HeartbeatManager:
    """Driver-side peer registry (ref:
    RapidsShuffleHeartbeatManager.scala:51 registerExecutor /
    :81 executorHeartbeat): executors register their block-server
    endpoint; each heartbeat returns peers that appeared since the
    executor last asked; silent peers age out."""

    def __init__(self, timeout_s: Optional[float] = None):
        from spark_rapids_tpu.config import get_conf

        self._lock = threading.Lock()
        #: executor_id -> (host, port, last_seen, join_seq)
        self._peers: dict[str, tuple[str, int, float, int]] = {}
        #: executor_id -> highest join_seq already reported to it
        self._acked: dict[str, int] = {}
        self._seq = 0
        self._timeout = timeout_s if timeout_s is not None \
            else get_conf().get(HEARTBEAT_TIMEOUT_S)

    def register(self, executor_id: str, host: str,
                 port: int) -> list[tuple[str, str, int]]:
        now = time.monotonic()
        with self._lock:
            self._prune(now)  # never hand long-dead peers to a joiner
            self._seq += 1
            self._peers[executor_id] = (host, port, now, self._seq)
            self._acked[executor_id] = self._seq
            return [(eid, h, p) for eid, (h, p, _, _)
                    in self._peers.items() if eid != executor_id]

    def heartbeat(self, executor_id: str) -> list[tuple[str, str, int]]:
        """Refresh liveness; returns peers NEW since the last call."""
        now = time.monotonic()
        with self._lock:
            entry = self._peers.get(executor_id)
            if entry is None:
                raise KeyError(f"unregistered executor {executor_id}")
            self._peers[executor_id] = entry[:2] + (now, entry[3])
            self._prune(now)
            last = self._acked.get(executor_id, 0)
            fresh = [(eid, h, p) for eid, (h, p, _, seq)
                     in self._peers.items()
                     if seq > last and eid != executor_id]
            self._acked[executor_id] = self._seq
            return fresh

    def live_peers(self) -> list[tuple[str, str, int]]:
        with self._lock:
            self._prune(time.monotonic())
            return [(eid, h, p) for eid, (h, p, _, _)
                    in self._peers.items()]

    def _prune(self, now: float) -> None:
        dead = [eid for eid, (_, _, seen, _) in self._peers.items()
                if now - seen > self._timeout]
        for eid in dead:
            del self._peers[eid]
            self._acked.pop(eid, None)


class _RegistryHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        try:
            req = json.loads(_recv_msg(self.request).decode())
        except Exception:
            return
        mgr: HeartbeatManager = self.server.manager  # type: ignore
        try:
            if req["op"] == "register":
                peers = mgr.register(req["executor_id"], req["host"],
                                     int(req["port"]))
            elif req["op"] == "heartbeat":
                peers = mgr.heartbeat(req["executor_id"])
            else:
                raise ValueError(f"bad op {req['op']!r}")
            resp = {"peers": peers}
        except Exception as e:
            resp = {"error": str(e)}
        _send_msg(self.request, json.dumps(resp).encode())


class HeartbeatServer:
    """TCP front for a HeartbeatManager (the driver plugin endpoint)."""

    def __init__(self, manager: Optional[HeartbeatManager] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.manager = manager or HeartbeatManager()
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _RegistryHandler, bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.manager = self.manager
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name="tpu-shuffle-registry")

    @property
    def address(self) -> tuple[str, int]:
        return self._srv.server_address[:2]

    def start(self) -> "HeartbeatServer":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class HeartbeatClient:
    """Executor-side registry client: register once, then periodic
    heartbeats; accumulates the known-peer table (the executor's
    `transport.connect(peer)` trigger in the reference)."""

    def __init__(self, registry_host: str, registry_port: int,
                 executor_id: str, block_host: str, block_port: int):
        self._addr = (registry_host, registry_port)
        self.executor_id = executor_id
        self._me = (block_host, block_port)
        self.peers: dict[str, tuple[str, int]] = {}
        self._timer: Optional[threading.Timer] = None
        self._stopped = False

    def _call(self, payload: dict) -> list:
        try:
            with socket.create_connection(self._addr,
                                          timeout=10.0) as sock:
                _send_msg(sock, json.dumps(payload).encode())
                resp = json.loads(_recv_msg(sock).decode())
        except (OSError, ValueError) as e:
            raise FetchFailedError(f"registry unreachable: {e}") from e
        if "error" in resp:
            raise FetchFailedError(resp["error"])
        return resp["peers"]

    def register(self) -> None:
        peers = self._call({
            "op": "register", "executor_id": self.executor_id,
            "host": self._me[0], "port": self._me[1]})
        for eid, h, p in peers:
            self.peers[eid] = (h, p)

    def heartbeat(self) -> None:
        for eid, h, p in self._call({"op": "heartbeat",
                                     "executor_id": self.executor_id}):
            self.peers[eid] = (h, p)

    def start_background(self, interval_s: Optional[float] = None
                         ) -> None:
        from spark_rapids_tpu.config import get_conf

        interval = interval_s if interval_s is not None \
            else get_conf().get(HEARTBEAT_INTERVAL_S)

        def tick():
            if self._stopped:
                return
            try:
                self.heartbeat()
            except FetchFailedError as e:
                # pruned after a long stall (registry said
                # "unregistered")?  re-register — otherwise this
                # executor stays invisible to new peers forever
                if "unregistered" in str(e):
                    try:
                        self.register()
                    except FetchFailedError:
                        pass
                # registry unreachable: keep last-known peers
            except Exception:
                # any other failure (malformed registry response, socket
                # teardown race) must not kill the heartbeat chain — a
                # dead chain silently ages this executor out of the
                # registry
                pass
            finally:
                if not self._stopped:
                    self._timer = threading.Timer(interval, tick)
                    self._timer.daemon = True
                    self._timer.start()

        tick()

    def stop(self) -> None:
        self._stopped = True
        if self._timer is not None:
            self._timer.cancel()
