"""Host-platform pinning for tests and dry runs.

This environment's sitecustomize registers a remote TPU PJRT plugin in
every interpreter and *forcibly* sets jax_platforms="axon,cpu" via
jax.config.update, which overrides the JAX_PLATFORMS env var.  Multi-chip
sharding is validated on a virtual CPU mesh (no pod available), so both
the test suite and the driver's `dryrun_multichip` gate must win the
override back *before* any JAX backend initializes.  This is the single
shared implementation of that dance.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def pin_cpu_platform(n_devices: int):
    """Force JAX onto the CPU platform with >= n_devices virtual devices.

    Must be called before any backend initializes (first jnp op /
    jax.devices() call).  Returns the CPU device list; raises RuntimeError
    with a diagnostic when the backend was already initialized with fewer
    devices.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" {_COUNT_FLAG}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"{_COUNT_FLAG}={n_devices}")

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized; the count check below decides
    devs = jax.devices("cpu")
    if len(devs) < n_devices:
        raise RuntimeError(
            f"need {n_devices} CPU devices, have {len(devs)}; the JAX "
            "backend initialized before pin_cpu_platform could raise "
            f"{_COUNT_FLAG} (run in a fresh process, or export "
            f"JAX_PLATFORMS=cpu XLA_FLAGS={_COUNT_FLAG}={n_devices} first)")
    return devs
