"""Python worker pool (parent side): process-isolated arrow UDFs.

The PythonWorkerSemaphore + daemon management analog (ref:
rapids/python/PythonWorkerSemaphore.scala and python/rapids/daemon.py):
a bounded pool of persistent child interpreters, one pickled UDF per
pool, batches dispatched over Arrow IPC pipes.  Workers restart on
death; UDF exceptions come back as UdfError without killing the
worker.
"""

from __future__ import annotations

import atexit
import pickle
import struct
import subprocess
import sys
import threading
from typing import Callable, Optional

import pyarrow as pa

from spark_rapids_tpu.config import register, get_conf

PYTHON_WORKERS = register(
    "spark.rapids.tpu.python.concurrentWorkers", 2,
    "Maximum concurrently running python UDF worker processes (the "
    "PythonWorkerSemaphore analog).")

_ERR = 0xFFFFFFFF


class UdfError(RuntimeError):
    """The user's UDF raised inside the worker."""


class _Worker:
    def __init__(self, fn_bytes: bytes):
        import os

        # the child must locate this package BEFORE the sys.path frame
        # arrives (the -m import happens at spawn), so propagate the
        # parent's import roots through the environment
        env = dict(os.environ)
        extra = [p for p in sys.path if p]
        prior = env.get("PYTHONPATH")
        env["PYTHONPATH"] = os.pathsep.join(
            extra + ([prior] if prior else []))
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.python_worker.worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
        paths = pickle.dumps([p for p in sys.path if p])
        self._proc.stdin.write(struct.pack("<I", len(paths)))
        self._proc.stdin.write(paths)
        self._proc.stdin.write(struct.pack("<I", len(fn_bytes)))
        self._proc.stdin.write(fn_bytes)
        self._proc.stdin.flush()

    @property
    def alive(self) -> bool:
        return self._proc.poll() is None

    def run(self, tbl: pa.Table) -> pa.Table:
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, tbl.schema) as w:
            w.write_table(tbl)
        data = sink.getvalue().to_pybytes()
        self._proc.stdin.write(struct.pack("<I", len(data)))
        self._proc.stdin.write(data)
        self._proc.stdin.flush()
        (n,) = struct.unpack("<I", self._read(4))
        if n == _ERR:
            (m,) = struct.unpack("<I", self._read(4))
            raise UdfError(self._read(m).decode())
        return pa.ipc.open_stream(self._read(n)).read_all()

    def _read(self, n: int) -> bytes:
        chunks = []
        while n:
            b = self._proc.stdout.read(n)
            if not b:
                raise EOFError("python worker died")
            chunks.append(b)
            n -= len(b)
        return b"".join(chunks)

    def close(self) -> None:
        try:
            if self.alive:
                self._proc.stdin.write(struct.pack("<I", 0))
                self._proc.stdin.flush()
                self._proc.wait(timeout=5)
        except Exception:
            self._proc.kill()


class PythonWorkerPool:
    """Bounded pool of persistent workers for ONE pickled function."""

    def __init__(self, fn: Callable[[pa.Table], pa.Table],
                 max_workers: Optional[int] = None):
        self._fn_bytes = pickle.dumps(fn)
        self._max = max_workers if max_workers is not None \
            else get_conf().get(PYTHON_WORKERS)
        self._sem = threading.Semaphore(self._max)
        self._idle: list[_Worker] = []
        self._lock = threading.Lock()
        self._spawned = 0
        self._closed = False
        atexit.register(self.close)

    def run(self, tbl: pa.Table) -> pa.Table:
        """Apply the UDF to one batch in a worker process (blocks while
        all workers are busy — the semaphore gate)."""
        with self._sem:
            w = self._take()
            try:
                out = w.run(tbl)
            except UdfError:
                self._give(w)  # worker survived the user error
                raise
            except Exception:
                w.close()  # broken pipe / dead worker: do not recycle
                with self._lock:
                    self._spawned -= 1
                raise
            self._give(w)
            return out

    def _take(self) -> _Worker:
        with self._lock:
            while self._idle:
                w = self._idle.pop()
                if w.alive:
                    return w
                self._spawned -= 1
            self._spawned += 1
        return _Worker(self._fn_bytes)

    def _give(self, w: _Worker) -> None:
        with self._lock:
            if not self._closed and w.alive:
                self._idle.append(w)
                return
        w.close()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            workers, self._idle = self._idle, []
        for w in workers:
            w.close()
        try:
            # drop the atexit reference so closed pools (and their
            # pickled UDF bytes) can be collected
            atexit.unregister(self.close)
        except Exception:
            pass
