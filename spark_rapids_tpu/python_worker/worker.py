"""Out-of-process Python UDF worker (child side).

Analog of the reference's patched PySpark worker (ref:
python/rapids/worker.py:21-50 — a dedicated python process per
executor slot, initialized once, fed columnar batches).  The TPU
version speaks length-prefixed Arrow IPC frames over stdin/stdout:

    parent -> child:  [u32 len][pickled fn]            (once)
                      [u32 len][arrow IPC stream]...   (per batch)
                      [u32 0]                          (shutdown)
    child  -> parent: [u32 len][arrow IPC stream]      (per batch)
                      on error: [u32 0xFFFFFFFF][u32 len][utf-8 msg]

Process isolation is the point: user code that segfaults, leaks, or
monopolizes the GIL cannot take the engine down, and the parent's
worker semaphore caps how many such processes run concurrently
(PythonWorkerSemaphore analog).
"""

from __future__ import annotations

import pickle
import struct
import sys

_ERR = 0xFFFFFFFF


def _read_exact(f, n: int) -> bytes:
    chunks = []
    while n:
        b = f.read(n)
        if not b:
            raise EOFError
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def main() -> int:
    import os

    import pyarrow as pa

    stdin = sys.stdin.buffer
    # claim the framing pipe on a PRIVATE fd and point the process's
    # stdout at stderr: a UDF that print()s must not inject bytes into
    # the length-prefixed protocol (the reference PySpark worker does
    # the same stdout redirection)
    framing_fd = os.dup(sys.stdout.fileno())
    os.dup2(sys.stderr.fileno(), sys.stdout.fileno())
    sys.stdout = sys.stderr
    stdout = os.fdopen(framing_fd, "wb")
    # frame 0: the parent's sys.path — plain pickle resolves functions
    # by module reference, so the child must see the same import roots
    (n,) = struct.unpack("<I", _read_exact(stdin, 4))
    for p in pickle.loads(_read_exact(stdin, n)):
        if p not in sys.path:
            sys.path.append(p)
    (n,) = struct.unpack("<I", _read_exact(stdin, 4))
    fn = pickle.loads(_read_exact(stdin, n))
    while True:
        (n,) = struct.unpack("<I", _read_exact(stdin, 4))
        if n == 0:
            return 0
        payload = _read_exact(stdin, n)
        try:
            tbl = pa.ipc.open_stream(payload).read_all()
            out = fn(tbl)
            if isinstance(out, pa.RecordBatch):
                out = pa.Table.from_batches([out])
            if not isinstance(out, pa.Table):
                raise TypeError(
                    f"UDF must return a pyarrow Table/RecordBatch, "
                    f"got {type(out).__name__}")
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, out.schema) as w:
                w.write_table(out)
            data = sink.getvalue().to_pybytes()
            stdout.write(struct.pack("<I", len(data)))
            stdout.write(data)
        except Exception as e:  # report, stay alive for the next batch
            msg = f"{type(e).__name__}: {e}".encode()
            stdout.write(struct.pack("<II", _ERR, len(msg)))
            stdout.write(msg)
        stdout.flush()


if __name__ == "__main__":
    sys.exit(main())
