"""Frontend adapters behind the plugin's frontend seam.

The reference's entire value proposition is transparently intercepting
SOMEONE ELSE'S plans (ref: Plugin.scala:45-52 injecting into
SparkSessionExtensions); `plugin.register_frontend` is this engine's
equivalent seam, and each module here adapts one external plan surface
onto plan/logical.py nodes.  `native` (the DataFrame API) registers in
plugin.py; `substrait` registers on import."""

from spark_rapids_tpu.frontends import substrait  # noqa: F401
from spark_rapids_tpu.frontends import sql  # noqa: F401
