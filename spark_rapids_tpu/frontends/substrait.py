"""Substrait frontend: execute foreign plans on this engine.

The PROOF of the frontend seam (ref: the reference's whole premise —
Plugin.scala:45-52 intercepts plans Spark built, not plans the plugin's
own API built): this adapter ingests the Substrait plan format
(substrait.io — the cross-engine relational IR; its canonical JSON form
is the protobuf JSON mapping) and lowers it onto plan/logical.py nodes,
after which tagging, TPU conversion, and CPU fallback behave exactly as
for native plans.  A producer like Spark/Ibis/DuckDB emits Substrait;
this engine consumes it.

Supported rels: read (namedTable over registered tables, or
local_files parquet), filter, project, aggregate, sort, fetch, join.
Supported expressions: field selections, literals, and the standard
extension functions (comparison/boolean/arithmetic + sum/min/max/
count/avg measures).  Anything else raises SubstraitError — and an
expression that translates but is not TPU-supported falls back to the
CPU engine through the normal planner path.
"""

from __future__ import annotations

import json
from typing import Optional, Sequence, Union

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exprs import aggregates as AG
from spark_rapids_tpu.exprs import base as B
from spark_rapids_tpu.exprs import predicates as P
from spark_rapids_tpu.exprs import arithmetic as A
from spark_rapids_tpu.plan import logical as L


class SubstraitError(ValueError):
    """Plan outside the supported Substrait subset."""


#: substrait standard function name -> binary constructor
_BINARY_FNS = {
    "gt": P.GreaterThan,
    "gte": P.GreaterThanOrEqual,
    "lt": P.LessThan,
    "lte": P.LessThanOrEqual,
    "equal": P.EqualTo,
    "add": A.Add,
    "subtract": A.Subtract,
    "multiply": A.Multiply,
    "divide": A.Divide,
    "modulus": A.Remainder,
}

_VARIADIC_BOOL = {"and": P.And, "or": P.Or}

_MEASURE_FNS = {
    "sum": AG.Sum,
    "min": AG.Min,
    "max": AG.Max,
    "avg": AG.Average,
    "count": AG.Count,
}

_LITERAL_KEYS = {
    "boolean": T.BOOLEAN,
    "i8": T.BYTE,
    "i16": T.SHORT,
    "i32": T.INT,
    "i64": T.LONG,
    "fp32": T.FLOAT,
    "fp64": T.DOUBLE,
    "string": T.STRING,
    "date": T.DATE,
}

_TYPE_KEYS = {
    "bool": T.BOOLEAN,
    "i8": T.BYTE,
    "i16": T.SHORT,
    "i32": T.INT,
    "i64": T.LONG,
    "fp32": T.FLOAT,
    "fp64": T.DOUBLE,
    "string": T.STRING,
    "date": T.DATE,
    "timestamp": T.TIMESTAMP,
    "timestampTz": T.TIMESTAMP,
}


class SubstraitFrontend:
    """Session-like adapter: register tables, execute Substrait plans.

    Constructed through the plugin seam:
    `TpuPlugin.get_or_create().session("substrait")`."""

    def __init__(self, conf=None):
        from spark_rapids_tpu.session import TpuSession

        self._session = TpuSession(conf)
        self._tables: dict[str, L.LogicalPlan] = {}

    # -- catalog ------------------------------------------------------- #

    def register_table(self, name: str, source) -> None:
        """`source`: pa.Table, or parquet path(s) (str / list)."""
        import pyarrow as pa

        if isinstance(source, pa.Table):
            self._tables[name.lower()] = L.InMemoryRelation(source)
        else:
            paths = [source] if isinstance(source, str) else list(source)
            df = self._session.read_parquet(*paths)
            self._tables[name.lower()] = df._plan

    # -- entry points --------------------------------------------------- #

    def execute_plan(self, plan: Union[str, dict], engine=None):
        """Substrait plan (JSON text or dict) -> pa.Table."""
        return self.dataframe(plan).collect(engine=engine)

    def dataframe(self, plan: Union[str, dict]):
        from spark_rapids_tpu.session import DataFrame

        if isinstance(plan, str):
            plan = json.loads(plan)
        logical = self._lower_root(plan)
        return DataFrame(logical, self._session)

    def explain(self, plan: Union[str, dict]) -> str:
        return self.dataframe(plan).explain()

    # -- plan lowering --------------------------------------------------- #

    def _lower_root(self, plan: dict) -> L.LogicalPlan:
        fns = _extension_functions(plan)
        rels = plan.get("relations") or []
        if len(rels) != 1:
            raise SubstraitError(
                f"expected exactly 1 relation, got {len(rels)}")
        root = rels[0].get("root")
        if root is None:
            raise SubstraitError("relation has no root")
        out = self._lower_rel(root["input"], fns)
        names = root.get("names")
        if names:
            if len(names) != len(out.schema.fields):
                raise SubstraitError(
                    f"root names {names} do not match output arity "
                    f"{len(out.schema.fields)}")
            exprs = [B.Alias(B.BoundReference(i, f.dtype, f.nullable,
                                              f.name), n)
                     for i, (f, n) in enumerate(zip(out.schema.fields,
                                                    names))]
            out = L.Project(exprs, out)
        return out

    def _lower_rel(self, rel: dict, fns: dict) -> L.LogicalPlan:
        common_emit = None
        if len(rel) != 1:
            raise SubstraitError(f"malformed rel object: {list(rel)}")
        (kind, body), = rel.items()
        common_emit = (body.get("common") or {}).get("emit")
        if kind == "read":
            out = self._lower_read(body)
        elif kind == "filter":
            child = self._lower_rel(body["input"], fns)
            cond = self._expr(body["condition"], child.schema, fns)
            out = L.Filter(cond, child)
        elif kind == "project":
            child = self._lower_rel(body["input"], fns)
            new = [self._expr(e, child.schema, fns)
                   for e in body.get("expressions", [])]
            # substrait project OUTPUT = input fields ++ expressions
            # (emit below then selects)
            base = [B.BoundReference(i, f.dtype, f.nullable, f.name)
                    for i, f in enumerate(child.schema.fields)]
            out = L.Project(base + new, child)
        elif kind == "aggregate":
            child = self._lower_rel(body["input"], fns)
            groupings = body.get("groupings", [])
            if len(groupings) > 1:
                raise SubstraitError("grouping sets not supported")
            groups = [self._expr(g, child.schema, fns)
                      for g in (groupings[0].get("groupingExpressions",
                                                 [])
                                if groupings else [])]
            aggs = []
            for i, m in enumerate(body.get("measures", [])):
                if "filter" in m:
                    raise SubstraitError(
                        "measure-level FILTER is not supported")
                fn = m.get("measure", {})
                name = fns.get(fn.get("functionReference", 0))
                base_name = (name or "").split(":", 1)[0]
                ctor = _MEASURE_FNS.get(base_name)
                if ctor is None:
                    raise SubstraitError(
                        f"aggregate function {name!r} not supported")
                args = [self._expr(a["value"], child.schema, fns)
                        for a in fn.get("arguments", [])]
                if len(args) != 1:
                    raise SubstraitError(
                        f"{base_name} expects 1 argument")
                aggs.append(AG.NamedAgg(ctor(args[0]), f"m{i}"))
            out = L.Aggregate(groups, aggs, child)
        elif kind == "fetch":
            child = self._lower_rel(body["input"], fns)
            off = int(body.get("offset", body.get("offsetExpr", {})
                               .get("literal", {}).get("i64", 0)))
            if off:
                raise SubstraitError("fetch offset is not supported")
            # spec: count -1 = all records (always serialized since
            # it is non-default); an ABSENT count is proto3's omitted
            # zero -> LIMIT 0
            n = int(body.get("count", body.get("countExpr", {})
                             .get("literal", {}).get("i64", 0)))
            out = child if n < 0 else L.Limit(n, child)
        elif kind == "sort":
            from spark_rapids_tpu.execs.sort import SortKey

            child = self._lower_rel(body["input"], fns)
            keys = []
            for s in body.get("sorts", []):
                e = self._expr(s["expr"], child.schema, fns)
                direction = s.get("direction",
                                  "SORT_DIRECTION_ASC_NULLS_FIRST")
                desc = "DESC" in direction
                nulls_last = "NULLS_LAST" in direction
                keys.append(SortKey(e, desc, nulls_last))
            out = L.Sort(keys, child)
        elif kind == "join":
            jt = {
                "JOIN_TYPE_INNER": "inner",
                "JOIN_TYPE_LEFT": "left_outer",
                "JOIN_TYPE_RIGHT": "right_outer",
                "JOIN_TYPE_OUTER": "full_outer",
                "JOIN_TYPE_LEFT_SEMI": "left_semi",
                "JOIN_TYPE_LEFT_ANTI": "left_anti",
            }.get(body.get("type"))
            if jt is None:
                raise SubstraitError(
                    f"join type {body.get('type')!r} not supported")
            left = self._lower_rel(body["left"], fns)
            right = self._lower_rel(body["right"], fns)
            lk, rk = _equi_keys(self._expr(
                body["expression"],
                _joined_schema(left.schema, right.schema), fns),
                len(left.schema.fields))
            out = L.Join(left, right, lk, rk, jt, None)
        else:
            raise SubstraitError(f"rel type {kind!r} not supported")
        if common_emit:
            idx = common_emit.get("outputMapping", [])
            exprs = [B.BoundReference(i, out.schema.fields[i].dtype,
                                      out.schema.fields[i].nullable,
                                      out.schema.fields[i].name)
                     for i in idx]
            out = L.Project(exprs, out)
        return out

    def _lower_read(self, body: dict) -> L.LogicalPlan:
        nt = body.get("namedTable")
        if nt is not None:
            name = ".".join(nt.get("names", [])).lower()
            plan = self._tables.get(name)
            if plan is None:
                raise SubstraitError(
                    f"table {name!r} is not registered "
                    f"(have: {sorted(self._tables)})")
        else:
            lf = body.get("localFiles")
            if lf is None:
                raise SubstraitError(
                    "read rel needs namedTable or localFiles")
            paths = []
            for item in lf.get("items", []):
                uri = item.get("uriFile") or item.get("uriPath")
                if not uri:
                    raise SubstraitError("local_files item without uri")
                fmt = [k for k in item
                       if k.endswith(("parquet", "orc", "dwrf",
                                      "arrow", "text"))
                       or k in ("parquet",)]
                if fmt and "parquet" not in fmt:
                    raise SubstraitError(
                        f"local_files format {fmt[0]!r} not supported "
                        "(parquet only)")
                paths.append(uri.removeprefix("file://"))
            plan = self._session.read_parquet(*paths)._plan
        schema = plan.schema
        base_names = (body.get("baseSchema") or {}).get("names")
        if base_names:
            # projection by base-schema name order
            idx = [schema.index_of(n) for n in base_names
                   if n in schema.names]
            if len(idx) != len(base_names):
                missing = [n for n in base_names
                           if n not in schema.names]
                raise SubstraitError(
                    f"read schema names {missing} not in table")
            exprs = [B.BoundReference(i, schema.fields[i].dtype,
                                      schema.fields[i].nullable,
                                      schema.fields[i].name)
                     for i in idx]
            plan = L.Project(exprs, plan)
        proj = body.get("projection")
        if proj is not None:
            idx = [int(r.get("field", 0)) for r in
                   proj.get("select", {}).get("structItems", [])]
            sch = plan.schema
            exprs = [B.BoundReference(i, sch.fields[i].dtype,
                                      sch.fields[i].nullable,
                                      sch.fields[i].name) for i in idx]
            plan = L.Project(exprs, plan)
        return plan

    # -- expressions ------------------------------------------------------ #

    def _expr(self, e: dict, schema: T.Schema, fns: dict) -> B.Expression:
        if "selection" in e:
            ref = e["selection"].get("directReference", {})
            sf = ref.get("structField", {})
            i = int(sf.get("field", 0))
            if i >= len(schema.fields):
                raise SubstraitError(
                    f"field reference {i} out of range "
                    f"({len(schema.fields)} fields)")
            f = schema.fields[i]
            return B.BoundReference(i, f.dtype, f.nullable, f.name)
        if "literal" in e:
            return _literal(e["literal"])
        if "scalarFunction" in e:
            sf = e["scalarFunction"]
            name = fns.get(sf.get("functionReference", 0))
            base = (name or "").split(":", 1)[0]
            args = [self._expr(a["value"], schema, fns)
                    for a in sf.get("arguments", [])]
            if base in _VARIADIC_BOOL:
                if len(args) < 2:
                    raise SubstraitError(f"{base} needs >= 2 args")
                out = args[0]
                for a in args[1:]:
                    out = _VARIADIC_BOOL[base](out, a)
                return out
            ctor = _BINARY_FNS.get(base)
            if ctor is not None:
                if len(args) != 2:
                    raise SubstraitError(f"{base} needs 2 args")
                return ctor(args[0], args[1])
            if base == "not":
                return P.Not(args[0])
            if base == "is_null":
                return P.IsNull(args[0])
            if base == "is_not_null":
                return P.IsNotNull(args[0])
            raise SubstraitError(
                f"scalar function {name!r} not supported")
        if "cast" in e:
            from spark_rapids_tpu.exprs.cast import Cast

            c = e["cast"]
            dst = _type_of(c.get("type", {}))
            return Cast(self._expr(c["input"], schema, fns), dst)
        raise SubstraitError(f"expression {list(e)} not supported")


def _extension_functions(plan: dict) -> dict:
    fns: dict = {}
    for ext in plan.get("extensions", []):
        ef = ext.get("extensionFunction")
        if ef is not None:
            fns[ef.get("functionAnchor", 0)] = ef.get("name", "")
    return fns


def _literal(lit: dict) -> B.Literal:
    for key, dtype in _LITERAL_KEYS.items():
        if key in lit:
            v = lit[key]
            if dtype in (T.BYTE, T.SHORT, T.INT, T.LONG, T.DATE):
                v = int(v)
            elif dtype in (T.FLOAT, T.DOUBLE):
                v = float(v)
            return B.Literal.of(v, dtype)
    if "null" in lit:
        return B.Literal.of(None, _type_of(lit["null"]))
    raise SubstraitError(f"literal {list(lit)} not supported")


def _type_of(t: dict) -> T.DataType:
    for key, dtype in _TYPE_KEYS.items():
        if key in t:
            return dtype
    if "decimal" in t:
        d = t["decimal"]
        return T.DecimalType(int(d.get("precision", 10)),
                             int(d.get("scale", 0)))
    raise SubstraitError(f"type {list(t)} not supported")


def _joined_schema(ls: T.Schema, rs: T.Schema) -> T.Schema:
    return T.Schema(list(ls.fields) + list(rs.fields))


def _equi_keys(cond: B.Expression, n_left: int):
    """Decompose an AND-of-equalities join expression into
    (left_keys, right_keys); anything else is unsupported."""
    conjs = []
    stack = [cond]
    while stack:
        c = stack.pop()
        if isinstance(c, P.And):
            stack += [c.left, c.right]
        else:
            conjs.append(c)
    lk, rk = [], []
    for c in conjs:
        if not isinstance(c, P.EqualTo):
            raise SubstraitError(
                "join expression must be AND of equalities")
        sides = []
        for e in (c.left, c.right):
            if not isinstance(e, B.BoundReference):
                raise SubstraitError(
                    "join keys must be field references")
            sides.append(e)
        a, b = sides
        if a.ordinal < n_left <= b.ordinal:
            lk.append(a)
            rk.append(B.BoundReference(b.ordinal - n_left, b.dtype,
                                       b.nullable, b.name))
        elif b.ordinal < n_left <= a.ordinal:
            lk.append(b)
            rk.append(B.BoundReference(a.ordinal - n_left, a.dtype,
                                       a.nullable, a.name))
        else:
            raise SubstraitError(
                "join equality must reference one side each")
    return lk, rk


def _register() -> None:
    from spark_rapids_tpu.plugin import register_frontend

    register_frontend("substrait", SubstraitFrontend)


_register()
