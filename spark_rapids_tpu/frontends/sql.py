"""SQL-text frontend: run real SQL strings through the engine.

The reference's entire premise is accelerating the user's SQL,
unmodified (ref: sql-plugin/src/main/scala/com/nvidia/spark/
SQLPlugin.scala:26-31 — the plugin intercepts plans Spark built from
SQL text; the user changes nothing).  This frontend is the SQL-shaped
occupant of the `register_frontend` seam: a self-contained
tokenizer + recursive-descent parser that lowers a practical SQL subset
directly onto the engine's DataFrame/logical-plan surface, after which
tagging, TPU conversion and CPU fallback behave exactly as for native
plans.

Supported (enough to run the actual text of TPC-H q1/q3/q6 and
TPC-DS q3, and the common shapes around them):

- SELECT projections with aliases, `*`;
- FROM with comma joins and explicit [INNER|LEFT|RIGHT|FULL] JOIN ..
  ON; single-table WHERE conjuncts are pushed to their table and
  cross-table equality conjuncts become equi-join keys (left-deep, in
  FROM order — the textbook rewrite Spark's analyzer performs);
- WHERE / GROUP BY / HAVING / ORDER BY [ASC|DESC] (names, aliases or
  1-based ordinals) / LIMIT;
- aggregates sum/avg/min/max/count/count(*) over arbitrary input
  expressions;
- expressions: arithmetic, comparisons, AND/OR/NOT, BETWEEN, IN,
  [NOT] LIKE, IS [NOT] NULL, CASE (searched + simple), CAST(x AS t),
  EXTRACT(field FROM x), scalar functions (substring, upper, lower,
  length, coalesce, abs, round, year/month/day, concat, trim, nullif),
  string/number/date literals, and `date '...' +/- interval 'N' day`
  arithmetic (folded at parse time, as in TPC-H predicates);
- named parameters (`WHERE k = :k`, bound via `sql(text, params=...)` /
  `PreparedQuery.execute(params=...)`): each reference binds to a
  literal at parse time; unbound names raise SqlError with position —
  the template substrate of the serving tier's prepared-plan cache
  (docs/serving.md).

Identifiers resolve case-insensitively against the registered tables'
schemas; qualified refs (`alias.col`) check the alias but lower to the
bare column name (TPC schemas have globally unique column names, and
the engine resolves by name).
"""

from __future__ import annotations

import datetime as _dt
import re
from typing import Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.execs.sort import SortKey
from spark_rapids_tpu.exprs import aggregates as AG
from spark_rapids_tpu.exprs import arithmetic as A
from spark_rapids_tpu.exprs import base as B
from spark_rapids_tpu.exprs import cast as C
from spark_rapids_tpu.exprs import datetime as DT
from spark_rapids_tpu.exprs import math as M
from spark_rapids_tpu.exprs import predicates as P
from spark_rapids_tpu.exprs import strings as S
from spark_rapids_tpu.session import AnalysisException


class SqlError(ValueError):
    """Query outside the supported SQL subset (with position info)."""


#: grammar-fix kill switches for the sweep harness's fix probes
#: (tools/sweep.py): adding one of {"not_in_subquery",
#: "month_year_interval", "grouping_sets"} restores the pre-fix
#: rejection at that production, so the sweep can measure exactly
#: which TPC-DS queries each satellite fix advances.  Production code
#: never sets this.
DISABLED_FEATURES: set = set()


# ------------------------------------------------------------------ #
# Tokenizer
# ------------------------------------------------------------------ #

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?
           |\d+(?:[eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<qid>"(?:[^"]|"")*")
  | (?P<param>:[A-Za-z_][A-Za-z_0-9]*)
  | (?P<id>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><>|!=|>=|<=|=|<|>|\|\||[(),.*/%+\-;])
""", re.VERBOSE)


def _tokenize(text: str) -> list[tuple[str, str, int]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise SqlError(f"cannot tokenize at offset {pos}: "
                           f"{text[pos:pos + 20]!r}")
        kind = m.lastgroup
        if kind != "ws":
            out.append((kind, m.group(), pos))
        pos = m.end()
    out.append(("eof", "", len(text)))
    return out


def param_names(text: str) -> frozenset:
    """The named parameters (``:name``) a query template references —
    the prepared-statement substrate: ``SqlSession.prepare`` collects
    these up front so an unbound execute fails before any parsing."""
    return frozenset(tok[1][1:] for tok in _tokenize(text)
                     if tok[0] == "param")


def _param_literal(name: str, value, pos: int) -> B.Literal:
    """Bind one parameter value as an engine literal (the 'literal
    rebinding' seam: bound values become plain literals, so the lowered
    plan is indistinguishable from inline-literal SQL and keys into the
    jit/plan caches the same way)."""
    if isinstance(value, _dt.datetime):
        raise SqlError(
            f"parameter :{name}: timestamp parameters are not "
            "supported yet (bind epoch seconds or a date)")
    if isinstance(value, _dt.date):
        return B.Literal((value - _EPOCH).days, T.DATE)
    try:
        return B.Literal.of(value)
    except TypeError:
        raise SqlError(
            f"parameter :{name} at offset {pos} has unsupported type "
            f"{type(value).__name__} (bind int/float/str/bool/date/"
            f"None)") from None


_AGG_FNS = {"sum": AG.Sum, "min": AG.Min, "max": AG.Max,
            "avg": AG.Average, "mean": AG.Average, "count": AG.Count}


def _window_fn_table():
    from spark_rapids_tpu.exprs import window as W

    return {"rank": W.rank, "dense_rank": W.dense_rank,
            "row_number": W.row_number}


_WINDOW_FNS = _window_fn_table()


class _SubqueryExpr(B.Expression):
    """Parse-time marker for an uncorrelated scalar subquery; the
    lowering pass replaces it with the engine's ScalarSubquery over the
    lowered subplan (evaluated once by the planner prepass, ref:
    GpuScalarSubquery)."""

    def __init__(self, q: dict):
        self.q = q

    @property
    def dtype(self) -> T.DataType:
        raise RuntimeError("unresolved scalar subquery")

    @property
    def name(self) -> str:
        return "scalar_subquery"

    @property
    def children(self):
        return ()


class _ExistsSubquery(B.Expression):
    """Parse-time marker for [NOT] EXISTS (SELECT ... WHERE
    outer.col = inner.col ...); lowered to a LEFT SEMI / LEFT ANTI
    join on the correlated equality conjuncts (Spark's
    RewritePredicateSubquery)."""

    def __init__(self, q: dict, negated: bool):
        self.q = q
        self.negated = negated

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    @property
    def name(self) -> str:
        return "exists_subquery"

    @property
    def children(self):
        return ()


class _InSubquery(B.Expression):
    """Parse-time marker for `expr [NOT] IN (SELECT ...)`; IN lowers to
    a LEFT SEMI join (Spark's RewritePredicateSubquery), NOT IN to the
    null-aware anti-join shape: a LEFT ANTI equi-join plus the two
    scalar-subquery guards that reproduce Spark's
    NULL-aware semantics (empty subquery keeps every row; any NULL in
    the subquery, or a NULL probe value against a non-empty subquery,
    keeps none)."""

    def __init__(self, lhs, q: dict, negated: bool = False):
        self.lhs = lhs
        self.q = q
        self.negated = negated

    @property
    def dtype(self) -> T.DataType:
        return T.BOOLEAN

    @property
    def name(self) -> str:
        return "in_subquery"

    @property
    def children(self):
        return (self.lhs,)

def _lit_int(e, what: str) -> int:
    if isinstance(e, B.Literal) and isinstance(e.value, int):
        return e.value
    raise SqlError(f"{what} must be an integer literal")


#: scalar function name -> constructor over positional expr args
_SCALAR_FNS = {
    "upper": lambda x: S.Upper(x),
    "lower": lambda x: S.Lower(x),
    "length": lambda x: S.Length(x),
    "char_length": lambda x: S.Length(x),
    "substring": lambda x, p, n=None: S.Substring(
        x, _lit_int(p, "substring position"),
        None if n is None else _lit_int(n, "substring length")),
    "substr": lambda x, p, n=None: S.Substring(
        x, _lit_int(p, "substring position"),
        None if n is None else _lit_int(n, "substring length")),
    "trim": lambda x: S.StringTrim(x),
    "ltrim": lambda x: S.StringTrimLeft(x),
    "rtrim": lambda x: S.StringTrimRight(x),
    "concat": lambda *xs: S.Concat(*xs),
    "coalesce": lambda *xs: P.Coalesce(*xs),
    "abs": lambda x: A.Abs(x),
    "round": lambda x, n=None: M.Round(
        x, 0 if n is None else _lit_int(n, "round scale")),
    "bround": lambda x, n=None: M.BRound(
        x, 0 if n is None else _lit_int(n, "round scale")),
    "pmod": lambda a, b: A.Pmod(a, b),
    "year": lambda x: DT.Year(x),
    "month": lambda x: DT.Month(x),
    "day": lambda x: DT.DayOfMonth(x),
    "dayofmonth": lambda x: DT.DayOfMonth(x),
    "quarter": lambda x: DT.Quarter(x),
    "nullif": lambda a, b: P.If(P.EqualTo(a, b),
                                B.Literal(None, T.NULL), a),
    "if": lambda c, a, b: P.If(c, a, b),
    "least": lambda *xs: A.Least(*xs),
    "greatest": lambda *xs: A.Greatest(*xs),
}

_EXTRACT_FIELDS = {
    "year": DT.Year, "month": DT.Month, "day": DT.DayOfMonth,
    "quarter": DT.Quarter, "hour": DT.Hour, "minute": DT.Minute,
    "second": DT.Second, "dayofyear": DT.DayOfYear,
}

_CAST_TYPES = {
    "int": T.INT, "integer": T.INT, "bigint": T.LONG, "long": T.LONG,
    "smallint": T.SHORT, "tinyint": T.BYTE, "float": T.FLOAT,
    "real": T.FLOAT, "double": T.DOUBLE, "string": T.STRING,
    "varchar": T.STRING, "char": T.STRING, "boolean": T.BOOLEAN,
    "date": T.DATE, "timestamp": T.TIMESTAMP,
}

_EPOCH = _dt.date(1970, 1, 1)

class _Interval:
    """Parse-time interval value; only valid folded into date ± or as
    a calendar interval for month/year arithmetic."""

    def __init__(self, n: int, unit: str):
        self.n = n
        self.unit = unit.rstrip("s") if unit.endswith("s") else unit


def _fold_literal(e):
    """Constant-fold a literal-only arithmetic expression (the
    `IN (2001, 2001 + 1)` benchmark idiom) to a Literal, else None."""
    if isinstance(e, B.Literal):
        return e
    if isinstance(e, (A.Add, A.Subtract, A.Multiply)):
        l = _fold_literal(e.left)
        r = _fold_literal(e.right)
        if l is not None and r is not None \
                and isinstance(l.value, (int, float)) \
                and not isinstance(l.dtype, T.DateType) \
                and isinstance(r.value, (int, float)):
            op = {A.Add: lambda a, b: a + b,
                  A.Subtract: lambda a, b: a - b,
                  A.Multiply: lambda a, b: a * b}[type(e)]
            return B.Literal.of(op(l.value, r.value))
    return None


def _date_lit(s: str) -> B.Literal:
    d = _dt.date.fromisoformat(s)
    return B.Literal((d - _EPOCH).days, T.DATE)


def _shift_date(lit: B.Literal, iv: _Interval, sign: int) -> B.Literal:
    d = _EPOCH + _dt.timedelta(days=int(lit.value))
    if iv.unit == "day":
        d2 = d + _dt.timedelta(days=sign * iv.n)
    elif iv.unit == "week":
        d2 = d + _dt.timedelta(days=7 * sign * iv.n)
    elif iv.unit in ("month", "year"):
        months = iv.n * (12 if iv.unit == "year" else 1) * sign
        mi = d.year * 12 + (d.month - 1) + months
        y, m = divmod(mi, 12)
        import calendar

        day = min(d.day, calendar.monthrange(y, m + 1)[1])
        d2 = _dt.date(y, m + 1, day)
    else:
        raise SqlError(f"unsupported interval unit {iv.unit!r}")
    return B.Literal((d2 - _EPOCH).days, T.DATE)


# ------------------------------------------------------------------ #
# Parser
# ------------------------------------------------------------------ #


class _Parser:
    def __init__(self, text: str, params: Optional[dict] = None):
        self.toks = _tokenize(text)
        self.i = 0
        #: named-parameter bindings (:name -> python value); every
        #: reference binds to a literal at parse time, unbound names
        #: raise SqlError at their position
        self.params: dict = params or {}
        self.params_used: set = set()

    # -- token helpers -- #

    def peek(self, k: int = 0):
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def kw(self, k: int = 0) -> str:
        t = self.peek(k)
        return t[1].lower() if t[0] == "id" else ""

    def at(self, *words: str) -> bool:
        return self.kw() in words

    def accept(self, word: str) -> bool:
        if self.kw() == word:
            self.i += 1
            return True
        return False

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t[0] == "op" and t[1] == op:
            self.i += 1
            return True
        return False

    def expect(self, word: str) -> None:
        if not self.accept(word):
            t = self.peek()
            raise SqlError(f"expected {word!r}, got {t[1]!r} at {t[2]}")

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            t = self.peek()
            raise SqlError(f"expected {op!r}, got {t[1]!r} at {t[2]}")

    def ident(self) -> str:
        t = self.peek()
        if t[0] == "id":
            self.i += 1
            return t[1].lower()
        if t[0] == "qid":
            self.i += 1
            return t[1][1:-1].replace('""', '"')
        raise SqlError(f"expected identifier, got {t[1]!r} at {t[2]}")

    # -- statement -- #

    def parse_select(self, sub: bool = False) -> dict:
        """One full query: [WITH name AS (...), ...]
        core (UNION [ALL] core)* ORDER BY/LIMIT.  `sub` parses a
        parenthesized subquery (stops at the closing paren instead of
        requiring end-of-input)."""
        ctes: list[tuple] = []
        if self.accept("with"):
            # common table expressions: each name scopes over the rest
            # of the statement (and later CTEs); lowered once per
            # statement and shared by every reference (Spark's
            # CTESubstitution)
            while True:
                cname = self.ident()
                self.expect("as")
                self.expect_op("(")
                if self.kw() not in ("select", "with"):
                    raise SqlError(
                        f"expected SELECT in WITH {cname!r} at "
                        f"{self.peek()[2]}")
                ctes.append((cname, self.parse_select(sub=True)))
                self.expect_op(")")
                if not self.accept_op(","):
                    break
        q = self._select_core()
        unions: list[tuple] = []  # (member q dict, dedup?)
        while self.at("union"):
            self.i += 1
            dedup = not self.accept("all")
            if self.peek()[0] == "op" and self.peek()[1] == "(":
                # parenthesized member: a full subquery (its own
                # ORDER BY/LIMIT/unions allowed inside the parens)
                self.i += 1
                member = self.parse_select(sub=True)
                self.expect_op(")")
            else:
                member = self._select_core()
            unions.append((member, dedup))
        q["unions"] = unions
        q["ctes"] = ctes
        q["order_by"] = self._order_by_clause()
        q["limit"] = None
        if self.accept("limit"):
            t = self.peek()
            if t[0] != "num":
                raise SqlError(f"expected LIMIT count at {t[2]}")
            q["limit"] = int(t[1])
            self.i += 1
        if not sub:
            self.accept_op(";")
            if self.peek()[0] != "eof":
                t = self.peek()
                raise SqlError(f"unexpected trailing {t[1]!r} at {t[2]}")
        return q

    def _order_by_clause(self) -> list[tuple]:
        order_by: list[tuple] = []
        if self.accept("order"):
            self.expect("by")
            while True:
                e = self.expr()
                desc = False
                if self.accept("desc"):
                    desc = True
                else:
                    self.accept("asc")
                nulls_last = desc
                if self.accept("nulls"):
                    if self.accept("last"):
                        nulls_last = True
                    else:
                        self.expect("first")
                        nulls_last = False
                order_by.append((e, desc, nulls_last))
                if not self.accept_op(","):
                    break
        return order_by

    def _select_core(self) -> dict:
        self.expect("select")
        distinct = self.accept("distinct")
        items: list[tuple] = []  # (expr|"*", alias|None)
        while True:
            if self.accept_op("*"):
                items.append(("*", None))
            else:
                e = self.expr()
                alias = None
                if self.accept("as"):
                    alias = self.ident()
                elif (self.peek()[0] in ("id", "qid")
                      and self.kw() not in _CLAUSE_KWS):
                    alias = self.ident()
                items.append((e, alias))
            if not self.accept_op(","):
                break
        self.expect("from")
        tables = [self.table_ref()]
        joins: list[tuple] = []  # ("cross"|how, table_ref, on_expr|None)
        while True:
            if self.accept_op(","):
                joins.append(("cross", self.table_ref(), None))
                continue
            how = None
            if self.at("inner") and self.kw(1) == "join":
                self.i += 2
                how = "inner"
            elif self.at("left", "right", "full"):
                how = {"left": "left_outer", "right": "right_outer",
                       "full": "full_outer"}[self.kw()]
                self.i += 1
                self.accept("outer")
                if self.accept("semi"):
                    how = "left_semi"
                elif self.accept("anti"):
                    how = "left_anti"
                self.expect("join")
            elif self.accept("join"):
                how = "inner"
            if how is None:
                break
            tr = self.table_ref()
            self.expect("on")
            joins.append((how, tr, self.expr()))
        where = self.expr() if self.accept("where") else None
        group_by: list = []
        group_kind = None  # None | "rollup" | "cube" | "sets"
        group_sets: list = []  # for "sets": list of per-set expr lists
        if self.accept("group"):
            self.expect("by")
            if self.at("rollup") or self.at("cube"):
                group_kind = self.kw()
                self.i += 1
                self.expect_op("(")
                while True:
                    group_by.append(self.expr())
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            elif self.at("grouping") and self.kw(1) == "sets" \
                    and "grouping_sets" not in DISABLED_FEATURES:
                # GROUP BY GROUPING SETS ((a, b), (a), (), b): the
                # general form of the rollup/cube sugar — each set is a
                # parenthesized (possibly empty) key list or a bare
                # expression; group_by becomes the first-appearance
                # union of the keys and lowers through the same
                # Expand-based machinery (session.grouping_sets)
                self.i += 2
                group_kind = "sets"
                self.expect_op("(")
                while True:
                    one: list = []
                    if self.accept_op("("):
                        if not self.accept_op(")"):
                            one.append(self.expr())
                            while self.accept_op(","):
                                one.append(self.expr())
                            self.expect_op(")")
                    else:
                        one.append(self.expr())
                    group_sets.append(one)
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                from spark_rapids_tpu.execs.jit_cache import expr_key

                seen: set = set()
                for s in group_sets:
                    for e in s:
                        k = expr_key(e)
                        if k not in seen:
                            seen.add(k)
                            group_by.append(e)
            else:
                while True:
                    group_by.append(self.expr())
                    if not self.accept_op(","):
                        break
        having = self.expr() if self.accept("having") else None
        return {"items": items, "distinct": distinct, "tables": tables,
                "joins": joins, "where": where, "group_by": group_by,
                "group_kind": group_kind, "group_sets": group_sets,
                "having": having,
                "order_by": [], "limit": None, "unions": []}

    def table_ref(self) -> tuple:
        if self.peek()[0] == "op" and self.peek()[1] == "(":
            # derived table: FROM ( SELECT ... ) [AS] alias
            self.i += 1
            if self.kw() != "select":
                raise SqlError(
                    f"expected SELECT in derived table at "
                    f"{self.peek()[2]}")
            subq = self.parse_select(sub=True)
            self.expect_op(")")
            alias = None
            if self.accept("as"):
                alias = self.ident()
            elif (self.peek()[0] in ("id", "qid")
                  and self.kw() not in _TABLE_STOP_KWS):
                alias = self.ident()
            if alias is None:
                raise SqlError("derived table requires an alias")
            return (("__sub__", subq), alias)
        name = self.ident()
        alias = None
        if self.accept("as"):
            alias = self.ident()
        elif (self.peek()[0] in ("id", "qid")
              and self.kw() not in _TABLE_STOP_KWS):
            alias = self.ident()
        return (name, alias or name)

    # -- expressions (precedence climbing) -- #

    def expr(self):
        return self.or_expr()

    def or_expr(self):
        e = self.and_expr()
        while self.accept("or"):
            e = P.Or(e, self.and_expr())
        return e

    def and_expr(self):
        e = self.not_expr()
        while self.accept("and"):
            e = P.And(e, self.not_expr())
        return e

    def not_expr(self):
        if self.at("not") and self.kw(1) == "exists":
            self.i += 2
            return self._exists(negated=True)
        if self.accept("not"):
            return P.Not(self.not_expr())
        if self.accept("exists"):
            return self._exists(negated=False)
        return self.cmp_expr()

    def _exists(self, negated: bool):
        self.expect_op("(")
        if self.kw() != "select":
            raise SqlError(f"expected SELECT after EXISTS at "
                           f"{self.peek()[2]}")
        subq = self.parse_select(sub=True)
        self.expect_op(")")
        return _ExistsSubquery(subq, negated)

    def cmp_expr(self):
        e = self.add_expr()
        negate = False
        if self.at("not") and self.kw(1) in ("between", "in", "like"):
            self.i += 1
            negate = True
        if self.accept("between"):
            lo = self.add_expr()
            self.expect("and")
            hi = self.add_expr()
            out = P.And(P.GreaterThanOrEqual(e, lo),
                        P.LessThanOrEqual(e, hi))
            return P.Not(out) if negate else out
        if self.accept("in"):
            self.expect_op("(")
            if self.kw() == "select":
                subq = self.parse_select(sub=True)
                self.expect_op(")")
                if negate and "not_in_subquery" in DISABLED_FEATURES:
                    raise SqlError(
                        "NOT IN (subquery) is not supported (Spark's "
                        "null-aware anti-join semantics; rewrite with "
                        "NOT EXISTS or an explicit anti join)")
                return _InSubquery(e, subq, negated=negate)
            vals = [self.expr()]
            while self.accept_op(","):
                vals.append(self.expr())
            self.expect_op(")")
            folded = []
            for v in vals:
                fv = _fold_literal(v)
                if fv is None:
                    raise SqlError("IN list must be literals")
                folded.append(fv)
            out = P.In(e, tuple(v.value for v in folded))
            return P.Not(out) if negate else out
        if self.accept("like"):
            pat = self.add_expr()
            if not isinstance(pat, B.Literal):
                raise SqlError("LIKE pattern must be a literal")
            out = S.Like(e, str(pat.value))
            return P.Not(out) if negate else out
        if self.accept("is"):
            neg = self.accept("not")
            self.expect("null")
            return P.IsNotNull(e) if neg else P.IsNull(e)
        _ne = lambda a, b: P.Not(P.EqualTo(a, b))
        for op, ctor in (("=", P.EqualTo), ("<>", _ne),
                         ("!=", _ne), ("<=", P.LessThanOrEqual),
                         (">=", P.GreaterThanOrEqual), ("<", P.LessThan),
                         (">", P.GreaterThan)):
            if self.accept_op(op):
                return ctor(e, self.add_expr())
        return e

    def add_expr(self):
        e = self.mul_expr()
        while True:
            if self.accept_op("+"):
                r = self.mul_expr()
                e = self._plus_minus(e, r, +1)
            elif self.accept_op("-"):
                r = self.mul_expr()
                e = self._plus_minus(e, r, -1)
            elif self.accept_op("||"):
                e = S.Concat(e, self.mul_expr())
            else:
                return e

    @staticmethod
    def _plus_minus(left, right, sign: int):
        if isinstance(right, _Interval):
            if isinstance(left, B.Literal) \
                    and isinstance(left.dtype, T.DateType):
                return _shift_date(left, right, sign)
            # date column ± interval: day/week lower to DateAdd/DateSub
            days = right.n * (7 if right.unit == "week" else 1)
            if right.unit in ("day", "week"):
                ctor = DT.DateAdd if sign > 0 else DT.DateSub
                return ctor(left, B.Literal.of(days))
            if "month_year_interval" in DISABLED_FEATURES:
                raise SqlError("month/year interval arithmetic is only "
                               "supported on date literals")
            # month/year on a date COLUMN (or any non-literal date
            # expression): AddMonths-style calendar shift with
            # end-of-month clamping (exprs/datetime.AddMonths)
            months = right.n * (12 if right.unit == "year" else 1)
            return DT.AddMonths(left, sign * months)
        if isinstance(left, _Interval):
            raise SqlError("interval must be the right operand")
        return (A.Add if sign > 0 else A.Subtract)(left, right)

    def mul_expr(self):
        e = self.unary_expr()
        while True:
            if self.accept_op("*"):
                e = A.Multiply(e, self.unary_expr())
            elif self.accept_op("/"):
                e = A.Divide(e, self.unary_expr())
            elif self.accept_op("%"):
                e = A.Remainder(e, self.unary_expr())
            else:
                return e

    def unary_expr(self):
        if self.accept_op("-"):
            e = self.unary_expr()
            if isinstance(e, B.Literal) and not isinstance(
                    e.dtype, (T.StringType, T.DateType)):
                return B.Literal(-e.value, e.dtype)
            return A.UnaryMinus(e)
        self.accept_op("+")
        return self.primary()

    def primary(self):
        t = self.peek()
        if t[0] == "num":
            self.i += 1
            txt = t[1]
            if "." in txt or "e" in txt or "E" in txt:
                return B.Literal.of(float(txt))
            return B.Literal.of(int(txt))
        if t[0] == "str":
            self.i += 1
            return B.Literal.of(t[1][1:-1].replace("''", "'"))
        if t[0] == "param":
            self.i += 1
            name = t[1][1:]
            if name not in self.params:
                raise SqlError(
                    f"unbound parameter :{name} at offset {t[2]} — "
                    f"pass params={{'{name}': ...}} to sql()/execute()")
            self.params_used.add(name)
            return _param_literal(name, self.params[name], t[2])
        if self.accept_op("("):
            if self.kw() == "select":
                # uncorrelated scalar subquery: (SELECT <agg> FROM ...)
                subq = self.parse_select(sub=True)
                self.expect_op(")")
                return _SubqueryExpr(subq)
            e = self.expr()
            self.expect_op(")")
            return e
        if t[0] not in ("id", "qid"):
            raise SqlError(f"unexpected {t[1]!r} at {t[2]}")

        word = self.kw()
        if word == "date" and self.peek(1)[0] == "str":
            self.i += 1
            s = self.peek()
            self.i += 1
            return _date_lit(s[1][1:-1])
        if word == "interval":
            self.i += 1
            n_t = self.peek()
            if n_t[0] == "str":
                n = int(n_t[1][1:-1])
            elif n_t[0] == "num":
                n = int(n_t[1])
            else:
                raise SqlError(f"expected interval count at {n_t[2]}")
            self.i += 1
            unit = self.ident()
            if unit.rstrip("s") not in ("day", "week", "month", "year"):
                raise SqlError(f"unsupported interval unit {unit!r}")
            return _Interval(n, unit)
        if word == "case":
            return self._case()
        if word == "cast":
            self.i += 1
            self.expect_op("(")
            e = self.expr()
            self.expect("as")
            tname = self.ident()
            if tname == "decimal":
                # DECIMAL(p, s)
                self.expect_op("(")
                p = int(self.peek()[1])
                self.i += 1
                sc = 0
                if self.accept_op(","):
                    sc = int(self.peek()[1])
                    self.i += 1
                self.expect_op(")")
                dtype: T.DataType = T.DecimalType(p, sc)
            else:
                if tname not in _CAST_TYPES:
                    raise SqlError(f"unsupported cast type {tname!r}")
                dtype = _CAST_TYPES[tname]
                if self.accept_op("("):  # varchar(n) etc.
                    while not self.accept_op(")"):
                        self.i += 1
            self.expect_op(")")
            return C.Cast(e, dtype)
        if word == "extract":
            self.i += 1
            self.expect_op("(")
            field = self.ident()
            self.expect("from")
            e = self.expr()
            self.expect_op(")")
            if field not in _EXTRACT_FIELDS:
                raise SqlError(f"unsupported extract field {field!r}")
            return _EXTRACT_FIELDS[field](e)
        if word in ("null",):
            self.i += 1
            return B.Literal(None, T.NULL)
        if word in ("true", "false"):
            self.i += 1
            return B.Literal.of(word == "true")

        # function call or column reference
        if self.peek(1)[0] == "op" and self.peek(1)[1] == "(":
            fname = self.ident()
            self.expect_op("(")
            if fname == "count" and self.accept_op("*"):
                self.expect_op(")")
                star = AG.CountStar()
                if self.at("over"):
                    self.i += 1
                    return star.over(self._window_spec())
                return star
            distinct = self.accept("distinct")
            args: list = []
            if not self.accept_op(")"):
                args.append(self.expr())
                while self.accept_op(","):
                    args.append(self.expr())
                self.expect_op(")")
            if fname in _WINDOW_FNS:
                if args or distinct:
                    raise SqlError(f"{fname}() takes no arguments")
                self.expect("over")
                return _WINDOW_FNS[fname]().over(self._window_spec())
            if fname in ("lead", "lag"):
                from spark_rapids_tpu.exprs.window import lag, lead

                if not 1 <= len(args) <= 3 or distinct:
                    raise SqlError(f"{fname}(expr[, offset[, default]])")
                off = 1
                if len(args) >= 2:
                    off = _lit_int(args[1], f"{fname} offset")
                dflt = None
                if len(args) == 3:
                    if not isinstance(args[2], B.Literal):
                        raise SqlError(
                            f"{fname} default must be a literal")
                    dflt = args[2].value
                fn = (lead if fname == "lead" else lag)(
                    args[0], off, dflt)
                self.expect("over")
                return fn.over(self._window_spec())
            if fname in _AGG_FNS:
                if len(args) != 1:
                    raise SqlError(f"{fname} takes one argument")
                if distinct:
                    if fname != "count":
                        raise SqlError(
                            f"DISTINCT unsupported for {fname}")
                    from spark_rapids_tpu.session import count_distinct

                    return count_distinct(args[0])
                agg = _AGG_FNS[fname](args[0])
                if self.at("over"):
                    self.i += 1
                    return agg.over(self._window_spec())
                return agg
            if fname in _SCALAR_FNS:
                try:
                    return _SCALAR_FNS[fname](*args)
                except TypeError as e:
                    raise SqlError(f"bad arguments for {fname}: {e}")
            raise SqlError(f"unknown function {fname!r}")

        name = self.ident()
        if self.accept_op("."):
            col = self.ident()
            return _QualifiedRef(name, col)
        return B.ColumnReference(name)

    def _window_spec(self):
        """OVER ( [PARTITION BY e,..] [ORDER BY e [ASC|DESC],..]
        [ROWS|RANGE BETWEEN <bound> AND <bound>] )"""
        from spark_rapids_tpu.execs.sort import SortKey
        from spark_rapids_tpu.exprs.window import WindowSpecBuilder

        self.expect_op("(")
        b = WindowSpecBuilder()
        if self.accept("partition"):
            self.expect("by")
            parts = [self.expr()]
            while self.accept_op(","):
                parts.append(self.expr())
            b.partition_by(*parts)
        if self.at("order"):
            b.order_by(*[SortKey(e, descending=d, nulls_last=n)
                         for e, d, n in self._order_by_clause()])
        if self.at("rows") or self.at("range"):
            mode = self.kw()
            self.i += 1
            self.expect("between")
            lo = self._frame_bound(start=True)
            self.expect("and")
            hi = self._frame_bound(start=False)
            if mode == "rows":
                b.rows_between(lo, hi)
            else:
                b.range_between(lo, hi)
        self.expect_op(")")
        return b

    def _frame_bound(self, start: bool):
        """UNBOUNDED PRECEDING/FOLLOWING | CURRENT ROW | n PRECEDING |
        n FOLLOWING -> the builder's signed-offset convention
        (None = unbounded, 0 = current row).  `start` validates the
        direction: a frame may not start at UNBOUNDED FOLLOWING nor end
        at UNBOUNDED PRECEDING."""
        if self.accept("unbounded"):
            if self.accept("preceding"):
                if not start:
                    raise SqlError(
                        "frame cannot end at UNBOUNDED PRECEDING")
            elif self.accept("following"):
                if start:
                    raise SqlError(
                        "frame cannot start at UNBOUNDED FOLLOWING")
            else:
                raise SqlError("expected PRECEDING/FOLLOWING after "
                               "UNBOUNDED")
            return None
        if self.accept("current"):
            self.expect("row")
            return 0
        t = self.peek()
        if t[0] != "num":
            raise SqlError(f"expected frame bound at {t[2]}")
        n = int(t[1])
        self.i += 1
        if self.accept("preceding"):
            return -n
        self.expect("following")
        return n

    def _case(self):
        self.expect("case")
        operand = None
        if not self.at("when"):
            operand = self.expr()
        branches: list[tuple] = []
        while self.accept("when"):
            cond = self.expr()
            if operand is not None:
                cond = P.EqualTo(operand, cond)
            self.expect("then")
            branches.append((cond, self.expr()))
        default = self.expr() if self.accept("else") else None
        self.expect("end")
        return P.CaseWhen(tuple(branches), default)


_CLAUSE_KWS = {"from", "where", "group", "having", "order", "limit",
               "as", "on", "join", "inner", "left", "right", "full",
               "and", "or", "not", "asc", "desc", "nulls", "union",
               "when", "then", "else", "end", "between", "in", "like",
               "is", "by"}
_TABLE_STOP_KWS = _CLAUSE_KWS


def _rebuild(e, vals: dict, changed: bool):
    """dataclasses.replace with a with_children fallback for expression
    classes whose custom *args __init__ rejects keyword field names
    (Concat, Coalesce, Least/Greatest)."""
    import dataclasses as _dcs

    if not changed:
        return e
    try:
        return _dcs.replace(e, **vals)
    except TypeError:
        kids = [vals.get(f.name, getattr(e, f.name))
                for f in _dcs.fields(e)]
        flat = []
        for k in kids:
            if isinstance(k, (tuple, list)):
                flat.extend(k)
            else:
                flat.append(k)
        return e.with_children(flat)


class _QualifiedRef(B.ColumnReference):
    """alias.col — carries the qualifier for alias checking, lowers to
    a bare name reference (engine resolution is by column name)."""

    def __init__(self, qualifier: str, col: str):
        super().__init__(col)
        self.qualifier = qualifier


# ------------------------------------------------------------------ #
# Lowering onto the DataFrame surface
# ------------------------------------------------------------------ #


def _walk(e):
    """Every sub-node, crossing BOTH Expression children and aggregate
    functions hiding in expression slots (AggregateFunction is not an
    Expression, so `children` alone would miss e.g. the count(*) inside
    a HAVING comparison)."""
    import dataclasses as _dcs

    yield e
    if isinstance(e, AG.AggregateFunction):
        if e.child is not None:
            yield from _walk(e.child)
        return
    for c in getattr(e, "children", ()):
        yield from _walk(c)
    if _dcs.is_dataclass(e):
        for f in _dcs.fields(e):
            v = getattr(e, f.name)
            vs = v if isinstance(v, (tuple, list)) else (v,)
            for x in vs:
                if isinstance(x, AG.AggregateFunction):
                    yield from _walk(x)


def _has_agg(e) -> bool:
    return any(isinstance(x, AG.AggregateFunction) for x in _walk(e))


def _refs(e) -> set:
    return {x.col_name for x in _walk(e)
            if isinstance(x, B.ColumnReference)}


def _qualifiers(e) -> set:
    """The table aliases qualifying references under ``e`` (empty for
    fully-unqualified expressions)."""
    return {x.qualifier.lower() for x in _walk(e)
            if isinstance(x, _QualifiedRef)}


def _conjuncts(e) -> list:
    if isinstance(e, P.And):
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _disjuncts(e) -> list:
    if isinstance(e, P.Or):
        return _disjuncts(e.left) + _disjuncts(e.right)
    return [e]


def _factor_common_conjuncts(e):
    """(A ∧ X ∧ ...) ∨ (A ∧ Y ∧ ...) -> A ∧ (X... ∨ Y...): Spark's
    common-conjunct extraction from disjunctive predicates (the rewrite
    that surfaces TPC-H q19's join condition out of its OR blocks)."""
    from functools import reduce

    from spark_rapids_tpu.execs.jit_cache import expr_key

    if not isinstance(e, P.Or):
        return e
    branches = [_conjuncts(b) for b in _disjuncts(e)]
    try:
        common = set.intersection(
            *({expr_key(c) for c in cs} for cs in branches))
    except TypeError:
        # a branch holds an unkeyable marker (e.g. an IN-subquery
        # inside OR): skip factoring; downstream checks report it
        return e
    if not common:
        return e
    kept = []
    seen0: set = set()
    for c in branches[0]:
        k = expr_key(c)
        if k in common and k not in seen0:
            seen0.add(k)
            kept.append(c)
    residues = []
    for cs in branches:
        seen: set = set()
        res = []
        for c in cs:
            k = expr_key(c)
            if k in common and k not in seen:
                seen.add(k)
                continue
            res.append(c)
        residues.append(_and_all(res))
    if any(r is None for r in residues):
        # some branch was ENTIRELY common conjuncts: the residue
        # disjunction is a tautology
        return _and_all(kept)
    return _and_all(kept + [reduce(P.Or, residues)])


def _and_all(es: Sequence):
    out = None
    for e in es:
        out = e if out is None else P.And(out, e)
    return out


class SqlSession:
    """The `frontend("sql")` object: register tables, run SQL text.

    Registered tables are engine DataFrames (from `register_parquet`,
    `register_table`, or any DataFrame built with the native API); the
    planner then treats SQL-built plans identically to native ones."""

    def __init__(self, conf=None, session=None):
        """``session`` shares an existing TpuSession (the connect
        server pairs one session across its Substrait and SQL
        frontends); otherwise a fresh one is built from ``conf``."""
        from spark_rapids_tpu.session import TpuSession

        if session is not None:
            self.session = session
        else:
            self.session = TpuSession(conf) if conf is not None \
                else TpuSession()
        self._tables: dict[str, object] = {}

    # -- registry -- #

    def register_parquet(self, name: str, *paths: str) -> None:
        self._tables[name.lower()] = self.session.read_parquet(*paths)

    def register_table(self, name: str, df) -> None:
        """Register an engine DataFrame (or a pyarrow Table)."""
        import pyarrow as pa

        if isinstance(df, pa.Table):
            df = self.session.create_dataframe(df)
        self._tables[name.lower()] = df

    def table(self, name: str):
        try:
            return self._tables[name.lower()]
        except KeyError:
            raise SqlError(f"table {name!r} is not registered "
                           f"(have: {sorted(self._tables)})") from None

    # -- execution -- #

    def sql(self, text: str, params: Optional[dict] = None):
        """Parse + lower one SELECT; returns an engine DataFrame.

        ``params`` binds named parameters (``WHERE k = :k`` with
        ``params={"k": 5}``) as literals at parse time — the template
        substrate of the prepared-plan cache (docs/serving.md).
        Unbound references and unreferenced bindings both raise
        SqlError (a silently ignored binding is a typo'd template)."""
        p = _Parser(text, params=params)
        q = p.parse_select()
        if params:
            unused = sorted(set(params) - p.params_used)
            if unused:
                raise SqlError(
                    "unknown parameter(s) "
                    + ", ".join(f":{n}" for n in unused)
                    + " — not referenced by the query")
        return self._lower(q)

    def prepare(self, text: str):
        """Prepare a SQL template (named ``:name`` parameters allowed):
        returns a PreparedQuery whose ``execute(params=...)`` parses +
        lowers once PER BINDING and re-drains the cached lowered plan
        on repeats — the repeated-template path skips parse/plan/tag/
        lower entirely (docs/serving.md).  Parameterless templates are
        lowered eagerly here; parameterized ones on first execute."""
        from spark_rapids_tpu.serving.prepared import PreparedQuery

        names = param_names(text)
        pq = PreparedQuery(self.session, sql_text=text,
                           sql_session=self, param_names=names)
        if not names:
            pq._resolve(None)  # validate + warm the cache now
        return pq

    def _lower(self, q: dict, ctes: Optional[dict] = None):
        # CTE scope: outer names plus this statement's WITH list, each
        # lowered ONCE (left to right, so later CTEs and the body see
        # earlier ones) and shared by every reference
        scope = dict(ctes) if ctes else {}
        for cname, cq in q.get("ctes") or []:
            scope[cname.lower()] = self._lower(cq, scope)
        if q.get("unions"):
            # left-associative UNION chain; plain UNION dedups (Spark's
            # Distinct over Union), outer ORDER BY/LIMIT bind the chain
            core = dict(q, unions=[], order_by=[], limit=None, ctes=[])
            out = self._lower(core, scope)
            for member, dedup in q["unions"]:
                m = self._lower(member, scope)
                try:
                    # DataFrame.union validates column count and applies
                    # WidenSetOperationTypes at the engine layer;
                    # surface its deliberate analysis failures as
                    # SqlError (incidental TypeErrors still propagate)
                    out = out.union(m)
                except AnalysisException as e:
                    raise SqlError(str(e)) from None
                if dedup:
                    out = out.group_by(
                        *[B.ColumnReference(f.name)
                          for f in out.schema.fields]).agg()
            return self._order_and_limit(out, q)

        # resolve tables and alias -> column-set mapping (a table name
        # may be a parsed derived-table subquery)
        frames = []  # (alias, df, colnames)
        for name, alias in [q["tables"][0]] + [j[1] for j in q["joins"]]:
            if isinstance(name, tuple) and name[0] == "__sub__":
                df = self._lower(name[1], scope)
            elif isinstance(name, tuple) and name[0] == "__df__":
                df = name[1]  # pre-lowered derived table (EXISTS path)
            elif name in scope:
                df = scope[name]
            else:
                df = self.table(name)
            cols = {f.name.lower() for f in df.schema.fields}
            frames.append((alias.lower(), df, cols))
        self._check_qualifiers(q, frames)
        # qualifiers are kept through pushdown/join-key analysis (a
        # `t1.x = t2.x` self-join equality must not collapse into a
        # pushable tautology when both frames expose `x`); they strip
        # at each point an expression is handed to the engine, and
        # wholesale before projection
        self._resolve_scalar_subqueries(q, scope)

        if q["where"] is not None:
            q["where"] = _and_all([_factor_common_conjuncts(c)
                                   for c in _conjuncts(q["where"])])
        where_conjs = _conjuncts(q["where"]) if q["where"] is not None \
            else []
        # `x IN (SELECT ...)` and [NOT] EXISTS conjuncts become LEFT
        # SEMI / LEFT ANTI joins applied after the FROM joins (Spark's
        # RewritePredicateSubquery)
        in_subs = [cj for cj in where_conjs
                   if isinstance(cj, _InSubquery)]
        exists_subs = [cj for cj in where_conjs
                       if isinstance(cj, _ExistsSubquery)]
        where_conjs = [cj for cj in where_conjs
                       if not isinstance(cj, (_InSubquery,
                                              _ExistsSubquery))]
        for cj in where_conjs:
            if any(isinstance(x, (_InSubquery, _ExistsSubquery))
                   for x in _walk(cj)):
                raise SqlError("IN/EXISTS (subquery) is only supported "
                               "as a top-level AND condition")
        joins = q["joins"]

        # push single-table conjuncts down to their frame (the textbook
        # predicate-pushdown rewrite; lets the scan prefilter see them).
        # ONLY sound when every join is inner: a WHERE conjunct over the
        # null-producing side of an outer join filters post-join NULLs,
        # which a pre-join filter cannot reproduce — with any outer join
        # present, all WHERE conjuncts stay above the joins.
        all_inner = all(j[0] in ("cross", "inner") for j in joins)
        pushed_ids: set = set()
        frames2 = []
        for alias, df, cols in frames:
            mine = []
            if all_inner:
                for cj in where_conjs:
                    r = _refs(cj)
                    quals = _qualifiers(cj)
                    if id(cj) not in pushed_ids and r and r <= cols \
                            and quals <= {alias} \
                            and not _has_agg(cj):
                        mine.append(cj)
                        pushed_ids.add(id(cj))
            pushed = _and_all([self._strip_expr(c) for c in mine])
            if pushed is not None:
                df = df.where(pushed)
            frames2.append((alias, df, cols))
        remaining = [cj for cj in where_conjs
                     if id(cj) not in pushed_ids]

        # left-deep join in FROM order; comma joins consume equality
        # conjuncts from WHERE as join keys.  Self-join collisions
        # (both sides expose a column name) rename the RIGHT frame's
        # colliding columns to __<alias>__<col> before the join;
        # qualified references resolve through `renames` from then on
        # (the engine and the CPU oracle both resolve by name, so
        # duplicates must never reach the joined schema).
        renames: dict = {}
        acc_alias, acc_df, acc_cols = frames2[0]
        acc_cols = set(acc_cols)
        acc_aliases = {acc_alias}
        for (how, _tr, on_expr), (alias, df, cols) in zip(
                joins, frames2[1:]):
            clash = cols & acc_cols
            if clash:
                exprs = []
                for f in df.schema.fields:
                    n = f.name.lower()
                    if n in clash:
                        renames[(alias, n)] = f"__{alias}__{n}"
                        exprs.append(B.Alias(
                            B.ColumnReference(f.name),
                            renames[(alias, n)]))
                    else:
                        exprs.append(B.ColumnReference(f.name))
                df = df.select(*exprs)
                cols = {f.name.lower() for f in df.schema.fields}
            lk, rk, extra = [], [], []
            if how == "cross":
                how = "inner"
                take_ids = set()
                for cj in remaining:
                    sides = self._equi_sides(cj, acc_cols, cols,
                                             acc_aliases, alias,
                                             renames)
                    if sides is not None:
                        lk.append(sides[0])
                        rk.append(sides[1])
                        # identity, NOT equality: self-join conjuncts
                        # (t1.x = t2.x, t1.x = t3.x) compare
                        # structurally equal once qualifiers are
                        # ignored — consuming one must not consume all
                        take_ids.add(id(cj))
                remaining = [c for c in remaining
                             if id(c) not in take_ids]
                if not lk:
                    raise SqlError(
                        f"no join condition links table "
                        f"{alias!r} to the preceding tables "
                        "(cartesian products are not supported)")
            else:
                for cj in _conjuncts(on_expr):
                    sides = self._equi_sides(cj, acc_cols, cols,
                                             acc_aliases, alias,
                                             renames)
                    if sides is not None:
                        lk.append(sides[0])
                        rk.append(sides[1])
                    else:
                        extra.append(self._strip_expr(cj, renames))
                if not lk:
                    raise SqlError("JOIN ON needs at least one "
                                   "equality condition")
            acc_df = acc_df.join(df, left_on=lk, right_on=rk, how=how,
                                 condition=_and_all(extra))
            acc_cols |= cols
            acc_aliases.add(alias)

        post_where = _and_all([self._strip_expr(c, renames)
                               for c in remaining])
        if post_where is not None:
            acc_df = acc_df.where(post_where)

        for isq in in_subs:
            sub = self._lower(isq.q, scope)
            if len(sub.schema.fields) != 1:
                raise SqlError(
                    "IN subquery must select exactly one column")
            rcol = B.ColumnReference(sub.schema.fields[0].name)
            lhs = self._strip_expr(isq.lhs, renames)
            if not isq.negated:
                acc_df = acc_df.join(sub, left_on=[lhs],
                                     right_on=[rcol], how="left_semi")
                continue
            # NOT IN (subquery): Spark's null-aware anti-join semantics
            # out of shapes the engine already executes — a LEFT ANTI
            # equi-join drops the definite matches, then two
            # uncorrelated scalar-subquery guards (evaluated once by
            # the planner prepass) restore the NULL cases: an EMPTY
            # subquery keeps every row (even NULL probes); any NULL in
            # the subquery, or a NULL probe against a non-empty
            # subquery, yields UNKNOWN and keeps none.
            from spark_rapids_tpu.exprs.subquery import ScalarSubquery

            n_rows = sub.agg((AG.CountStar(), "__nin_rows"))
            n_nulls = sub.where(P.IsNull(rcol)).agg(
                (AG.CountStar(), "__nin_nulls"))
            zero = B.Literal(0, T.LONG)
            acc_df = acc_df.join(sub, left_on=[lhs],
                                 right_on=[rcol], how="left_anti")
            acc_df = acc_df.where(P.Or(
                P.EqualTo(ScalarSubquery(n_rows._plan), zero),
                P.And(P.EqualTo(ScalarSubquery(n_nulls._plan), zero),
                      P.IsNotNull(lhs))))

        for ex in exists_subs:
            acc_df = self._lower_exists(acc_df, acc_cols, ex, scope)

        self._strip_qualifiers(q, renames)
        return self._project(q, acc_df)

    def _lower_exists(self, acc_df, acc_cols: set, ex: "_ExistsSubquery",
                      scope: Optional[dict] = None):
        """[NOT] EXISTS with equality correlation -> LEFT SEMI/ANTI
        join: correlated equality conjuncts in the subquery's WHERE
        become join keys; everything else must be inner-only and stays
        the subquery's filter."""
        q = ex.q
        if q.get("unions"):
            raise SqlError("EXISTS over UNION is not supported")
        if q["group_by"] or q["having"] is not None or any(
                it != "*" and _has_agg(it) for it, _a in q["items"]):
            # an ungrouped aggregate subquery always returns one row
            # (EXISTS trivially true) and a grouped one filters on
            # group existence — neither maps to the plain semi join
            # this rewrite produces
            raise SqlError("EXISTS over an aggregating subquery is "
                           "not supported")
        inner_cols: set = set()
        refs = [q["tables"][0]] + [j[1] for j in q["joins"]]
        resolved: list[tuple] = []  # table refs for q2: derived tables
        # pre-lowered ONCE here as ("__df__", df) entries, so the
        # _lower(q2) below reuses them instead of lowering them again
        for name, alias in refs:
            if isinstance(name, tuple) and name[0] == "__sub__":
                df = self._lower(name[1], scope)
                inner_cols |= {f.name.lower() for f in df.schema.fields}
                resolved.append((("__df__", df), alias))
            else:
                src = (scope or {}).get(name) or self.table(name)
                inner_cols |= {f.name.lower()
                               for f in src.schema.fields}
                resolved.append((name, alias))

        def colname(e):
            if isinstance(e, (B.ColumnReference, _QualifiedRef)):
                return e.col_name.lower()
            return None

        outer_keys, inner_keys, keep = [], [], []
        for cj in (_conjuncts(q["where"])
                   if q["where"] is not None else []):
            sides = None
            if isinstance(cj, P.EqualTo):
                an, bn = colname(cj.left), colname(cj.right)
                if an is not None and bn is not None:
                    if an in inner_cols and bn not in inner_cols \
                            and bn in acc_cols:
                        sides = (bn, an)
                    elif bn in inner_cols and an not in inner_cols \
                            and an in acc_cols:
                        sides = (an, bn)
            if sides is not None:
                outer_keys.append(B.ColumnReference(sides[0]))
                inner_keys.append(B.ColumnReference(sides[1]))
                continue
            for x in _walk(cj):
                n = colname(x)
                if n is not None and n not in inner_cols:
                    raise SqlError(
                        f"EXISTS correlation on {n!r} must be a plain "
                        "equality conjunct (non-equality correlated "
                        "predicates are not supported)")
            keep.append(cj)
        if not outer_keys:
            raise SqlError("EXISTS subquery must correlate with the "
                           "outer query through at least one equality")
        q2 = dict(q, where=_and_all(keep),
                  tables=[resolved[0]],
                  joins=[(how, r, on) for (how, _tr, on), r
                         in zip(q["joins"], resolved[1:])],
                  items=[(B.ColumnReference(n), None)
                         for n in dict.fromkeys(
                             k.col_name for k in inner_keys)],
                  distinct=False, order_by=[], limit=None)
        sub = self._lower(q2, scope)
        how = "left_anti" if ex.negated else "left_semi"
        return acc_df.join(sub, left_on=outer_keys,
                           right_on=inner_keys, how=how)

    def _resolve_scalar_subqueries(self, q: dict,
                                   scope: Optional[dict] = None) -> None:
        """Replace scalar-subquery markers with the engine's
        ScalarSubquery over the recursively lowered subplan."""
        import dataclasses as _dcs

        from spark_rapids_tpu.exprs.subquery import ScalarSubquery

        def rw(e):
            if isinstance(e, _SubqueryExpr):
                sub = self._lower(e.q, scope)
                if len(sub.schema.fields) != 1:
                    raise SqlError("scalar subquery must select "
                                   "exactly one column")
                return ScalarSubquery(sub._plan)
            if isinstance(e, _InSubquery):
                return _InSubquery(rw(e.lhs), e.q, e.negated)
            if isinstance(e, _ExistsSubquery):
                return e
            if isinstance(e, AG.AggregateFunction):
                if _dcs.is_dataclass(e) and e.child is not None:
                    nc = rw(e.child)
                    return _dcs.replace(e, child=nc) \
                        if nc is not e.child else e
                return e
            if not _dcs.is_dataclass(e):
                return e
            vals = {}
            changed = False
            for f in _dcs.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, (B.Expression, AG.AggregateFunction)):
                    nv = rw(v)
                elif isinstance(v, (tuple, list)):
                    nv = type(v)(
                        rw(x) if isinstance(
                            x, (B.Expression, AG.AggregateFunction))
                        else x for x in v)
                else:
                    nv = v
                vals[f.name] = nv
                changed = changed or nv is not v
            return _rebuild(e, vals, changed)

        q["items"] = [(it if it == "*" else rw(it), al)
                      for it, al in q["items"]]
        for part in ("where", "having"):
            if q[part] is not None:
                q[part] = rw(q[part])
        q["order_by"] = [(rw(e), d, n) for e, d, n in q["order_by"]]
        q["group_by"] = [rw(e) for e in q["group_by"]]
        q["group_sets"] = [[rw(e) for e in s]
                           for s in q.get("group_sets") or []]
        q["joins"] = [(how, tr, rw(on) if on is not None else None)
                      for how, tr, on in q["joins"]]
        # IN (subquery) lowers only from top-level WHERE conjuncts;
        # anywhere else would reach the engine as an unplannable marker
        def no_insub(e, where_word):
            if e is not None and any(isinstance(x, _InSubquery)
                                     for x in _walk(e)):
                raise SqlError("IN (subquery) is only supported as a "
                               f"top-level WHERE condition, not in "
                               f"{where_word}")

        for it, _al in q["items"]:
            if it != "*":
                no_insub(it, "the SELECT list")
        no_insub(q["having"], "HAVING")
        for e in q["group_by"]:
            no_insub(e, "GROUP BY")
        for e, _d, _n in q["order_by"]:
            no_insub(e, "ORDER BY")
        for _how, _tr, on in q["joins"]:
            no_insub(on, "JOIN ON")

    def _order_and_limit(self, out, q: dict):
        """Outer ORDER BY (names or 1-based ordinals) + LIMIT."""
        out_names = [f.name for f in out.schema.fields]
        if q["order_by"]:
            keys = []
            for e, desc, nulls_last in q["order_by"]:
                if isinstance(e, B.Literal) and isinstance(e.value, int) \
                        and 1 <= e.value <= len(out_names):
                    e = B.ColumnReference(out_names[e.value - 1])
                keys.append(SortKey(e, descending=desc,
                                    nulls_last=nulls_last))
            out = out.order_by(*keys)
        if q["limit"] is not None:
            out = out.limit(q["limit"])
        return out

    @staticmethod
    def _side_ok(e, cols: set, aliases: set, renames: dict) -> bool:
        """Every reference in ``e`` resolves within ONE join side:
        unqualified names must be in the side's columns, qualified
        names must ALSO name one of the side's table aliases (the
        self-join disambiguator: after stripping, ``t1.x`` and
        ``t2.x`` read the same, but the qualifier pins the frame).
        ``renames`` maps (alias, col) to its disambiguated output
        name for frames whose columns collided at join time."""
        for x in _walk(e):
            if isinstance(x, _QualifiedRef):
                qual = x.qualifier.lower()
                eff = renames.get((qual, x.col_name.lower()),
                                  x.col_name.lower())
                if qual not in aliases or eff not in cols:
                    return False
            elif isinstance(x, B.ColumnReference):
                if x.col_name.lower() not in cols:
                    return False
        return True

    def _equi_sides(self, cj, left_cols: set, right_cols: set,
                    left_aliases: set, right_alias: str,
                    renames: dict):
        """An equality whose two sides reference disjoint frames is an
        equi-join key pair — either side may be an EXPRESSION over one
        frame's columns (``d_week_seq1 = d_week_seq2 - 53``), the
        engine's join keys accept expressions.  Returns the key pair
        with qualifiers stripped through the rename map (engine
        resolution is by name; the right side's unqualified refs map
        through its own frame's renames)."""
        if not isinstance(cj, P.EqualTo):
            return None
        a, b = cj.left, cj.right
        ra, rb = _refs(a), _refs(b)
        if not ra or not rb or _has_agg(a) or _has_agg(b):
            return None
        right_aliases = {right_alias}

        def right_ok(e):
            for x in _walk(e):
                if isinstance(x, B.ColumnReference):
                    if isinstance(x, _QualifiedRef) \
                            and x.qualifier.lower() != right_alias:
                        return False
                    eff = renames.get((right_alias,
                                       x.col_name.lower()),
                                      x.col_name.lower())
                    if eff not in right_cols:
                        return False
            return True

        if self._side_ok(a, left_cols, left_aliases, renames) \
                and right_ok(b):
            return (self._strip_expr(a, renames),
                    self._strip_expr(b, renames, frame=right_alias))
        if self._side_ok(b, left_cols, left_aliases, renames) \
                and right_ok(a):
            return (self._strip_expr(b, renames),
                    self._strip_expr(a, renames, frame=right_alias))
        return None

    def _strip_expr(self, e, renames: Optional[dict] = None,
                    frame: Optional[str] = None):
        """Lower every alias.col reference in ``e`` to a plain
        ColumnReference (engine resolution is by name; expr_key embeds
        the class name, so a surviving _QualifiedRef would falsely
        split `select t.a ... group by a`).  ``renames`` maps
        (alias, col) to the disambiguated output name minted when a
        self-join collided; ``frame`` maps UNQUALIFIED refs through
        that frame's renames (used for right-side join keys, whose
        refs all resolve within one frame by construction)."""
        import dataclasses as _dcs

        renames = renames or {}

        def rw(e):
            if isinstance(e, _QualifiedRef):
                return B.ColumnReference(renames.get(
                    (e.qualifier.lower(), e.col_name.lower()),
                    e.col_name))
            if isinstance(e, _InSubquery):
                return _InSubquery(rw(e.lhs), e.q, e.negated)
            if frame is not None and isinstance(e, B.ColumnReference):
                return B.ColumnReference(renames.get(
                    (frame, e.col_name.lower()), e.col_name))
            if isinstance(e, (_SubqueryExpr, _ExistsSubquery)):
                return e
            if not _dcs.is_dataclass(e):
                return e
            changed = False
            vals = {}
            for f in _dcs.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, (B.Expression, AG.AggregateFunction)):
                    nv = rw(v)
                elif isinstance(v, (tuple, list)):
                    nv = type(v)(
                        rw(x) if isinstance(
                            x, (B.Expression, AG.AggregateFunction))
                        else x for x in v)
                else:
                    nv = v
                vals[f.name] = nv
                changed = changed or nv is not v
            return _rebuild(e, vals, changed)

        return rw(e)

    def _strip_qualifiers(self, q: dict,
                          renames: Optional[dict] = None) -> None:
        """Strip alias qualifiers from every clause of ``q`` — called
        AFTER pushdown/join analysis consumed WHERE (the qualifiers
        are the self-join disambiguators there, _side_ok).  ``renames``
        maps collided self-join columns to their disambiguated
        names."""
        import dataclasses as _dcs

        def rw(e):
            return self._strip_expr(e, renames)

        def rwa(a):
            if a is None:
                return None
            if isinstance(a, AG.AggregateFunction):
                if a.child is not None:
                    return _dcs.replace(a, child=rw(a.child)) \
                        if _dcs.is_dataclass(a) else a
                return a
            return rw(a)

        q["items"] = [(it if it == "*" else rwa(it), al)
                      for it, al in q["items"]]
        for part in ("where", "having"):
            if q[part] is not None:
                q[part] = rwa(q[part])
        q["group_by"] = [rwa(e) for e in q["group_by"]]
        q["group_sets"] = [[rwa(e) for e in s]
                          for s in q.get("group_sets") or []]
        q["order_by"] = [(rwa(e), d, n) for e, d, n in q["order_by"]]
        q["joins"] = [(how, tr, rwa(on) if on is not None else None)
                      for how, tr, on in q["joins"]]

    def _check_qualifiers(self, q: dict, frames) -> None:
        alias_cols = {a: cols for a, _df, cols in frames}

        def check(e):
            for x in _walk(e):
                if isinstance(x, _QualifiedRef):
                    cols = alias_cols.get(x.qualifier.lower())
                    if cols is None:
                        raise SqlError(
                            f"unknown table alias {x.qualifier!r}")
                    if x.col_name.lower() not in cols:
                        raise SqlError(
                            f"column {x.col_name!r} not in table "
                            f"{x.qualifier!r}")

        for item, _alias in q["items"]:
            if item != "*":
                check(item)
        for part in ("where", "having"):
            if q[part] is not None:
                check(q[part])
        for e in q["group_by"]:
            check(e)
        for e, _d, _n in q["order_by"]:
            check(e)

    def _project(self, q: dict, df):
        items = q["items"]
        group_by = q["group_by"]
        has_aggs = any(item != "*" and _has_agg(item)
                       for item, _ in items) or q["having"] is not None

        plain = not group_by and not has_aggs
        pre_sorted = False
        if plain and q["order_by"]:
            # Spark resolves ORDER BY against the CHILD when a key is
            # not in the SELECT output (order by a dropped column):
            # sort BEFORE projecting in that case (a projection is
            # order-preserving), resolving select aliases to their
            # expressions
            out_names = {a.lower() for _it, a in items if a}
            in_names = {f.name.lower() for f in df.schema.fields}

            def post_resolvable(e) -> bool:
                if isinstance(e, B.Literal) and isinstance(e.value, int):
                    return True
                if isinstance(e, B.ColumnReference):
                    n = e.col_name.lower()
                    if n in out_names:
                        return True
                    return n in in_names and any(
                        it != "*" and (
                            (a is None and isinstance(
                                it, B.ColumnReference)
                             and it.col_name.lower() == n)
                            or a == e.col_name)
                        for it, a in items) or any(
                        it == "*" for it, _a in items)
                return False

            if not all(post_resolvable(e)
                       for e, _d, _n in q["order_by"]):
                if q["distinct"]:
                    raise SqlError("ORDER BY column must appear in "
                                   "SELECT DISTINCT output")
                aliases = {a.lower(): it for it, a in items
                           if a and it != "*"}
                # ordinals resolve against the STAR-EXPANDED output
                # layout (a bare `*` occupies one position per input
                # column)
                positions: list = []
                for it, _a in items:
                    if it == "*":
                        positions.extend(
                            B.ColumnReference(f.name)
                            for f in df.schema.fields)
                    else:
                        positions.append(it)
                keys = []
                for e, desc, nulls_last in q["order_by"]:
                    if isinstance(e, B.Literal) \
                            and isinstance(e.value, int) \
                            and 1 <= e.value <= len(positions):
                        # ordinal keys resolve to the select-list
                        # EXPRESSION when sorting pre-projection
                        e = positions[e.value - 1]
                    elif isinstance(e, B.ColumnReference) \
                            and e.col_name.lower() in aliases \
                            and e.col_name.lower() not in in_names:
                        e = aliases[e.col_name.lower()]
                    keys.append(SortKey(e, descending=desc,
                                        nulls_last=nulls_last))
                df = df.order_by(*keys)
                pre_sorted = True

        if plain:
            out = self._plain_select(items, df, q["distinct"])
        else:
            out = self._grouped_select(items, group_by, df, q)
            if q["order_by"]:
                # ORDER BY over aggregate calls (order by sum(x) desc):
                # Spark resolves these against the aggregate output —
                # rewrite each aggregate sub-expression to its output
                # column when the SELECT list computes it
                q["order_by"] = [
                    (self._resolve_order_agg(e, items), d, n)
                    for e, d, n in q["order_by"]]
            if q["distinct"]:
                # SELECT DISTINCT over an aggregate: dedup the result
                out = out.group_by(
                    *[B.ColumnReference(f.name)
                      for f in out.schema.fields]).agg()

        # ORDER BY: output names, aliases, 1-based ordinals, or (for
        # non-aggregate queries) arbitrary expressions over the input
        out_names = [f.name for f in out.schema.fields]
        if q["order_by"] and not pre_sorted:
            keys = []
            for e, desc, nulls_last in q["order_by"]:
                if isinstance(e, B.Literal) and isinstance(e.value, int) \
                        and 1 <= e.value <= len(out_names):
                    e = B.ColumnReference(out_names[e.value - 1])
                keys.append(SortKey(e, descending=desc,
                                    nulls_last=nulls_last))
            out = out.order_by(*keys)
        if q["limit"] is not None:
            out = out.limit(q["limit"])
        return out

    def _resolve_order_agg(self, e, items):
        """Rewrite aggregate calls inside an ORDER BY key to references
        to the matching SELECT-list aggregate's output column (the
        analyzer's ResolveAggregateFunctions for sort keys).  Unmatched
        aggregates are left alone and fail downstream with the normal
        diagnostic."""
        import dataclasses as _dcs

        if not _has_agg(e):
            return e
        agg_names = {}
        for it, al in items:
            if it != "*" and isinstance(it, AG.AggregateFunction):
                agg_names[self._agg_key(it)] = al or it.name

        def rw(x):
            if isinstance(x, AG.AggregateFunction):
                name = agg_names.get(self._agg_key(x))
                return B.ColumnReference(name) if name else x
            if not _dcs.is_dataclass(x):
                return x
            vals = {}
            changed = False
            for f in _dcs.fields(x):
                v = getattr(x, f.name)
                if isinstance(v, (B.Expression, AG.AggregateFunction)):
                    nv = rw(v)
                elif isinstance(v, (tuple, list)):
                    nv = type(v)(
                        rw(y) if isinstance(
                            y, (B.Expression, AG.AggregateFunction))
                        else y for y in v)
                else:
                    nv = v
                vals[f.name] = nv
                changed = changed or nv is not v
            return _rebuild(x, vals, changed)

        return rw(e)

    @staticmethod
    def _agg_key(a) -> tuple:
        from spark_rapids_tpu.execs.jit_cache import expr_key

        return (type(a).__name__,
                expr_key(a.child) if a.child is not None else None)

    def _rewrite_agg_refs(self, hv, aggs, hidden):
        """Replace aggregate calls inside an expression with references to the
        aggregate's output column, adding hidden aggregates for calls
        not already in the SELECT list (dropped by the re-projection)."""
        import dataclasses as _dcs

        def ref_for(a):
            k = self._agg_key(a)
            for fn, name in aggs:
                if self._agg_key(fn) == k:
                    return B.ColumnReference(name)
            for fn, name in hidden:
                if self._agg_key(fn) == k:
                    return B.ColumnReference(name)
            name = f"__having{len(hidden)}"
            hidden.append((a, name))
            return B.ColumnReference(name)

        def rw(e):
            if isinstance(e, AG.AggregateFunction):
                return ref_for(e)
            if not _dcs.is_dataclass(e):
                return e
            changed = False
            vals = {}
            for f in _dcs.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, (B.Expression, AG.AggregateFunction)):
                    nv = rw(v)
                elif isinstance(v, tuple):
                    nv = tuple(
                        rw(x) if isinstance(
                            x, (B.Expression, AG.AggregateFunction))
                        else x for x in v)
                else:
                    nv = v
                vals[f.name] = nv
                changed = changed or nv is not v
            return _rebuild(e, vals, changed)

        return rw(hv)

    def _plain_select(self, items, df, distinct):
        star = [f.name for f in df.schema.fields]
        exprs = []
        for item, alias in items:
            if item == "*":
                exprs.extend(B.ColumnReference(n) for n in star)
            elif alias:
                exprs.append(B.Alias(item, alias))
            else:
                exprs.append(item)
        out = df.select(*exprs)
        if distinct:
            out = out.group_by(
                *[B.ColumnReference(f.name)
                  for f in out.schema.fields]).agg()
        return out

    def _grouped_select(self, items, group_by, df, q):
        from spark_rapids_tpu.execs.jit_cache import expr_key

        # SELECT items must be group keys or single aggregate calls
        # (arbitrary input expressions inside the aggregate are fine)
        aliases = {al.lower(): it for it, al in items
                   if al and it != "*"}
        # GROUP BY may name select ALIASES (Spark allows it)
        group_exprs = []
        for g in group_by:
            if isinstance(g, B.ColumnReference) \
                    and g.col_name.lower() in aliases \
                    and g.col_name.lower() not in {
                        f.name.lower() for f in df.schema.fields}:
                g = aliases[g.col_name.lower()]
            group_exprs.append(g)
        gkeys = {expr_key(e) for e in group_exprs}

        aggs = []
        #: per select item: ("agg", out_name) | ("post", rewritten
        #: expr, out_name) | ("key", idx)
        plan_items: list = []
        for item, alias in items:
            if item == "*":
                raise SqlError("SELECT * with GROUP BY is not supported")
            if _has_agg(item):
                if isinstance(item, AG.AggregateFunction):
                    aggs.append((item, alias or item.name))
                    plan_items.append(("agg", alias or item.name))
                else:
                    # arithmetic over aggregate results (sum(a)/sum(b),
                    # 100*sum(case..)/sum(x)): each aggregate call
                    # becomes a (possibly hidden) aggregate output and
                    # the arithmetic projects over those outputs —
                    # Spark's physical split between the aggregate and
                    # its result expressions
                    plan_items.append(("post", item,
                                       alias or item.name))
            else:
                if expr_key(item) not in gkeys:
                    if not _refs(item):
                        # constant select item ('s' sale_type): no
                        # column refs, foldable — projected over the
                        # aggregate output (Spark allows it)
                        plan_items.append(("post", item,
                                           alias or item.name))
                        continue
                    raise SqlError(
                        f"non-aggregate select item {item.name!r} must "
                        "appear in GROUP BY")
                idx = [i for i, g in enumerate(group_exprs)
                       if expr_key(g) == expr_key(item)][0]
                plan_items.append(("key", idx, alias))

        hidden: list = []
        plan_items = [
            ("post", self._rewrite_agg_refs(it[1], aggs, hidden), it[2])
            if it[0] == "post" else it
            for it in plan_items]
        having = q["having"]
        if having is not None and _has_agg(having):
            having = self._rewrite_agg_refs(having, aggs, hidden)
        if q.get("group_kind") == "sets":
            names = []
            for g in group_exprs:
                if not isinstance(g, B.ColumnReference):
                    raise SqlError("GROUPING SETS keys must be "
                                   "plain columns")
                if g.col_name not in names:
                    names.append(g.col_name)
            sets = []
            for s in q.get("group_sets") or []:
                one = []
                for g in s:
                    if not isinstance(g, B.ColumnReference):
                        raise SqlError("GROUPING SETS keys must be "
                                       "plain columns")
                    one.append(g.col_name)
                sets.append(one)
            grouped = df.grouping_sets(sets, names)
        elif q.get("group_kind"):
            names = []
            for g in group_exprs:
                if not isinstance(g, B.ColumnReference):
                    raise SqlError(f"{q['group_kind']} keys must be "
                                   "plain columns")
                names.append(g.col_name)
            grouped = getattr(df, q["group_kind"])(*names)
        else:
            grouped = df.group_by(*group_exprs)
        out = grouped.agg(*aggs, *hidden)
        if having is not None:
            out = out.where(having)

        # aggregate output = [group keys..., aggs...]; re-project when
        # the SELECT order/aliases differ from that layout
        out_fields = [f.name for f in out.schema.fields]
        sel = []
        for it in plan_items:
            if it[0] == "agg":
                sel.append(B.ColumnReference(it[1]))
            elif it[0] == "post":
                sel.append(B.Alias(it[1], it[2]))
            else:
                _k, idx, alias = it
                ref = B.ColumnReference(out_fields[idx])
                sel.append(B.Alias(ref, alias) if alias else ref)
        want = [a or (it.name if it != "*" else "*")
                for it, a in items]
        if want != out_fields or any(al for _it, al in items) \
                or any(it[0] == "post" for it in plan_items):
            out = out.select(*sel)
        return out


def _sql_frontend(conf=None) -> SqlSession:
    return SqlSession(conf)


from spark_rapids_tpu.plugin import register_frontend  # noqa: E402

register_frontend("sql", _sql_frontend)
