"""Concurrency linter: lock-discipline AST dataflow over the engine's
threaded tiers (CON*).

Every concurrency bug this codebase has shipped was found LATE and
DYNAMICALLY — the admitted-thread drain-lock deadlock took two review
rounds, the half-open breaker probe wedged under storm load, the shared
sidecar freed under spill.  The reference plugin leans on RMM/cudf
enforcing synchronization discipline at the library layer; our
equivalent is enforced here, by tooling — the lockdep/TSan analog for a
thread-pooled accelerator runtime.  The dynamic sibling
(robustness/lock_tracker.py, docs/concurrency.md) watches the same
invariants at runtime.

Scope: ``serving/``, ``parallel/``, ``memory/``, ``shuffle/``,
``trace/``, ``connect/`` — the packages whose objects are shared
across the serving tier's thread populations.

Guard annotations
-----------------
A shared field is declared with a trailing comment on its ``__init__``
(or class-body) assignment::

    self._entries = {}          # guard: _mu
    self._done = False          # guard: _cv

meaning: every read/write of ``self._entries`` in this class must sit
lexically inside a ``with self._mu:`` scope.  Conditions constructed
over an explicit lock (``threading.Condition(self.lock)``) ALIAS that
lock — holding any member of the alias group satisfies the guard.
Methods whose names end in ``_locked`` are exempt by the repo's
caller-holds-the-lock convention (scheduler's ``_pump_locked``).
Cross-object accesses (``e._done`` from the registry that owns ``e``)
are checked too, when the field name is guarded by exactly one class in
the module and the base is a simple name: the access must sit inside
``with e._cv:``.

Rules
-----
- CON001 (error): a ``# guard:``-annotated field read or written
  outside a ``with``-scope of its declared lock (in-class ``self.F``
  and cross-object ``name.F`` forms).
- CON002 (warning): lock-scope escape — ``return self.F`` of a
  guarded MUTABLE container (dict/list/set/deque literal or ctor in
  ``__init__``) while holding its lock: the caller keeps mutating the
  shared object after the lock is released.  Return a copy.
- CON003 (error): static lock-order cycle.  Nested ``with``-lock
  scopes build a global acquisition graph (node = declaring class +
  lock attr, or module global); any cycle is the PR8 deadlock class
  and fails the lint.  Purely lexical — call-chain edges are the
  runtime tracker's job.
- CON004 (error): a Condition ``.wait()`` not inside a ``while``
  predicate loop — a naked wait misses wakeups (spurious or stolen)
  and re-checks nothing.
- CON005 (error): ``notify()``/``notify_all()`` on a Condition whose
  lock is not lexically held (alias groups honored; ``_locked``
  helpers exempt).  Python raises at runtime; the lint fails at
  review time.
- CON006 (error): same-lock re-acquisition through a call — while
  holding a NON-reentrant ``self.<lock>``, calling a sibling method
  that itself acquires ``with self.<lock>``: a guaranteed
  self-deadlock (the callback-under-lock class, scoped to the
  intra-class form that is statically decidable; the runtime tracker
  owns the cross-module form).

Unit-test entry: :func:`lint_concurrency_text`.  Repo entry:
:func:`check_concurrency` (wired into ``run_lint`` and the tier-1
repo-clean gate).  Rule catalog with examples: docs/concurrency.md.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from spark_rapids_tpu.lint.diagnostic import Diagnostic

#: packages under spark_rapids_tpu/ whose objects cross threads
_CON_DIRS = ("serving", "parallel", "memory", "shuffle", "trace",
             "connect")

_GUARD_RE = re.compile(r"#\s*guard:\s*([A-Za-z_]\w*)")

#: constructors that declare a lock.  tracked_lock/TrackedLock are the
#: robustness/lock_tracker wrappers around a plain mutex.
_LOCK_CTORS = {"Lock": "lock", "DrainLock": "lock",
               "tracked_lock": "lock", "TrackedLock": "lock",
               "RLock": "rlock", "Condition": "condition"}

#: __init__ value shapes that make a guarded field a MUTABLE container
#: (the CON002 escape surface)
_MUTABLE_CTORS = {"dict", "list", "set", "deque", "OrderedDict",
                  "defaultdict", "Counter"}


def _terminal_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


class _LockDecl:
    __slots__ = ("kind", "wraps")

    def __init__(self, kind: str, wraps: Optional[str] = None):
        self.kind = kind    # "lock" | "rlock" | "condition"
        self.wraps = wraps  # condition's explicit lock attr, if any


def _lock_decl(value: ast.expr) -> Optional[_LockDecl]:
    """The lock declaration a ``self.X = <value>`` makes, or None."""
    if not isinstance(value, ast.Call):
        return None
    kind = _LOCK_CTORS.get(_terminal_name(value.func) or "")
    if kind is None:
        return None
    wraps = None
    if kind == "condition" and value.args:
        a = value.args[0]
        if isinstance(a, ast.Attribute):
            wraps = a.attr
        elif isinstance(a, ast.Name):
            wraps = a.id
    return _LockDecl(kind, wraps)


def _is_mutable_ctor(value: ast.expr) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        return _terminal_name(value.func) in _MUTABLE_CTORS
    return False


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        self.locks: dict[str, _LockDecl] = {}
        self.guards: dict[str, str] = {}       # field -> lock attr
        self.mutable_fields: set[str] = set()
        self.methods: dict[str, ast.FunctionDef] = {}
        #: method name -> canonical self-lock attrs it acquires
        self.method_acquires: dict[str, set[str]] = {}
        #: annotation text of each self.F field (type witnesses)
        self.raw_ann: dict[str, str] = {}
        #: container field -> module class name of its ELEMENTS,
        #: resolved from the field's type annotation; lets the checker
        #: type values pulled out of `self._entries` and apply the
        #: element class's guard contract to them
        self.container_elem: dict[str, str] = {}

    def canon(self, attr: str) -> str:
        """Alias-group representative: a Condition over an explicit
        lock resolves to that lock; everything else is itself."""
        decl = self.locks.get(attr)
        if decl is not None and decl.wraps \
                and decl.wraps in self.locks:
            return decl.wraps
        return attr

    def lock_kind(self, attr: str) -> Optional[str]:
        decl = self.locks.get(attr)
        return decl.kind if decl else None


class _ModuleInfo:
    def __init__(self, path: str):
        self.path = path
        self.module_locks: dict[str, _LockDecl] = {}
        self.classes: dict[str, _ClassInfo] = {}
        #: module-level container NAME -> element class name
        self.module_container_elem: dict[str, str] = {}

    def lock_attr_owner(self, attr: str) -> Optional[_ClassInfo]:
        """The unique class declaring lock attr `attr`, else None."""
        owners = [c for c in self.classes.values()
                  if attr in c.locks]
        return owners[0] if len(owners) == 1 else None

    def elem_class_of_field(self, field: str) -> Optional[_ClassInfo]:
        """Element class of a typed container field, when the field
        name maps to exactly one element class across the module."""
        hits = {c.container_elem[field] for c in self.classes.values()
                if field in c.container_elem}
        if field in self.module_container_elem:
            hits.add(self.module_container_elem[field])
        if len(hits) != 1:
            return None
        return self.classes.get(next(iter(hits)))


def _ann_elem_class(ann_text: str, class_names) -> Optional[str]:
    """The unique module class named inside an annotation string
    (``OrderedDict[str, ScanShareEntry]`` -> ``ScanShareEntry``)."""
    hits = [n for n in class_names
            if re.search(rf"\b{re.escape(n)}\b", ann_text)]
    return hits[0] if len(hits) == 1 else None


def _collect_module(tree: ast.Module, src_lines: list[str],
                    path: str) -> _ModuleInfo:
    info = _ModuleInfo(path)
    module_anns: dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            decl = _lock_decl(node.value)
            if decl is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        info.module_locks[t.id] = decl
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            try:
                module_anns[node.target.id] = ast.unparse(
                    node.annotation)
            except Exception:  # pragma: no cover
                pass
        elif isinstance(node, ast.ClassDef):
            info.classes[node.name] = _collect_class(node, src_lines)
    # second pass: resolve container element types now that every
    # class name in the module is known
    names = list(info.classes)
    for ci in info.classes.values():
        for field, ann in ci.raw_ann.items():
            elem = _ann_elem_class(ann, names)
            if elem is not None:
                ci.container_elem[field] = elem
    for name, ann in module_anns.items():
        elem = _ann_elem_class(ann, names)
        if elem is not None:
            info.module_container_elem[name] = elem
    return info


def _guard_on_line(src_lines: list[str], lineno: int) -> Optional[str]:
    """Guard annotation for the assignment starting at `lineno`: a
    trailing ``# guard: X`` on the line itself, or a standalone
    comment line directly above (for assignments whose first line has
    no room — long annotated declarations)."""
    if 1 <= lineno <= len(src_lines):
        m = _GUARD_RE.search(src_lines[lineno - 1])
        if m:
            return m.group(1)
    if lineno >= 2:
        above = src_lines[lineno - 2].strip()
        if above.startswith("#"):
            m = _GUARD_RE.search(above)
            if m:
                return m.group(1)
    return None


def _collect_class(node: ast.ClassDef,
                   src_lines: list[str]) -> _ClassInfo:
    ci = _ClassInfo(node.name)
    for stmt in node.body:
        if isinstance(stmt, ast.Assign):
            # class-level lock (TpuSemaphore._lock style)
            decl = _lock_decl(stmt.value)
            if decl is not None:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        ci.locks[t.id] = decl
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ci.methods[stmt.name] = stmt
    init = ci.methods.get("__init__")
    if init is not None:
        for sub in ast.walk(init):
            targets: list[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(sub, ast.Assign):
                targets, value = sub.targets, sub.value
            elif isinstance(sub, ast.AnnAssign) \
                    and sub.value is not None:
                targets, value = [sub.target], sub.value
            for t in targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                decl = _lock_decl(value)
                if decl is not None:
                    ci.locks[t.attr] = decl
                if isinstance(sub, ast.AnnAssign):
                    try:
                        ci.raw_ann[t.attr] = ast.unparse(
                            sub.annotation)
                    except Exception:  # pragma: no cover
                        pass
                guard = _guard_on_line(src_lines, sub.lineno)
                if guard is not None:
                    ci.guards[t.attr] = guard
                    if _is_mutable_ctor(value):
                        ci.mutable_fields.add(t.attr)
    # drop guards naming a lock the class never declares — a typo'd
    # annotation must not silently disable checking; surface it
    # through CON001 firing on every access instead of hiding, so keep
    # the guard but canonicalization falls back to the raw name.
    for name, fn in ci.methods.items():
        ci.method_acquires[name] = _self_acquires(fn, ci)
    return ci


def _self_acquires(fn: ast.FunctionDef, ci: _ClassInfo) -> set[str]:
    """Canonical self-lock attrs a method's body acquires lexically
    (nested defs excluded — they run on their own schedule)."""
    out: set[str] = set()
    stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                e = item.context_expr
                if isinstance(e, ast.Attribute) \
                        and isinstance(e.value, ast.Name) \
                        and e.value.id in ("self", "cls") \
                        and e.attr in ci.locks:
                    out.add(ci.canon(e.attr))
        stack.extend(ast.iter_child_nodes(node))
    return out


# ------------------------------------------------------------------ #
# Per-function checking
# ------------------------------------------------------------------ #


class _Hold:
    """One acquired lock in the lexical with-stack."""

    __slots__ = ("base", "attr", "kind", "node_id", "line")

    def __init__(self, base: str, attr: str, kind: str,
                 node_id: str, line: int):
        self.base = base        # "self", "cls", a var name, "<module>"
        self.attr = attr        # canonical lock attr (or global name)
        self.kind = kind
        self.node_id = node_id  # global lock-order graph node
        self.line = line


class _FunctionChecker:
    def __init__(self, fn: ast.FunctionDef, qual: str,
                 module: _ModuleInfo, cls: Optional[_ClassInfo],
                 out: list[Diagnostic],
                 edges: list[tuple[str, str, str, int]]):
        self.fn = fn
        self.qual = qual
        self.module = module
        self.cls = cls
        self.out = out
        self.edges = edges  # (from_node, to_node, path, line)
        self.holds: list[_Hold] = []
        self.while_depth = 0
        self.exempt = qual.rsplit(".", 1)[-1].endswith("_locked") \
            or qual.rsplit(".", 1)[-1] == "__init__"
        #: local var name -> module class it is known to hold, from
        #: type witnesses: parameter annotations, ClassName(...) ctor
        #: assignments, and bindings pulled out of typed container
        #: fields (for/comprehension targets, .get()/[...] results)
        self.local_types: dict[str, _ClassInfo] = {}
        self._collect_local_types()

    # -- type witnesses ---------------------------------------------- #

    def _expr_witness(self, expr: ast.expr) -> Optional[_ClassInfo]:
        """The module class a bound value is known to be: a direct
        ``ClassName(...)`` construction, or an expression that reaches
        into a typed container field (``self._entries.get(k)``,
        ``self._entries.values()``, ``self._entries[k]``)."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name in self.module.classes:
                    return self.module.classes[name]
            if isinstance(node, ast.Attribute):
                hit = self.module.elem_class_of_field(node.attr)
                if hit is not None:
                    return hit
            if isinstance(node, ast.Name):
                hit = self.module.module_container_elem.get(node.id)
                if hit is not None:
                    return self.module.classes.get(hit)
        return None

    def _collect_local_types(self) -> None:
        args = self.fn.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            if a.annotation is None:
                continue
            try:
                ann = ast.unparse(a.annotation)
            except Exception:  # pragma: no cover
                continue
            elem = _ann_elem_class(ann, list(self.module.classes))
            if elem is not None:
                self.local_types[a.arg] = self.module.classes[elem]
        # two passes: the second resolves bindings that forward-refer
        # through another local (`for e in entries` where `entries`
        # was typed deeper in the AST walk order)
        for _ in range(2):
            for node in ast.walk(self.fn):
                target: Optional[ast.expr] = None
                source: Optional[ast.expr] = None
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1:
                    target, source = node.targets[0], node.value
                elif isinstance(node, ast.For):
                    target, source = node.target, node.iter
                elif isinstance(node, ast.comprehension):
                    target, source = node.target, node.iter
                if not isinstance(target, ast.Name) or source is None:
                    continue
                hit = self._expr_witness(source) \
                    or self._name_passthrough(source)
                if hit is not None:
                    self.local_types[target.id] = hit

    def _name_passthrough(self, expr: ast.expr
                          ) -> Optional[_ClassInfo]:
        """Type flow through a bare rebinding or a shape-preserving
        wrapper (``list(entries)``, ``sorted(entries)``) of an
        already-typed local — NOT a general expression walk, which
        would mis-type derived values."""
        if isinstance(expr, ast.Name):
            return self.local_types.get(expr.id)
        if isinstance(expr, ast.Call) and not expr.keywords \
                and len(expr.args) == 1 \
                and isinstance(expr.args[0], ast.Name) \
                and _terminal_name(expr.func) in ("list", "sorted",
                                                  "tuple", "reversed"):
            return self.local_types.get(expr.args[0].id)
        return None

    # -- lock resolution -------------------------------------------- #

    def _resolve_lock(self, e: ast.expr) -> Optional[_Hold]:
        """A with-item context expr as an acquired lock, or None."""
        line = getattr(e, "lineno", 0)
        if isinstance(e, ast.Name):
            decl = self.module.module_locks.get(e.id)
            if decl is None:
                return None
            return _Hold("<module>", e.id, decl.kind,
                         f"{self.module.path}::{e.id}", line)
        if not isinstance(e, ast.Attribute):
            return None
        base = e.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                and self.cls is not None \
                and e.attr in self.cls.locks:
            canon = self.cls.canon(e.attr)
            return _Hold("self", canon, self.cls.lock_kind(e.attr),
                         f"{self.module.path}::"
                         f"{self.cls.name}.{canon}", line)
        owner = None
        if isinstance(base, ast.Name):
            typed = self.local_types.get(base.id)
            if typed is not None and e.attr in typed.locks:
                owner = typed
        if owner is None:
            owner = self.module.lock_attr_owner(e.attr)
        if owner is None or e.attr not in owner.locks:
            return None
        canon = owner.canon(e.attr)
        try:
            base_key = ast.unparse(base)
        except Exception:  # pragma: no cover - unparse is total here
            return None
        return _Hold(base_key, canon, owner.lock_kind(e.attr),
                     f"{self.module.path}::{owner.name}.{canon}", line)

    def _held(self, base: str, canon_attr: str) -> bool:
        return any(h.base == base and h.attr == canon_attr
                   for h in self.holds)

    # -- emission ---------------------------------------------------- #

    def _emit(self, rule: str, severity: str, node: ast.AST,
              message: str, hint: str = "") -> None:
        self.out.append(Diagnostic(
            rule, severity, f"{self.module.path}::{self.qual}",
            message, hint=hint, line=getattr(node, "lineno", 0)))

    # -- traversal --------------------------------------------------- #

    def run(self) -> None:
        for child in ast.iter_child_nodes(self.fn):
            self._visit(child)

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, on its own thread/schedule —
            # fresh checker, empty lock stack
            _FunctionChecker(node, f"{self.qual}.{node.name}",
                             self.module, self.cls, self.out,
                             self.edges).run()
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: list[_Hold] = []
            for item in node.items:
                hold = self._resolve_lock(item.context_expr)
                if hold is None:
                    continue
                for h in self.holds:
                    if h.node_id != hold.node_id:
                        self.edges.append((h.node_id, hold.node_id,
                                           self.module.path,
                                           hold.line))
                self.holds.append(hold)
                acquired.append(hold)
            for child in ast.iter_child_nodes(node):
                self._visit(child)
            for _ in acquired:
                self.holds.pop()
            return
        if isinstance(node, ast.While):
            self.while_depth += 1
            for child in ast.iter_child_nodes(node):
                self._visit(child)
            self.while_depth -= 1
            return
        if isinstance(node, ast.Call):
            self._check_call(node)
        elif isinstance(node, ast.Attribute):
            self._check_attribute(node)
        elif isinstance(node, ast.Return):
            self._check_return(node)
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    # -- CON001 / CON002 -------------------------------------------- #

    def _guard_satisfied(self, owner: _ClassInfo, base_label: str,
                         lock_attr: str) -> tuple[bool, str]:
        """(held?, required-scope label).  The guard names either a
        lock of the owning class (held via `with <base>.<lock>`) or a
        MODULE-level lock (the _Breaker-under-_BREAKERS_MU shape, held
        via `with <LOCK>`), whichever the declaration resolves to."""
        if lock_attr in owner.locks:
            guard = owner.canon(lock_attr)
            return (self._held(base_label, guard),
                    f"with {base_label}.{guard}")
        if lock_attr in self.module.module_locks:
            return (self._held("<module>", lock_attr),
                    f"with {lock_attr}")
        # a guard naming nothing declared anywhere is a typo: treat as
        # never-held so every access fires rather than silently passing
        return False, f"with {base_label}.{lock_attr} (undeclared!)"

    def _check_attribute(self, node: ast.Attribute) -> None:
        if self.exempt:
            return
        base = node.value
        if not isinstance(base, ast.Name):
            return
        field = node.attr
        if base.id in ("self", "cls"):
            if self.cls is None or field not in self.cls.guards:
                return
            held, scope = self._guard_satisfied(
                self.cls, "self", self.cls.guards[field])
            if not held:
                self._emit(
                    "CON001", "error", node,
                    f"guarded field `self.{field}` (guard: "
                    f"{self.cls.guards[field]}) accessed outside "
                    f"`{scope}`",
                    hint="take the declared lock around the access, "
                         "move the access into a *_locked helper "
                         "called under the lock, or drop the guard "
                         "annotation if the field is genuinely "
                         "unshared (docs/concurrency.md)")
            return
        owner = self.local_types.get(base.id)
        if owner is None or field not in owner.guards:
            return
        lock_attr = owner.guards[field]
        held, scope = self._guard_satisfied(owner, base.id, lock_attr)
        if not held:
            self._emit(
                "CON001", "error", node,
                f"guarded field `{base.id}.{field}` "
                f"({owner.name} guards it with {lock_attr}) accessed "
                f"outside `{scope}`",
                hint=f"read/write it inside `{scope}:` — the owning "
                     "class mutates it under that lock, so an "
                     "unlocked peek is a data race "
                     "(docs/concurrency.md)")

    def _check_return(self, node: ast.Return) -> None:
        if self.exempt or self.cls is None or node.value is None:
            return
        v = node.value
        if not (isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "self"):
            return
        field = v.attr
        if field not in self.cls.guards \
                or field not in self.cls.mutable_fields:
            return
        held, scope = self._guard_satisfied(
            self.cls, "self", self.cls.guards[field])
        if held:
            self._emit(
                "CON002", "warning", node,
                f"`return self.{field}` escapes a guarded mutable "
                f"container out of its `{scope}` scope",
                hint="return a copy (list(...)/dict(...)) — the "
                     "caller holds a live alias the lock no longer "
                     "protects (docs/concurrency.md)")

    # -- CON004 / CON005 / CON006 ------------------------------------ #

    def _condition_recv(self, func: ast.Attribute
                        ) -> Optional[tuple[str, str, _ClassInfo]]:
        """(base_key, cond attr, owner class) when the receiver of a
        wait/notify resolves to a declared Condition."""
        recv = func.value
        if not isinstance(recv, ast.Attribute):
            return None
        base = recv.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                and self.cls is not None:
            if self.cls.lock_kind(recv.attr) == "condition":
                return "self", recv.attr, self.cls
            return None
        if not isinstance(base, ast.Name):
            return None
        owner = self.local_types.get(base.id) \
            or self.module.lock_attr_owner(recv.attr)
        if owner is not None \
                and owner.lock_kind(recv.attr) == "condition":
            return base.id, recv.attr, owner
        return None

    def _check_call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr == "wait":
                hit = self._condition_recv(func)
                if hit is not None and self.while_depth == 0:
                    self._emit(
                        "CON004", "error", node,
                        f"naked Condition `.wait()` on "
                        f"`{hit[0]}.{hit[1]}` — not inside a "
                        "`while <predicate>` loop",
                        hint="wrap the wait in a while loop "
                             "re-checking the predicate: wakeups are "
                             "spurious and stealable "
                             "(docs/concurrency.md)")
            elif func.attr in ("notify", "notify_all"):
                hit = self._condition_recv(func)
                if hit is not None and not self.exempt:
                    base, attr, owner = hit
                    guard = owner.canon(attr)
                    if not self._held(base, guard):
                        self._emit(
                            "CON005", "error", node,
                            f"`.{func.attr}()` on `{base}.{attr}` "
                            "without its lock held",
                            hint=f"notify inside `with {base}."
                                 f"{guard}:` (or any alias of it) — "
                                 "an unlocked notify raises "
                                 "RuntimeError at runtime "
                                 "(docs/concurrency.md)")
            # CON006: self-deadlock through a sibling call
            if self.cls is not None \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id == "self" \
                    and func.attr in self.cls.method_acquires:
                reacquired = {
                    h.attr for h in self.holds
                    if h.base == "self" and h.kind in ("lock",
                                                       "condition")
                } & self.cls.method_acquires[func.attr]
                if reacquired:
                    lock = sorted(reacquired)[0]
                    self._emit(
                        "CON006", "error", node,
                        f"`self.{func.attr}()` called while holding "
                        f"non-reentrant `self.{lock}`, and that "
                        "method acquires the same lock — guaranteed "
                        "self-deadlock",
                        hint="hoist the call out of the critical "
                             "section, or split the callee into a "
                             "*_locked body the caller invokes under "
                             "the lock (docs/concurrency.md)")


# ------------------------------------------------------------------ #
# Lock-order cycle detection (CON003)
# ------------------------------------------------------------------ #


def _find_cycles(edges: Iterable[tuple[str, str, str, int]]
                 ) -> list[Diagnostic]:
    """Tarjan SCCs over the acquisition graph; every non-trivial SCC
    (>= 2 nodes, or a self-loop) is one CON003 error.  Deterministic
    output: nodes and members sorted, so the baseline key is stable."""
    graph: dict[str, set[str]] = {}
    sites: dict[tuple[str, str], tuple[str, int]] = {}
    for a, b, path, line in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
        sites.setdefault((a, b), (path, line))

    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(graph[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)

    for node in sorted(graph):
        if node not in index:
            strongconnect(node)

    out: list[Diagnostic] = []
    for scc in sccs:
        members = sorted(scc)
        cyclic = len(members) > 1 or (
            members and members[0] in graph[members[0]])
        if not cyclic:
            continue
        cycle_edges = [(a, b) for a in members
                       for b in sorted(graph[a]) if b in set(members)]
        where = "; ".join(
            f"{a.split('::')[-1]}->{b.split('::')[-1]} at "
            f"{sites[(a, b)][0]}:{sites[(a, b)][1]}"
            for a, b in cycle_edges if (a, b) in sites)
        first = sites.get(cycle_edges[0]) if cycle_edges else None
        out.append(Diagnostic(
            "CON003", "error", "concurrency::lock-order",
            "static lock-order cycle: "
            + " <-> ".join(m.split("::")[-1] for m in members),
            hint="pick ONE global acquisition order and release the "
                 f"outer lock before taking the inner ({where}); "
                 "the runtime tracker raises LockCycleError on the "
                 "dynamic form (docs/concurrency.md)",
            line=first[1] if first else 0))
    return out


# ------------------------------------------------------------------ #
# Entry points
# ------------------------------------------------------------------ #


def _analyze_module(src: str, path: str
                    ) -> tuple[list[Diagnostic],
                               list[tuple[str, str, str, int]]]:
    out: list[Diagnostic] = []
    edges: list[tuple[str, str, str, int]] = []
    try:
        tree = ast.parse(src)
    except SyntaxError as exc:
        out.append(Diagnostic(
            "CON000", "error", path, f"syntax error: {exc}",
            line=exc.lineno or 0))
        return out, edges
    info = _collect_module(tree, src.splitlines(), path)
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FunctionChecker(node, node.name, info, None, out,
                             edges).run()
        elif isinstance(node, ast.ClassDef):
            ci = info.classes[node.name]
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    _FunctionChecker(stmt,
                                     f"{node.name}.{stmt.name}",
                                     info, ci, out, edges).run()
    return out, edges


def lint_concurrency_text(src: str, path: str) -> list[Diagnostic]:
    """Lint one module's source text (unit-test entry point) —
    per-module rules plus lock-order cycles over this module's own
    acquisition edges."""
    out, edges = _analyze_module(src, path)
    out.extend(_find_cycles(edges))
    return out


def _is_concurrency_module(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return any(d in parts for d in _CON_DIRS)


def check_concurrency(root: Optional[str] = None) -> list[Diagnostic]:
    """Run the concurrency rules over the engine's threaded tiers and
    the GLOBAL lock-order graph (cycles across modules are cycles)."""
    from spark_rapids_tpu.lint.source_rules import (
        _package_root,
        iter_source_files,
    )

    root = root or _package_root()
    base = os.path.dirname(root)
    out: list[Diagnostic] = []
    edges: list[tuple[str, str, str, int]] = []
    for path in iter_source_files(root):
        rel = os.path.relpath(path, base)
        if not _is_concurrency_module(rel):
            continue
        with open(path) as f:
            src = f.read()
        diags, mod_edges = _analyze_module(src, rel)
        out.extend(diags)
        edges.extend(mod_edges)
    out.extend(_find_cycles(edges))
    return out
