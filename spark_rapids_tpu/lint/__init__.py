"""tpulint: static analysis for plans, registries, and engine source.

Five analyzers share one Diagnostic model and one baseline:

- ``dtype_flow``   — dtype propagation through lowered physical plans
                     (DT*: the UNION-truncation bug class, statically)
- ``registry``     — registry/TypeSig/docs consistency (REG*)
- ``plan_rules``   — plan anti-patterns: fallback islands, redundant
                     sorts, nondeterminism above exchanges (PL*)
- ``source_rules`` — host-device sync hazards in traced code (SRC*)
- ``concurrency_rules`` — lock-discipline over the threaded tiers:
                     guard breaches, lock-order cycles, CV hygiene
                     (CON*; runtime sibling: robustness/lock_tracker)

CLI: ``python -m spark_rapids_tpu.tools.lint [--strict]``.
Docs: ``docs/lint.md``.
"""

from spark_rapids_tpu.lint.diagnostic import (  # noqa: F401
    Diagnostic,
    SEVERITIES,
    default_baseline_path,
    load_baseline,
    save_baseline,
    sort_diags,
)
from spark_rapids_tpu.lint.runner import (  # noqa: F401
    evaluate,
    lint_exec_tree,
    run_lint,
)
