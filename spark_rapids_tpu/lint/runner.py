"""tpulint runner: compose the five analyzers into one pass.

A repo run covers:
- the engine-source linter over spark_rapids_tpu/ (source_rules);
- the concurrency/lock-discipline linter over the threaded tiers
  (concurrency_rules, CON*);
- the registry consistency checker (registry);
- dtype-flow + plan lint over a built-in corpus of representative
  plans lowered by the LIVE planner — every lint run statically
  re-verifies that the planner still produces dtype-consistent,
  anti-pattern-free physical plans for the core shapes (the UNION
  truncation bug would have been caught right here).

Callers with a specific plan in hand (explain(), tests) use
``lint_exec_tree`` directly.
"""

from __future__ import annotations

from typing import Optional, Sequence

from spark_rapids_tpu.lint.diagnostic import (
    Diagnostic,
    filter_at_least,
    load_baseline,
    sort_diags,
    split_new,
)


def lint_exec_tree(root) -> list[Diagnostic]:
    """Dtype-flow + plan anti-pattern diagnostics for one lowered
    physical plan (the explain() feed)."""
    from spark_rapids_tpu.lint.dtype_flow import check_exec_tree
    from spark_rapids_tpu.lint.plan_rules import check_plan

    return sort_diags(check_exec_tree(root) + check_plan(root))


def _corpus_plans(errors: Optional[list] = None):
    """Lower a handful of representative queries with the live planner
    and yield their physical roots.  In-memory sources, CPU-friendly:
    plans are built, never executed.  A query that fails to LOWER is
    itself a finding (appended to `errors`) — swallowing it would
    silently shrink the coverage this corpus exists to provide."""
    import pyarrow as pa

    from spark_rapids_tpu.plan.planner import plan_query
    from spark_rapids_tpu.session import TpuSession, col, sum_

    s = TpuSession()
    t = pa.table({"k": [1, 2, 1, 3], "v": [1.5, 2.5, 3.5, 4.5],
                  "s": ["a", "b", "a", "c"]})
    left = s.create_dataframe(t)
    right = s.create_dataframe(pa.table({"k": [1, 2], "w": [10, 20]}))

    frames = [
        # project/filter pipeline
        left.filter(col("v") > 2.0).select(
            (col("v") * 2).alias("v2"), col("s")),
        # partial -> exchange -> final aggregate
        left.group_by("k").agg((sum_("v"), "sv")),
        # shuffled equi-join
        left.join(right, on="k"),
        # distributed sort
        left.order_by(col("v")),
        # union of identically-typed members
        left.select(col("k")).union(right.select(col("k"))),
    ]
    for i, df in enumerate(frames):
        try:
            root, _meta = plan_query(df._plan, s.conf)
        except Exception as exc:  # never crash the linter itself
            if errors is not None:
                errors.append(Diagnostic(
                    "PL000", "warning", f"plan::corpus[{i}]",
                    f"corpus query failed to lower: "
                    f"{type(exc).__name__}: {exc}",
                    hint="a planner regression broke a core query "
                         "shape; see lint/runner.py _corpus_plans"))
            continue
        yield root


def run_lint(source: bool = True, registry: bool = True,
             plans: bool = True, metrics: bool = True,
             concurrency: bool = True,
             extra_roots: Sequence = ()) -> list[Diagnostic]:
    """Run the selected analyzers; returns ALL findings (unbaselined)."""
    out: list[Diagnostic] = []
    if source:
        from spark_rapids_tpu.lint.source_rules import check_sources

        out.extend(check_sources())
    if concurrency:
        # CON*: guard discipline, lock-order cycles, CV hygiene over
        # the serving tier's shared classes (docs/concurrency.md)
        from spark_rapids_tpu.lint.concurrency_rules import (
            check_concurrency,
        )

        out.extend(check_concurrency())
    if registry:
        from spark_rapids_tpu.lint.registry import check_registries

        out.extend(check_registries())
    if metrics:
        # MET001: exec metric registrations vs settle sites — the
        # names the event log persists must stay trustworthy
        from spark_rapids_tpu.lint.metric_rules import (
            check_metric_registry,
        )

        out.extend(check_metric_registry())
    roots = list(extra_roots)
    if plans:
        roots.extend(_corpus_plans(errors=out))
    for root in roots:
        out.extend(lint_exec_tree(root))
    return sort_diags(out)


def evaluate(diags: Sequence[Diagnostic], strict: bool = False,
             baseline_path: Optional[str] = None
             ) -> tuple[list[Diagnostic], list[Diagnostic], int]:
    """(new, accepted, exit_code) against the baseline.  Non-strict
    fails on new errors; --strict fails on new warnings too."""
    new, accepted = split_new(list(diags), load_baseline(baseline_path))
    floor = "warning" if strict else "error"
    failing = filter_at_least(new, floor)
    return new, accepted, (1 if failing else 0)
