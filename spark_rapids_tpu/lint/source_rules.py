"""Engine-source linter: AST pass over spark_rapids_tpu/ flagging
host-device sync hazards inside traced (jit) regions.

The JAX/TPU analog of a race/sanitizer pass: inside a `jax.jit` trace,
`.item()`, `float(arr)`, `np.asarray(traced)` and Python `if` on a
traced boolean either fail at trace time or — far worse, when they
happen to run on concrete values during warmup paths — silently insert
a blocking device->host transfer into a hot loop (on the tunneled
backend each costs a full link round trip, the dominant latency term;
see execs/base.py's deferred-metric design for how much the codebase
works to avoid exactly this).

Traced-region discovery (per module, purely syntactic):
- functions decorated with jit / jax.jit / partial(jax.jit, ...)
- functions passed by name to jit()/jax.jit()/pjit()/cached_jit()
  (including `cached_jit(key, lambda: fn)` thunks)
- Expression.eval methods (signature `eval(self, ctx)`) — they run
  inside the fused pipeline's trace
- inner functions returned by `make_*_fn`/`_make_decode` factories —
  the fusion machinery jits them

Taint: a region's parameters (minus self/cls) are traced values;
assignments propagate taint; reads through shape/ndim/dtype/size,
len(), isinstance() etc. are static and clear it.

Rules
-----
- SRC001 (error): .item() inside a traced region
- SRC002 (warning): host materialization of a traced value
  (np.asarray/np.array/jax.device_get/.tolist()/.block_until_ready())
- SRC003 (error): Python scalar conversion float()/int()/bool() of a
  traced value
- SRC004 (warning): Python if/while branching on a traced boolean
- SRC005 (warning): raw blocking device->host readback
  (jax.device_get / .item()) in an exec module (execs/) instead of the
  software pipeline's deferred-readback helper
  (parallel.pipeline.device_read / device_read_many) — an inline sync
  in a stream loop stalls the loop for a full link round trip per
  batch where the pipelined form overlaps it with the next batch's
  dispatch.  Intentional syncs (metric settlement, ANSI error polls)
  are baselined, not suppressed inline.
- SRC006 (warning): raw wall-clock timing (time.time /
  time.perf_counter / time.perf_counter_ns / time.monotonic) in an
  exec or pipeline module (execs/, parallel/) instead of MetricTimer
  (device-aware, feeds the metric tree) or trace.span (lands on the
  correlated timeline).  Ad-hoc timing is invisible to profile_query,
  EXPLAIN ANALYZE and the Chrome-trace export; the timing
  INFRASTRUCTURE itself (MetricTimer, the metric reaper, the pipeline
  wait counters) is baselined, mirroring SRC005's posture.
- SRC007 (warning): `.block_until_ready()` or `np.asarray(...)` /
  `np.array(...)` on a (potential) device value inside an exec or ops
  module (execs/, ops/) — the sync hazards SRC005's
  `device_get`/`.item()` patterns miss.  Both force a blocking
  device->host wait when handed a device array; a stream loop must
  route the sync through parallel.pipeline.device_read* /
  device_read_async instead (np.asarray of a device_read* RESULT is
  exempt — the value is already host memory).  Intentional
  infrastructure sites (metric settlement in execs/base.py, the
  split-count conversion in ops/partition.py) are baselined.
- SRC008 (warning): a broad `except` clause (bare / Exception /
  BaseException / RuntimeError) in an exec, io, or shuffle module
  that SWALLOWS the exception — no re-raise anywhere in the handler
  and no routing through the retry classification gate
  (execs/retry.classify / is_retryable / should_cpu_fallback /
  note_recovered).  A bare `except Exception: pass` in those layers
  can eat a retryable device error (XlaRuntimeError subclasses
  RuntimeError), silently skipping the spill/split/task-retry
  escalation ladder AND the chaos-mode fault accounting.  Intentional
  fall-back-to-slow-path sites (the fastpar decoder's per-column
  bailouts) are baselined, not suppressed inline.  execs/retry.py
  itself — the classification gate — is exempt by construction.
- SRC010 (error): source-level use-after-donate.  In execs//ops/
  modules, a local assigned from ``cached_jit(..., donate=...)`` is a
  DONATING program: the locals passed at its donated argnum positions
  are consumed by the call (XLA reuses their buffers for the outputs
  — docs/fusion.md), so any later reference to those locals in the
  same function is a use-after-free waiting for a TPU backend.  The
  direct-call spelling ``cached_jit(..., donate=...)(x)`` is covered
  too.  Deliberately narrow (local names, source order within one
  function): donation routed through the blessed consuming helper
  (``transfer.run_consuming``, which memoizes the output and marks
  the batch consumed) is exempt by construction — that is the
  spelling engine code is supposed to use.  Intentional raw sites,
  if any ever appear, are baselined, not suppressed inline.
- SRC011 (error): direct mutation of a shared-cache object in a
  serving-path module (serving/, execs/, io/).  Cross-tenant work
  sharing (serving/work_share.py, docs/work_sharing.md) hands the
  SAME objects — a shared scan's published units and device batches
  (``subscribe_units``), a cached query result (``lookup_result``) —
  to every concurrent consumer: an in-place mutation (item/attribute
  assignment, ``append``/``update``/``sort``/... on the object or
  anything reached through it) corrupts OTHER tenants' in-flight
  queries and the cache itself.  Consumers must copy-on-write or
  re-materialize.  Taint is local-name based (assignments from the
  accessor calls, loop targets iterating them, and propagation
  through attribute/subscript reads); serving/work_share.py — the
  cache's own bookkeeping — is exempt by construction.
- SRC009 (error): raw ``jax.jit`` in an exec or ops module (execs/,
  ops/) bypassing ``execs/jit_cache.cached_jit``.  Every program the
  engine compiles is supposed to flow through the structural-key
  cache: a raw jit is UNMETERED — it escapes the jit-cache hit/miss
  stats that explain("analyze") reports, AND the device-utilization
  ledger (trace/ledger.py) that attributes per-program dispatches,
  device time and roofline fractions — and it re-traces per exec
  instance where the cache would share one compiled program across
  every query presenting the same key.  Sites with no stable
  structural key (the fused-pipeline fallback when a chain member has
  no fuse key, the module-level Pallas kernel wrappers) are
  baselined, not suppressed inline.  execs/jit_cache.py — the cache
  itself — is exempt by construction.
- SRC013 (error): host syncs inside collective step functions /
  shard_map bodies (parallel/exchange.py, parallel/spmd.py,
  execs/collective.py).  The SPMD whole-stage contract (docs/spmd.md)
  defers per-round host syncs to stage exit: a
  ``concrete_num_rows()`` / ``.block_until_ready()`` /
  ``np.asarray`` / ``jax.device_get`` / ``.item()`` inside a step
  builder's nested body, a function passed to ``shard_map``, or a
  collective-exec method handed to a builder either fails at trace
  time or silently re-inserts the per-round host round-trip the
  partitioned stage architecture exists to remove.  The host driver
  code in the same modules (round staging, stage-exit
  ``stage_counts``/``fetch``) is out of scope by construction.
- SRC012 (error): unbounded blocking waits in serving/ and parallel/.
  Every wait on the serving path must be INTERRUPTIBLE — the
  cancellation substrate (serving/cancel.py) can only unwind a query
  whose blocked seams wake up to poll the token, so a
  ``Condition.wait()`` / ``Event.wait()`` / ``queue.get()`` /
  ``Thread.join()`` with no timeout is a query that session.cancel()
  and the deadline cannot reach.  Syntactic: zero-argument
  ``.wait()`` / ``.get()`` / ``.join()`` calls without a ``timeout=``
  keyword (``dict.get`` always takes a key, so a bare ``.get()`` is a
  queue read — except ``ClassName.get()`` singleton accessors, which
  are exempt by the leading-capital convention; a bare ``.join()`` is
  a thread join — ``str.join`` takes an iterable).  The deliberate
  sites (prefetch's
  abort-then-join teardown, whose wake-up is the channel abort, not a
  poll) are baselined with their justification in
  tests/test_lint.py's coverage contract.
- SRC014 (error): wire-facing handler discipline in connect/.  A
  frame length read off the wire (``struct.unpack``) must be clamped
  by an ``if``-raise guard BEFORE it feeds any allocation or read —
  an 8-byte hostile length must cost an error frame, never a giant
  bytearray; and nothing under connect/ may call ``.collect()`` /
  ``collect_exec()`` / ``execute_cpu()`` directly — every wire query
  routes through the admission-controlled serving seam
  (PreparedQuery.execute_stream → _stream_tpu) so deadline/cancel
  propagation and the per-query ``connect`` record engage
  (docs/connect.md).
- SRC015 (error): raw executable persistence outside the warm-start
  module.  Serialized program artifacts (``.serialize()`` products —
  jax.export blobs) and ``pickle`` writes of engine objects MUST flow
  through spark_rapids_tpu/persist.py's validated writer (magic +
  checksummed header + env stamp + temp-file-and-rename atomicity —
  docs/warm_start.md): a raw ``open().write(blob)`` or
  ``pickle.dump`` elsewhere produces files with no torn-write
  protection and no staleness stamp, which a later process would
  deserialize blind.  Syntactic: ``pickle.dump``/``dumps``/
  ``Pickler`` calls, and ``.write(x)`` where x is a ``.serialize()``
  result (directly or through a local).  persist.py IS the writer —
  exempt by construction — and python_worker/ (the UDF pipe
  protocol, pickled function frames over stdin, never files) is out
  of scope.
- SRC016 (error): raw ``jax.device_put`` in execs/ and parallel/
  outside parallel/placement.py.  Stage-input placement has ONE choke
  point (docs/pod_serving.md): placement.place_piece /
  placement.adopt_batch classify every move (host upload vs
  device-born vs device-to-device) into the ``placement.*`` counters
  that back the pod-serving zero-host-upload gate — a raw
  ``device_put`` elsewhere is an untracked transfer that silently
  re-opens the host round-trip the device-born contract closed.
  Syntactic and module-wide: any ``jax.device_put(...)`` call (or
  bare ``device_put`` imported from jax) in scope.  placement.py IS
  the choke point — exempt by construction.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Optional

from spark_rapids_tpu.lint.diagnostic import Diagnostic

#: attribute reads that yield static (trace-time) values — includes the
#: codebase's shape-derived properties (Column.capacity/width/max_len
#: are all static functions of array shapes)
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "name", "names",
                "fields", "itemsize", "kind", "capacity", "width",
                "max_len", "num_cols"}
#: calls whose results are static regardless of argument taint
STATIC_CALLS = {"len", "isinstance", "hasattr", "getattr", "type",
                "repr", "str", "range", "enumerate", "zip", "id"}
JIT_NAMES = {"jit", "pjit", "cached_jit"}
FACTORY_NAMES = {"_make_decode"}


def _terminal_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_jit_decorator(dec: ast.expr) -> bool:
    name = _terminal_name(dec)
    if name in JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fname = _terminal_name(dec.func)
        if fname in JIT_NAMES:
            return True
        if fname == "partial" and dec.args \
                and _terminal_name(dec.args[0]) in JIT_NAMES:
            return True
    return False


def _static_params(fn: ast.FunctionDef) -> set[str]:
    """Parameter names a jit decorator declares static
    (static_argnames / static_argnums): host values, never traced."""
    out: set[str] = set()
    all_args = fn.args.posonlyargs + fn.args.args
    for dec in fn.decorator_list:
        if not (isinstance(dec, ast.Call) and _is_jit_decorator(dec)):
            continue
        for kw in dec.keywords:
            v = kw.value
            items = v.elts if isinstance(v, (ast.Tuple, ast.List)) \
                else [v]
            if kw.arg == "static_argnames":
                out |= {x.value for x in items
                        if isinstance(x, ast.Constant)
                        and isinstance(x.value, str)}
            elif kw.arg == "static_argnums":
                for x in items:
                    if isinstance(x, ast.Constant) \
                            and isinstance(x.value, int) \
                            and x.value < len(all_args):
                        out.add(all_args[x.value].arg)
    return out


def _is_factory(name: str) -> bool:
    return name in FACTORY_NAMES or (
        name.startswith("make_") and name.endswith("_fn")) or (
        name.startswith("_make_") and name.endswith("_fn"))


class _RegionFinder(ast.NodeVisitor):
    """Collect (FunctionDef, why) traced regions in one module."""

    def __init__(self):
        self.by_name: dict[str, list[ast.FunctionDef]] = {}
        self.regions: dict[int, tuple[ast.FunctionDef, str]] = {}
        self.jit_referenced: set[str] = set()
        self._parent_fn: list[ast.FunctionDef] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.by_name.setdefault(node.name, []).append(node)
        if any(_is_jit_decorator(d) for d in node.decorator_list):
            self.regions[id(node)] = (node, "@jit")
        elif node.name == "eval" and len(node.args.args) >= 2 \
                and node.args.args[0].arg == "self" \
                and node.args.args[1].arg == "ctx":
            self.regions[id(node)] = (node, "Expression.eval")
        elif self._parent_fn and _is_factory(self._parent_fn[-1].name):
            self.regions[id(node)] = (
                node, f"returned by {self._parent_fn[-1].name}")
        self._parent_fn.append(node)
        self.generic_visit(node)
        self._parent_fn.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        if _terminal_name(node.func) in JIT_NAMES:
            for a in node.args:
                if isinstance(a, ast.Name):
                    self.jit_referenced.add(a.id)
                elif isinstance(a, ast.Lambda) \
                        and isinstance(a.body, ast.Name):
                    self.jit_referenced.add(a.body.id)
        self.generic_visit(node)

    def finish(self) -> list[tuple[ast.FunctionDef, str]]:
        for name in self.jit_referenced:
            for fn in self.by_name.get(name, []):
                self.regions.setdefault(id(fn), (fn, "passed to jit()"))
        return list(self.regions.values())


class _Taint:
    def __init__(self, params: set[str]):
        self.names = set(params)

    def expr(self, e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.names
        if isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Attribute):
            if e.attr in STATIC_ATTRS:
                return False
            return self.expr(e.value)
        if isinstance(e, ast.Call):
            fname = _terminal_name(e.func)
            if fname in STATIC_CALLS:
                return False
            parts = [e.func] + list(e.args) \
                + [k.value for k in e.keywords]
            return any(self.expr(x) for x in parts)
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                return False  # identity tests are static
            return any(self.expr(x) for x in [e.left] + e.comparators)
        if isinstance(e, ast.Lambda):
            return False
        return any(self.expr(c) for c in ast.iter_child_nodes(e)
                   if isinstance(c, ast.expr))


class _RegionChecker(ast.NodeVisitor):
    def __init__(self, region: ast.FunctionDef, why: str, path: str,
                 out: list[Diagnostic]):
        self.path = path
        self.why = why
        self.qual = region.name
        params = {a.arg for a in (region.args.posonlyargs
                                  + region.args.args
                                  + region.args.kwonlyargs)}
        params.discard("self")
        params.discard("cls")
        params -= _static_params(region)
        self.taint = _Taint(params)
        self.out = out

    def _loc(self) -> str:
        return f"{self.path}::{self.qual}"

    def _emit(self, rule: str, severity: str, node: ast.AST,
              message: str, hint: str = "") -> None:
        self.out.append(Diagnostic(
            rule, severity, self._loc(),
            f"{message} (traced region: {self.why})", hint=hint,
            line=getattr(node, "lineno", 0)))

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        if self.taint.expr(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.taint.names.add(t.id)
                elif isinstance(t, ast.Tuple):
                    for el in t.elts:
                        if isinstance(el, ast.Name):
                            self.taint.names.add(el.id)

    def visit_Call(self, node: ast.Call) -> None:
        fname = _terminal_name(node.func)
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "item" and not node.args \
                    and self.taint.expr(node.func.value):
                self._emit(
                    "SRC001", "error", node,
                    "`.item()` forces a blocking device->host sync",
                    hint="keep the value on device, or move the read "
                         "outside the traced region")
            elif node.func.attr in ("tolist", "block_until_ready") \
                    and self.taint.expr(node.func.value):
                self._emit(
                    "SRC002", "warning", node,
                    f"`.{node.func.attr}()` materializes a traced "
                    "value on the host")
            elif node.func.attr in ("asarray", "array") \
                    and _terminal_name(node.func.value) in ("np",
                                                            "numpy") \
                    and any(self.taint.expr(a) for a in node.args):
                self._emit(
                    "SRC002", "warning", node,
                    "np.asarray/np.array on a traced value forces a "
                    "host transfer (or fails at trace time)",
                    hint="use jnp.asarray, or hoist the conversion "
                         "out of the traced region")
            elif node.func.attr == "device_get" \
                    and _terminal_name(node.func.value) == "jax":
                self._emit(
                    "SRC002", "warning", node,
                    "jax.device_get inside a traced region blocks on "
                    "the device")
        elif fname in ("float", "int", "bool") and len(node.args) == 1 \
                and self.taint.expr(node.args[0]):
            self._emit(
                "SRC003", "error", node,
                f"{fname}() of a traced value fails at trace time "
                "(ConcretizationTypeError) or hides a host sync",
                hint="keep the computation in jnp, or compute the "
                     "scalar before tracing")
        self.generic_visit(node)

    def _check_branch(self, node, kind: str) -> None:
        if self.taint.expr(node.test):
            self._emit(
                "SRC004", "warning", node,
                f"Python `{kind}` on a traced boolean: the branch is "
                "resolved at TRACE time, not per batch",
                hint="use jnp.where / lax.cond, or branch on static "
                     "metadata (shape/dtype) only")

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, "while")
        self.generic_visit(node)


#: call names whose results are the BLESSED readback path — calls inside
#: parallel/pipeline.py itself, and call sites routed through it
_PIPELINE_HELPERS = {"device_read", "device_read_int", "device_read_many"}


class _ExecSyncChecker(ast.NodeVisitor):
    """SRC005: raw blocking device->host readbacks inside exec modules.

    Exec `execute`/stream-loop bodies must route their syncs through
    parallel.pipeline.device_read* so the software pipeline can defer
    the readback behind the next batch's dispatch (and so tests can
    trace readback ordering).  Scope is syntactic and module-wide for
    execs/: a raw sync in ANY exec helper ends up in some per-batch
    driver path."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out
        self._fn_stack: list[str] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _loc(self) -> str:
        qual = self._fn_stack[-1] if self._fn_stack else "<module>"
        return f"{self.path}::{qual}"

    def _emit(self, node: ast.AST, what: str) -> None:
        self.out.append(Diagnostic(
            "SRC005", "warning", self._loc(),
            f"{what} is a raw blocking device->host readback in an "
            "exec body",
            hint="route it through parallel.pipeline.device_read / "
                 "device_read_many (pipelined stream loops defer it "
                 "behind the next batch's dispatch); baseline it only "
                 "if the sync is intentional",
            line=getattr(node, "lineno", 0)))

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "device_get" \
                    and _terminal_name(node.func.value) == "jax":
                self._emit(node, "jax.device_get")
            elif node.func.attr == "item" and not node.args:
                self._emit(node, ".item()")
        self.generic_visit(node)


#: numpy module aliases seen in engine code
_NP_NAMES = {"np", "numpy", "_np"}


class _HostMaterializeChecker(ast.NodeVisitor):
    """SRC007: `.block_until_ready()` / `np.asarray` / `np.array` on
    potential device values in execs/ and ops/ modules.

    SRC005 catches the explicit sync spellings (`jax.device_get`,
    `.item()`); these two are the quiet ones — `np.asarray(device_arr)`
    is a full blocking transfer that LOOKS like a free host-side cast.
    The rule is syntactic and module-wide like SRC005; converting the
    RESULT of a blessed `device_read*` call is exempt (that value is
    already host memory), and intentional infrastructure conversions
    are baselined, not suppressed inline."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out
        self._fn_stack: list[str] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _emit(self, node: ast.AST, what: str) -> None:
        qual = self._fn_stack[-1] if self._fn_stack else "<module>"
        self.out.append(Diagnostic(
            "SRC007", "warning", f"{self.path}::{qual}",
            f"{what} on a device value blocks on the device in an "
            "engine hot path",
            hint="route the sync through parallel.pipeline.device_read"
                 " / device_read_async (speculative sizing harvests it "
                 "off the critical path); np.asarray of a device_read* "
                 "result is already exempt; baseline only intentional "
                 "infrastructure sites",
            line=getattr(node, "lineno", 0)))

    @staticmethod
    def _is_blessed(arg: ast.expr) -> bool:
        return isinstance(arg, ast.Call) \
            and _terminal_name(arg.func) in _PIPELINE_HELPERS

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "block_until_ready" and not node.args:
                self._emit(node, "`.block_until_ready()`")
            elif node.func.attr in ("asarray", "array") \
                    and _terminal_name(node.func.value) in _NP_NAMES \
                    and node.args \
                    and not self._is_blessed(node.args[0]):
                self._emit(node,
                           f"`np.{node.func.attr}(...)`")
        self.generic_visit(node)


#: time-module attributes whose call is a raw wall-clock measurement
_TIMING_ATTRS = {"time", "perf_counter", "perf_counter_ns",
                 "monotonic", "monotonic_ns"}


class _RawTimingChecker(ast.NodeVisitor):
    """SRC006: raw time.* readings inside exec/pipeline modules.

    Engine timing must flow through MetricTimer (settled, device-aware,
    visible to profile_query/EXPLAIN ANALYZE) or trace.span (on the
    correlated timeline); a bare perf_counter in an exec body produces
    numbers no tool can see or correlate.  Like SRC005, the rule is
    syntactic and module-wide; the blessed timing infrastructure
    (MetricTimer itself, the reaper, the pipeline wait counters) lives
    in these modules too and is baselined rather than special-cased."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out
        self._fn_stack: list[str] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _TIMING_ATTRS \
                and _terminal_name(node.func.value) == "time":
            qual = self._fn_stack[-1] if self._fn_stack else "<module>"
            self.out.append(Diagnostic(
                "SRC006", "warning", f"{self.path}::{qual}",
                f"raw `time.{node.func.attr}()` timing in an engine "
                "module bypasses MetricTimer/span",
                hint="time the region with MetricTimer (device-aware "
                     "metrics) or trace.span (correlated timeline); "
                     "baseline only timing-infrastructure sites",
                line=getattr(node, "lineno", 0)))
        self.generic_visit(node)


#: SRC012: blocking-wait method names.  `wait` covers Condition/Event,
#: `get` covers queue.Queue (dict.get always takes a key, so the
#: zero-arg form is a queue read), `join` covers Thread/Queue
#: (str.join takes an iterable, so the zero-arg form is a thread join)
_WAIT_ATTRS = {"wait", "get", "join"}


class _UnboundedWaitChecker(ast.NodeVisitor):
    """SRC012: unbounded blocking waits on the serving path (serving/
    and parallel/ modules).

    The cancellation substrate is COOPERATIVE: a cancelled query
    unwinds only when its blocked seams wake up and poll the token, so
    a timeout-less wait anywhere on the serving path is a query that
    session.cancel(), PreparedQuery.cancel() and the per-query
    deadline cannot reach — it blocks until some other party happens
    to notify.  Every wait must pass a timeout (the
    serving/cancel.poll_timeout cadence) and re-check the token, or be
    baselined with its wake-up justification (docs/robustness.md)."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out
        self._fn_stack: list[str] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    @staticmethod
    def _is_class_accessor(node: ast.Call) -> bool:
        """`TpuSemaphore.get()` / `_MetricReaper.get()` are singleton
        ACCESSORS, not blocking reads: skip zero-arg `.get()` whose
        receiver follows the ClassName convention (leading capital,
        optionally underscore-prefixed)."""
        if node.func.attr != "get":  # type: ignore[union-attr]
            return False
        recv = _terminal_name(node.func.value)  # type: ignore[union-attr]
        return bool(recv) and recv.lstrip("_")[:1].isupper()

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _WAIT_ATTRS \
                and not node.args \
                and not any(kw.arg == "timeout"
                            for kw in node.keywords) \
                and not self._is_class_accessor(node):
            qual = self._fn_stack[-1] if self._fn_stack else "<module>"
            self.out.append(Diagnostic(
                "SRC012", "error", f"{self.path}::{qual}",
                f"unbounded blocking `.{node.func.attr}()` on the "
                "serving path cannot be interrupted by "
                "cancellation/deadline",
                hint="wait with a timeout on the "
                     "serving/cancel.poll_timeout cadence and "
                     "re-check the cancel token each wake-up; "
                     "baseline only sites with a guaranteed "
                     "non-poll wake-up",
                line=getattr(node, "lineno", 0)))
        self.generic_visit(node)


#: SRC014: engine entry points a wire-facing handler must NOT call
#: directly — the connect ingress routes every query through the
#: admission-controlled serving seam (PreparedQuery.execute_stream /
#: _stream_tpu), never a bare collect
_WIRE_FORBIDDEN_CALLS = {"collect_exec", "execute_cpu"}


class _WireHandlerChecker(ast.NodeVisitor):
    """SRC014: wire-facing code under connect/ must (a) clamp a frame
    length read off the wire BEFORE allocating with it, and (b) never
    call collect()/collect_exec()/execute_cpu() directly.

    (a) syntactically: a function that assigns from ``struct.unpack``
    (the length-prefix read) and then passes one of those names to any
    call (``recv``/``_recv_exact``/``bytearray`` — the allocation)
    must also contain an ``if``-guard comparing that name and raising.
    Without the clamp, an 8-byte hostile length becomes an arbitrary
    allocation — the server must reject oversized frames, not die
    trying to honor them (docs/connect.md).

    (b) a direct collect bypasses admission control, the deadline/
    cancellation substrate and the per-query serving record; the
    blessed path is the prepared-statement streaming seam."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out
        self._fn_stack: list[str] = []

    def _qual(self) -> str:
        return self._fn_stack[-1] if self._fn_stack else "<module>"

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn_stack.append(node.name)
        self._check_unclamped_lengths(node)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        name = _terminal_name(node.func)
        is_collect_attr = isinstance(node.func, ast.Attribute) \
            and node.func.attr == "collect"
        if is_collect_attr or name in _WIRE_FORBIDDEN_CALLS:
            what = (f".{node.func.attr}()" if is_collect_attr
                    else f"{name}()")
            self.out.append(Diagnostic(
                "SRC014", "error", f"{self.path}::{self._qual()}",
                f"wire-facing handler calls {what} directly, "
                "bypassing the admission-controlled serving seam",
                hint="route wire queries through "
                     "PreparedQuery.execute_stream/_stream_tpu so "
                     "admission, deadline/cancel propagation and the "
                     "per-query connect record all engage "
                     "(docs/connect.md)",
                line=getattr(node, "lineno", 0)))
        self.generic_visit(node)

    # -- (a): unpack-then-allocate without a clamp ------------------- #

    @staticmethod
    def _assigned_names(target: ast.expr) -> set[str]:
        return {n.id for n in ast.walk(target)
                if isinstance(n, ast.Name)}

    @classmethod
    def _own_nodes(cls, node: ast.AST):
        """This function's own statements/expressions — nested defs
        are excluded (they get their own visit)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            yield child
            yield from cls._own_nodes(child)

    def _check_unclamped_lengths(self, fn: ast.FunctionDef) -> None:
        unpacked: dict[str, int] = {}  # name -> lineno
        guarded: set[str] = set()
        used: dict[str, int] = {}
        for node in self._own_nodes(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and _terminal_name(node.value.func) == "unpack":
                for t in node.targets:
                    for nm in self._assigned_names(t):
                        unpacked[nm] = node.lineno
            if isinstance(node, ast.If):
                has_raise = any(isinstance(x, ast.Raise)
                                for x in ast.walk(node))
                if has_raise:
                    for x in ast.walk(node.test):
                        if isinstance(x, ast.Name):
                            guarded.add(x.id)
            if isinstance(node, ast.Call) \
                    and _terminal_name(node.func) != "unpack":
                for a in list(node.args) \
                        + [k.value for k in node.keywords]:
                    for x in ast.walk(a):
                        if isinstance(x, ast.Name):
                            used.setdefault(x.id, node.lineno)
        for nm, line in sorted(unpacked.items()):
            if nm in used and nm not in guarded:
                self.out.append(Diagnostic(
                    "SRC014", "error",
                    f"{self.path}::{fn.name}",
                    f"wire frame length {nm!r} (struct.unpack) is "
                    "used to allocate/read without a clamp guard",
                    hint="validate the length against "
                         "spark.rapids.tpu.connect.maxFrameBytes and "
                         "raise BEFORE any allocation — an 8-byte "
                         "hostile length must never become a giant "
                         "bytearray (docs/connect.md)",
                    line=used[nm]))


def _is_wire_module(path: str) -> bool:
    """SRC014 scope: the wire-facing connect ingress package."""
    parts = path.replace("\\", "/").split("/")
    return "connect" in parts


#: SRC013: attribute-call spellings that force a device->host sync —
#: fatal inside a collective step / shard_map body, where they either
#: fail at trace time or silently serialize the partitioned program
_STEP_SYNC_ATTRS = {"concrete_num_rows", "block_until_ready", "item",
                    "tolist"}
#: builder-function name prefixes whose NESTED defs are traced step
#: bodies (make_hash_exchange_step's shard_fn, make_agg_stage's
#: shard_fn/body, ...)
_STEP_BUILDER_PREFIXES = ("make_", "spmd_")


class _CollectiveStepSyncChecker(ast.NodeVisitor):
    """SRC013: host syncs inside collective step functions / shard_map
    bodies (parallel/exchange.py, parallel/spmd.py,
    execs/collective.py).

    The SPMD whole-stage contract (docs/spmd.md) is that per-round
    host syncs are DEFERRED to stage exit: everything inside a stage
    program — the shard_map body, the lax.scan round body, the fused
    pre/merge/finalize phases — must stay traceable.  A
    `concrete_num_rows()` / `.block_until_ready()` / `np.asarray` /
    `jax.device_get` / `.item()` in one of those bodies either fails
    at trace time or, on a warm-up path handed concrete values,
    silently re-inserts the per-round host round-trip the whole
    architecture exists to remove.

    Traced bodies, syntactically:
    - any function nested inside a step/stage BUILDER (a function
      whose name starts with ``make_`` or ``spmd_``);
    - any function passed by name to ``shard_map``/``_shard_map``;
    - in execs/collective.py: methods handed to a builder as a bound
      reference or called from a lambda passed to a builder
      (``make_route_step(mesh, lambda b: self._route_build(b))``
      makes ``_route_build`` a traced body).

    The host DRIVER code in the same modules (round staging,
    stage-exit counts fetches) legitimately syncs and is out of
    scope."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out
        self._fn_stack: list[ast.FunctionDef] = []
        #: method names referenced as `self._x` in builder-call args
        self.traced_methods: set[str] = set()

    # -- pass 1: find traced bodies --------------------------------- #

    @staticmethod
    def _is_builder_call(node: ast.Call) -> bool:
        name = _terminal_name(node.func)
        return bool(name) and name.startswith(_STEP_BUILDER_PREFIXES)

    @staticmethod
    def _self_attrs(e: ast.expr) -> list[str]:
        """`self._x` attribute names referenced anywhere under `e`."""
        out = []
        for n in ast.walk(e):
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == "self":
                out.append(n.attr)
        return out

    def collect_traced(self, tree: ast.Module) -> tuple[set[int],
                                                        set[str]]:
        """(ids of traced FunctionDef nodes, traced method names)."""
        traced: set[int] = set()
        methods: set[str] = set()
        parents: list[ast.FunctionDef] = []

        def visit(node):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                if any(p.name.startswith(_STEP_BUILDER_PREFIXES)
                       for p in parents):
                    traced.add(id(node))
                parents.append(node)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                parents.pop()
                return
            if isinstance(node, ast.Call):
                if self._is_builder_call(node):
                    for a in list(node.args) \
                            + [k.value for k in node.keywords]:
                        methods.update(self._self_attrs(a))
                        if isinstance(a, ast.Name):
                            methods.add(a.id)
                fname = _terminal_name(node.func)
                if fname in ("shard_map", "_shard_map"):
                    for a in node.args:
                        if isinstance(a, ast.Name):
                            methods.add(a.id)  # resolved by name below
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(tree)
        return traced, methods

    # -- pass 2: flag syncs inside traced bodies --------------------- #

    def _emit(self, node: ast.AST, what: str) -> None:
        qual = self._fn_stack[-1].name if self._fn_stack else "<module>"
        self.out.append(Diagnostic(
            "SRC013", "error", f"{self.path}::{qual}",
            f"{what} is a host sync inside a collective step / "
            "shard_map body — the SPMD stage contract defers syncs "
            "to stage exit (docs/spmd.md)",
            hint="keep the body traceable (jnp/lax only); read counts "
                 "once at stage exit via parallel.spmd.stage_counts / "
                 "fetch",
            line=getattr(node, "lineno", 0)))

    def check_body(self, fn: ast.FunctionDef) -> None:
        self._fn_stack.append(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in _STEP_SYNC_ATTRS \
                        and not node.args:
                    self._emit(node, f"`.{node.func.attr}()`")
                elif node.func.attr in ("asarray", "array") \
                        and _terminal_name(node.func.value) \
                        in _NP_NAMES:
                    self._emit(node, f"`np.{node.func.attr}(...)`")
                elif node.func.attr == "device_get" \
                        and _terminal_name(node.func.value) == "jax":
                    self._emit(node, "`jax.device_get`")
        self._fn_stack.pop()

    def run(self, tree: ast.Module) -> None:
        traced, method_names = self.collect_traced(tree)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if id(node) in traced or node.name in method_names:
                self.check_body(node)


class _RawJitChecker(ast.NodeVisitor):
    """SRC009: raw ``jax.jit`` calls (or decorators, including
    ``partial(jax.jit, ...)``) in execs//ops/ modules instead of
    ``cached_jit``.

    Scope is syntactic and module-wide like SRC005: a raw jit
    ANYWHERE in an exec/ops module produces a program the ledger and
    the compile-cache stats cannot see.  ``pjit`` is out of scope (the
    collective tier's partitioned programs have their own lifecycle);
    ``cached_jit`` itself obviously passes."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out
        self._fn_stack: list[str] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn_stack.append(node.name)
        # bare decorator forms (`@jax.jit`, `@jit`) are plain
        # Attribute/Name nodes — no Call for visit_Call to see;
        # `@partial(jax.jit, ...)` IS a Call and lands there
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call) and self._is_raw_jit(dec):
                self._emit(dec, "a raw `@jax.jit` decorator")
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _emit(self, node: ast.AST, what: str) -> None:
        qual = self._fn_stack[-1] if self._fn_stack else "<module>"
        self.out.append(Diagnostic(
            "SRC009", "error", f"{self.path}::{qual}",
            f"{what} bypasses the jit cache — the compiled program is "
            "unmetered (no ledger attribution, no cache stats, no "
            "cross-query sharing)",
            hint="route it through execs.jit_cache.cached_jit with a "
                 "structural key (and op= for per-operator roofline "
                 "attribution); baseline only sites that genuinely "
                 "have no stable key",
            line=getattr(node, "lineno", 0)))

    @staticmethod
    def _is_raw_jit(e: ast.expr) -> bool:
        """A reference to jax.jit / bare jit (imported from jax)."""
        if isinstance(e, ast.Attribute):
            return e.attr == "jit" and _terminal_name(e.value) == "jax"
        return isinstance(e, ast.Name) and e.id == "jit"

    def visit_Call(self, node: ast.Call) -> None:
        if self._is_raw_jit(node.func):
            self._emit(node, "raw `jax.jit(...)`")
        elif _terminal_name(node.func) == "partial" and node.args \
                and self._is_raw_jit(node.args[0]):
            self._emit(node, "`partial(jax.jit, ...)`")
        self.generic_visit(node)


class _UseAfterDonateChecker(ast.NodeVisitor):
    """SRC010: a local passed at a donated argnum of a
    ``cached_jit(..., donate=...)`` program, referenced after the call
    site.

    Per-function, source-order analysis: assignments like
    ``fn = cached_jit(key, mk, donate=(0,))`` register ``fn`` as a
    donating callable with its (constant) argnums; a later ``fn(b)``
    marks ``b`` consumed at that line; any LOAD of ``b`` on a later
    line in the same function is flagged.  A re-assignment of the
    consumed name clears it (the local now holds something else).
    When the donate spec is not a constant tuple/int, every positional
    arg of the call is treated as donated — conservative, loud.
    ``transfer.run_consuming`` is the blessed escape hatch and is not
    tracked (it owns the consumed-state bookkeeping)."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out

    @staticmethod
    def _donate_spec(call: ast.Call):
        """The donate= keyword of a cached_jit call: a tuple of
        argnums, None when absent/disabled, or "all" when not
        statically known."""
        if _terminal_name(call.func) != "cached_jit":
            return None
        for kw in call.keywords:
            if kw.arg != "donate":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and v.value is None:
                return None
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                nums = []
                for el in v.elts:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, int):
                        nums.append(el.value)
                    else:
                        return "all"
                return tuple(nums) if nums else None
            return "all"
        return None

    @staticmethod
    def _own_nodes(fn: ast.FunctionDef):
        """Walk a function body WITHOUT descending into nested
        function definitions — each function is its own scope and is
        checked by its own visit (no double reports)."""
        stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(node))

    def _check_function(self, fn: ast.FunctionDef) -> None:
        consumed: dict[str, tuple[int, str]] = {}  # name -> (line, fn)
        rebound: dict[str, int] = {}  # name -> earliest later rebind

        def consume_args(call: ast.Call, spec, via: str) -> None:
            args = call.args
            idxs = range(len(args)) if spec == "all" else spec
            for i in idxs:
                if i < len(args) and isinstance(args[i], ast.Name):
                    consumed[args[i].id] = (call.lineno, via)

        # pass 0: EVERY assignment to each name, in source order (the
        # walk itself is not source ordered) — a call site then
        # resolves against the latest assignment at or before its own
        # line, so re-binding a donating name to a plain callable (or
        # vice versa) is honored for straight-line code
        assigns: dict[str, list[tuple[int, object]]] = {}
        for node in self._own_nodes(fn):
            if isinstance(node, ast.Assign):
                spec = self._donate_spec(node.value) \
                    if isinstance(node.value, ast.Call) else None
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        assigns.setdefault(t.id, []).append(
                            (node.lineno, spec))
        for history in assigns.values():
            history.sort()

        def spec_at(name: str, line: int):
            """The donate spec of `name`'s latest assignment at or
            before `line` (None = plain / not assigned yet)."""
            spec = None
            for lineno, s in assigns.get(name, ()):
                if lineno > line:
                    break
                spec = s
            return spec

        for node in self._own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name):
                spec = spec_at(node.func.id, node.lineno)
                if spec is not None:
                    consume_args(node, spec, node.func.id)
            elif isinstance(node.func, ast.Call):
                spec = self._donate_spec(node.func)
                if spec is not None:
                    consume_args(node, spec, "cached_jit(...)")
        if not consumed:
            return
        for node in self._own_nodes(fn):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Store) \
                    and node.id in consumed \
                    and node.lineno >= consumed[node.id][0]:
                rebound[node.id] = min(
                    node.lineno, rebound.get(node.id, node.lineno))
        # lambda parameters SHADOW: a Load of a consumed name inside a
        # lambda whose own params bind that name refers to the
        # parameter, not the donated local — exempt those Loads
        shadowed: set[int] = set()
        for node in self._own_nodes(fn):
            if not isinstance(node, ast.Lambda):
                continue
            params = {a.arg for a in (node.args.posonlyargs
                                      + node.args.args
                                      + node.args.kwonlyargs)}
            if not params & set(consumed):
                continue
            for sub in ast.walk(node.body):
                if isinstance(sub, ast.Name) and sub.id in params:
                    shadowed.add(id(sub))
        for node in self._own_nodes(fn):
            if not (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)) \
                    or id(node) in shadowed:
                continue
            hit = consumed.get(node.id)
            if hit is None or node.lineno <= hit[0] \
                    or node.lineno >= rebound.get(node.id, 1 << 30):
                continue  # before the donate, or after a rebind
            line, via = hit
            self.out.append(Diagnostic(
                "SRC010", "error", f"{self.path}::{fn.name}",
                f"`{node.id}` was donated into `{via}` at line {line} "
                "and referenced afterwards — its device buffers "
                "belong to the program's outputs now (use-after-free "
                "on a TPU backend)",
                hint="route donation through "
                     "transfer.run_consuming (memoizes the output, "
                     "marks the batch consumed) or stop referencing "
                     "the donated local; baseline only intentional "
                     "sites",
                line=node.lineno))

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_function(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]


#: SRC011: accessor calls whose results are SHARED cache objects
#: (serving/work_share.py) — every concurrent consumer sees the same
#: Python objects, so mutating them corrupts other tenants' queries
_SHARED_ACCESSORS = {"subscribe_units", "lookup_result"}
#: method names that mutate their receiver in place
_MUTATOR_METHODS = {"append", "extend", "insert", "pop", "remove",
                    "clear", "update", "sort", "reverse",
                    "setdefault", "popitem", "add", "discard"}


def _base_name(node: ast.expr) -> Optional[str]:
    """The root Name of an attribute/subscript chain
    (``x.cols[0].data`` -> ``x``), or None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _SharedMutationChecker(ast.NodeVisitor):
    """SRC011: in-place mutation of shared-cache objects (see module
    doc).  Per function: pass 1 collects tainted local names —
    assignments from the shared accessors, loop targets iterating
    them, and propagation through plain / attribute / subscript
    reads; pass 2 flags item/attribute assignment, ``del``, augmented
    assignment, and mutator-method calls whose receiver chain roots
    in a tainted name.  Conservative within one function body (taint
    is not flow-sensitive): shared-cache consumers are expected to
    copy before touching, which never taints."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out

    # -- pass 1: taint ---------------------------------------------- #

    @staticmethod
    def _names_in_target(t: ast.expr) -> list[str]:
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, (ast.Tuple, ast.List)):
            out = []
            for e in t.elts:
                out.extend(_SharedMutationChecker._names_in_target(e))
            return out
        return []

    @staticmethod
    def _is_shared_source(v: ast.expr, tainted: set) -> bool:
        if isinstance(v, ast.Call):
            return _terminal_name(v.func) in _SHARED_ACCESSORS
        return _base_name(v) in tainted

    def _collect_taint(self, fn: ast.FunctionDef) -> set:
        tainted: set = set()
        # iterate to a fixpoint so `b = dev; c = b.columns` taints c
        # regardless of statement visit order (bounded: names only
        # ever get ADDED)
        while True:
            before = len(tainted)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    if self._is_shared_source(node.value, tainted):
                        for t in node.targets:
                            tainted.update(self._names_in_target(t))
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    if self._is_shared_source(node.iter, tainted):
                        tainted.update(
                            self._names_in_target(node.target))
            if len(tainted) == before:
                return tainted

    # -- pass 2: mutations ------------------------------------------ #

    def _flag(self, name: str, node: ast.AST, what: str) -> None:
        self.out.append(Diagnostic(
            "SRC011", "error", self.path,
            f"{what} mutates `{name}`, a shared-cache object "
            "(serving/work_share.py) — other tenants' in-flight "
            "queries and the cache itself see the same Python "
            "object, so in-place mutation corrupts their results",
            hint="cached results are immutable by contract: copy "
                 "first (table.combine_chunks(), list(...), a fresh "
                 "batch) or re-materialize, then mutate the copy "
                 "(docs/work_sharing.md)",
            line=getattr(node, "lineno", 0)))

    def _check_function(self, fn: ast.FunctionDef) -> None:
        tainted = self._collect_taint(fn)
        if not tainted:
            return
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        name = _base_name(t)
                        if name in tainted:
                            self._flag(name, node,
                                       "item/attribute assignment")
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target,
                              (ast.Attribute, ast.Subscript)):
                    name = _base_name(node.target)
                    if name in tainted:
                        self._flag(name, node, "augmented assignment")
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        name = _base_name(t)
                        if name in tainted:
                            self._flag(name, node, "del")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATOR_METHODS:
                name = _base_name(node.func.value)
                if name in tainted:
                    self._flag(name, node,
                               f"`.{node.func.attr}()`")

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_function(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]


#: handler-body calls that prove the exception was CLASSIFIED before
#: being absorbed (the execs/retry gate + the fault-accounting hooks)
_CLASSIFY_CALLS = {"classify", "is_retryable", "should_cpu_fallback",
                   "note_recovered"}
#: broad exception type names whose swallow can eat a retryable device
#: error (XlaRuntimeError subclasses RuntimeError)
_BROAD_EXC = {"Exception", "BaseException", "RuntimeError"}


class _SwallowChecker(ast.NodeVisitor):
    """SRC008: broad except clauses that swallow without consulting
    the retry classification gate in recovery-critical modules
    (execs/, io/, shuffle/).

    A handler is CLEAN when its body re-raises anywhere (`raise`,
    bare or not) or calls one of the classification/fault-accounting
    helpers; everything else absorbing Exception/BaseException/
    RuntimeError (or a bare except) is flagged.  Narrow catches
    (OSError, ValueError, a project error type) are out of scope —
    they cannot eat an XlaRuntimeError."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out
        self._fn_stack: list[str] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    @staticmethod
    def _is_broad(handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True  # bare except
        types = t.elts if isinstance(t, ast.Tuple) else [t]
        return any(_terminal_name(x) in _BROAD_EXC for x in types)

    @staticmethod
    def _routes(handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                if _terminal_name(n.func) in _CLASSIFY_CALLS:
                    return True
                # FORWARDING the caught exception object as a call's
                # SOLE argument (queue.put(e), chan.finish(e),
                # callback(e)) is propagation, not a swallow — the
                # consumer re-raises it.  Deliberately narrow: a
                # logging call (`log.warning("failed: %s", e)`) passes
                # the exception among other args and IS a swallow.
                if handler.name and len(n.args) == 1 \
                        and not n.keywords \
                        and isinstance(n.args[0], ast.Name) \
                        and n.args[0].id == handler.name:
                    return True
        return False

    def visit_Try(self, node: ast.Try) -> None:
        for handler in node.handlers:
            if self._is_broad(handler) and not self._routes(handler):
                qual = self._fn_stack[-1] if self._fn_stack \
                    else "<module>"
                caught = "bare except" if handler.type is None else \
                    f"except {ast.unparse(handler.type)}"
                self.out.append(Diagnostic(
                    "SRC008", "warning", f"{self.path}::{qual}",
                    f"`{caught}` swallows without routing through "
                    "retry.classify — it can eat a retryable device "
                    "error and skip the recovery ladder",
                    hint="re-raise, or consult execs/retry.classify / "
                         "is_retryable before absorbing (and "
                         "note_recovered for absorbed injected "
                         "faults); baseline only intentional "
                         "fall-back-to-slow-path sites",
                    line=getattr(handler, "lineno", 0)))
        self.generic_visit(node)


class _PersistWriteChecker(ast.NodeVisitor):
    """SRC015: raw persistence of serialized executables outside
    spark_rapids_tpu/persist.py (see Rules).  Taint is local-name
    based: a name assigned from a ``.serialize()`` call (or from an
    already-tainted name) is a serialized artifact; any ``.write()``
    taking it — or taking a ``.serialize()`` call directly — is a raw
    unvalidated write.  ``pickle.dump``/``dumps``/``Pickler`` are
    flagged outright (the engine has exactly one blessed pickle
    surface, the python_worker pipe protocol, which is out of
    scope)."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out
        self._fn_stack: list[str] = []
        self._tainted: set[str] = set()

    def _qual(self) -> str:
        return self._fn_stack[-1] if self._fn_stack else "<module>"

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn_stack.append(node.name)
        saved = self._tainted
        self._tainted = set()
        self.generic_visit(node)
        self._tainted = saved
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    @staticmethod
    def _is_serialize_call(v: ast.expr) -> bool:
        return isinstance(v, ast.Call) \
            and _terminal_name(v.func) == "serialize"

    def _is_tainted(self, v: ast.expr) -> bool:
        if self._is_serialize_call(v):
            return True
        return isinstance(v, ast.Name) and v.id in self._tainted

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._is_tainted(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._tainted.add(t.id)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = _terminal_name(node.func)
        if name in ("dump", "dumps", "Pickler") \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "pickle":
            self.out.append(Diagnostic(
                "SRC015", "error", f"{self.path}::{self._qual()}",
                f"raw `pickle.{name}` outside the persist module — "
                "engine artifacts written to disk must go through "
                "persist.py's validated writer (magic + checksum + "
                "env stamp + atomic rename)",
                hint="route the write through "
                     "spark_rapids_tpu/persist.py, or keep the data "
                     "in memory",
                line=node.lineno))
        elif name == "write" and node.args \
                and self._is_tainted(node.args[0]):
            self.out.append(Diagnostic(
                "SRC015", "error", f"{self.path}::{self._qual()}",
                "raw `.write()` of a serialized executable — a file "
                "written outside persist.py's validated writer has "
                "no torn-write protection and no staleness stamp",
                hint="route the artifact through "
                     "spark_rapids_tpu/persist.py's save_* APIs",
                line=node.lineno))
        self.generic_visit(node)


class _RawDevicePutChecker(ast.NodeVisitor):
    """SRC016: raw ``jax.device_put`` calls in execs//parallel/
    modules instead of the placement choke point.

    Scope is syntactic and module-wide like SRC009: a raw device_put
    anywhere in these layers moves a stage-input leaf without
    classifying it into the ``placement.*`` counters, so the
    pod-serving steady-state-zero-host-uploads gate (and the
    device-born evidence it rests on) silently stops covering that
    transfer.  parallel/placement.py IS the choke point — exempt by
    construction."""

    def __init__(self, path: str, out: list[Diagnostic]):
        self.path = path
        self.out = out
        self._fn_stack: list[str] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    @staticmethod
    def _is_raw_device_put(e: ast.expr) -> bool:
        """A reference to jax.device_put / bare device_put."""
        if isinstance(e, ast.Attribute):
            return e.attr == "device_put" \
                and _terminal_name(e.value) == "jax"
        return isinstance(e, ast.Name) and e.id == "device_put"

    def visit_Call(self, node: ast.Call) -> None:
        if self._is_raw_device_put(node.func):
            qual = self._fn_stack[-1] if self._fn_stack else "<module>"
            self.out.append(Diagnostic(
                "SRC016", "error", f"{self.path}::{qual}",
                "raw `jax.device_put` bypasses the stage-input "
                "placement choke point — the transfer is unclassified "
                "(no placement.* counter), so the pod-serving "
                "zero-host-upload gate no longer covers it",
                hint="route the move through parallel/placement."
                     "place_piece (per-shard pieces) or "
                     "placement.adopt_batch (whole batches)",
                line=node.lineno))
        self.generic_visit(node)


def _is_exec_module(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "execs" in parts


def _is_timed_module(path: str) -> bool:
    """SRC006 scope: exec bodies and the pipeline layer."""
    parts = path.replace("\\", "/").split("/")
    return "execs" in parts or "parallel" in parts


def _is_sync_hazard_module(path: str) -> bool:
    """SRC007 scope: exec bodies and the device kernels under ops/."""
    parts = path.replace("\\", "/").split("/")
    return "execs" in parts or "ops" in parts


def _is_program_module(path: str) -> bool:
    """SRC009 scope: the modules that compile device programs.
    execs/jit_cache.py IS the cache — exempt by construction."""
    norm = path.replace("\\", "/")
    if norm.endswith("execs/jit_cache.py"):
        return False
    parts = norm.split("/")
    return "execs" in parts or "ops" in parts


def _is_sharing_module(path: str) -> bool:
    """SRC011 scope: the layers that consume shared-cache objects
    (the serving tier, exec stream loops, the scan subscribers).
    serving/work_share.py IS the cache — its own bookkeeping mutates
    its own lists by construction — so it is exempt."""
    norm = path.replace("\\", "/")
    if norm.endswith("serving/work_share.py"):
        return False
    parts = norm.split("/")
    return any(p in parts for p in ("serving", "execs", "io"))


def _is_collective_step_module(path: str) -> bool:
    """SRC013 scope: the modules that define collective step /
    shard_map bodies — the exchange program builders, the SPMD stage
    builders, and the collective execs whose methods trace into
    them."""
    norm = path.replace("\\", "/")
    return norm.endswith(("parallel/exchange.py", "parallel/spmd.py",
                          "execs/collective.py"))


def _is_wait_module(path: str) -> bool:
    """SRC012 scope: the serving tier and the parallel substrate — the
    layers whose blocking waits sit on the serving path a cancelled
    query must be able to unwind through."""
    parts = path.replace("\\", "/").split("/")
    return "serving" in parts or "parallel" in parts


def _is_persist_scope_module(path: str) -> bool:
    """SRC015 scope: the whole engine EXCEPT persist.py (it IS the
    validated writer) and python_worker/ (its pickle use is the UDF
    pipe protocol — function frames over stdin, never disk files)."""
    norm = path.replace("\\", "/")
    if norm.endswith("spark_rapids_tpu/persist.py") \
            or norm == "persist.py":
        return False
    return "python_worker" not in norm.split("/")


def _is_placement_scope_module(path: str) -> bool:
    """SRC016 scope: exec bodies and the parallel substrate — the
    layers that feed stage inputs — EXCEPT parallel/placement.py (it
    IS the classified mover)."""
    norm = path.replace("\\", "/")
    if norm.endswith("parallel/placement.py"):
        return False
    parts = norm.split("/")
    return "execs" in parts or "parallel" in parts


def _is_recovery_module(path: str) -> bool:
    """SRC008 scope: the layers whose exceptions feed the recovery
    ladder.  execs/retry.py IS the classification gate — exempt."""
    norm = path.replace("\\", "/")
    parts = norm.split("/")
    if norm.endswith("execs/retry.py"):
        return False
    return any(p in parts for p in ("execs", "io", "shuffle"))


def lint_source_text(src: str, path: str) -> list[Diagnostic]:
    """Lint one module's source text (unit-test entry point)."""
    out: list[Diagnostic] = []
    try:
        tree = ast.parse(src)
    except SyntaxError as exc:
        out.append(Diagnostic(
            "SRC000", "error", path, f"syntax error: {exc}",
            line=exc.lineno or 0))
        return out
    finder = _RegionFinder()
    finder.visit(tree)
    for region, why in finder.finish():
        _RegionChecker(region, why, path, out).visit(region)
    if _is_exec_module(path):
        _ExecSyncChecker(path, out).visit(tree)
    if _is_timed_module(path):
        _RawTimingChecker(path, out).visit(tree)
    if _is_sync_hazard_module(path):
        _HostMaterializeChecker(path, out).visit(tree)
    if _is_program_module(path):
        _RawJitChecker(path, out).visit(tree)
        _UseAfterDonateChecker(path, out).visit(tree)
    if _is_recovery_module(path):
        _SwallowChecker(path, out).visit(tree)
    if _is_sharing_module(path):
        _SharedMutationChecker(path, out).visit(tree)
    if _is_wait_module(path):
        _UnboundedWaitChecker(path, out).visit(tree)
    if _is_collective_step_module(path):
        _CollectiveStepSyncChecker(path, out).run(tree)
    if _is_wire_module(path):
        _WireHandlerChecker(path, out).visit(tree)
    if _is_persist_scope_module(path):
        _PersistWriteChecker(path, out).visit(tree)
    if _is_placement_scope_module(path):
        _RawDevicePutChecker(path, out).visit(tree)
    return out


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_source_files(root: Optional[str] = None) -> Iterable[str]:
    root = root or _package_root()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames[:] = sorted(d for d in dirnames
                             if not d.startswith(("_", ".")))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def check_sources(root: Optional[str] = None) -> list[Diagnostic]:
    """Lint every engine source file under spark_rapids_tpu/."""
    root = root or _package_root()
    base = os.path.dirname(root)
    out: list[Diagnostic] = []
    for path in iter_source_files(root):
        with open(path) as f:
            src = f.read()
        rel = os.path.relpath(path, base)
        out.extend(lint_source_text(src, rel))
    return out
