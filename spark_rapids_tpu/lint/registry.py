"""Registry consistency checker: the hard-pass extension of
tools/api_validation.

The reference generates docs/supported_ops.md from its rule tables and
diffs its registries against Spark via api_validation, so a rule
without an implementation (or an implementation without a rule) is
caught before it ships wrong results.  This analyzer makes the same
properties hard-checkable here:

- REG001 (error): registered expression has no declared TypeSig — the
  tagging pass would trust the operator code it is supposed to check
- REG002 (error): registered expression/aggregate has no evaluator
  implementation (phantom registry entry: tagging says TPU, execution
  has nothing to run)
- REG003 (error): registered entry missing its docs/supported_ops.md
  row — the generated docs drifted from the live registries
- REG004 (warning): an evaluator exists but is unregistered — it can
  never engage, or worse engages through a side door without tagging
- REG005 (error): api_validation exec-map drift — the coverage map
  names a module/class that no longer exists
- REG006 (error): registered aggregate has no AGG_SIGS entry
- REG007 (error): wire-codec registry drift — a codec registered in
  columnar/compression/ without a declared decoder program key, or
  missing from the round-trip test matrix
  (tests/test_wire_compression.py): a codec whose decode is untested
  could ship wrong bytes over the wire
"""

from __future__ import annotations

import importlib
import inspect
import os
import pkgutil

from spark_rapids_tpu.lint.diagnostic import Diagnostic

#: evaluators that are deliberately NOT in SUPPORTED_EXPRS, with the
#: reason — anything new landing here should either be registered or
#: get an entry with a justification
UNREGISTERED_OK = {
    "OpaquePythonUDF": "deliberately unregistered: opaque row UDFs "
                       "always fall back to the CPU engine",
    "ScalarSubquery": "rewritten to a Literal by the planner prepass; "
                      "never evaluated as a device expression",
    "Explode": "generator expression: tagged through the Generate "
               "exec's check_supported, not the expression registry",
}


def _loc(name: str) -> str:
    return f"registry::{name}"


def _docs_text(docs_dir: str = None) -> str:
    if docs_dir is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        docs_dir = os.path.join(root, "docs")
    path = os.path.join(docs_dir, "supported_ops.md")
    if not os.path.exists(path):
        return ""
    with open(path) as f:
        return f.read()


def _roundtrip_matrix_text(tests_dir: str = None) -> str:
    """The round-trip test matrix source (the REG007 coverage check
    reads the test module the same way REG003 reads the generated
    docs: the registry and its test matrix must not drift)."""
    if tests_dir is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        tests_dir = os.path.join(root, "tests")
    path = os.path.join(tests_dir, "test_wire_compression.py")
    if not os.path.exists(path):
        return ""
    with open(path) as f:
        return f.read()


def check_wire_codecs(tests_dir: str = None) -> list[Diagnostic]:
    """REG007: every codec in the wire-codec registry declares a
    decoder program key and appears in the round-trip test matrix."""
    from spark_rapids_tpu.columnar import compression as WC

    out: list[Diagnostic] = []
    matrix = _roundtrip_matrix_text(tests_dir)
    for name, codec in WC.registry_items():
        if not getattr(codec, "decoder_program_key", ""):
            out.append(Diagnostic(
                "REG007", "error", _loc(f"codec:{name}"),
                f"wire codec {name!r} declares no decoder_program_key: "
                "nothing names the program that undoes its encode",
                hint="set decoder_program_key on the Codec subclass "
                     "(device:<program> or host:<routine>)"))
        if matrix and f'"{name}"' not in matrix \
                and f"'{name}'" not in matrix:
            out.append(Diagnostic(
                "REG007", "error", _loc(f"codec:{name}"),
                f"wire codec {name!r} is missing from the round-trip "
                "test matrix (tests/test_wire_compression.py): its "
                "decode path would ship untested bytes",
                hint="add the codec to ROUND_TRIP_MATRIX in "
                     "tests/test_wire_compression.py"))
    if not matrix:
        out.append(Diagnostic(
            "REG007", "error", _loc("tests/test_wire_compression.py"),
            "the wire-codec round-trip test matrix is missing "
            "(tests/test_wire_compression.py)",
            hint="restore the round-trip property tests"))
    return out


def _expr_classes():
    """Every Expression subclass defined under spark_rapids_tpu.exprs
    (plus the UDF expression module), keyed by class."""
    import spark_rapids_tpu.exprs as EX
    from spark_rapids_tpu.exprs.base import Expression

    mods = ["spark_rapids_tpu.exprs." + m.name
            for m in pkgutil.iter_modules(EX.__path__)]
    mods.append("spark_rapids_tpu.udf.exprs")
    out = []
    for mn in mods:
        mod = importlib.import_module(mn)
        for cls in vars(mod).values():
            if inspect.isclass(cls) and issubclass(cls, Expression) \
                    and cls is not Expression and cls.__module__ == mn:
                out.append(cls)
    return out


def check_registries(docs_dir: str = None) -> list[Diagnostic]:
    from spark_rapids_tpu.exprs.base import Expression
    from spark_rapids_tpu.plan import planner as PL
    from spark_rapids_tpu.tools import api_validation as AV

    out: list[Diagnostic] = []
    docs = _docs_text(docs_dir)
    if not docs:
        out.append(Diagnostic(
            "REG003", "error", _loc("docs/supported_ops.md"),
            "docs/supported_ops.md is missing",
            hint="run python -m spark_rapids_tpu.tools.gen_docs"))

    # -- registered expressions: sig + implementation + doc row -------- #
    for cls in PL.SUPPORTED_EXPRS:
        name = cls.__name__
        if cls not in PL.EXPR_SIGS:
            out.append(Diagnostic(
                "REG001", "error", _loc(name),
                f"expression {name} is registered without a TypeSig: "
                "tagging cannot check its input types",
                hint="pass a TS.ExprSig to register_expr"))
        if "eval" not in cls.__dict__ and not any(
                "eval" in b.__dict__ for b in cls.__mro__[1:-1]
                if b is not Expression):
            out.append(Diagnostic(
                "REG002", "error", _loc(name),
                f"expression {name} is registered but implements no "
                "eval(): tagging would accept plans execution cannot "
                "run"))
        if docs and f"| {name} |" not in docs:
            out.append(Diagnostic(
                "REG003", "error", _loc(name),
                f"registered expression {name} has no "
                "docs/supported_ops.md row",
                hint="regenerate: python -m "
                     "spark_rapids_tpu.tools.gen_docs"))

    # -- registered aggregates ---------------------------------------- #
    for cls in PL.SUPPORTED_AGGS:
        name = cls.__name__
        if cls not in PL.AGG_SIGS:
            out.append(Diagnostic(
                "REG006", "error", _loc(name),
                f"aggregate {name} is registered without an AGG_SIGS "
                "entry: its input types go unchecked at tagging",
                hint="add a TS.ExprSig to planner.AGG_SIGS"))
        impl = any("update_ops" in b.__dict__ for b in cls.__mro__[:-1])
        if not impl and "expand" not in cls.__dict__:
            out.append(Diagnostic(
                "REG002", "error", _loc(name),
                f"aggregate {name} defines neither update_ops nor an "
                "expand() rewrite: it cannot execute"))
        if docs and f"| {name} |" not in docs:
            out.append(Diagnostic(
                "REG003", "error", _loc(name),
                f"registered aggregate {name} has no "
                "docs/supported_ops.md row",
                hint="regenerate: python -m "
                     "spark_rapids_tpu.tools.gen_docs"))

    # -- exec conf table: doc rows ------------------------------------ #
    for cls in PL._EXEC_CONFS:
        name = cls.__name__
        if docs and f"| {name} |" not in docs:
            out.append(Diagnostic(
                "REG003", "error", _loc(name),
                f"exec conf entry {name} has no "
                "docs/supported_ops.md row",
                hint="regenerate: python -m "
                     "spark_rapids_tpu.tools.gen_docs"))

    # -- wire-codec registry: decoder key + round-trip coverage --------- #
    out.extend(check_wire_codecs())

    # -- api_validation drift becomes a hard failure ------------------- #
    for ref in AV.validate()["exec_drift"]:
        out.append(Diagnostic(
            "REG005", "error", _loc(ref),
            f"api_validation exec map names a missing implementation "
            f"for {ref}: the coverage doc would report phantom "
            "coverage",
            hint="update _EXEC_MAP in tools/api_validation.py"))

    # -- no evaluator exists unregistered ------------------------------ #
    registered = set(PL.SUPPORTED_EXPRS)

    def covered(cls) -> bool:
        if cls in registered:
            return True
        return any(covered(sub) for sub in cls.__subclasses__())

    for cls in _expr_classes():
        if "eval" not in cls.__dict__:
            continue  # abstract helper: no own evaluator
        if covered(cls):
            continue
        if cls.__name__ in UNREGISTERED_OK:
            continue
        out.append(Diagnostic(
            "REG004", "warning", _loc(cls.__name__),
            f"evaluator {cls.__module__}.{cls.__name__} is not in "
            "SUPPORTED_EXPRS (and no subclass is): it can never be "
            "tagged for TPU execution",
            hint="register_expr it with a TypeSig, or add it to "
                 "lint.registry.UNREGISTERED_OK with a justification"))
    return out
