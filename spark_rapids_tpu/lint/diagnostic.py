"""Shared diagnostic model for the tpulint static-analysis subsystem.

The reference never trusts operator code: TypeChecks verifies declared
TypeSigs during tagging, api_validation diffs registries against Spark,
and docs/supported_ops.md is generated from the rule tables.  tpulint is
the unifying pass over all of that — every analyzer (dtype flow,
registry consistency, plan anti-patterns, engine-source hazards) emits
the same Diagnostic record, so one CLI, one baseline file and one
explain() feed serve them all.

Baselines: a checked-in JSON file of accepted finding keys.  Keys are
line-number-free (rule + location symbol + message) so routine edits
above a finding do not churn the baseline; a finding is NEW only when
its key is absent from the baseline.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterable, Optional, Sequence

#: severity order, weakest first
SEVERITIES = ("info", "warning", "error")


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: rule id, severity, location, message, fix hint.

    `location` is a stable symbol — ``path/to/file.py::qualname`` for
    source findings, ``plan::NodeName`` / ``registry::ClassName`` for
    the others.  `line` (0 = unknown) is display-only and deliberately
    excluded from the baseline key."""

    rule: str
    severity: str
    location: str
    message: str
    hint: str = ""
    line: int = 0

    def __post_init__(self):
        assert self.severity in SEVERITIES, self.severity

    @property
    def key(self) -> str:
        """Stable baseline identity."""
        return f"{self.rule}::{self.location}::{self.message}"

    def render(self) -> str:
        loc = self.location + (f":{self.line}" if self.line else "")
        s = f"{self.severity:7s} {self.rule} {loc} — {self.message}"
        if self.hint:
            s += f"\n        hint: {self.hint}"
        return s

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def max_severity(diags: Sequence[Diagnostic]) -> Optional[str]:
    if not diags:
        return None
    return max((d.severity for d in diags), key=SEVERITIES.index)


def filter_at_least(diags: Iterable[Diagnostic],
                    severity: str) -> list[Diagnostic]:
    floor = SEVERITIES.index(severity)
    return [d for d in diags if SEVERITIES.index(d.severity) >= floor]


def sort_diags(diags: Iterable[Diagnostic]) -> list[Diagnostic]:
    return sorted(diags, key=lambda d: (-SEVERITIES.index(d.severity),
                                        d.rule, d.location, d.line,
                                        d.message))


# ------------------------------------------------------------------ #
# Baseline handling
# ------------------------------------------------------------------ #

def default_baseline_path() -> str:
    """The checked-in accepted-findings file, next to this module."""
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: Optional[str] = None) -> set[str]:
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        data = json.load(f)
    return set(data.get("accepted", []))


def save_baseline(diags: Sequence[Diagnostic],
                  path: Optional[str] = None) -> str:
    path = path or default_baseline_path()
    payload = {
        "comment": "Accepted tpulint findings; regenerate with "
                   "python -m spark_rapids_tpu.tools.lint "
                   "--update-baseline",
        "accepted": sorted({d.key for d in diags}),
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def split_new(diags: Sequence[Diagnostic],
              baseline: set[str]) -> tuple[list[Diagnostic],
                                           list[Diagnostic]]:
    """(new, accepted) partition against a baseline key set."""
    new, accepted = [], []
    for d in diags:
        (accepted if d.key in baseline else new).append(d)
    return new, accepted
