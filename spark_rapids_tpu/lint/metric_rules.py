"""Metric-registry consistency checker (MET*).

The event log persists the per-operator metric tree verbatim
(docs/eventlog.md), and tools/history compares those names across
runs — so a metric an exec REGISTERS but never settles is a column of
permanent zeros in every report, and a name settled without
registration is a KeyError waiting in a rarely-taken branch (metrics
live in a plain dict populated from ``additional_metrics()``).  Both
are silent schema rot in the persisted record.

MET001 (error) cross-checks the two sides statically over the exec
modules (``execs/``, ``io/`` — the layers that define TpuExec
subclasses):

- every name returned by an ``additional_metrics()`` implementation
  must be SETTLED somewhere in those modules (referenced as
  ``<x>.metrics[name]`` — add/add_lazy/MetricTimer all go through the
  subscript);
- every constant-keyed ``<x>.metrics[name]`` reference must resolve to
  a registered name (an ``additional_metrics`` entry, or one of the
  standard metrics the TpuExec base registers).

Name resolution is syntactic: string literals, plus module-level
``NAME = "literal"`` constants of any scanned module (the
``execs/base.py`` standard-name constants resolve this way at every
import site).  Dynamic keys (``self.metrics[k] = v`` copies) are
skipped, and a class whose ``additional_metrics`` returns a COMPUTED
list is exempt on both sides — but only for itself: its registration
can't be enumerated and its own settle sites may name what that list
declares; every other class stays fully checked.  The rule is a
typo/rot catcher, not an alias tracker.  Intentional exceptions are
baselined, not suppressed inline (the SRC005 posture).
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from spark_rapids_tpu.lint.diagnostic import Diagnostic

#: metric names the TpuExec base class registers for every exec
#: (execs/base.py TpuExec.__init__) — always valid to settle
BASE_METRICS = {"numOutputRows", "numOutputBatches", "totalTime"}


def _module_str_constants(tree: ast.Module) -> dict[str, str]:
    """Module-level NAME = "literal" assignments (the standard metric
    name constants and their re-exports)."""
    out: dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _resolve_key(node: ast.expr, consts: dict[str, str]
                 ) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


class _ModuleScan(ast.NodeVisitor):
    """One module's registrations + settle references."""

    def __init__(self, path: str, consts: dict[str, str]):
        self.path = path
        self.consts = consts
        #: (name, class, line) per additional_metrics entry
        self.registered: list[tuple[str, str, int]] = []
        #: (name, qualname, line, owning class|None) per resolvable
        #: metrics[...] subscript
        self.used: list[tuple[str, str, int, Optional[str]]] = []
        #: classes whose additional_metrics we could not fully resolve
        self.dynamic_classes: set[str] = set()
        self._cls: list[str] = []
        self._fn: list[str] = []

    # -- structure ------------------------------------------------- #

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn.append(node.name)
        if node.name == "additional_metrics" and self._cls:
            self._collect_registrations(node)
        self.generic_visit(node)
        self._fn.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _collect_registrations(self, fn: ast.FunctionDef) -> None:
        cls = self._cls[-1]
        for ret in ast.walk(fn):
            if not isinstance(ret, ast.Return) or ret.value is None:
                continue
            if not isinstance(ret.value, (ast.List, ast.Tuple)):
                # computed list (super() + extras, comprehension):
                # can't enumerate — exempt this class from the
                # never-settled side rather than guessing
                self.dynamic_classes.add(cls)
                continue
            for el in ret.value.elts:
                if isinstance(el, ast.Tuple) and el.elts:
                    name = _resolve_key(el.elts[0], self.consts)
                    if name is not None:
                        self.registered.append(
                            (name, cls, el.lineno))
                        continue
                self.dynamic_classes.add(cls)

    # -- settle references ----------------------------------------- #

    def visit_Subscript(self, node: ast.Subscript) -> None:
        v = node.value
        if isinstance(v, ast.Attribute) and v.attr == "metrics":
            name = _resolve_key(node.slice, self.consts)
            if name is not None:
                cls = self._cls[-1] if self._cls else None
                qual = self._fn[-1] if self._fn else "<module>"
                if cls:
                    qual = f"{cls}.{qual}"
                self.used.append((name, qual, node.lineno, cls))
        self.generic_visit(node)


def _is_metric_module(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "execs" in parts or "io" in parts


def check_metric_sources(sources: dict[str, str]) -> list[Diagnostic]:
    """Cross-check registrations vs settle sites over a set of
    modules ({relpath: source}); unit-test entry point."""
    scans: list[_ModuleScan] = []
    all_consts: dict[str, str] = {}
    trees: dict[str, ast.Module] = {}
    for path, src in sources.items():
        try:
            trees[path] = ast.parse(src)
        except SyntaxError:
            continue  # SRC000's problem, not ours
        all_consts.update(_module_str_constants(trees[path]))
    out: list[Diagnostic] = []
    for path, tree in trees.items():
        scan = _ModuleScan(path, all_consts)
        scan.visit(tree)
        scans.append(scan)
    registered_names = BASE_METRICS | {
        n for s in scans for (n, _c, _l) in s.registered}
    used_names = {n for s in scans for (n, _q, _l, _cls) in s.used}
    for s in scans:
        for name, cls, line in s.registered:
            if name not in used_names:
                out.append(Diagnostic(
                    "MET001", "error", f"{s.path}::{cls}",
                    f"metric {name!r} is registered by "
                    f"additional_metrics but never settled — it will "
                    "persist as a permanent zero in every event-log "
                    "record and report",
                    hint="settle it via self.metrics[...] "
                         ".add/.add_lazy/MetricTimer, or drop the "
                         "registration; baseline only intentional "
                         "placeholders",
                    line=line))
        for name, qual, line, cls in s.used:
            # a dynamic class may settle names its computed
            # registration list declares — exempt ITS uses only (a
            # repo-wide exemption would let one dynamic class turn
            # the typo catcher off everywhere)
            if cls is not None and cls in s.dynamic_classes:
                continue
            if name not in registered_names:
                out.append(Diagnostic(
                    "MET001", "error", f"{s.path}::{qual}",
                    f"metric {name!r} is settled but registered "
                    "nowhere — a KeyError in waiting, and a name the "
                    "persisted metric schema never declares",
                    hint="add it to the owning exec's "
                         "additional_metrics() so readers can trust "
                         "the name set",
                    line=line))
    return out


def check_metric_registry(root: Optional[str] = None
                          ) -> list[Diagnostic]:
    """Run MET001 over the repo's exec modules (execs/, io/)."""
    from spark_rapids_tpu.lint.source_rules import (
        _package_root,
        iter_source_files,
    )

    root = root or _package_root()
    base = os.path.dirname(root)
    sources: dict[str, str] = {}
    for path in iter_source_files(root):
        rel = os.path.relpath(path, base)
        if not _is_metric_module(rel):
            continue
        with open(path) as f:
            sources[rel] = f.read()
    return check_metric_sources(sources)
