"""Dtype-flow checker: verify dtypes across every edge of a lowered
physical plan WITHOUT executing it.

The round-5 UNION bug is the motivating class: TpuUnionExec re-tags
every member batch with the first member's schema, so an INT first
member unioned with a DOUBLE second member ships float data under an
int tag and downstream ops silently truncate.  Nothing at runtime can
catch that — the data is already mislabeled — but it is fully visible
statically: the second child's declared schema disagrees with the
union's output schema.  This analyzer propagates declared dtypes
through bound expression trees and exec edges and flags every
disagreement between what a node DECLARES and what its inputs/
evaluators actually produce (the physical-level twin of the tagging
pass's TypeSig checks, ref: TypeChecks.scala:483).

Rules
-----
- DT000 (warning): a node the analyzer crashed on — analysis never
  kills the caller, but --strict fails so a refactor that breaks
  _check_node cannot silently turn the other rules off
- DT001 (error): set-operation member schema mismatch (the UNION class)
- DT002 (error): bound reference out of range / stale dtype vs the
  input schema it is evaluated against
- DT003 (warning): expression input dtype outside its declared TypeSig
  (the tagging pass should have routed this to the CPU engine — seeing
  it in a lowered plan means tagging drifted)
- DT004 (error): predicate position (filter/join condition) whose
  expression is not boolean-typed
- DT005 (error): declared output field dtype disagrees with the
  evaluator's expression dtype
- DT006 (error): equi-join key dtype mismatch between sides (hash
  parity requires identical physical hashing)
- DT007 (error): schema-preserving exec whose declared schema disagrees
  with its child's
"""

from __future__ import annotations

from typing import Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.lint.diagnostic import Diagnostic


def _loc(node) -> str:
    return f"plan::{type(node).__name__}"


def _check_bound_tree(e, schema: Optional[T.Schema], where: str,
                      node, out: list[Diagnostic]) -> None:
    """Walk one bound expression tree: reference/TypeSig/dtype checks."""
    from spark_rapids_tpu.exprs import base as B
    from spark_rapids_tpu.plan import planner as PL
    from spark_rapids_tpu.plan import typesig as TS

    if isinstance(e, B.BoundReference) and schema is not None:
        if not (0 <= e.ordinal < len(schema.fields)):
            out.append(Diagnostic(
                "DT002", "error", _loc(node),
                f"{where}: bound reference ordinal {e.ordinal} out of "
                f"range for input schema of {len(schema.fields)} "
                "columns",
                hint="re-bind the expression against the exec's actual "
                     "input schema"))
            return
        f = schema.fields[e.ordinal]
        if f.dtype != e.dtype:
            out.append(Diagnostic(
                "DT002", "error", _loc(node),
                f"{where}: bound reference input[{e.ordinal}] declares "
                f"{e.dtype.name} but the input column "
                f"{f.name!r} is {f.dtype.name}",
                hint="stale binding — re-bind after schema-changing "
                     "rewrites"))
    sig = PL.EXPR_SIGS.get(type(e))
    if sig is not None:
        for c in e.children:
            try:
                dt = c.dtype
            except Exception:
                continue
            if not sig.inputs.supports(dt):
                out.append(Diagnostic(
                    "DT003", "warning", _loc(node),
                    f"{where}: {type(e).__name__} evaluates a "
                    f"{dt.name} input outside its declared TypeSig "
                    f"({sig.inputs.describe()})",
                    hint="the tagging pass should have kept this on "
                         "the CPU engine; widen the TypeSig or fix "
                         "tagging"))
    for c in e.children:
        _check_bound_tree(c, schema, where, node, out)


def _expr_dtype(e) -> Optional[T.DataType]:
    try:
        return e.dtype
    except Exception:
        return None


def _check_predicate(e, schema, where: str, node,
                     out: list[Diagnostic]) -> None:
    _check_bound_tree(e, schema, where, node, out)
    dt = _expr_dtype(e)
    if dt is not None and not isinstance(dt, (T.BooleanType, T.NullType)):
        out.append(Diagnostic(
            "DT004", "error", _loc(node),
            f"{where}: predicate expression {e.name} has type "
            f"{dt.name}, not boolean",
            hint="wrap the condition in an explicit comparison"))


def _schemas_equal(a: T.Schema, b: T.Schema) -> bool:
    return len(a.fields) == len(b.fields) and all(
        fa.dtype == fb.dtype for fa, fb in zip(a.fields, b.fields))


def _check_union(node, out: list[Diagnostic]) -> None:
    first = node.children[0].schema
    for mi, child in enumerate(node.children[1:], start=2):
        s = child.schema
        if len(s.fields) != len(first.fields):
            out.append(Diagnostic(
                "DT001", "error", _loc(node),
                f"union member {mi} has {len(s.fields)} columns, "
                f"member 1 has {len(first.fields)}"))
            continue
        for i, (fa, fb) in enumerate(zip(first.fields, s.fields)):
            if fa.dtype != fb.dtype:
                out.append(Diagnostic(
                    "DT001", "error", _loc(node),
                    f"union member {mi} column {i + 1} ({fb.name!r}) "
                    f"is {fb.dtype.name} but member 1 declares "
                    f"{fa.dtype.name}: batches would be re-tagged and "
                    "silently coerced",
                    hint="insert widening casts on the members "
                         "(Spark's WidenSetOperationTypes) before the "
                         "union"))


def _check_node(node, out: list[Diagnostic]) -> None:
    from spark_rapids_tpu.execs import basic as XB
    from spark_rapids_tpu.execs import sort as XS
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.execs.coalesce import TpuCoalescePartitionsExec
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.execs.limit import (
        TpuCollectLimitExec,
        TpuGlobalLimitExec,
        TpuLocalLimitExec,
    )
    from spark_rapids_tpu.plan.planner import CpuFallbackExec

    if isinstance(node, CpuFallbackExec):
        return  # the CPU engine re-derives types itself

    if isinstance(node, XB.TpuUnionExec):
        _check_union(node, out)
        return

    child_schema = node.children[0].schema if node.children else None

    if isinstance(node, XB.TpuProjectExec):
        for i, e in enumerate(node.exprs):
            _check_bound_tree(e, child_schema, f"projection {i + 1}",
                              node, out)
            dt, declared = _expr_dtype(e), node.schema.fields[i].dtype
            if dt is not None and dt != declared:
                out.append(Diagnostic(
                    "DT005", "error", _loc(node),
                    f"projection {i + 1} ({node.schema.fields[i].name!r})"
                    f" declares {declared.name} but its expression "
                    f"evaluates to {dt.name}"))
    elif isinstance(node, XB.TpuFilterExec):
        _check_predicate(node.condition, child_schema, "filter condition",
                         node, out)
    elif isinstance(node, XS._SortMixin):
        for i, k in enumerate(getattr(node, "keys", [])):
            _check_bound_tree(k.expr, child_schema, f"sort key {i + 1}",
                              node, out)
    elif isinstance(node, TpuHashAggregateExec):
        if node.mode != "final":
            for i, g in enumerate(node.groups):
                _check_bound_tree(g, child_schema,
                                  f"grouping key {i + 1}", node, out)
        if node.mode != "partial":
            # declared output vs the finalize projection's dtypes
            for i, (f, fe) in enumerate(zip(node.schema.fields,
                                            node.final_exprs)):
                dt = _expr_dtype(fe)
                if dt is not None and dt != f.dtype:
                    out.append(Diagnostic(
                        "DT005", "error", _loc(node),
                        f"aggregate output {i + 1} ({f.name!r}) "
                        f"declares {f.dtype.name} but finalizes to "
                        f"{dt.name}"))
    elif hasattr(node, "left_keys") and hasattr(node, "right_keys") \
            and len(node.children) >= 2:
        ls, rs = node.children[0].schema, node.children[1].schema
        for i, (lk, rk) in enumerate(zip(node.left_keys,
                                         node.right_keys)):
            _check_bound_tree(lk, ls, f"left join key {i + 1}", node, out)
            _check_bound_tree(rk, rs, f"right join key {i + 1}", node,
                              out)
            ld, rd = _expr_dtype(lk), _expr_dtype(rk)
            if ld is not None and rd is not None and ld != rd:
                out.append(Diagnostic(
                    "DT006", "error", _loc(node),
                    f"join key {i + 1} dtypes differ: {ld.name} vs "
                    f"{rd.name} — hash partitioning would disagree "
                    "between sides",
                    hint="cast both sides to their common type before "
                         "the join"))
        cond = getattr(node, "condition", None)
        if cond is not None:
            _check_predicate(cond, None, "join condition", node, out)
    elif isinstance(node, (TpuShuffleExchangeExec,
                           TpuCoalescePartitionsExec,
                           XB.TpuCoalesceBatchesExec,
                           TpuGlobalLimitExec, TpuLocalLimitExec,
                           TpuCollectLimitExec)):
        if child_schema is not None \
                and not _schemas_equal(node.schema, child_schema):
            out.append(Diagnostic(
                "DT007", "error", _loc(node),
                f"{type(node).__name__} is schema-preserving but its "
                "declared schema disagrees with its child's"))


def check_exec_tree(root) -> list[Diagnostic]:
    """Dtype-flow diagnostics for one lowered physical plan."""
    out: list[Diagnostic] = []
    seen: set[int] = set()

    def walk(node) -> None:
        if id(node) in seen:  # plans are DAGs (reused subtrees)
            return
        seen.add(id(node))
        try:
            _check_node(node, out)
        except Exception as exc:  # never let analysis kill the caller
            # warning, not info: an analyzer crash silently disables
            # DT001-DT007 for this node, and --strict must notice that
            # coverage shrink (same rationale as PL000 in runner.py)
            out.append(Diagnostic(
                "DT000", "warning", _loc(node),
                f"dtype-flow analysis skipped: {type(exc).__name__}: "
                f"{exc}"))
        for c in node.children:
            walk(c)

    walk(root)
    return out
