"""Plan linter: walk a lowered physical plan (no execution) and flag
structural anti-patterns.

The reference's GpuTransitionOverrides pass polices the same shapes on
the GPU side — device/host transition placement, redundant exchanges
and sorts.  Here the patterns are advisory diagnostics feeding the CLI
and explain() output instead of plan mutations.

Rules
-----
- PL001 (warning): CPU-fallback island — a CpuFallbackExec sandwiched
  between TPU execs; every batch bounces device->host->device
- PL002 (info): shuffle exchange whose child streams raw (un-coalesced)
  batches — many small map blocks inflate shuffle bookkeeping
- PL003 (warning): nondeterministic (partition-aware) expression above
  an exchange — a retried/recomputed reduce partition would observe
  different values than the original attempt
- PL004 (warning): redundant sort-under-sort — an inner sort whose
  ordering is destroyed by an outer sort reachable through
  order-agnostic narrow execs
- PL005 (error): a runtime join filter attached to an INELIGIBLE join
  type — outer/anti joins preserve non-matching rows, so pruning the
  probe side by build-key reachability would silently drop output rows
  (the planner pass only ever creates inner/left_semi filters; this
  rule is the backstop for hand-built plans)
"""

from __future__ import annotations

from spark_rapids_tpu.lint.diagnostic import Diagnostic


def _loc(node) -> str:
    return f"plan::{type(node).__name__}"


def _node_exprs(node):
    """Expression trees an exec evaluates per batch (for PL003)."""
    from spark_rapids_tpu.execs.base import FusableExec

    if isinstance(node, FusableExec):
        return node.fusion_exprs()
    keys = getattr(node, "keys", None)
    if keys:
        return tuple(k.expr for k in keys if hasattr(k, "expr"))
    return ()


def check_plan(root) -> list[Diagnostic]:
    from spark_rapids_tpu.execs.basic import TpuCoalesceBatchesExec
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.execs.sort import TpuSortExec
    from spark_rapids_tpu.exprs.nondeterministic import (
        tree_is_partition_aware,
    )
    from spark_rapids_tpu.plan.planner import CpuFallbackExec

    out: list[Diagnostic] = []
    has_exchange: dict[int, bool] = {}

    def exchange_below(node) -> bool:
        k = id(node)
        if k not in has_exchange:
            has_exchange[k] = isinstance(node, TpuShuffleExchangeExec) \
                or any(exchange_below(c) for c in node.children)
        return has_exchange[k]

    #: narrow per-batch execs that neither produce nor rely on an
    #: ordering — an outer sort looking through these at an inner sort
    #: proves the inner sort's work is discarded
    from spark_rapids_tpu.execs.basic import TpuFilterExec, TpuProjectExec

    ORDER_AGNOSTIC = (TpuProjectExec, TpuFilterExec,
                      TpuCoalesceBatchesExec)

    def inner_sort_through_narrow(node):
        n = node.children[0] if node.children else None
        while isinstance(n, ORDER_AGNOSTIC):
            n = n.children[0]
        return n if isinstance(n, TpuSortExec) else None

    seen: set[int] = set()

    def walk(node, parent) -> None:
        if id(node) in seen:
            return
        seen.add(id(node))

        if isinstance(node, CpuFallbackExec):
            if parent is not None \
                    and not isinstance(parent, CpuFallbackExec) \
                    and node.children \
                    and any(not isinstance(c, CpuFallbackExec)
                            for c in node.children):
                out.append(Diagnostic(
                    "PL001", "warning", _loc(node),
                    "CPU-fallback island between TPU execs "
                    f"({node.plan.name} falls back): every batch "
                    "bounces device->host->device",
                    hint="add TPU support for the falling-back "
                         "operator, or check explain() for the "
                         "will-not-work reason"))
        elif isinstance(node, TpuShuffleExchangeExec):
            child = node.children[0]
            if not isinstance(child, (TpuCoalesceBatchesExec,
                                      TpuShuffleExchangeExec)):
                out.append(Diagnostic(
                    "PL002", "info", _loc(node),
                    "shuffle exchange consumes raw "
                    f"{type(child).__name__} batches without a "
                    "coalesce: many small map blocks inflate shuffle "
                    "bookkeeping",
                    hint="insert TpuCoalesceBatchesExec below the "
                         "exchange when map batches are small"))
        elif isinstance(node, TpuSortExec):
            inner = inner_sort_through_narrow(node)
            if inner is not None and inner.scope != "partition":
                out.append(Diagnostic(
                    "PL004", "warning", _loc(node),
                    "redundant sort-under-sort: the inner "
                    f"{inner.node_desc()} ordering is destroyed by "
                    "this sort",
                    hint="drop the inner sort, or order once"))

        from spark_rapids_tpu.execs.join import TpuRuntimeFilterBuildExec
        from spark_rapids_tpu.plan.runtime_filter import (
            ELIGIBLE_JOIN_TYPES,
        )

        bad_rfs = []
        if isinstance(node, TpuRuntimeFilterBuildExec):
            bad_rfs = [rf for _k, rf in node.entries
                       if rf.join_type not in ELIGIBLE_JOIN_TYPES]
        for _name, rf in getattr(node, "runtime_filters", ()):
            if rf.join_type not in ELIGIBLE_JOIN_TYPES:
                bad_rfs.append(rf)
        for rf in bad_rfs:
            out.append(Diagnostic(
                "PL005", "error", _loc(node),
                f"runtime filter {rf.describe()} derives from a "
                f"{rf.join_type!r} join: outer/anti joins preserve "
                "non-matching rows, so build-key pruning would drop "
                "output rows",
                hint="runtime filters are only sound for "
                     f"{'/'.join(ELIGIBLE_JOIN_TYPES)} joins; remove "
                     "the filter or change the join type"))

        for e in _node_exprs(node):
            try:
                aware = tree_is_partition_aware(e)
            except Exception:
                aware = False
            if aware and any(exchange_below(c) for c in node.children):
                out.append(Diagnostic(
                    "PL003", "warning", _loc(node),
                    "nondeterministic expression "
                    f"{getattr(e, 'name', type(e).__name__)!r} above "
                    "an exchange: a recomputed reduce partition "
                    "observes different values than the original "
                    "attempt",
                    hint="evaluate nondeterministic columns below the "
                         "exchange and ship them as data"))
                break

        for c in node.children:
            walk(c, node)

    walk(root, None)
    return out
