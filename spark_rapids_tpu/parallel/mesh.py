"""Device mesh construction.

The SQL engine's parallelism is data-parallel over partitions (the
reference's model: one Spark task per partition, §2.9 of SURVEY.md), so
the canonical mesh is 1-D over the `data` axis.  Multi-host meshes come
from jax.distributed the usual way; everything downstream only sees axis
names.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"


#: process-active mesh for collective shuffle lowering (the executor's
#: "device topology" state; ref: GpuShuffleEnv.scala:26 detecting the
#: transport-backed shuffle manager)
_ACTIVE_MESH: Optional[Mesh] = None


def set_active_mesh(mesh: Optional[Mesh]) -> None:
    global _ACTIVE_MESH
    _ACTIVE_MESH = mesh


def active_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH


def mesh_key(mesh: Mesh) -> tuple:
    """Structural identity of a mesh for compile-cache keys: device ids,
    axis names and axis sizes.  Two meshes over DIFFERENT device sets
    must never share a cached partitioned executable (the sharding's
    repr alone does not carry device identity), so every SPMD stage
    program folds this into its cached_jit key."""
    return (tuple(int(d.id) for d in mesh.devices.flat),
            tuple(mesh.axis_names),
            tuple(int(mesh.shape[a]) for a in mesh.axis_names))


def make_mesh(n_devices: Optional[int] = None,
              axes: Sequence[str] = (DATA_AXIS,),
              shape: Optional[Sequence[int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)}; for CPU tests "
                "set XLA_FLAGS=--xla_force_host_platform_device_count=N")
        devs = devs[:n_devices]
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axes) - 1)
    arr = np.array(devs).reshape(tuple(shape))
    return Mesh(arr, tuple(axes))
