"""Multi-chip parallelism: device meshes and collective exchanges.

TPU-native replacement for the reference's shuffle transport layer
(ref: shuffle-plugin/.../ucx/UCX.scala point-to-point RDMA): partitioned
exchanges become XLA `all_to_all` collectives over a `jax.sharding.Mesh`,
riding ICI within a pod slice (DCN across slices) with no explicit
endpoint/bounce-buffer management — the compiler owns the transport.
"""

from spark_rapids_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    mesh_key,
)
from spark_rapids_tpu.parallel.exchange import (  # noqa: F401
    make_hash_exchange_step,
    stack_batches,
    unstack_batch,
)
from spark_rapids_tpu.parallel.pipeline import (  # noqa: F401
    device_read,
    device_read_int,
    device_read_many,
    pipelined,
    prefetch,
    stage_snapshot,
)
