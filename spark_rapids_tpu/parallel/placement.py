"""Per-shard stage-input placement: the device-born data contract of
pod-scale serving (docs/pod_serving.md).

The reference system's shuffle story is LOCALITY: RapidsShuffleManager
moves blocks device-to-device over UCX so a child task's inputs are
already resident where they are consumed (PAPER.md 2.10), and the TPU
mapping of that story is ICI collectives plus per-shard placement
(PAPER.md 5.8).  Before this module the SPMD tier broke that contract
at every stage boundary: ``spmd._assemble`` called a raw
``jax.device_put`` per shard piece, so even a shard that a previous
stage had just produced ON its mesh device round-tripped through the
default device on re-assembly.

This module is the single choke point for moving a stage-input leaf
onto its mesh device (tpulint SRC016 forbids raw ``jax.device_put`` of
stage inputs anywhere else in execs// parallel/):

- :func:`place_piece` classifies and performs the move — a host-born
  source (numpy / python) counts ``host_uploads``; a jax Array already
  resident on the target device counts ``device_born`` and skips the
  copy when it is exactly placed; anything else is a
  ``d2d_transfers`` device-to-device move;
- :func:`adopt_batch` is the PRODUCER-side half: stage outputs adopt
  their shard's device as they are shrunk (spmd.shrink_rounds /
  unstack_*), so the next stage's assembly finds every piece
  device-born;
- the counters surface as ``placement.*`` event-log counters and the
  ``placement_host_uploads`` bench field — steady state under mesh
  serving is ZERO host uploads (the smoke gate
  tools/bench_smoke.run_mesh_serving_smoke asserts it).

Control-plane leaves (the tiny int32 row-count arrays assembled from
host ``concrete_num_rows`` values) are tallied separately as
``control_uploads``: they are genuinely host-born by design and their
bytes are O(rounds), not O(rows) — counting them as data uploads would
hide a real data-plane regression behind a constant.
"""

from __future__ import annotations

import threading

import jax

_STATS = {"host_uploads": 0, "device_born": 0, "d2d_transfers": 0,
          "control_uploads": 0, "adoptions": 0}
_LOCK = threading.Lock()


def _bump(key: str, n: int = 1) -> None:
    with _LOCK:
        _STATS[key] += n


def place_piece(x, device, control: bool = False):
    """Move one per-shard stage-input piece onto ``device``, counting
    the move's class.  Returns a single-device array suitable for
    ``jax.make_array_from_single_device_arrays``."""
    if not isinstance(x, jax.Array):
        _bump("control_uploads" if control else "host_uploads")
        return jax.device_put(x, device)
    try:
        devs = x.devices()
    except Exception:
        devs = None
    if devs is not None and device in devs:
        _bump("device_born")
        if len(devs) == 1:
            return x  # already exactly placed: zero-copy adoption
        return jax.device_put(x, device)
    _bump("d2d_transfers")
    return jax.device_put(x, device)


def adopt_batch(batch, device):
    """Producer-side adoption: commit every column leaf of a per-shard
    batch onto ITS mesh device, so the consuming stage's assembly finds
    the pieces device-born instead of paying a transfer per leaf.
    Leaves already resident on ``device`` are untouched (adoption is
    idempotent and free in steady state).  Columns move as pytrees, so
    every column kind (string dictionaries, list/struct/map children)
    adopts uniformly; ``num_rows`` is deliberately left alone — host
    ints must stay host ints."""
    import dataclasses

    def move(leaf):
        if isinstance(leaf, jax.Array):
            try:
                if leaf.devices() == {device}:
                    return leaf
            except Exception:
                pass
            _bump("adoptions")
            return jax.device_put(leaf, device)
        return leaf  # host scalars/aux stay put

    cols = [jax.tree_util.tree_map(move, c) for c in batch.columns]
    return dataclasses.replace(batch, columns=cols)


def stats() -> dict[str, int]:
    """Process-cumulative placement counters (the ``placement.*``
    event-log surface)."""
    with _LOCK:
        return dict(_STATS)


def reset_stats() -> None:
    """Test/bench isolation (the reset_stage_counters discipline)."""
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0
