"""Software-pipelining layer: bounded background stages + deferred
device->host readbacks.

The engine's latency profile is dominated by two serialization points
(BENCH_r05: Q6 host decode 1.196s vs 4ms upload; Q3 at 0.248x CPU):

1. host-side stage work (Parquet decode, table accumulation, the final
   Arrow fetch) running inline with device dispatch, where the
   reference overlaps them on a reader thread pool (ref:
   GpuParquetScan.scala:882-895 MultiFileCloudParquetPartitionReader);
2. blocking per-batch device->host syncs (`int(jax.device_get(total))`
   in the join stream loop, per-partial sizing syncs in the aggregate,
   split counts in the exchange) that stop the stream loop cold — JAX
   dispatch is asynchronous, so the COMPUTE for batch k+1 could already
   be in flight while batch k's scalar is fetched; only the readback
   ordering serializes it.

Two primitives fix both, shared by every exec:

- :func:`prefetch` — run a generator on a background thread behind a
  bounded queue (a pipeline *stage*).  Condition-variable handshake:
  no poll loops, clean cancellation (closing the consumer closes the
  producer's generator on the producer thread and joins it),
  exceptions propagate in stream order, and the caller's thread-local
  conf snapshot is installed on the producer thread (conf is
  thread-local; a bare thread would silently read defaults).
- :func:`pipelined` + :func:`device_read` — a software-pipelined
  stream loop: ``dispatch(item)`` launches batch k+1's device work
  BEFORE ``retire`` performs batch k's one blocking readback, so the
  readback wait overlaps real compute.  ``device_read*`` is the single
  blessed blocking-sync helper (the tpulint SRC005 rule flags raw
  ``jax.device_get`` in exec bodies) and is traceable in tests via
  :func:`trace_events`.
- :func:`device_read_async` + :class:`ReadbackFuture` — the
  future-style sibling for SPECULATIVE sizing (parallel/speculation.py,
  docs/speculation.md): the exec dispatches work at a predicted
  capacity and the true count is harvested off-thread; ``result()``
  one batch later is free in steady state, so even the deferred
  readback leaves the critical path.

Per-stage occupancy and wait counters feed bench.py's
``pipeline_occupancy`` metric and the docs/pipeline.md tuning guide.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

from spark_rapids_tpu import trace as _tr
from spark_rapids_tpu.config import get_conf, register, set_conf
from spark_rapids_tpu.robustness.lock_tracker import tracked_lock

PIPELINE_ENABLED = register(
    "spark.rapids.tpu.sql.pipeline.enabled", True,
    "Enable the software-pipelined executor: scan decode/upload run as "
    "bounded background stages and per-batch device->host readbacks "
    "(join probe counts, aggregate partial sizing, exchange split "
    "counts, the final result fetch) are deferred one batch behind "
    "dispatch so they overlap device compute (the reader-thread-pool + "
    "JoinGatherer overlap of the reference, GpuParquetScan.scala:882).")

PIPELINE_DEPTH = register(
    "spark.rapids.tpu.sql.pipeline.depth", 2,
    "Bounded-queue depth of each pipeline stage, and (depth - 1) the "
    "lookahead window for deferred readbacks.  Higher values smooth "
    "jittery stages at the cost of one extra in-flight batch of host "
    "(stage queues) or device (readback window) memory per step.",
    check=lambda v: v >= 1)


def stage_depth(conf=None) -> int:
    """Queue depth for pipeline stages; 0 = pipelining disabled."""
    conf = conf or get_conf()
    if not conf.get(PIPELINE_ENABLED):
        return 0
    return int(conf.get(PIPELINE_DEPTH))


def readback_lookahead(conf=None) -> int:
    """How many batches a stream loop dispatches ahead of its blocking
    readback (0 = retire immediately, the unpipelined order)."""
    d = stage_depth(conf)
    return max(0, d - 1) if d else 0


# ------------------------------------------------------------------ #
# Stage metrics
# ------------------------------------------------------------------ #


class StageMetrics:
    """Counters for one named stage, accumulated across queries: item
    count, queue-occupancy samples (taken at each consumer pop), and
    the time each side spent blocked on the other."""

    __slots__ = ("name", "depth", "items", "occupancy_sum", "samples",
                 "producer_wait_ns", "consumer_wait_ns", "readbacks",
                 "async_readbacks", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.depth = 0              # guard: _lock
        self.items = 0              # guard: _lock
        self.occupancy_sum = 0      # guard: _lock
        self.samples = 0            # guard: _lock
        self.producer_wait_ns = 0   # guard: _lock
        self.consumer_wait_ns = 0   # guard: _lock
        self.readbacks = 0          # guard: _lock
        self.async_readbacks = 0    # guard: _lock
        self._lock = threading.Lock()

    def snapshot(self) -> dict:
        with self._lock:
            occ = (self.occupancy_sum / self.samples) if self.samples \
                else 0.0
            return {
                "depth": self.depth,
                "items": self.items,
                "avg_occupancy": round(occ, 3),
                "occupancy_fraction": round(occ / self.depth, 3)
                if self.depth else 0.0,
                "producer_wait_s": round(self.producer_wait_ns / 1e9, 4),
                "consumer_wait_s": round(self.consumer_wait_ns / 1e9, 4),
                "readbacks": self.readbacks,
                "async_readbacks": self.async_readbacks,
            }


_STAGES: dict[str, StageMetrics] = {}
_STAGES_LOCK = tracked_lock("pipeline.stages")


def _stage_metrics(name: str) -> StageMetrics:
    with _STAGES_LOCK:
        m = _STAGES.get(name)
        if m is None:
            m = _STAGES[name] = StageMetrics(name)
        return m


def stage_snapshot(prefix: Optional[str] = None) -> dict[str, dict]:
    """Point-in-time counters for every stage seen so far (bench.py's
    pipeline_occupancy source).  `prefix` filters to one stage family —
    e.g. ``stage_snapshot("serve.stream")`` isolates the serving tier's
    streaming-fetch backpressure counters from the scan stages."""
    with _STAGES_LOCK:
        stages = list(_STAGES.values())
    return {m.name: m.snapshot() for m in stages
            if prefix is None or m.name.startswith(prefix)}


def reset_stage_counters() -> None:
    """Clear every stage's counters — bench.py calls this between
    benchmark queries so pipeline_occupancy reports PER QUERY instead
    of accumulating across configs."""
    with _STAGES_LOCK:
        _STAGES.clear()


def live_stage_threads() -> int:
    """Gauge: pipeline stage PRODUCER threads alive right now (the
    ``tpu-pipe-<stage>`` family; the persistent readback harvester
    pool is excluded).  Zero between queries — a nonzero reading after
    a query unwound is a leaked stage, the cancellation tests' and
    HC013's leak surface."""
    return sum(1 for t in threading.enumerate()
               if t.name.startswith("tpu-pipe-")
               and not t.name.startswith("tpu-pipe-harvest"))


# ------------------------------------------------------------------ #
# Readback tracing (test instrumentation)
# ------------------------------------------------------------------ #

_TRACE: Optional[list] = None
_TRACE_LOCK = threading.Lock()


@contextmanager
def trace_events():
    """Capture ("dispatch"|"readback", tag) events from pipelined() and
    device_read*() — the acceptance-test hook verifying that batch
    k+1's dispatch precedes batch k's readback."""
    global _TRACE
    events: list[tuple[str, Optional[str]]] = []
    with _TRACE_LOCK:
        prev, _TRACE = _TRACE, events
    try:
        yield events
    finally:
        with _TRACE_LOCK:
            _TRACE = prev


def _trace(kind: str, tag: Optional[str]) -> None:
    t = _TRACE
    if t is not None:
        with _TRACE_LOCK:
            if _TRACE is t:
                t.append((kind, tag))


# ------------------------------------------------------------------ #
# Deferred readback helpers (the SRC005-blessed sync points)
# ------------------------------------------------------------------ #


def device_read(x, tag: Optional[str] = None):
    """THE blocking device->host readback.  Host scalars pass through
    free.  Stream loops must not call this inline per batch — route the
    loop through :func:`pipelined` so the next batch's dispatch is
    already in flight when this blocks (tpulint SRC005 flags raw
    ``jax.device_get`` in exec bodies for exactly that reason)."""
    if isinstance(x, (int, float, bool)):
        return x
    import jax

    _trace("readback", tag)
    if tag is not None:
        m = _stage_metrics(tag)
        with m._lock:
            m.readbacks += 1
    if _tr.TRACER.enabled:
        with _tr.span("pipe.readback", tag=tag or ""):
            return jax.device_get(x)
    return jax.device_get(x)


def device_read_int(x, tag: Optional[str] = None) -> int:
    v = device_read(x, tag)
    return v if isinstance(v, int) else int(v)


def device_read_many(xs: Sequence, tag: Optional[str] = None) -> list:
    """Fetch MANY device scalars in ONE transfer round (a per-item
    device_get pays a full link round trip each on tunneled
    backends)."""
    xs = list(xs)
    host = [x for x in xs if isinstance(x, (int, float, bool))]
    if len(host) == len(xs):
        return xs
    import jax

    _trace("readback", tag)
    if tag is not None:
        m = _stage_metrics(tag)
        with m._lock:
            m.readbacks += 1
    if _tr.TRACER.enabled:
        with _tr.span("pipe.readback", tag=tag or "", n=len(xs)):
            return list(jax.device_get(xs))
    return list(jax.device_get(xs))


#: how long ReadbackFuture.result() waits for the harvester before the
#: wait counts as a BLOCKING sizing sync: scheduling jitter on a local
#: backend — including GC pauses and harvester-thread preemption under
#: a loaded process, which full-suite runs showed can exceed 5ms — is
#: under this, while a genuine link round trip on the tunneled backend
#: (~100ms median) is still 4x over it — so the counter measures
#: critical-path stalls, not thread-scheduling noise
_HARVEST_GRACE_S = 0.025

_HARVESTER = None
_HARVESTER_LOCK = threading.Lock()


def _harvester():
    """ONE process-wide harvest pool (the readbacks it runs serialize on
    the device link anyway; per-call threads would leak)."""
    global _HARVESTER
    with _HARVESTER_LOCK:
        if _HARVESTER is None:
            from concurrent.futures import ThreadPoolExecutor

            _HARVESTER = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="tpu-pipe-harvest")
        return _HARVESTER


class ReadbackFuture:
    """A device->host readback in flight on the harvester thread — the
    speculative-sizing counterpart of :func:`device_read`: the exec
    dispatches work sized by a PREDICTION and reconciles with the true
    count when this resolves, so the sizing sync leaves the critical
    path entirely.  ``result()`` only counts as a blocking readback
    (trace event + stage counter) when the harvest genuinely was not
    finished — the zero-blocking-sync acceptance tests key off that."""

    __slots__ = ("_fut", "_value", "_tag", "_resolved")

    def __init__(self, fut, tag: Optional[str], value=None):
        self._fut = fut
        self._tag = tag
        self._value = value
        self._resolved = fut is None

    def done(self) -> bool:
        return self._resolved or self._fut.done()

    def result(self):
        if self._resolved:
            return self._value
        fut = self._fut
        if fut.done():
            v = fut.result()
        else:
            import concurrent.futures as _cf

            try:
                v = fut.result(timeout=_HARVEST_GRACE_S)
            except _cf.TimeoutError:
                # a real critical-path stall: account it like an inline
                # device_read so host_sync_count stays honest
                _trace("readback", self._tag)
                if self._tag is not None:
                    m = _stage_metrics(self._tag)
                    with m._lock:
                        m.readbacks += 1
                if _tr.TRACER.enabled:
                    with _tr.span("pipe.readback", tag=self._tag or "",
                                  blocking=True):
                        v = fut.result()
                else:
                    v = fut.result()
        self._value = v
        self._resolved = True
        self._fut = None
        return v


def device_read_async(x, tag: Optional[str] = None) -> ReadbackFuture:
    """Submit a device->host readback to the harvester thread and return
    a :class:`ReadbackFuture` — the future-style sibling of
    :func:`device_read` for speculative stream loops: dispatch at the
    predicted size NOW, reconcile with ``result()`` (usually already
    harvested) one batch later.  Host scalars resolve immediately."""
    if isinstance(x, (int, float, bool)):
        return ReadbackFuture(None, tag, value=x)
    import jax

    _trace("readback_async", tag)
    if tag is not None:
        m = _stage_metrics(tag)
        with m._lock:
            m.async_readbacks += 1
    return ReadbackFuture(_harvester().submit(jax.device_get, x), tag)


def pipelined(items: Iterable, dispatch: Callable[[Any], Any],
              retire: Callable[[Any], Optional[Iterable]],
              depth: Optional[int] = None,
              tag: Optional[str] = None) -> Iterator:
    """Software-pipeline a stream loop: ``dispatch(item)`` launches
    (async) device work and returns its in-flight state; ``retire``
    performs the blocking readback + output for the OLDEST state.  With
    depth >= 1, item k+1 is dispatched before item k retires, so JAX's
    async dispatch overlaps k+1's compute with k's readback wait.
    retire may return an iterable of outputs (yielded in stream order)
    or None.  depth defaults to the conf lookahead; 0 degenerates to
    the serial dispatch-then-retire order."""
    if depth is None:
        depth = readback_lookahead()
    depth = max(0, int(depth))
    pending: deque = deque()
    for item in items:
        pending.append(dispatch(item))
        _trace("dispatch", tag)
        while len(pending) > depth:
            out = retire(pending.popleft())
            if out is not None:
                yield from out
    while pending:
        out = retire(pending.popleft())
        if out is not None:
            yield from out


def _stage_checkpoint(stage: str) -> None:
    """The ``pipeline.stage`` fault seam, hit once per produced item ON
    the producer thread, with in-place bounded recovery: an INJECTED
    stage fault releases pressure and re-checks instead of tearing the
    stage down — only a persistent one re-raises at the consumer in
    stream order (the prefetch contract).  Real failures from the
    producer's own work (`gen`) keep that contract untouched: they
    re-raise at the consumer, whose recovery ladder owns them (the
    producer cannot re-run a generator it does not control).
    Disarmed, this is one global read per item."""
    from spark_rapids_tpu.robustness import faults as _faults

    attempts = 3
    caught = []
    for attempt in range(attempts):
        try:
            _faults.fault_point("pipeline.stage", stage=stage)
        except BaseException as e:  # noqa: BLE001 - classified below
            from spark_rapids_tpu.execs.retry import (
                is_retryable,
                release_pressure,
            )

            if not is_retryable(e) or attempt == attempts - 1:
                raise
            caught.append(e)
            release_pressure()
            continue
        for e in caught:
            _faults.note_recovered(e, action="stage_retry")
        return


# ------------------------------------------------------------------ #
# Bounded background stage
# ------------------------------------------------------------------ #


class _Chan:
    """Bounded channel with a condition-variable handshake (no poll
    loops anywhere): producer blocks in put() while full, consumer
    blocks in pop() while empty, and abort() wakes both sides
    immediately."""

    __slots__ = ("depth", "buf", "lock", "not_full", "not_empty",
                 "done", "aborted", "error")

    def __init__(self, depth: int):
        self.depth = depth
        self.buf: deque = deque()   # guard: lock
        self.lock = threading.Lock()
        # both conditions share the ONE channel lock (an alias group:
        # holding either holds `lock`; they differ only in who waits)
        self.not_full = threading.Condition(self.lock)
        self.not_empty = threading.Condition(self.lock)
        self.done = False           # guard: lock
        self.aborted = False        # guard: lock
        # `error` is deliberately NOT guarded: written under the lock
        # in finish(), read by the consumer only after pop() returned
        # (None, False) — the lock release/acquire pair orders the two
        self.error: Optional[BaseException] = None

    # producer side ---------------------------------------------------- #

    def put(self, item, m: StageMetrics) -> bool:
        """False once the consumer aborted (producer should stop).
        The full-queue wait is bounded and cancel-aware (SRC012): a
        cancelled query's producer raises out of the wait instead of
        blocking until a consumer that already unwound drains it."""
        from spark_rapids_tpu.serving import cancel as _cancel

        with self.not_full:
            if len(self.buf) >= self.depth and not self.aborted:
                t0 = time.perf_counter_ns()
                tok = _cancel.current_token()
                while len(self.buf) >= self.depth and not self.aborted:
                    self.not_full.wait(_cancel.poll_timeout(tok))
                    if tok is not None:
                        tok.check()
                dt = time.perf_counter_ns() - t0
                with m._lock:
                    m.producer_wait_ns += dt
                if _tr.TRACER.enabled:  # reuse the wait already timed
                    _tr.record_complete(f"pipe.{m.name}.wait_full",
                                        t0, dt, stage=m.name)
            if self.aborted:
                return False
            self.buf.append(item)
            if _tr.TRACER.enabled:
                _tr.event(f"pipe.{m.name}.enqueue", stage=m.name,
                          qlen=len(self.buf))
            self.not_empty.notify()
            return True

    def finish(self, error: Optional[BaseException]) -> None:
        with self.not_empty:
            self.error = self.error or error
            self.done = True
            self.not_empty.notify_all()

    # consumer side ---------------------------------------------------- #

    def pop(self, m: StageMetrics):
        """(item, True) or (None, False) when the stream ended."""
        with self.not_empty:
            # occupancy sampled BEFORE waiting, so an empty queue (a
            # starved stage) counts as 0 — sampling after the wait
            # would floor the metric at 1/depth and a fully serial
            # pipeline would read as half-full
            with m._lock:
                m.occupancy_sum += len(self.buf)
                m.samples += 1
            if not self.buf and not self.done:
                from spark_rapids_tpu.serving import cancel as _cancel

                t0 = time.perf_counter_ns()
                tok = _cancel.current_token()
                while not self.buf and not self.done:
                    # bounded, cancel-aware wait (SRC012): a cancelled
                    # consumer raises here; the enclosing prefetch's
                    # finally then aborts the stage and joins the
                    # producer, so nothing leaks
                    self.not_empty.wait(_cancel.poll_timeout(tok))
                    if tok is not None:
                        tok.check()
                dt = time.perf_counter_ns() - t0
                with m._lock:
                    m.consumer_wait_ns += dt
                if _tr.TRACER.enabled:
                    _tr.record_complete(f"pipe.{m.name}.wait_empty",
                                        t0, dt, stage=m.name)
            if self.buf:
                with m._lock:
                    m.items += 1
                item = self.buf.popleft()
                if _tr.TRACER.enabled:
                    _tr.event(f"pipe.{m.name}.dequeue", stage=m.name,
                              qlen=len(self.buf))
                self.not_full.notify()
                return item, True
            return None, False

    def abort(self) -> None:
        with self.lock:
            self.aborted = True
            self.buf.clear()
            self.not_full.notify_all()
            self.not_empty.notify_all()


def prefetch(gen: Iterable, depth: Optional[int] = None,
             stage: str = "stage") -> Iterator:
    """Run `gen` on a background thread behind a bounded queue so the
    producer's work overlaps the consumer's (one pipeline *stage*).

    Contracts:
    - order preserved; items should stay HOST-side unless the caller
      owns the device-memory budget for `depth` in-flight batches;
    - a producer exception is re-raised at the consumer, after the
      items produced before it;
    - closing the consumer generator (or leaving it via break/raise)
      aborts the stage: the producer wakes from any blocked put, its
      generator is closed ON the producer thread (finally blocks run
      there), and the thread is joined — a sentinel handshake, not a
      poll-drain;
    - the caller's thread-local conf snapshot is installed on the
      producer thread.

    depth defaults to the conf stage depth; depth <= 0 yields from
    `gen` inline (pipelining disabled)."""
    if depth is None:
        depth = stage_depth()
    if depth <= 0:
        yield from gen
        return
    from spark_rapids_tpu.serving import cancel as _cancel

    m = _stage_metrics(stage)
    with m._lock:
        m.depth = max(m.depth, depth)
    chan = _Chan(depth)
    conf = get_conf()
    # trace correlation (query_id, ...) is thread-local and does NOT
    # follow the generator onto the stage thread: capture here, attach
    # there — the same hop the conf snapshot makes.  The query's
    # cancel token rides the same capture/attach channel, so the
    # producer observes cancellation mid-decode, not only at the
    # channel boundary
    tctx = _tr.current_context()
    ctok = _cancel.current_token()

    def produce() -> None:
        err: Optional[BaseException] = None
        set_conf(conf)
        with _tr.attach_context(tctx), _cancel.attach_token(ctok), \
                _tr.span(f"pipe.{stage}.run", stage=stage):
            try:
                try:
                    for item in gen:
                        _stage_checkpoint(stage)
                        _cancel.check_point()
                        if not chan.put(item, m):
                            return
                except BaseException as e:  # noqa: BLE001 — re-raised at consumer
                    err = e
            finally:
                close = getattr(gen, "close", None)
                if close is not None:
                    try:
                        close()
                    except BaseException as e:  # noqa: BLE001
                        err = err or e
                chan.finish(err)

    t = threading.Thread(target=produce, daemon=True,
                         name=f"tpu-pipe-{stage}")
    t.start()
    try:
        while True:
            item, ok = chan.pop(m)
            if not ok:
                break
            yield item
        if chan.error is not None:
            raise chan.error
    finally:
        chan.abort()
        t.join()
