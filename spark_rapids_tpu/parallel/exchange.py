"""Collective hash-partitioned exchange (the TPU shuffle fast path).

The reference implements shuffle as N x N point-to-point pulls over UCX
with device bounce buffers and a flatbuffer control plane
(ref: RapidsShuffleClient.scala:96, BufferSendState.scala:53,
shuffle-plugin/.../UCX.scala).  On TPU the idiomatic equivalent is a
single fused XLA program per exchange:

    partition ids (Spark-parity murmur3 pmod)
      -> stable sort rows by destination
      -> scatter into a (n_dest, capacity) send buffer
      -> lax.all_to_all over the mesh axis (ICI/DCN, compiler-scheduled)
      -> compact received rows

Rows travel with an explicit *occupancy* mask (a row can be occupied yet
carry NULL columns), so the received buffer compacts into the standard
prefix-compact ColumnarBatch invariant.  The whole step — including any
fused upstream project/filter and downstream partial aggregation — is one
jit-compiled SPMD program via shard_map; there is no host round-trip
between map and reduce sides.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 stable API
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import AnyColumn, Column, StringColumn
from spark_rapids_tpu.exprs.hashing import partition_ids
from spark_rapids_tpu.parallel.mesh import DATA_AXIS

#: older jax spells shard_map's replication-check flag `check_rep`
#: (the newer name is `check_vma`); probe once at import
_SM_CHECK_KW = ("check_vma" if "check_vma"
                in __import__("inspect").signature(shard_map).parameters
                else "check_rep")


def _shard_map(fn, mesh, in_specs, out_specs):
    """shard_map with the replication check off, spelled portably
    across jax versions — every collective step / SPMD stage program
    builds through this one wrapper."""
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **{_SM_CHECK_KW: False})


def _sharded_jit(mapped) -> Callable:
    """jit a shard_map program and route its dispatch through the
    process-wide collective gate (jit_cache.serialize_sharded): the
    step builders below are the only multi-device programs compiled
    outside cached_jit, and an unguarded concurrent launch can starve
    XLA's CPU collective thread pool mid-rendezvous
    (docs/pod_serving.md)."""
    from spark_rapids_tpu.execs.jit_cache import serialize_sharded

    return serialize_sharded(jax.jit(mapped))


def take_piece(arr: jax.Array, idx: tuple):
    """``arr[idx]`` for leading-dim integer indices, resolved against
    the array's addressable shards.  An eager ``__getitem__`` on a
    PARTITIONED array compiles and launches a cross-device gather —
    an unguarded multi-device program that can rendezvous against a
    concurrently launched one and starve XLA's CPU collective pool
    (the jit_cache._SHARDED_DISPATCH_LOCK deadlock, through the eager
    door).  A stage output's (round, shard) piece is wholly resident
    on its shard's device, so the local-shard slice below is both
    collective-free and copy-free; anything not covered by a local
    shard falls back to the plain (single-device) getitem."""
    try:
        shards = arr.addressable_shards
    except (AttributeError, RuntimeError):
        return arr[idx]
    for s in shards:
        sl = s.index
        loc = []
        for i, g in enumerate(idx):
            start = sl[i].start or 0
            stop = sl[i].stop if sl[i].stop is not None \
                else arr.shape[i]
            if not (start <= g < stop):
                break
            loc.append(g - start)
        else:
            return s.data[tuple(loc)]
    return arr[idx]


def _stack_parts(parts: list):
    """``jnp.stack`` for per-device leaves that may be COMMITTED to
    distinct devices (take_piece's local-shard slices are).  An eager
    jnp.stack of committed arrays on different devices is an
    incompatible-devices error, so the committed case assembles the
    stacked global array shard-by-shard with
    make_array_from_single_device_arrays — no cross-device op at all;
    duplicated-device pieces fall back to placement-routed moves onto
    the first piece's device."""
    try:
        return jnp.stack(parts)
    except ValueError:
        devsets = [getattr(p, "devices", lambda: None)() for p in parts]
        singles = all(ds is not None and len(ds) == 1
                      for ds in devsets)
        if singles:
            devs = [next(iter(ds)) for ds in devsets]
            if len(set(devs)) == len(devs):
                from jax.sharding import NamedSharding
                shape = (len(parts),) + parts[0].shape
                mesh = Mesh(np.asarray(devs), ("stack",))
                sh = NamedSharding(
                    mesh, P("stack", *([None] * parts[0].ndim)))
                return jax.make_array_from_single_device_arrays(
                    shape, sh, [p[None] for p in parts])
        from spark_rapids_tpu.parallel import placement as _placement

        target = next((next(iter(ds)) for ds in devsets if ds), None)
        if target is None:
            raise
        return jnp.stack([_placement.place_piece(p, target)
                          for p in parts])


def stack_batches(batches: Sequence[ColumnarBatch]) -> ColumnarBatch:
    """Stack per-device batches into one batch whose leaves carry a leading
    device axis (num_rows becomes an int32 vector)."""
    schema = batches[0].schema
    cols: list[AnyColumn] = []
    for ci in range(batches[0].num_cols):
        parts = [b.columns[ci] for b in batches]
        if isinstance(parts[0], StringColumn):
            cols.append(StringColumn(
                _stack_parts([p.chars for p in parts]),
                _stack_parts([p.lengths for p in parts]),
                _stack_parts([p.validity for p in parts])))
        else:
            cols.append(Column(
                _stack_parts([p.data for p in parts]),
                _stack_parts([p.validity for p in parts]),
                parts[0].dtype))
    n_rows = jnp.asarray([b.concrete_num_rows() for b in batches], jnp.int32)
    return ColumnarBatch(cols, n_rows, schema)


def unstack_batch(stacked: ColumnarBatch) -> list[ColumnarBatch]:
    n_dev = stacked.columns[0].data.shape[0] if isinstance(
        stacked.columns[0], Column) else stacked.columns[0].chars.shape[0]
    counts = np.asarray(jax.device_get(stacked.num_rows))
    out = []
    for d in range(n_dev):
        cols: list[AnyColumn] = []
        for c in stacked.columns:
            if isinstance(c, StringColumn):
                cols.append(StringColumn(take_piece(c.chars, (d,)),
                                         take_piece(c.lengths, (d,)),
                                         take_piece(c.validity, (d,))))
            else:
                cols.append(Column(take_piece(c.data, (d,)),
                                   take_piece(c.validity, (d,)),
                                   c.dtype))
        out.append(ColumnarBatch(cols, int(counts[d]),
                                 stacked.schema))
    return out


def _squeeze0(batch: ColumnarBatch) -> ColumnarBatch:
    cols: list[AnyColumn] = []
    for c in batch.columns:
        if isinstance(c, StringColumn):
            cols.append(StringColumn(c.chars[0], c.lengths[0], c.validity[0]))
        else:
            cols.append(Column(c.data[0], c.validity[0], c.dtype))
    return ColumnarBatch(cols, batch.num_rows[0], batch.schema)


def _unsqueeze0(batch: ColumnarBatch) -> ColumnarBatch:
    cols: list[AnyColumn] = []
    for c in batch.columns:
        if isinstance(c, StringColumn):
            cols.append(StringColumn(c.chars[None], c.lengths[None],
                                     c.validity[None]))
        else:
            cols.append(Column(c.data[None], c.validity[None], c.dtype))
    return ColumnarBatch(cols, batch.num_rows[None], batch.schema)


def route_shard(batch: ColumnarBatch, pid: jax.Array,
                n_dest: int, axis_name: str) -> ColumnarBatch:
    """Per-shard body: send each live row of this shard's batch to the
    destination in `pid` via all_to_all; returns the rows this shard
    owns afterwards (capacity = n_dest * input capacity,
    prefix-compact).  `pid` entries for dead rows are ignored."""
    cap = batch.capacity
    live = batch.row_mask()
    pid = jnp.where(live, pid, jnp.int32(n_dest))  # dead rows -> dropped

    order = jnp.argsort(pid, stable=True)
    spid = jnp.take(pid, order)
    # rank of each row within its destination group
    first_pos = jnp.searchsorted(spid, spid, side="left")
    rank = jnp.arange(cap, dtype=jnp.int32) - first_pos.astype(jnp.int32)
    slot = spid * cap + rank  # OOB for dead rows (spid == n_dest)

    def scatter(x, fill=0):
        out_shape = (n_dest * cap,) + x.shape[1:]
        return jnp.full(out_shape, fill, x.dtype).at[slot].set(
            jnp.take(x, order, axis=0), mode="drop")

    occ = jnp.zeros((n_dest * cap,), bool).at[slot].set(
        jnp.ones((cap,), bool), mode="drop")
    sent_cols: list[AnyColumn] = []
    for c in batch.columns:
        if isinstance(c, StringColumn):
            sent_cols.append(StringColumn(
                scatter(c.chars), scatter(c.lengths), scatter(c.validity)))
        else:
            sent_cols.append(Column(scatter(c.data), scatter(c.validity),
                                    c.dtype))

    a2a = partial(jax.lax.all_to_all, axis_name=axis_name, split_axis=0,
                  concat_axis=0, tiled=True)
    occ = a2a(occ)
    recv_cols: list[AnyColumn] = []
    for c in sent_cols:
        if isinstance(c, StringColumn):
            recv_cols.append(StringColumn(a2a(c.chars), a2a(c.lengths),
                                          a2a(c.validity)))
        else:
            recv_cols.append(Column(a2a(c.data), a2a(c.validity), c.dtype))

    # compact occupied rows to a prefix (stable: preserves sender order)
    corder = jnp.argsort(~occ, stable=True)
    n_out = jnp.sum(occ).astype(jnp.int32)
    out_live = jnp.arange(n_dest * cap, dtype=jnp.int32) < n_out
    out_cols: list[AnyColumn] = []
    for c in recv_cols:
        g = c.gather(corder)
        out_cols.append(g.with_validity(g.validity & out_live))
    return ColumnarBatch(out_cols, n_out, batch.schema)


def exchange_shard(batch: ColumnarBatch, key_ordinals: Sequence[int],
                   n_dest: int, axis_name: str) -> ColumnarBatch:
    """route_shard with Spark-parity murmur3-pmod hash routing."""
    key_cols = [batch.columns[o] for o in key_ordinals]
    pid = partition_ids(key_cols, batch.capacity, n_dest)
    return route_shard(batch, pid, n_dest, axis_name)


def make_hash_exchange_step(
    mesh: Mesh,
    key_ordinals: Sequence[int],
    axis_name: str = DATA_AXIS,
    pre: Optional[Callable[[ColumnarBatch], ColumnarBatch]] = None,
    post: Optional[Callable[[ColumnarBatch], ColumnarBatch]] = None,
) -> Callable[[ColumnarBatch], ColumnarBatch]:
    """Build the jitted SPMD exchange program.  `pre`/`post` are traceable
    per-shard batch transforms fused into the same program (map-side
    project/filter/partial-agg, reduce-side merge-agg) — the analog of the
    reference pipelining partitioning and aggregation around its shuffle,
    but in ONE compiled program."""
    n_dest = mesh.shape[axis_name]

    def shard_fn(stacked: ColumnarBatch) -> ColumnarBatch:
        b = _squeeze0(stacked)
        if pre is not None:
            b = pre(b)
        b = exchange_shard(b, key_ordinals, n_dest, axis_name)
        if post is not None:
            b = post(b)
        return _unsqueeze0(b)

    mapped = _shard_map(shard_fn, mesh, P(axis_name),
                       P(axis_name))
    return _sharded_jit(mapped)


def make_route_step(
    mesh: Mesh,
    pid_fn: Callable[..., jax.Array],
    axis_name: str = DATA_AXIS,
    n_extra: int = 0,
) -> Callable:
    """Generalized exchange: `pid_fn(batch, *extras) -> int32[capacity]`
    computes each row's destination shard (hash, range-bounds bisect,
    round-robin — any traceable rule).  `extras` are REPLICATED batch
    args (e.g. sampled range bounds) passed through to pid_fn, so one
    compiled program serves every bounds value."""
    n_dest = mesh.shape[axis_name]

    def shard_fn(stacked: ColumnarBatch, *extras):
        b = _squeeze0(stacked)
        pid = pid_fn(b, *extras)
        b = route_shard(b, pid, n_dest, axis_name)
        return _unsqueeze0(b)

    in_specs = (P(axis_name),) + (P(),) * n_extra
    mapped = _shard_map(shard_fn, mesh, in_specs,
                       P(axis_name))
    return _sharded_jit(mapped)


def make_local_step(
    mesh: Mesh,
    fn: Callable[[ColumnarBatch], ColumnarBatch],
    axis_name: str = DATA_AXIS,
) -> Callable:
    """Per-shard local transform (no collectives) over stacked shard
    batches — the reduce-side tail of a multi-round exchange (final
    merge, local sort) runs through this."""

    def shard_fn(stacked: ColumnarBatch) -> ColumnarBatch:
        return _unsqueeze0(fn(_squeeze0(stacked)))

    mapped = _shard_map(shard_fn, mesh, P(axis_name),
                       P(axis_name))
    return _sharded_jit(mapped)


def make_join_step(
    mesh: Mesh,
    shard_fn: Callable[[ColumnarBatch, ColumnarBatch],
                       tuple[ColumnarBatch, jax.Array]],
    axis_name: str = DATA_AXIS,
) -> Callable:
    """Two-input SPMD step for the collective shuffled join: shard_fn
    gets (stream_shard, build_shard) per device and returns the joined
    shard plus a scalar diagnostic (the true output row count, for the
    host-side capacity-overflow check)."""

    def wrapped(stream_stacked, build_stacked):
        out, total = shard_fn(_squeeze0(stream_stacked),
                              _squeeze0(build_stacked))
        return _unsqueeze0(out), total[None]

    mapped = _shard_map(wrapped, mesh,
                        (P(axis_name), P(axis_name)),
                        (P(axis_name), P(axis_name)))
    return _sharded_jit(mapped)
