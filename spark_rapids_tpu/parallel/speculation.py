"""Speculative output sizing: predict data-dependent output counts so
stream loops never block on a per-batch sizing readback.

BENCH_r05 traced the two worst numbers in the suite (Q3 join at 0.248x
CPU, Q1 at 0.566x) to the one remaining structural serialization: the
per-batch device->host SIZING sync (join pair count, aggregate partial
row count, exchange split counts) that the software pipeline can only
defer by a single batch — the expansion/shrink for batch k still waits
on batch k's count before it can dispatch.  The reference never pays
this shape-driven sync: JoinGatherer sizes output chunks from a target
(ref: JoinGatherer.scala:55), and the OOM-retry framework
(RmmRapidsRetryIterator.scala ``withRetry``, mirrored by
``execs/retry.py``) is the repo's blessed "guess, then recover" shape.

This module is that pattern for sizing:

- :class:`SizePredictor` — per-program-key EWMA of observed output
  counts (keyed by the same structural key ``jit_cache.cached_jit``
  uses), scaled by a safety factor and clamped to pow2 capacity
  buckets, with a conservative sync-on-first-batches warm-up;
- the exec dispatches its expansion/gather at the SPECULATED bucket
  immediately and harvests the true count asynchronously
  (``parallel.pipeline.device_read_async``);
- reconciliation is cheap by construction: ``ops/join.py``
  ``expand_pairs(state, out_cap, offset)`` emits statically-shaped
  chunks with a live mask, so an undershoot is not a rollback — the
  exec emits continuation chunks from ``offset`` — and an overshoot
  only costs masked dead rows (trimmed when chunks are
  spilled/coalesced).

Hit/overflow counters feed ``bench.py``'s
``q*_speculation_hit_rate`` fields and the per-exec
``specHits``/``specOverflows`` metrics shown by
``df.explain("analyze")``; ``speculation.hit``/``speculation.overflow``
instants land on the structured trace timeline.  Docs:
``docs/speculation.md``.
"""

from __future__ import annotations

import collections
import threading
from typing import Optional

from spark_rapids_tpu import trace as _tr
from spark_rapids_tpu.config import get_conf, register
from spark_rapids_tpu.parallel import pipeline as _P

SPECULATION_ENABLED = register(
    "spark.rapids.tpu.sql.speculation.enabled", True,
    "Enable speculative output sizing: joins/aggregates/exchanges "
    "dispatch their output expansion at a predicted pow2 capacity "
    "bucket (per-program-key EWMA of observed counts) and harvest the "
    "true count asynchronously, instead of blocking on a per-batch "
    "device->host sizing readback (the JoinGatherer guess-then-recover "
    "shape, ref: JoinGatherer.scala:55).  Undershoots emit "
    "continuation chunks; overshoots only cost masked dead rows.")

SPECULATION_SAFETY_FACTOR = register(
    "spark.rapids.tpu.sql.speculation.safetyFactor", 1.5,
    "Multiplier applied to the predicted output count before pow2 "
    "bucket clamping.  Larger values trade dead padded rows for fewer "
    "undershoot continuation chunks.",
    check=lambda v: v >= 1.0)

SPECULATION_WARMUP_BATCHES = register(
    "spark.rapids.tpu.sql.speculation.warmupBatches", 1,
    "Observed batches per program key before the predictor speculates; "
    "warm-up batches pay the conservative blocking sizing sync and "
    "seed the EWMA.",
    check=lambda v: v >= 1)

SPECULATION_TEST_FORCE_CAPACITY = register(
    "spark.rapids.tpu.sql.speculation.testForceCapacity", 0,
    "Test aid: when > 0, a warmed-up predictor returns exactly this "
    "capacity bucket instead of its EWMA-derived one (forces the "
    "under-/over-speculation paths deterministically).",
    internal=True)

SPECULATION_ADAPTIVE_MIN_HIT_RATE = register(
    "spark.rapids.tpu.sql.speculation.adaptive.minHitRate", 0.0,
    "Adaptive kill-switch: when > 0, a predictor TAG (join.probe, "
    "agg.size, ...) whose rolling hit rate over the last "
    "speculation.adaptive.window outcomes falls below this is "
    "auto-DISABLED for the rest of the process (or until "
    "reset_stats) — its execs revert to the conservative blocking "
    "sizing sync.  BISECT_q3_r07's conviction: a workload whose output "
    "counts the EWMA cannot track pays continuation chunks on every "
    "batch, and turning speculation off recovered 1.294x on q3.  The "
    "disable lands as a speculation.disabled event-log counter and a "
    "speculation.disabled trace instant; 0.0 = never disable.",
    check=lambda v: 0.0 <= v <= 1.0)

SPECULATION_ADAPTIVE_WINDOW = register(
    "spark.rapids.tpu.sql.speculation.adaptive.window", 16,
    "Rolling outcome-window length per predictor tag for the adaptive "
    "kill-switch: the hit rate is judged only once this many "
    "speculative dispatches (hits + overflows) have been observed, so "
    "one unlucky warm-up batch cannot convict a tag.",
    check=lambda v: v >= 2)

#: EWMA step: ~4 batches of memory — fast enough to track a selectivity
#: shift mid-stream, slow enough that one outlier batch does not thrash
#: the bucket choice
_EWMA_ALPHA = 0.4


def speculation_enabled(conf=None) -> bool:
    conf = conf or get_conf()
    return bool(conf.get(SPECULATION_ENABLED))


class SizePredictor:
    """EWMA of observed output counts for ONE program key.  Thread-safe:
    partition-wise joins and exchange map tasks observe concurrently."""

    __slots__ = ("key", "ewma", "observations", "_lock")

    def __init__(self, key):
        self.key = key
        self.ewma = 0.0
        self.observations = 0
        self._lock = threading.Lock()

    def observe(self, n: int) -> None:
        with self._lock:
            self.observations += 1
            if self.observations == 1:
                self.ewma = float(n)
            else:
                self.ewma += _EWMA_ALPHA * (float(n) - self.ewma)

    def predict(self, conf=None,
                cap_ceiling: Optional[int] = None) -> Optional[int]:
        """Speculated pow2 capacity bucket, or None during warm-up (the
        caller then pays the conservative blocking sizing sync)."""
        from spark_rapids_tpu.columnar.column import pad_capacity

        conf = conf or get_conf()
        with self._lock:
            obs, ewma = self.observations, self.ewma
        if obs < int(conf.get(SPECULATION_WARMUP_BATCHES)):
            return None
        forced = int(conf.get(SPECULATION_TEST_FORCE_CAPACITY))
        if forced > 0:
            cap = pad_capacity(forced)
        else:
            est = ewma * float(conf.get(SPECULATION_SAFETY_FACTOR))
            cap = pad_capacity(max(1, int(est)))
        if cap_ceiling is not None:
            cap = min(cap, cap_ceiling)
        return cap


#: LRU like jit_cache's MAX_ENTRIES: a long-lived process serving many
#: distinct ad-hoc query shapes must not pin one predictor per key
#: forever (the key space is the compile-cache key space)
_PREDICTORS: "collections.OrderedDict" = collections.OrderedDict()
MAX_PREDICTORS = 512
_PRED_LOCK = threading.Lock()


def predictor(key) -> SizePredictor:
    """Get-or-create the process-global predictor for a structural
    program key (the jit_cache key discipline: two execs whose sizing
    is determined by equal expression trees/specs share one)."""
    with _PRED_LOCK:
        p = _PREDICTORS.get(key)
        if p is None:
            p = _PREDICTORS[key] = SizePredictor(key)
            while len(_PREDICTORS) > MAX_PREDICTORS:
                _PREDICTORS.popitem(last=False)
        else:
            _PREDICTORS.move_to_end(key)
        return p


def reset_predictors() -> None:
    """Drop every predictor (test isolation)."""
    with _PRED_LOCK:
        _PREDICTORS.clear()


# ------------------------------------------------------------------ #
# Hit/overflow accounting (bench.py + explain("analyze") source)
# ------------------------------------------------------------------ #

_STATS: dict[str, dict] = {}
_STATS_LOCK = threading.Lock()

#: per-tag rolling outcome window (True = hit) for the adaptive
#: kill-switch, plus the set of convicted tags
_WINDOWS: dict[str, "collections.deque"] = {}
_DISABLED: set[str] = set()
_DISABLED_TOTAL = 0


def _stat(tag: str) -> dict:
    s = _STATS.get(tag)
    if s is None:
        s = _STATS[tag] = {"hits": 0, "overflows": 0, "synced": 0}
    return s


def _observe_outcome_locked(tag: str, hit: bool) -> bool:
    """Feed the tag's rolling window; returns True when this outcome
    just convicted the tag (caller emits the events OUTSIDE the
    lock).  Caller holds _STATS_LOCK."""
    global _DISABLED_TOTAL
    conf = get_conf()
    min_rate = float(conf.get(SPECULATION_ADAPTIVE_MIN_HIT_RATE))
    if min_rate <= 0.0 or tag in _DISABLED:
        return False
    window = int(conf.get(SPECULATION_ADAPTIVE_WINDOW))
    w = _WINDOWS.get(tag)
    if w is None or w.maxlen != window:
        w = _WINDOWS[tag] = collections.deque(w or (), maxlen=window)
    w.append(hit)
    if len(w) < window:
        return False
    if sum(w) / float(window) >= min_rate:
        return False
    _DISABLED.add(tag)
    _DISABLED_TOTAL += 1
    return True


def _note_disabled(tag: str, rate: float) -> None:
    _P._trace("spec_disabled", tag)
    if _tr.TRACER.enabled:
        _tr.event("speculation.disabled", tag=tag, hit_rate=rate)


def record_hit(tag: str, cap: int = 0, actual: int = 0) -> None:
    """The speculated capacity covered the true count: the batch ran
    with ZERO blocking sizing syncs."""
    with _STATS_LOCK:
        _stat(tag)["hits"] += 1
        tripped = _observe_outcome_locked(tag, True)
    _P._trace("spec_hit", tag)
    if _tr.TRACER.enabled:
        _tr.event("speculation.hit", tag=tag, cap=cap, actual=actual)
    if tripped:
        _note_disabled(tag, hit_rate((tag,)))


def record_overflow(tag: str, cap: int = 0, actual: int = 0) -> None:
    """Undershoot: the speculated chunk was emitted, and the exec
    continued with chunks from offset=cap (no rollback)."""
    with _STATS_LOCK:
        _stat(tag)["overflows"] += 1
        tripped = _observe_outcome_locked(tag, False)
    _P._trace("spec_overflow", tag)
    if _tr.TRACER.enabled:
        _tr.event("speculation.overflow", tag=tag, cap=cap,
                  actual=actual)
    if tripped:
        _note_disabled(tag, hit_rate((tag,)))


def record_sync(tag: str) -> None:
    """A conservative blocking sizing sync (warm-up batch)."""
    with _STATS_LOCK:
        _stat(tag)["synced"] += 1


def tag_enabled(tag: str) -> bool:
    """False once the adaptive kill-switch convicted this tag — the
    exec should skip predictor creation / speculation and pay the
    blocking sizing sync (which the kill-switch has just proven
    cheaper than the continuation-chunk churn)."""
    with _STATS_LOCK:
        return tag not in _DISABLED


def disabled_tags() -> list[str]:
    """Tags the adaptive kill-switch has disabled, sorted (bench.py's
    ``q*_speculation_disabled`` field)."""
    with _STATS_LOCK:
        return sorted(_DISABLED)


def disabled_total() -> int:
    """Cumulative count of kill-switch trips this process (the
    ``speculation.disabled`` event-log counter; monotonic across
    reset_stats like every other eventlog counter source is NOT —
    this one survives reset_stats precisely so per-query deltas in
    the event log attribute the trip to the query that caused it)."""
    with _STATS_LOCK:
        return _DISABLED_TOTAL


def stats() -> dict[str, dict]:
    """Per-tag {hits, overflows, synced} counters since the last
    reset."""
    with _STATS_LOCK:
        return {k: dict(v) for k, v in _STATS.items()}


def reset_stats() -> None:
    """bench.py resets between benchmark queries so hit rates report
    PER QUERY (the reset_stage_counters discipline).  Also re-arms the
    adaptive kill-switch (windows + convicted tags) so one query's
    conviction does not bleed into the next query's measurement; the
    cumulative ``disabled_total`` survives so event-log deltas stay
    monotonic."""
    with _STATS_LOCK:
        _STATS.clear()
        _WINDOWS.clear()
        _DISABLED.clear()


def hit_rate(tags=None) -> float:
    """Fraction of speculative dispatches whose capacity covered the
    true count, over `tags` (default: all)."""
    snap = stats()
    hits = ovf = 0
    for tag, s in snap.items():
        if tags is not None and tag not in tags:
            continue
        hits += s["hits"]
        ovf += s["overflows"]
    total = hits + ovf
    return round(hits / total, 3) if total else 0.0
