"""SPMD whole-stage execution: one pjit program per query stage.

The collective tier's original driver (execs/collective.py) ran a HOST
LOOP per exchange round: stack per-shard batches on the default device,
dispatch one shard_map step, unstack, host-sync every shard's row count,
shrink, fold.  Per round that is one program dispatch plus 2n host
round-trips — the dispatch-soup anti-pattern the DeviceLedger exists to
expose, and the opposite of how pjit/GSPMD programs are meant to run
(SNIPPETS [1][2]: partitioned compilation with `PartitionSpec` +
donation; [3]: mesh/`NamedSharding` helpers).

This module is the replacement: a query stage (exchange + its fused
agg/join/sort work) lowers to a SINGLE partitioned XLA program over the
active mesh with `NamedSharding` end-to-end —

- inputs arrive as GLOBAL sharded arrays: per-shard round batches are
  assembled with `jax.make_array_from_single_device_arrays` under
  ``NamedSharding(mesh, P(None, "data"))`` (leading axis = exchange
  rounds, second axis = mesh shard), so GSPMD never reshards at
  dispatch and nothing round-trips through one host-stacked array;
- the hash/range exchange is an IN-PROGRAM collective: the per-round
  ``all_to_all`` body of parallel/exchange.py runs inside a
  ``lax.scan`` over the rounds axis — R exchange rounds compile once
  and dispatch once, instead of R host dispatches;
- per-round host syncs are DEFERRED to stage exit: one
  ``stage_counts`` fetch of the output row-count array replaces the
  per-round per-shard `concrete_num_rows` + shrink choreography.

Programs compile through execs/jit_cache.cached_jit with the sharding
spec pair folded into the structural key (plus parallel.mesh.mesh_key,
so same-shaped meshes over different devices never share an
executable); donation composes — a stage's freshly assembled global
input is single-use and may be donated into the program.  The ledger
entry carries ``{"devices": n, "rounds": R}`` so partitioned programs
attribute per-device busy time and in-program collective rounds in
bench/analyze (docs/spmd.md).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import (
    ColumnarBatch,
    concat_batches_traced,
)
from spark_rapids_tpu.columnar.column import (
    AnyColumn,
    Column,
    MIN_CAPACITY,
    StringColumn,
    pad_capacity,
    pad_width,
)
from spark_rapids_tpu.parallel.exchange import (
    _shard_map,
    _squeeze0,
    _unsqueeze0,
    route_shard,
    take_piece,
)
from spark_rapids_tpu.parallel.mesh import DATA_AXIS, mesh_key


def rounds_sharding(mesh) -> NamedSharding:
    """Sharding of a round-stacked stage input: leaves are
    (rounds, n_shards, capacity, ...), sharded over the mesh axis."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def stage_sharding(mesh) -> NamedSharding:
    """Sharding of a per-shard stage output: leaves are
    (n_shards, capacity, ...)."""
    return NamedSharding(mesh, P(DATA_AXIS))


# ------------------------------------------------------------------ #
# Capacity unification (shared with the host-loop fallback path)
# ------------------------------------------------------------------ #


def repad_batch(batch: ColumnarBatch, cap: int,
                widths: dict[int, int]) -> ColumnarBatch:
    """Pad a batch to a common capacity/string-width profile so
    per-shard leaves stack into one array with leading device (and
    round) axes."""
    cols: list[AnyColumn] = []
    for ci, c in enumerate(batch.columns):
        if isinstance(c, StringColumn):
            w = widths[ci]
            chars = c.chars
            if c.width < w:
                chars = jnp.pad(chars, ((0, 0), (0, w - c.width)))
            if c.capacity < cap:
                pad = cap - c.capacity
                chars = jnp.pad(chars, ((0, pad), (0, 0)))
                cols.append(StringColumn(
                    chars,
                    jnp.pad(c.lengths, (0, pad)),
                    jnp.pad(c.validity, (0, pad))))
            else:
                cols.append(StringColumn(chars, c.lengths, c.validity))
        else:
            if c.capacity < cap:
                pad = cap - c.capacity
                cols.append(Column(jnp.pad(c.data, (0, pad)),
                                   jnp.pad(c.validity, (0, pad)),
                                   c.dtype))
            else:
                cols.append(c)
    return ColumnarBatch(cols, batch.num_rows, batch.schema)


def unify_batches(batches: Sequence[ColumnarBatch]
                  ) -> list[ColumnarBatch]:
    """Pad batches to ONE capacity/width profile (max over the set,
    width pow2-padded) so their leaves stack into rectangular arrays."""
    cap = max(b.capacity for b in batches)
    widths: dict[int, int] = {}
    for b in batches:
        for ci, c in enumerate(b.columns):
            if isinstance(c, StringColumn):
                widths[ci] = max(widths.get(ci, 1), c.width)
    for ci in widths:
        widths[ci] = pad_width(widths[ci])
    return [repad_batch(b, cap, widths) for b in batches]


# ------------------------------------------------------------------ #
# Global sharded-array assembly (stage entry)
# ------------------------------------------------------------------ #


def _assemble(mesh, per_dev: list, control: bool = False) -> jax.Array:
    """One global (R, n, ...) array from one (R, ...) piece per mesh
    device: each piece is placed onto ITS shard's device through
    parallel/placement.py (device-born pieces are adopted zero-copy;
    host-born ones are counted and uploaded) and the global array is
    assembled without ever materializing a host-stacked copy
    (`jax.make_array_from_single_device_arrays` — the NamedSharding
    idiom of SNIPPETS [3])."""
    from spark_rapids_tpu.parallel import placement as _placement

    devs = list(mesh.devices.flat)
    pieces = [_placement.place_piece(p[:, None], d, control=control)
              for p, d in zip(per_dev, devs)]
    shape = (per_dev[0].shape[0], len(devs)) + tuple(
        per_dev[0].shape[1:])
    return jax.make_array_from_single_device_arrays(
        shape, rounds_sharding(mesh), pieces)


def shard_stack_rounds(rounds: Sequence[Sequence[ColumnarBatch]],
                       mesh) -> ColumnarBatch:
    """Assemble R rounds of n per-shard batches into ONE global sharded
    batch: every leaf becomes a (R, n, capacity, ...) jax Array under
    ``NamedSharding(mesh, P(None, "data"))``, with shard d's slice
    resident on mesh device d.  num_rows becomes an int32 (R, n)
    global array.  This is the stage INPUT contract of every SPMD
    stage program."""
    n = int(mesh.shape[DATA_AXIS])
    flat = [b for shards in rounds for b in shards]
    assert flat and len(flat) == len(rounds) * n
    unified = unify_batches(flat)
    r_count = len(rounds)

    def at(r: int, d: int) -> ColumnarBatch:
        return unified[r * n + d]

    schema = flat[0].schema
    cols: list[AnyColumn] = []
    for ci, c0 in enumerate(unified[0].columns):
        if isinstance(c0, StringColumn):
            cols.append(StringColumn(
                _assemble(mesh, [
                    jnp.stack([at(r, d).columns[ci].chars
                               for r in range(r_count)])
                    for d in range(n)]),
                _assemble(mesh, [
                    jnp.stack([at(r, d).columns[ci].lengths
                               for r in range(r_count)])
                    for d in range(n)]),
                _assemble(mesh, [
                    jnp.stack([at(r, d).columns[ci].validity
                               for r in range(r_count)])
                    for d in range(n)])))
        else:
            cols.append(Column(
                _assemble(mesh, [
                    jnp.stack([at(r, d).columns[ci].data
                               for r in range(r_count)])
                    for d in range(n)]),
                _assemble(mesh, [
                    jnp.stack([at(r, d).columns[ci].validity
                               for r in range(r_count)])
                    for d in range(n)]),
                c0.dtype))
    num_rows = _assemble(mesh, [
        np.asarray([at(r, d).concrete_num_rows()
                    for r in range(r_count)], np.int32)
        for d in range(n)], control=True)
    return ColumnarBatch(cols, num_rows, schema)


def pad_rounds_pow2(rounds: list, schema: T.Schema, n: int) -> list:
    """Pad a round list with rounds of empty shard batches up to the
    next power of two, so the in-program scan length (part of the
    compiled program's key) takes a handful of bucketed values instead
    of minting one executable per data-dependent round count."""
    r = len(rounds)
    want = 1 << (r - 1).bit_length() if r > 1 else 1
    out = list(rounds)
    while len(out) < want:
        out.append([ColumnarBatch.empty(schema) for _ in range(n)])
    return out


def sample_fracs(mesh, n_rounds: int, k: int,
                 seed: int = 0x52414E47) -> jax.Array:
    """Deterministic per-(round, shard) sample-position fractions in
    [0, 1) for the sort stage's in-program sampling, assembled as a
    global (R, n, k) sharded array."""
    n = int(mesh.shape[DATA_AXIS])
    rng = np.random.default_rng(seed)
    fr = rng.random((n_rounds, n, k), dtype=np.float32)
    # host-chosen control plane (k floats per round-shard), not data
    return _assemble(mesh, [fr[:, d] for d in range(n)], control=True)


# ------------------------------------------------------------------ #
# Stage exit: ONE host sync, then unstack + shrink
# ------------------------------------------------------------------ #


def stage_counts(batch: ColumnarBatch) -> np.ndarray:
    """THE stage-exit sync: fetch the output row-count array (shape
    (n,) or (R, n)) in one device_get.  Everything the host loop used
    to learn per round (`concrete_num_rows` per shard, shrink sizes)
    comes out of this single fetch."""
    return np.asarray(jax.device_get(batch.num_rows))


def fetch(arr) -> np.ndarray:
    """Host fetch of a small stage-exit diagnostic array (the join
    stage's per-round true totals) — one device_get at a stage
    boundary, never inside the round loop."""
    return np.asarray(jax.device_get(arr))


def _slice_shard(batch: ColumnarBatch, idx: tuple, rows: int,
                 device=None) -> ColumnarBatch:
    # take_piece, not plain getitem: the (round, shard) piece of a
    # partitioned stage output is wholly resident on one device, and
    # an eager getitem on the sharded array would launch an unguarded
    # cross-device gather (exchange.take_piece documents the hazard)
    cols: list[AnyColumn] = []
    for c in batch.columns:
        if isinstance(c, StringColumn):
            cols.append(StringColumn(take_piece(c.chars, idx),
                                     take_piece(c.lengths, idx),
                                     take_piece(c.validity, idx)))
        else:
            cols.append(Column(take_piece(c.data, idx),
                               take_piece(c.validity, idx), c.dtype))
    out = ColumnarBatch(cols, rows, batch.schema)
    out = out.shrink_to_capacity(max(MIN_CAPACITY,
                                     pad_capacity(rows)))
    if device is not None:
        from spark_rapids_tpu.parallel import placement as _placement
        out = _placement.adopt_batch(out, device)
    return out


def _adoption_devices(mesh) -> Optional[list]:
    """Mesh device list when producer-side adoption is on (mesh
    serving), else None — the default keeps shrink outputs wherever
    slicing left them, bit-for-bit the pre-placement behavior."""
    if mesh is None:
        return None
    from spark_rapids_tpu.serving import mesh_serving_enabled
    if not mesh_serving_enabled():
        return None
    return list(mesh.devices.flat)


def unstack_stage(batch: ColumnarBatch,
                  counts: Optional[np.ndarray] = None,
                  mesh=None) -> list[ColumnarBatch]:
    """Split a (n, capacity, ...) stage output into n shrunk per-shard
    batches using the stage-exit counts (fetched once if not given).
    Under mesh serving (pass the mesh) shard d's batch adopts mesh
    device d at this producer boundary."""
    if counts is None:
        counts = stage_counts(batch)
    devs = _adoption_devices(mesh)
    return [_slice_shard(batch, (d,), int(counts[d]),
                         devs[d] if devs else None)
            for d in range(counts.shape[0])]


def unstack_round_stage(batch: ColumnarBatch,
                        counts: Optional[np.ndarray] = None,
                        mesh=None) -> list[list[ColumnarBatch]]:
    """Split a (R, n, capacity, ...) stage output into per-shard lists
    of per-round shrunk batches (empty rounds dropped)."""
    if counts is None:
        counts = stage_counts(batch)
    r_count, n = counts.shape
    devs = _adoption_devices(mesh)
    out: list[list[ColumnarBatch]] = [[] for _ in range(n)]
    for d in range(n):
        for r in range(r_count):
            rows = int(counts[r, d])
            if rows:
                out[d].append(_slice_shard(
                    batch, (r, d), rows, devs[d] if devs else None))
    return out


def shrink_rounds(batch: ColumnarBatch,
                  counts: Optional[np.ndarray] = None,
                  mesh=None) -> list[list[ColumnarBatch]]:
    """THE mid-stage shrink: split a (R, n, capacity, ...) exchange
    program output into a rectangular rounds[r][d] grid of shrunk
    batches (empty rounds kept), using ONE stage-exit counts fetch.
    The exchange program's outputs carry the worst-case n x cap
    receive capacity per shard; shrinking here — once per stage, not
    once per round — is what keeps the tail program's merge/sort/join
    work proportional to the LIVE rows instead of the padding.  Under
    mesh serving each shard column adopts its mesh device here, so the
    tail program's re-assembly finds every piece device-born."""
    if counts is None:
        counts = stage_counts(batch)
    r_count, n = counts.shape
    devs = _adoption_devices(mesh)
    return [[_slice_shard(batch, (r, d), int(counts[r, d]),
                          devs[d] if devs else None)
             for d in range(n)]
            for r in range(r_count)]


# ------------------------------------------------------------------ #
# Stage program builders (compiled via cached_jit: sharding + mesh in
# the key, ledger meta = {devices, rounds})
# ------------------------------------------------------------------ #


def _tree_index(tree, r: int):
    return jax.tree_util.tree_map(lambda leaf: leaf[r], tree)


def _concat_rounds(ys, n_rounds: int,
                   squeeze: bool = False) -> ColumnarBatch:
    """Fold a rounds-stacked pytree into one traced batch.  `squeeze`
    strips the per-shard device axis first — program INPUTS carry it
    (leaves (R, 1, cap, ...)); in-body scan outputs do not."""
    parts = [_tree_index(ys, r) for r in range(n_rounds)]
    if squeeze:
        parts = [_squeeze0(p) for p in parts]
    if n_rounds == 1:
        return parts[0]
    merged = concat_batches_traced(parts)
    assert merged is not None, \
        "collective schemas are flat (supports_schema gates nesting)"
    return merged


def _stage_jit(key: tuple, make_fn, mesh, op, in_shardings,
               out_shardings, donate, n_rounds: int):
    from spark_rapids_tpu.execs.jit_cache import cached_jit

    n = int(mesh.shape[DATA_AXIS])
    return cached_jit(
        key + (mesh_key(mesh),), make_fn, op=op,
        in_shardings=in_shardings, out_shardings=out_shardings,
        donate=donate,
        meta={"devices": n, "rounds": n_rounds})


def make_exchange_scan_stage(mesh, key: tuple, body: Callable,
                             n_rounds: int, op: Optional[str] = None,
                             donate: bool = False):
    """The EXCHANGE program of a stage: lax.scan over the rounds axis
    applying `body` (per-shard round batch -> per-shard batch; the
    in-program all_to_all — exchange_shard / route_shard — lives
    inside `body`, as do any fused map/reduce phases).  Emits the
    round-stacked per-shard outputs at the worst-case n x cap receive
    capacity; the host shrinks them ONCE at stage exit
    (`shrink_rounds`) before the tail program, so the tail's work is
    proportional to live rows, not padding."""
    axis = DATA_AXIS

    def make():
        def shard_fn(xs: ColumnarBatch) -> ColumnarBatch:
            def sbody(carry, x):
                return carry, _unsqueeze0(body(_squeeze0(x)))
            _, ys = jax.lax.scan(sbody, jnp.int32(0), xs)
            return ys

        return _shard_map(shard_fn, mesh, P(None, axis),
                          P(None, axis))

    return _stage_jit(
        ("spmdxchg", key, n_rounds), make, mesh, op,
        (rounds_sharding(mesh),), rounds_sharding(mesh),
        (0,) if donate else None, n_rounds)


def make_stage_tail(mesh, key: tuple, fn: Callable, n_rounds: int,
                    op: Optional[str] = None, donate: bool = False):
    """The TAIL program of a stage: concatenate the (shrunk,
    re-assembled) per-shard rounds and apply `fn` — the agg's
    cross-round merge + finalize, the sort's local sort, the join
    build side's fold.  No collectives: the exchange already owns
    placement, so the tail is pure per-shard work at tight
    capacity."""
    axis = DATA_AXIS

    def make():
        def shard_fn(xs: ColumnarBatch) -> ColumnarBatch:
            merged = _concat_rounds(xs, n_rounds, squeeze=True)
            return _unsqueeze0(fn(merged))

        return _shard_map(shard_fn, mesh, P(None, axis), P(axis))

    return _stage_jit(
        ("spmdtail", key, n_rounds), make, mesh, op,
        (rounds_sharding(mesh),), stage_sharding(mesh),
        (0,) if donate else None, n_rounds)


def make_join_scan_stage(mesh, key: tuple, join_fn: Callable,
                         n_rounds: int, op: Optional[str] = None):
    """Join probe program: scan the PRE-ROUTED stream rounds against
    the resident per-shard build batch — `join_fn(stream_shard,
    build_shard) -> (joined, total)` runs entirely in-program.
    Outputs round-stacked joined batches plus per-(round, shard) true
    totals for the host's stage-exit capacity-overflow check (the one
    decision that stays on the host, because it re-COMPILES at a
    bigger bucket).  Inputs are NOT donated: an overflow re-dispatches
    the same arrays."""
    axis = DATA_AXIS

    def make():
        def shard_fn(xs: ColumnarBatch, build: ColumnarBatch):
            b = _squeeze0(build)

            def body(carry, x):
                s = _squeeze0(x)
                out, total = join_fn(s, b)
                return carry, (_unsqueeze0(out), total[None])
            _, (ys, totals) = jax.lax.scan(body, jnp.int32(0), xs)
            return ys, totals

        return _shard_map(
            shard_fn, mesh, (P(None, axis), P(axis)),
            (P(None, axis), P(None, axis)))

    return _stage_jit(
        ("spmdjoin", key, n_rounds), make, mesh, op,
        (rounds_sharding(mesh), stage_sharding(mesh)),
        (rounds_sharding(mesh), rounds_sharding(mesh)),
        None, n_rounds)


def _all_gather_concat(b: ColumnarBatch, n: int,
                       axis: str) -> ColumnarBatch:
    """Pool one prefix-compact per-shard batch across the mesh INSIDE
    the program: all_gather every leaf, rebuild liveness from the
    gathered row counts, compact.  Every shard holds the identical
    pooled result afterwards (replicated by construction)."""
    rows_all = jax.lax.all_gather(
        jnp.asarray(b.num_rows, jnp.int32), axis)  # (n,)
    cap = b.capacity

    def ag(x):
        return jax.lax.all_gather(x, axis, tiled=True)

    cols: list[AnyColumn] = []
    for c in b.columns:
        if isinstance(c, StringColumn):
            cols.append(StringColumn(ag(c.chars), ag(c.lengths),
                                     ag(c.validity)))
        else:
            cols.append(Column(ag(c.data), ag(c.validity), c.dtype))
    idx = jnp.arange(n * cap, dtype=jnp.int32)
    live = (idx % cap) < jnp.take(rows_all, idx // cap)
    return ColumnarBatch(cols, n * cap, b.schema).compact(live)


def make_sort_sample_stage(mesh, key: tuple, part, n_rounds: int,
                           sample_k: int, op: Optional[str] = None):
    """Pass 1 of the BUCKETED distributed ORDER BY (mesh serving,
    docs/pod_serving.md): scan one bucket's rounds gathering per-shard
    sort-key samples at host-chosen fractional positions — the sample
    half of `make_sort_route_stage`, emitted as a round-stacked stage
    OUTPUT instead of being consumed in-program.  A million-round sort
    samples bucket by bucket (one bucket stacked at a time) instead of
    assembling every round into one resident global array.  Inputs are
    NOT donated: the same rounds re-stack for the route pass."""
    axis = DATA_AXIS

    def make():
        def shard_fn(xs: ColumnarBatch, fracs: jax.Array):
            def sample_body(carry, xf):
                x, frac = xf
                b = _squeeze0(x)
                kb = part.key_batch(b)
                rows = jnp.asarray(b.num_rows, jnp.int32)
                cap = b.capacity
                pos = jnp.clip(
                    (frac[0] * rows.astype(jnp.float32)).astype(
                        jnp.int32),
                    0, jnp.maximum(rows - 1, 0))
                n_valid = (sample_k * rows + cap - 1) // cap
                return carry, _unsqueeze0(kb.gather(pos, n_valid))
            _, samples = jax.lax.scan(sample_body, jnp.int32(0),
                                      (xs, fracs))
            return samples

        return _shard_map(
            shard_fn, mesh, (P(None, axis), P(None, axis)),
            P(None, axis))

    return _stage_jit(
        ("spmdsortsample", key, n_rounds, sample_k), make, mesh, op,
        (rounds_sharding(mesh), rounds_sharding(mesh)),
        rounds_sharding(mesh), None, n_rounds)


def make_bounds_route_stage(mesh, key: tuple, part, n_rounds: int,
                            op: Optional[str] = None,
                            donate: bool = False):
    """Pass 2 of the bucketed distributed ORDER BY: scan one bucket's
    rounds through the range-routed all_to_all, with the bounds batch
    riding as a REPLICATED program argument (the make_route_step
    idiom) — one compiled program serves every bounds value, so the
    bucket count never mints executables."""
    n = int(mesh.shape[DATA_AXIS])
    axis = DATA_AXIS

    def make():
        def shard_fn(xs: ColumnarBatch, bounds: ColumnarBatch):
            def route_body(carry, x):
                b = _squeeze0(x)
                pid = part.partition_ids_with_bounds(b, bounds)
                return carry, _unsqueeze0(
                    route_shard(b, pid, n, axis))
            _, routed = jax.lax.scan(route_body, jnp.int32(0), xs)
            return routed

        return _shard_map(
            shard_fn, mesh, (P(None, axis), P()), P(None, axis))

    return _stage_jit(
        ("spmdboundsroute", key, n_rounds), make, mesh, op,
        (rounds_sharding(mesh), NamedSharding(mesh, P())),
        rounds_sharding(mesh), (0,) if donate else None, n_rounds)


def make_sort_route_stage(mesh, key: tuple, part, n_rounds: int,
                          sample_k: int, op: Optional[str] = None,
                          donate: bool = False):
    """The exchange program of a distributed ORDER BY:

    1. scan rounds gathering per-shard sort-key samples at host-chosen
       fractional positions (sample count proportional to each round's
       live rows, so a 10-row tail batch cannot outweigh a full one);
    2. all_gather the samples and compute range bounds IN-PROGRAM
       (`choose_bounds_dynamic` — every shard derives identical bounds
       from the identical pooled sample);
    3. scan rounds again through the range-routed all_to_all.

    Emits the round-stacked routed rounds; after the mid-stage shrink
    the tail program (`make_stage_tail` with the local sort) sorts
    each shard at tight capacity — shard index order IS the total
    order.  The host-loop path needed a per-batch `concrete_num_rows`
    sync just to SIZE its samples; here the row counts never leave
    the device."""
    from spark_rapids_tpu.ops.range_partition import (
        choose_bounds_dynamic,
    )

    n = int(mesh.shape[DATA_AXIS])
    axis = DATA_AXIS
    orders = part.key_orders()

    def make():
        def shard_fn(xs: ColumnarBatch, fracs: jax.Array):
            def sample_body(carry, xf):
                x, frac = xf
                b = _squeeze0(x)
                kb = part.key_batch(b)
                rows = jnp.asarray(b.num_rows, jnp.int32)
                cap = b.capacity
                pos = jnp.clip(
                    (frac[0] * rows.astype(jnp.float32)).astype(
                        jnp.int32),
                    0, jnp.maximum(rows - 1, 0))
                n_valid = (sample_k * rows + cap - 1) // cap
                return carry, kb.gather(pos, n_valid)
            _, samples = jax.lax.scan(sample_body, jnp.int32(0),
                                      (xs, fracs))
            pooled = _all_gather_concat(
                _concat_rounds(samples, n_rounds), n, axis)
            bounds = choose_bounds_dynamic(pooled, orders, n)

            def route_body(carry, x):
                b = _squeeze0(x)
                pid = part.partition_ids_with_bounds(b, bounds)
                return carry, _unsqueeze0(
                    route_shard(b, pid, n, axis))
            _, routed = jax.lax.scan(route_body, jnp.int32(0), xs)
            return routed

        return _shard_map(
            shard_fn, mesh, (P(None, axis), P(None, axis)),
            P(None, axis))

    return _stage_jit(
        ("spmdsortroute", key, n_rounds, sample_k), make, mesh, op,
        (rounds_sharding(mesh), rounds_sharding(mesh)),
        rounds_sharding(mesh), (0,) if donate else None, n_rounds)
