"""The /metrics registry adapter: OpenMetrics text exposition over
the engine's EXISTING counter surfaces.

The naming contract is mechanical, never hand-curated — a metric
exists here if and only if its key exists on one of the four source
surfaces, so the scrape can be asserted EQUAL to the in-process
snapshot (run_ops_smoke) and a new eventlog counter appears on
/metrics with zero code:

- ``eventlog.counters_snapshot()`` -> ``tpu_<key . -> _>`` —
  ``_total``-suffixed counter families for MONOTONIC_COUNTERS keys,
  gauges for the residency gauges riding the same snapshot;
- ``telemetry.sample_now()`` -> ``tpu_telemetry_<key>`` gauges (the
  sampler's fleet-load view, namespaced because its keys overlap the
  snapshot's);
- ``scheduler.scheduler_stats()`` + per-tenant wait stats ->
  ``tpu_serving_<key>`` gauges (``tenant=``-labelled where
  per-tenant);
- the device ledger's per-op rollup -> ``tpu_ledger_<field>`` gauges
  labelled ``op=``.

Docs: ``docs/ops_plane.md`` (metric naming contract).
"""

from __future__ import annotations

from typing import Iterable, Optional


def metric_name(key: str, prefix: str = "tpu") -> str:
    """The mechanical derivation: eventlog/telemetry key ->
    OpenMetrics sample name."""
    return f"{prefix}_{key.replace('.', '_').replace('-', '_')}"


def counter_metric_name(key: str) -> str:
    """Monotonic counters additionally carry the OpenMetrics
    ``_total`` suffix."""
    return metric_name(key) + "_total"


def _fmt(value) -> str:
    if isinstance(value, bool):
        return "1" if value else "0"
    if value is None:
        return "0"
    f = float(value)
    return repr(int(f)) if f == int(f) else repr(f)


def _labels(kv: dict) -> str:
    if not kv:
        return ""
    inner = ",".join(
        '{}="{}"'.format(k, str(v).replace("\\", "\\\\")
                         .replace('"', '\\"').replace("\n", " "))
        for k, v in sorted(kv.items()))
    return "{" + inner + "}"


def families() -> list[tuple[str, str, list[tuple[dict, float]]]]:
    """Every family as (name, type, [(labels, value), ...]) — the
    single source both the text renderer and the parity smoke use."""
    from spark_rapids_tpu import obs as _obs
    from spark_rapids_tpu.eventlog import (
        MONOTONIC_COUNTERS,
        counters_snapshot,
    )
    from spark_rapids_tpu.serving.scheduler import scheduler_stats
    from spark_rapids_tpu.trace import ledger as _ledger
    from spark_rapids_tpu.trace.telemetry import sample_now

    out: list[tuple[str, str, list[tuple[dict, float]]]] = []
    monotonic = set(MONOTONIC_COUNTERS)
    for key, val in sorted(counters_snapshot().items()):
        if key in monotonic:
            out.append((counter_metric_name(key), "counter",
                        [({}, val)]))
        else:
            out.append((metric_name(key), "gauge", [({}, val)]))
    for key, val in sorted(sample_now().items()):
        out.append((metric_name(key, "tpu_telemetry"), "gauge",
                    [({}, val)]))
    for key, val in sorted(scheduler_stats().items()):
        out.append((metric_name(key, "tpu_serving"), "gauge",
                    [({}, val)]))
    try:
        from spark_rapids_tpu.serving.scheduler import tenant_wait_stats

        waits = tenant_wait_stats()
    except Exception:
        waits = {}
    for field in ("wait_p50_ms", "wait_p99_ms", "admitted"):
        samples = [({"tenant": t}, s.get(field, 0))
                   for t, s in sorted(waits.items())]
        if samples:
            out.append((metric_name(f"tenant.{field}", "tpu_serving"),
                        "gauge", samples))
    out.append(("tpu_queries_in_flight", "gauge",
                [({}, _obs.REGISTRY.count())]))
    if _ledger.LEDGER.enabled:
        per_op = _ledger.per_op(_ledger.snapshot())
        for field in ("device_ms", "dispatches", "roofline",
                      "live_capacity_ratio"):
            samples = [({"op": op}, v[field])
                       for op, v in sorted(per_op.items())
                       if v.get(field) is not None]
            if samples:
                out.append((metric_name(f"ledger.{field}"), "gauge",
                            samples))
    return out


def openmetrics_text() -> str:
    """The /metrics body: OpenMetrics text exposition, terminated by
    the spec's ``# EOF`` marker."""
    lines: list[str] = []
    for name, mtype, samples in families():
        lines.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            lines.append(f"{name}{_labels(labels)} {_fmt(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_openmetrics(text: str) -> dict[str, dict]:
    """Parse an exposition back into {name: {"type": t, "samples":
    {labels_str: value}}} — the smoke/bench side of the parity
    assertion (stdlib only, so the connect client tests could reuse
    it)."""
    out: dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line == "# EOF":
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, mtype = rest.partition(" ")
            out.setdefault(name, {"type": mtype.strip(),
                                  "samples": {}})
            continue
        if line.startswith("#"):
            continue
        head, _, val = line.rpartition(" ")
        name, labels = head, ""
        if "{" in head:
            name, _, rest = head.partition("{")
            labels = "{" + rest
        out.setdefault(name, {"type": "untyped", "samples": {}})
        out[name]["samples"][labels] = float(val)
    return out


def scrape_value(parsed: dict, name: str,
                 labels: str = "") -> Optional[float]:
    fam = parsed.get(name)
    if fam is None:
        return None
    return fam["samples"].get(labels)


def counter_keys() -> Iterable[str]:
    from spark_rapids_tpu.eventlog import MONOTONIC_COUNTERS

    return MONOTONIC_COUNTERS
