"""The ops-plane HTTP endpoint: stdlib ``http.server`` on the same
daemon-thread idiom as the connect/shuffle servers (a threading
server whose handler threads are daemons, one acceptor thread, an
explicit ``stop()`` that shuts the loop down and CLOSES the socket).

Endpoints (all GET, JSON unless noted):

- ``/metrics``  — OpenMetrics text exposition (obs/metrics.py);
- ``/queries``  — in-flight query list (plans elided);
- ``/queries/<id>`` — one in-flight query: rendered plan, elapsed,
  batches-so-far, cancel-token state, per-op ledger metrics-so-far;
- ``/queries/<id>/cancel`` (POST) — cancel via the registered token;
- ``/slo``      — per-tenant rolling p50/p99 + breach history;
- ``/healthz``  — liveness probe (``ok``).

The handler serves STRICTLY from in-process snapshots — it never
touches the device, takes no engine locks beyond the registry's own,
and a scrape concurrent with a measured bench window must not perturb
results (asserted by the bench.py --sessions scrape-under-storm arm).
Docs: ``docs/ops_plane.md``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class _Handler(BaseHTTPRequestHandler):
    # the plane is an operator surface, not a web app: no logging to
    # stderr (a scrape per second would drown real diagnostics)
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def _send(self, code: int, body: str,
              ctype: str = "application/json") -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype + "; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, obj, code: int = 200) -> None:
        self._send(code, json.dumps(obj, default=str))

    def _qid(self, part: str) -> Optional[int]:
        try:
            return int(part)
        except ValueError:
            self._send_json({"error": f"bad query id {part!r}"}, 400)
            return None

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        from spark_rapids_tpu import obs as _obs
        from spark_rapids_tpu.obs import metrics as _metrics
        from spark_rapids_tpu.obs import slo as _slo

        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                self._send(200, _metrics.openmetrics_text(),
                           ctype="application/openmetrics-text; "
                                 "version=1.0.0")
            elif path == "/queries":
                self._send_json(_obs.REGISTRY.snapshot())
            elif path.startswith("/queries/"):
                qid = self._qid(path.split("/", 2)[2])
                if qid is None:
                    return
                entry = _obs.REGISTRY.get(qid)
                if entry is None:
                    self._send_json(
                        {"error": f"query {qid} not in flight"}, 404)
                else:
                    self._send_json(entry)
            elif path == "/slo":
                self._send_json(_slo.WATCHDOG.snapshot())
            elif path == "/healthz":
                self._send(200, "ok\n", ctype="text/plain")
            else:
                self._send_json({"error": f"no route {path!r}"}, 404)
        except BrokenPipeError:
            pass  # scraper went away mid-body
        except Exception as e:  # noqa: BLE001 — the probe must live
            try:
                self._send_json({"error": repr(e)}, 500)
            except Exception:
                pass

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        from spark_rapids_tpu import obs as _obs

        path = self.path.split("?", 1)[0].rstrip("/")
        parts = path.split("/")
        if len(parts) == 4 and parts[1] == "queries" \
                and parts[3] == "cancel":
            qid = self._qid(parts[2])
            if qid is None:
                return
            ok = _obs.REGISTRY.cancel(qid)
            self._send_json({"query_id": qid, "cancelled": ok},
                            200 if ok else 404)
            return
        self._send_json({"error": f"no route {path!r}"}, 404)


class OpsHttpServer:
    """One acceptor thread + daemon handler threads; ``stop()`` shuts
    the serve loop down, closes the listening socket and JOINS the
    acceptor, so after stop() no thread and no bound port remain."""

    def __init__(self, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="tpu-obs-http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
