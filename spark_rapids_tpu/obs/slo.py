"""The SLO watchdog: rolling per-tenant latency objectives, evaluated
live, breaches persisted as ``slo`` event-log records.

The PR13 circuit breaker only sees CRASHES — a tenant can run 10x
over its latency target forever without tripping anything.  The
watchdog closes that loop: the shared query epilogue feeds every
completed query's (tenant, wall_ms, admit_wait_ms) into rolling
windows here, and ONE thread (tracer-style ownership, ``stop()``
joins) re-evaluates the per-tenant p50/p99 against the
``spark.rapids.tpu.obs.slo.*`` budgets every checkIntervalMs:

- a p99 over budget appends an ``slo`` record to every attached
  session event log (weakref writers, the telemetry-sampler idiom) —
  the input of the HC016 health rule in tools/history;
- ``/slo`` serves :func:`SloWatchdog.snapshot`: the live per-tenant
  percentiles, budgets and bounded breach history.

Budgets default to 0 (= no objective): enabling the ops plane never
invents an alarm threshold.  Docs: ``docs/ops_plane.md``.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Optional

#: breach history bound per process (the /slo payload stays small)
_MAX_BREACHES = 256


def _pctl(sorted_vals: list, p: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            int(round(p * (len(sorted_vals) - 1))))
    return sorted_vals[i]


class SloWatchdog:
    """See module doc.  Observations arrive on query threads
    (:meth:`observe`, epilogue-driven — cheap append under the lock);
    evaluation runs on the one watchdog thread."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: tenant -> deque[(monotonic_ts, wall_ms, admit_wait_ms)]
        self._windows: dict[str, deque] = {}
        self._writers: list[weakref.ref] = []
        self._breaches: deque = deque(maxlen=_MAX_BREACHES)
        self.breach_count = 0
        self.ticks = 0
        # budgets synced from the owning conf at query boundaries
        self.wall_budget_ms = 0.0
        self.admit_budget_ms = 0.0
        self.window_s = 60.0
        self.interval_ms = 1000.0

    # -- lifecycle ---------------------------------------------------- #

    def start(self) -> None:
        with self._lock:
            if self.enabled:
                return
            self.enabled = True
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._run, args=(self._stop_evt,),
                name="tpu-obs-slo", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            if not self.enabled:
                return
            self.enabled = False
            evt, t = self._stop_evt, self._thread
            self._thread = None
        evt.set()
        if t is not None:
            t.join()
        with self._lock:
            self._windows.clear()

    def sync_budgets(self, conf) -> None:
        from spark_rapids_tpu.obs import (
            SLO_ADMIT_BUDGET_MS,
            SLO_INTERVAL_MS,
            SLO_WALL_BUDGET_MS,
            SLO_WINDOW_S,
        )

        self.wall_budget_ms = float(conf.get(SLO_WALL_BUDGET_MS))
        self.admit_budget_ms = float(conf.get(SLO_ADMIT_BUDGET_MS))
        self.window_s = float(conf.get(SLO_WINDOW_S))
        self.interval_ms = float(conf.get(SLO_INTERVAL_MS))

    def attach_writer(self, writer) -> None:
        if writer is None:
            return
        with self._lock:
            for r in self._writers:
                if r() is writer:
                    return
            self._writers.append(weakref.ref(writer))

    # -- ingestion (query epilogue) ------------------------------------ #

    def observe(self, tenant: str, wall_ms: float,
                admit_wait_ms: float = 0.0,
                engine: str = "tpu") -> None:
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            win = self._windows.setdefault(tenant, deque())
            win.append((now, float(wall_ms), float(admit_wait_ms)))
            # bound the window eagerly too: a tenant hammering faster
            # than the prune tick must not grow without bound
            cutoff = now - self.window_s
            while win and win[0][0] < cutoff:
                win.popleft()

    # -- evaluation (watchdog thread) ---------------------------------- #

    def _tenant_stats(self, win: deque) -> dict:
        walls = sorted(w for _, w, _ in win)
        waits = sorted(a for _, _, a in win)
        return {
            "n": len(win),
            "wall_p50_ms": round(_pctl(walls, 0.50), 3),
            "wall_p99_ms": round(_pctl(walls, 0.99), 3),
            "admit_wait_p50_ms": round(_pctl(waits, 0.50), 3),
            "admit_wait_p99_ms": round(_pctl(waits, 0.99), 3),
        }

    def evaluate_now(self) -> list[dict]:
        """One evaluation pass (also the test hook): prune windows,
        compare per-tenant p99s against the budgets, record + emit
        breaches.  Returns the breaches found THIS pass."""
        now = time.monotonic()
        found: list[dict] = []
        with self._lock:
            cutoff = now - self.window_s
            for tenant, win in list(self._windows.items()):
                while win and win[0][0] < cutoff:
                    win.popleft()
                if not win:
                    del self._windows[tenant]
                    continue
                stats = self._tenant_stats(win)
                for metric, budget in (
                        ("wall_p99_ms", self.wall_budget_ms),
                        ("admit_wait_p99_ms", self.admit_budget_ms)):
                    if budget > 0 and stats[metric] > budget:
                        found.append({
                            "tenant": tenant,
                            "metric": metric,
                            "observed_ms": stats[metric],
                            "budget_ms": budget,
                            "window": stats["n"],
                            "ts": time.time(),
                        })
            for b in found:
                self._breaches.append(b)
                self.breach_count += 1
            refs = list(self._writers)
        for b in found:
            for r in refs:
                w = r()
                if w is None:
                    continue
                try:
                    w.log_slo(b)
                except Exception:
                    pass  # a full disk must not kill the watchdog
        return found

    def _run(self, stop_evt: threading.Event) -> None:
        while not stop_evt.wait(self.interval_ms / 1e3):
            try:
                self.evaluate_now()
            except Exception:
                continue  # a torn read must not kill the thread
            self.ticks += 1

    # -- /slo ----------------------------------------------------------- #

    def snapshot(self) -> dict:
        with self._lock:
            tenants = {t: self._tenant_stats(win)
                       for t, win in self._windows.items()}
            breaches = list(self._breaches)
        return {
            "budgets": {
                "wall_p99_ms": self.wall_budget_ms,
                "admit_wait_p99_ms": self.admit_budget_ms,
                "window_s": self.window_s,
            },
            "tenants": tenants,
            "breach_count": self.breach_count,
            "breaches": breaches[-32:],
        }

    def reset(self) -> None:
        with self._lock:
            self._windows.clear()
            self._breaches.clear()
            self.breach_count = 0


#: THE process watchdog
WATCHDOG = SloWatchdog()
