"""Live ops plane: OpenMetrics export, in-flight query registry and
the SLO watchdog.

Everything the last ten PRs built records to POST-HOC artifacts — the
trace rings, the event log, the device ledger, the lock stats are all
JSONL a CLI reads after the process exits.  An operator of the connect
front door has no live endpoint to scrape, no view of in-flight
queries, and no latency alarm.  This package is that read side:

- an **OpenMetrics HTTP endpoint** (:mod:`obs.server`, stdlib
  ``http.server`` on the connect/shuffle daemon-thread idiom) serving
  ``/metrics`` — the full existing counter surface, names derived
  MECHANICALLY from the eventlog keys (:mod:`obs.metrics`; scrape ==
  ``counters_snapshot`` parity is asserted by
  ``tools/bench_smoke.run_ops_smoke``) — plus ``/queries``,
  ``/queries/<id>``, ``/slo`` and ``/healthz`` JSON views;
- a **live query registry** (:class:`LiveQueryRegistry`): the shared
  per-query prologue/epilogue (``session._begin_query`` /
  ``_record_query``) registers every in-flight query with tenant,
  plan, elapsed, batches-so-far and its cancel token — the data under
  ``/queries`` and the ``tools/top.py`` terminal live view;
- an **SLO watchdog** (:mod:`obs.slo`): one thread (tracer-style
  ownership, ``stop()`` joins) holding rolling per-tenant wall /
  admission-wait windows fed by the registry's epilogue, comparing
  p50/p99 against the ``obs.slo.*`` budgets; breaches append ``slo``
  event-log records (the HC016 health-rule input) and surface at
  ``/slo``.

Cost discipline: disabled (the default) the per-query cost is one
conf read in :func:`sync_conf` — no thread, no socket, no registry
entry; the dispatch/readback pattern is bit-identical (asserted).
Ownership mirrors the tracer/telemetry sampler: a programmatic
:func:`start` survives ``sync_conf``; a conf-driven start is owned by
the enabling conf and only that conf's "off" tears the plane down.
Docs: ``docs/ops_plane.md``.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Optional

from spark_rapids_tpu.config import register

OBS_ENABLED = register(
    "spark.rapids.tpu.obs.enabled", False,
    "Enable the live ops plane: an OpenMetrics HTTP endpoint "
    "(/metrics, /queries, /slo on obs.port), the in-flight query "
    "registry and the SLO watchdog thread.  Off (the default) no "
    "thread or socket exists and a collect pays one conf read "
    "(docs/ops_plane.md).")

OBS_PORT = register(
    "spark.rapids.tpu.obs.port", 0,
    "TCP port of the ops-plane HTTP endpoint (0 = ephemeral; the "
    "bound port is logged and available as obs.plane().port).  The "
    "endpoint binds obs.host and serves /metrics (OpenMetrics text), "
    "/queries, /queries/<id>, /slo and /healthz.",
    check=lambda v: 0 <= v <= 65535)

OBS_HOST = register(
    "spark.rapids.tpu.obs.host", "127.0.0.1",
    "Bind address of the ops-plane HTTP endpoint.  The default stays "
    "loopback-only: the plane exposes query plans and tenant names, "
    "so fleet-wide exposure is an explicit opt-in.")

SLO_WALL_BUDGET_MS = register(
    "spark.rapids.tpu.obs.slo.wallBudgetMs", 0.0,
    "Per-tenant p99 wall-clock budget (ms) the SLO watchdog holds "
    "completed queries against over obs.slo.windowSeconds.  0 "
    "disables the wall objective.  A breach appends an `slo` "
    "event-log record (the HC016 health input) and surfaces at "
    "/slo (docs/ops_plane.md).",
    check=lambda v: v >= 0)

SLO_ADMIT_BUDGET_MS = register(
    "spark.rapids.tpu.obs.slo.admitWaitBudgetMs", 0.0,
    "Per-tenant p99 admission-wait budget (ms) for the SLO watchdog "
    "(0 disables the admission objective).  Complements the per-query "
    "HC009 rule: HC009 flags one recorded query after the fact, the "
    "watchdog alarms on the rolling fleet percentile while the "
    "process is alive.",
    check=lambda v: v >= 0)

SLO_WINDOW_S = register(
    "spark.rapids.tpu.obs.slo.windowSeconds", 60.0,
    "Rolling window the SLO watchdog computes per-tenant p50/p99 "
    "over.  Observations older than this fall out of the window.",
    check=lambda v: v > 0)

SLO_INTERVAL_MS = register(
    "spark.rapids.tpu.obs.slo.checkIntervalMs", 1000.0,
    "SLO watchdog evaluation period (ms).  At most one breach record "
    "per (tenant, objective) is emitted per evaluation.",
    check=lambda v: v >= 10)


# ------------------------------------------------------------------ #
# Live query registry
# ------------------------------------------------------------------ #


class LiveQueryRegistry:
    """In-flight queries, keyed by the process-global query id the
    shared prologue allocates.  ``enabled`` is the fast-path guard the
    session hooks read; everything else is behind the lock.  Entries
    hold the cancel token itself (so ``/queries/<id>/cancel`` works)
    but only WEAK state otherwise — plain strings and numbers, never
    the exec tree."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._live: dict[int, dict] = {}
        self._ledger_base: dict[int, dict] = {}

    def count(self) -> int:
        # len() of a dict is atomic under the GIL: this is the
        # queries.in_flight telemetry gauge, read lock-free at Hz
        return len(self._live)

    def begin(self, qid: int, tenant: Optional[str] = None,
              token: Any = None, conf_hash: str = "",
              plan: Optional[str] = None,
              plan_hash: Optional[str] = None) -> None:
        if not self.enabled:
            return
        entry = {
            "query_id": qid,
            "tenant": tenant,
            "conf_hash": conf_hash,
            "plan": plan,
            "plan_hash": plan_hash,
            "started_ts": time.time(),
            "started_pc": time.perf_counter(),
            "batches": 0,
            "rows": 0,
            "token": token,
        }
        with self._lock:
            self._live[qid] = entry
        # per-operator metrics-so-far ride the device ledger when it
        # is on: snapshot at begin, delta at read time (process-global
        # — concurrent queries share the delta, documented caveat)
        try:
            from spark_rapids_tpu.trace import ledger as _ledger

            if _ledger.LEDGER.enabled:
                self._ledger_base[qid] = _ledger.snapshot()
        except Exception:
            pass

    def annotate(self, qid: int, **kv: Any) -> None:
        """Attach facts learned after begin (rendered plan, plan
        hash, tenant discovered at admission)."""
        if not self.enabled:
            return
        with self._lock:
            e = self._live.get(qid)
            if e is not None:
                e.update({k: v for k, v in kv.items()
                          if v is not None})

    def note_batch(self, qid: int, rows: int) -> None:
        """One streamed batch retired for this query (called from the
        streaming drain loop; collect-path queries report 0)."""
        if not self.enabled:
            return
        with self._lock:
            e = self._live.get(qid)
            if e is not None:
                e["batches"] += 1
                e["rows"] += int(rows)

    def finish(self, qid: int, engine: str = "tpu") -> None:
        """The shared epilogue: deregister + feed the completed
        observation (tenant, wall, admission wait) to the SLO
        watchdog's rolling windows."""
        if not self.enabled:
            # plane turned off mid-query: drop any stale entry.  The
            # common disabled path (nothing ever registered) is two
            # attribute reads and no lock.
            if self._live or self._ledger_base:
                with self._lock:
                    self._live.pop(qid, None)
                    self._ledger_base.pop(qid, None)
            return
        with self._lock:
            e = self._live.pop(qid, None)
            self._ledger_base.pop(qid, None)
        if e is None:
            return
        from spark_rapids_tpu.obs import slo as _slo
        from spark_rapids_tpu.serving import current_serving_context

        sctx = current_serving_context() or {}
        wall_ms = (time.perf_counter() - e["started_pc"]) * 1e3
        _slo.WATCHDOG.observe(
            tenant=e.get("tenant") or sctx.get("tenant") or "",
            wall_ms=wall_ms,
            admit_wait_ms=float(sctx.get("admit_wait_ms") or 0.0),
            engine=engine)

    def drop(self, qid: int) -> None:
        """Silent deregistration (no SLO observation): the collect
        paths' ``finally`` safety net, so a crashed query or an
        ABANDONED stream (generator closed early, nothing recorded)
        cannot leak a forever-\"in-flight\" /queries entry.  No-op —
        two attribute reads, no lock — after a normal finish()."""
        if self._live or self._ledger_base:
            with self._lock:
                self._live.pop(qid, None)
                self._ledger_base.pop(qid, None)

    def _entry_json(self, e: dict, with_plan: bool) -> dict:
        tok = e.get("token")
        out = {
            "query_id": e["query_id"],
            "tenant": e.get("tenant"),
            "conf_hash": e.get("conf_hash"),
            "plan_hash": e.get("plan_hash"),
            "started_ts": e["started_ts"],
            "elapsed_ms": round(
                (time.perf_counter() - e["started_pc"]) * 1e3, 1),
            "batches": e["batches"],
            "rows": e["rows"],
            "cancel": _describe_token(tok),
        }
        if with_plan:
            out["plan"] = e.get("plan")
        return out

    def snapshot(self) -> list[dict]:
        """The /queries JSON: every in-flight query, oldest first
        (plans elided — fetch /queries/<id> for one)."""
        with self._lock:
            entries = list(self._live.values())
        entries.sort(key=lambda e: e["started_ts"])
        return [self._entry_json(e, with_plan=False) for e in entries]

    def get(self, qid: int) -> Optional[dict]:
        """The /queries/<id> JSON: the full entry (rendered plan +
        per-op device-ledger metrics-so-far when the ledger is on)."""
        with self._lock:
            e = self._live.get(qid)
            base = self._ledger_base.get(qid)
        if e is None:
            return None
        out = self._entry_json(e, with_plan=True)
        if base is not None:
            try:
                from spark_rapids_tpu.trace import ledger as _ledger

                out["operators"] = _ledger.per_op(
                    _ledger.delta(base, _ledger.snapshot()))
            except Exception:
                out["operators"] = None
        return out

    def cancel(self, qid: int, reason: str = "ops") -> bool:
        """Cancel one in-flight query via its registered token
        (POST /queries/<id>/cancel).  False when the query is gone or
        carries no token (cancellation tier off)."""
        with self._lock:
            e = self._live.get(qid)
        tok = e.get("token") if e else None
        if tok is None:
            return False
        tok.cancel(reason)
        return True

    def clear(self) -> None:
        with self._lock:
            self._live.clear()
            self._ledger_base.clear()


def _describe_token(tok: Any) -> Optional[dict]:
    if tok is None:
        return None
    try:
        from spark_rapids_tpu.serving.cancel import describe_token

        return describe_token(tok)
    except Exception:
        return None


#: THE process registry (the session hooks' target)
REGISTRY = LiveQueryRegistry()


# ------------------------------------------------------------------ #
# Plane lifecycle (endpoint + watchdog + registry, one owner)
# ------------------------------------------------------------------ #


class OpsPlane:
    """Owns the three moving parts: the HTTP endpoint thread, the SLO
    watchdog thread and the registry's enabled flag.  One instance per
    process; ownership discipline mirrors the telemetry sampler."""

    def __init__(self) -> None:
        self.enabled = False
        self.forced = False
        self._enabled_by: Optional[weakref.ref] = None
        self._lock = threading.Lock()
        self._server = None

    @property
    def port(self) -> Optional[int]:
        srv = self._server
        return srv.port if srv is not None else None

    def start(self, port: Optional[int] = None,
              host: Optional[str] = None,
              forced: bool = True) -> None:
        from spark_rapids_tpu.obs import slo as _slo
        from spark_rapids_tpu.obs.server import OpsHttpServer

        with self._lock:
            self.forced = self.forced or forced
            if self.enabled:
                return
            self._server = OpsHttpServer(
                host=host or str(OBS_HOST.default),
                port=int(OBS_PORT.default if port is None else port))
            self._server.start()
            REGISTRY.enabled = True
            _slo.WATCHDOG.start()
            self.enabled = True

    def stop(self) -> None:
        """Stop and JOIN both threads, close the socket — leak-free
        by contract (run_ops_smoke counts threads and probes the
        port after stop)."""
        from spark_rapids_tpu.obs import slo as _slo

        with self._lock:
            self.forced = False
            self._enabled_by = None
            if not self.enabled:
                return
            self.enabled = False
            srv, self._server = self._server, None
        REGISTRY.enabled = False
        REGISTRY.clear()
        _slo.WATCHDOG.stop()
        if srv is not None:
            srv.stop()

    def sync_conf(self, conf=None, writer=None) -> None:
        from spark_rapids_tpu.config import get_conf
        from spark_rapids_tpu.obs import slo as _slo

        conf = conf or get_conf()
        if self.forced:
            if self.enabled:
                _slo.WATCHDOG.sync_budgets(conf)
                _slo.WATCHDOG.attach_writer(writer)
            return
        want = bool(conf.get(OBS_ENABLED))
        if want:
            if not self.enabled:
                self.start(port=int(conf.get(OBS_PORT)),
                           host=str(conf.get(OBS_HOST)),
                           forced=False)
            self._enabled_by = weakref.ref(conf)
            _slo.WATCHDOG.sync_budgets(conf)
            _slo.WATCHDOG.attach_writer(writer)
        elif self.enabled and self._enabled_by is not None \
                and self._enabled_by() is conf:
            self.stop()


#: THE process plane
PLANE = OpsPlane()


def is_enabled() -> bool:
    return PLANE.enabled


def plane() -> OpsPlane:
    return PLANE


def start(port: Optional[int] = None,
          host: Optional[str] = None) -> None:
    """Force the plane on (tests/tools): survives sync_conf."""
    PLANE.start(port=port, host=host, forced=True)


def stop() -> None:
    PLANE.stop()


def sync_conf(conf=None, writer=None) -> None:
    """Query-boundary alignment with the session conf (one conf read
    when the plane is off — the whole disabled-path cost)."""
    PLANE.sync_conf(conf, writer=writer)
