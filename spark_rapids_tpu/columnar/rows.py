"""Row <-> columnar converters and the external export surface.

TPU analogs of the reference's transition/export pieces:
- GpuRowToColumnarExec / GpuColumnarToRowExec (row-iterator
  boundaries at plan transitions);
- ColumnarRdd (sql/rapids/execution/ColumnarRdd - the public API that
  hands the accelerated columnar data to external ML libraries).

Here the row form is plain Python tuples/dicts and the external
columnar form is Arrow record batches (or numpy/pandas) — the natural
interchange for the Python ecosystem this engine lives in."""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Sequence

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.arrow import (
    from_arrow,
    schema_to_arrow,
    to_arrow,
)
from spark_rapids_tpu.columnar.batch import ColumnarBatch


def rows_to_batch(rows: Iterable, schema: T.Schema) -> ColumnarBatch:
    """Python row tuples/dicts -> one device ColumnarBatch
    (GpuRowToColumnarExec's conversion, batched)."""
    aschema = schema_to_arrow(schema)
    names = [f.name for f in schema.fields]
    cols: list[list] = [[] for _ in names]
    for r in rows:
        if isinstance(r, dict):
            for i, n in enumerate(names):
                cols[i].append(r.get(n))
        else:
            for i, v in enumerate(r):
                cols[i].append(v)
    arrays = [pa.array(c, aschema.field(i).type)
              for i, c in enumerate(cols)]
    return from_arrow(pa.Table.from_arrays(arrays, schema=aschema))


def batch_to_rows(batch: ColumnarBatch) -> Iterator[tuple]:
    """Device ColumnarBatch -> row tuples (GpuColumnarToRowExec)."""
    tbl = to_arrow(batch)
    cols = [c.to_pylist() for c in tbl.columns]
    for i in range(tbl.num_rows):
        yield tuple(c[i] for c in cols)


def columnar_export(df, batch_rows: Optional[int] = None
                    ) -> Iterator[pa.RecordBatch]:
    """Stream a DataFrame's result as Arrow record batches without one
    giant materialization — the ColumnarRdd analog for handing
    accelerated data to external libraries."""
    from spark_rapids_tpu.config import SQL_ENABLED

    if not df._session.conf.get(SQL_ENABLED):
        # honor the engine switch exactly as collect() does
        from spark_rapids_tpu.cpu.engine import execute_cpu

        yield from execute_cpu(df._plan).to_batches(
            max_chunksize=batch_rows)
        return
    from spark_rapids_tpu.plan.planner import plan_query

    exec_, _ = plan_query(df._plan, df._session.conf)
    try:
        aschema = schema_to_arrow(exec_.schema)
        for b in exec_.execute():
            t = to_arrow(b).cast(aschema)
            for rb in t.to_batches(max_chunksize=batch_rows):
                yield rb
    finally:
        exec_.close()
