"""Arrow RecordBatch <-> device ColumnarBatch conversion.

TPU analog of the reference's row/columnar transitions and host interop:
HostColumnarToGpu (ref: sql-plugin/.../HostColumnarToGpu.scala) for
host Arrow -> device, and GpuColumnarToRowExec's device -> host path
(ref: GpuColumnarToRowExec.scala:287).  Arrow is the host substrate the
CPU engine and all file formats speak, so this module is the single H2D /
D2H seam of the framework.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (
    AnyColumn,
    Column,
    ListColumn,
    MapColumn,
    StringColumn,
    StructColumn,
    all_valid_mask,
    pad_capacity,
    pad_width,
)


def schema_from_arrow(aschema: pa.Schema) -> T.Schema:
    return T.Schema(
        [T.Field(f.name, T.from_arrow_type(f.type), f.nullable)
         for f in aschema]
    )


def schema_to_arrow(schema: T.Schema) -> pa.Schema:
    return pa.schema(
        [pa.field(f.name, T.to_arrow_type(f.dtype), f.nullable)
         for f in schema.fields]
    )


def _fixed_host(arr: pa.Array, dtype: T.DataType, cap: int
                ) -> tuple[np.ndarray, Optional[np.ndarray]]:
    """Decode one fixed-width column to padded host buffers:
    (data[cap], validity[cap] or None when fully valid)."""
    n = len(arr)
    phys = T.to_numpy_dtype(dtype)
    if isinstance(dtype, T.DecimalType):
        np_vals = np.zeros(n, np.int64)
        pylist = arr.to_pylist()
        scale = dtype.scale
        for i, v in enumerate(pylist):
            if v is not None:
                np_vals[i] = int(v.scaleb(scale))
        validity = np.array([v is not None for v in pylist], np.bool_)
    else:
        # zero-copy-ish: fill nulls then view as numpy
        if arr.null_count:
            validity = np.asarray(arr.is_valid())
            arr = arr.fill_null(_zero_value(dtype))
        else:
            validity = None
        if isinstance(dtype, T.DateType):
            np_vals = arr.cast(pa.int32()).to_numpy(zero_copy_only=False)
        elif isinstance(dtype, T.TimestampType):
            np_vals = arr.cast(pa.int64()).to_numpy(zero_copy_only=False)
        else:
            np_vals = arr.to_numpy(zero_copy_only=False)
    if n == cap:
        # exact-fit fast path: use the decoded buffer directly — no host
        # pad-copy (scans with power-of-two batch sizes hit this on every
        # full batch)
        data = np.ascontiguousarray(np_vals.astype(phys, copy=False))
    else:
        data = np.zeros(cap, phys)
        data[:n] = np_vals.astype(phys, copy=False)
    if validity is None and n == cap:
        vhost = None  # fully valid: the device-shared mask stands in
    else:
        vhost = np.zeros(cap, np.bool_)
        vhost[:n] = True if validity is None else validity
    return data, vhost


def _zero_value(dtype: T.DataType):
    if isinstance(dtype, T.BooleanType):
        return False
    if isinstance(dtype, (T.DateType,)):
        import datetime

        return datetime.date(1970, 1, 1)
    if isinstance(dtype, T.TimestampType):
        import datetime

        return datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
    if isinstance(dtype, (T.FloatType, T.DoubleType)):
        return 0.0
    return 0


def _string_host(arr: pa.Array, cap: int
                 ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Decode one string column to (chars[cap,w], lengths[cap],
    validity[cap]) host buffers."""
    n = len(arr)
    sarr = arr.cast(pa.large_string())
    buf_offsets = np.frombuffer(sarr.buffers()[1], dtype=np.int64,
                                count=n + 1, offset=sarr.offset * 8)
    data_buf = sarr.buffers()[2]
    raw = np.frombuffer(data_buf, dtype=np.uint8) if data_buf is not None \
        else np.zeros(0, np.uint8)
    lengths_np = (buf_offsets[1:] - buf_offsets[:-1]).astype(np.int32)
    validity = np.asarray(arr.is_valid()) if arr.null_count else np.ones(
        n, np.bool_)
    lengths_np = np.where(validity, lengths_np, 0).astype(np.int32)
    maxw = int(lengths_np.max()) if n else 0
    w = pad_width(max(maxw, 1))
    chars = np.zeros((cap, w), np.uint8)
    for i in range(n):
        ln = lengths_np[i]
        if ln:
            s = buf_offsets[i]
            chars[i, :ln] = raw[s:s + ln]
    lengths = np.zeros(cap, np.int32)
    lengths[:n] = lengths_np
    valid = np.zeros(cap, np.bool_)
    valid[:n] = validity
    return chars, lengths, valid


def _list_host(arr: pa.Array, dtype: T.ListType, cap: int
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Decode one list<primitive> column to dense host buffers:
    (values[cap, L], lengths[cap], elem_validity[cap, L], validity[cap])."""
    n = len(arr)
    phys = T.to_numpy_dtype(dtype.element)
    larr = arr.cast(pa.large_list(T.to_arrow_type(dtype.element)))
    offsets = np.frombuffer(larr.buffers()[1], dtype=np.int64,
                            count=n + 1, offset=larr.offset * 8)
    flat = larr.values
    if len(flat):
        fv = np.asarray(flat.is_valid()) if flat.null_count \
            else np.ones(len(flat), np.bool_)
        if flat.null_count:
            flat = flat.fill_null(_zero_value(dtype.element))
        if isinstance(dtype.element, T.DateType):
            flat_np = flat.cast(pa.int32()).to_numpy(zero_copy_only=False)
        elif isinstance(dtype.element, T.TimestampType):
            flat_np = flat.cast(pa.int64()).to_numpy(zero_copy_only=False)
        else:
            flat_np = flat.to_numpy(zero_copy_only=False).astype(
                phys, copy=False)
    else:
        flat_np = np.zeros(0, phys)
        fv = np.zeros(0, np.bool_)
    validity = np.asarray(arr.is_valid()) if arr.null_count \
        else np.ones(n, np.bool_)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    lens = np.where(validity, lens, 0).astype(np.int32)
    maxlen = int(lens.max()) if n else 0
    L = pad_width(max(maxlen, 1))
    values = np.zeros((cap, L), phys)
    evalid = np.zeros((cap, L), np.bool_)
    if n:
        idx = offsets[:-1, None] + np.arange(L)[None, :]
        mask = np.arange(L)[None, :] < lens[:, None]
        safe = np.clip(idx, 0, max(len(flat_np) - 1, 0))
        if len(flat_np):
            values[:n] = np.where(mask, flat_np[safe], 0)
            evalid[:n] = mask & fv[safe]
    lengths = np.zeros(cap, np.int32)
    lengths[:n] = lens
    valid = np.zeros(cap, np.bool_)
    valid[:n] = validity
    return values, lengths, evalid, valid


def _host_any_column(arr: pa.Array, dtype: T.DataType, cap: int):
    """Recursive host-side (numpy-backed) column builder for ANY dtype
    — the nested-type entry point (struct-of-columns / twin-matrix
    maps); flat types reuse the component decoders."""
    if isinstance(dtype, T.StructType):
        n = len(arr)
        validity = np.zeros(cap, np.bool_)
        validity[:n] = np.asarray(arr.is_valid()) if arr.null_count \
            else True
        kids = []
        for i, f in enumerate(dtype.fields):
            child = arr.field(i)
            # a null struct row must null its children too (arrow may
            # leave garbage under null parents)
            kids.append(_host_any_column(child, f.dtype, cap))
            kv = kids[-1].validity.copy()
            kv[:n] &= validity[:n]
            kids[-1] = kids[-1].with_validity(kv)
        return StructColumn(tuple(kids), validity, dtype)
    if isinstance(dtype, T.MapType):
        return _map_host_column(arr, dtype, cap)
    if isinstance(dtype, T.StringType):
        chars, lengths, valid = _string_host(arr, cap)
        return StringColumn(chars, lengths, valid)
    if isinstance(dtype, T.ListType):
        values, lengths, ev, valid = _list_host(arr, dtype, cap)
        return ListColumn(values, lengths, ev, valid, dtype)
    data, vhost = _fixed_host(arr, dtype, cap)
    if vhost is None:
        vhost = np.zeros(cap, np.bool_)
        vhost[:len(arr)] = True
    return Column(data, vhost, dtype)


def _map_host_column(arr: pa.Array, dtype: T.MapType,
                     cap: int) -> MapColumn:
    """pa.MapArray -> dense twin matrices (keys/values share lengths)."""
    n = len(arr)
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    offsets = np.asarray(arr.offsets)[: n + 1].astype(np.int64)
    keys_flat = arr.keys
    items_flat = arr.items
    kphys = T.to_numpy_dtype(dtype.key)
    vphys = T.to_numpy_dtype(dtype.value)

    def _flat_np(a, dt, phys):
        if len(a) == 0:
            return np.zeros(0, phys), np.zeros(0, np.bool_)
        fv = np.asarray(a.is_valid()) if a.null_count \
            else np.ones(len(a), np.bool_)
        if a.null_count:
            a = a.fill_null(_zero_value(dt))
        if isinstance(dt, T.DateType):
            vals = a.cast(pa.int32()).to_numpy(zero_copy_only=False)
        elif isinstance(dt, T.TimestampType):
            vals = a.cast(pa.int64()).to_numpy(zero_copy_only=False)
        else:
            vals = a.to_numpy(zero_copy_only=False).astype(
                phys, copy=False)
        return vals, fv

    kf, _ = _flat_np(keys_flat, dtype.key, kphys)
    vf, vvalid = _flat_np(items_flat, dtype.value, vphys)
    validity_np = np.asarray(arr.is_valid()) if arr.null_count \
        else np.ones(n, np.bool_)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    lens = np.where(validity_np, lens, 0).astype(np.int32)
    L = pad_width(max(int(lens.max()) if n else 0, 1))
    keys = np.zeros((cap, L), kphys)
    values = np.zeros((cap, L), vphys)
    evalid = np.zeros((cap, L), np.bool_)
    if n and len(kf):
        # offsets are ABSOLUTE into the full (unsliced) child arrays
        # that .keys/.items return — no base subtraction (a sliced
        # MapArray would otherwise decode shifted entries)
        idx = offsets[:-1, None] + np.arange(L)[None, :]
        mask = np.arange(L)[None, :] < lens[:, None]
        safe = np.clip(idx, 0, max(len(kf) - 1, 0))
        keys[:n] = np.where(mask, kf[safe], 0)
        values[:n] = np.where(mask, vf[safe], 0)
        evalid[:n] = mask & vvalid[safe]
    lengths = np.zeros(cap, np.int32)
    lengths[:n] = lens
    valid = np.zeros(cap, np.bool_)
    valid[:n] = validity_np
    return MapColumn(keys, values, evalid, lengths, valid, dtype)


# --------------------------------------------------------------------- #
# Packed upload: one H2D transfer per batch
# --------------------------------------------------------------------- #
# Device links have a per-transfer cost (dispatch + latency; large on
# tunneled/remote PJRT backends), so shipping a scan batch as one packed
# byte buffer + one jitted unpack program beats per-column uploads — the
# single staging-buffer design the reference gets from assembling one
# host buffer per Parquet read (ref: GpuParquetScan.scala:495-560).

_PACKED_UPLOAD = None  # config entry, registered lazily


def _packed_enabled() -> bool:
    global _PACKED_UPLOAD
    if _PACKED_UPLOAD is None:
        from spark_rapids_tpu.config import get_conf, register

        _PACKED_UPLOAD = register(
            "spark.rapids.tpu.sql.scan.packedUpload", True,
            "Ship each scanned batch's column components in one batched "
            "device_put (a single transfer round) instead of one "
            "transfer per component.")
    from spark_rapids_tpu.config import get_conf

    return get_conf().get(_PACKED_UPLOAD)


def _pack_components(comps: list[np.ndarray]) -> tuple[np.ndarray, tuple]:
    layout = []
    total = 0
    for a in comps:
        total = (total + 7) & ~7
        layout.append((total, a.shape, str(a.dtype)))
        total += a.nbytes
    buf = np.zeros(total, np.uint8)
    for a, (off, _, _) in zip(comps, layout):
        buf[off:off + a.nbytes] = np.ascontiguousarray(a).view(
            np.uint8).reshape(-1)
    return buf, tuple(layout)


def _make_unpack(layout: tuple):
    def unpack(buf: jax.Array) -> list[jax.Array]:
        out = []
        for off, shape, dt in layout:
            npdt = np.dtype(dt)
            count = int(np.prod(shape))
            raw = jax.lax.dynamic_slice(buf, (off,),
                                        (count * npdt.itemsize,))
            if npdt == np.uint8:
                col = raw.reshape(shape)
            elif npdt == np.bool_:
                col = (raw.reshape(shape) != 0)
            else:
                col = jax.lax.bitcast_convert_type(
                    raw.reshape(count, npdt.itemsize), npdt).reshape(shape)
            out.append(col)
        return out

    return unpack


def from_arrow(rb: pa.RecordBatch | pa.Table,
               capacity: Optional[int] = None) -> ColumnarBatch:
    """Host Arrow batch -> device ColumnarBatch (the H2D upload)."""
    if isinstance(rb, pa.Table):
        rb = rb.combine_chunks()
        arrays = [
            c.combine_chunks() if isinstance(c, pa.ChunkedArray) else c
            for c in rb.columns
        ]
        arrays = [a.chunk(0) if isinstance(a, pa.ChunkedArray) else a
                  for a in arrays]
        aschema = rb.schema
        n = rb.num_rows
    else:
        arrays = rb.columns
        aschema = rb.schema
        n = rb.num_rows
    schema = schema_from_arrow(aschema)

    if capacity is None and n > 0 and _packed_enabled():
        # encoded single-buffer upload: one device_put + cached unpack
        # program (bias/dict wire encodings, device-side validity synth)
        from spark_rapids_tpu.columnar import transfer

        enc = transfer.encode_for_device(arrays, schema, n)
        if enc is not None:
            comps_list, plan = enc
            cols = transfer.decode_on_device(comps_list, plan, schema)
            return ColumnarBatch(cols, n, schema)

    cap = capacity if capacity is not None else pad_capacity(n)

    # host-decode every column into padded component buffers
    comps: list[np.ndarray] = []
    recipe: list[tuple] = []  # (kind, first-component index, dtype)
    for arr, f in zip(arrays, schema.fields):
        if isinstance(arr, pa.DictionaryArray):
            # only the wire encoder ships dicts as-is; this fallback
            # materializes (cast through the value type)
            arr = arr.cast(arr.type.value_type)
        if isinstance(f.dtype, T.StringType):
            chars, lengths, valid = _string_host(arr, cap)
            recipe.append(("str", len(comps), f.dtype))
            comps.extend([chars, lengths, valid])
        elif isinstance(f.dtype, T.ListType):
            values, lengths, evalid, valid = _list_host(arr, f.dtype, cap)
            recipe.append(("list", len(comps), f.dtype))
            comps.extend([values, lengths, evalid, valid])
        elif isinstance(f.dtype, (T.StructType, T.MapType)):
            # nested: the column is itself a pytree of host buffers;
            # device_put moves every leaf in the same batched transfer
            recipe.append(("nested", len(comps), f.dtype))
            comps.append(_host_any_column(arr, f.dtype, cap))
        else:
            data, vhost = _fixed_host(arr, f.dtype, cap)
            if vhost is None:
                recipe.append(("fixed_shared", len(comps), f.dtype))
                comps.append(data)
            else:
                recipe.append(("fixed", len(comps), f.dtype))
                comps.extend([data, vhost])

    if (len(comps) > 1 and _packed_enabled()) or any(
            not isinstance(a, np.ndarray) for a in comps):
        # one batched transfer round for every component (beats a packed
        # staging buffer: no unpack program, and jax batches the
        # copies); nested columns are pytrees — device_put moves every
        # leaf, jnp.asarray would choke on the dataclass.  Routed
        # through the transfer.upload fault seam + in-place retry.
        from spark_rapids_tpu.columnar.transfer import upload_components

        dev = upload_components(comps)
    else:
        dev = [jnp.asarray(a) for a in comps]

    cols: list[AnyColumn] = []
    for kind, i, dtype in recipe:
        if kind == "str":
            cols.append(StringColumn(dev[i], dev[i + 1], dev[i + 2]))
        elif kind == "list":
            cols.append(ListColumn(dev[i], dev[i + 1], dev[i + 2],
                                   dev[i + 3], dtype))
        elif kind == "nested":
            cols.append(dev[i])
        elif kind == "fixed_shared":
            cols.append(Column(dev[i], all_valid_mask(cap), dtype))
        else:
            cols.append(Column(dev[i], dev[i + 1], dtype))
    return ColumnarBatch(cols, n, schema)


#: one-round fetch threshold: below this FULL-CAPACITY size, fetching
#: count+data together beats a count sync followed by a shrunk fetch
#: (breakeven = link_rtt * bandwidth; ~1-2MB on the tunneled link)
_FUSED_FETCH_BYTES = 2 << 20


def _full_fetch_bytes(batch: ColumnarBatch) -> int:
    """Static D2H size estimate if the batch shipped at full capacity."""
    total = 0
    for c in batch.columns:
        if isinstance(c, StringColumn):
            total += c.chars.shape[0] * (c.chars.shape[1] + 5)
        elif hasattr(c, "data"):
            total += c.data.shape[0] * (c.data.dtype.itemsize + 1)
        else:
            # nested (list/struct/map): no cheap estimate — report
            # over-threshold so the classic count-then-shrink path runs
            return _FUSED_FETCH_BYTES + 1
    return total


def _strip_dict_sidecar(batch: ColumnarBatch) -> ColumnarBatch:
    """Drop dictionary sidecars before D2H: the host rebuild reads only
    chars/lengths/validity, so the codes (full capacity) must never
    cross the link.  dict_len goes with them — it is jit-cache-keying
    aux (tree_flatten), and leaving it set on a column whose dictionary
    was just dropped would fragment the shrink/fetch program cache by
    the deleted dictionary's cardinality bucket."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch as _CB

    import dataclasses as _dc

    if not any(getattr(c, "codes", None) is not None
               for c in batch.columns):
        return batch

    def strip(c):
        if isinstance(c, StringColumn) and c.codes is not None:
            return _dc.replace(c, codes=None, dict_chars=None,
                               dict_lens=None, dict_len=None)
        if isinstance(c, Column) and c.codes is not None:
            return _dc.replace(c, codes=None, dict_values=None,
                               dict_len=None)
        return c

    return _CB([strip(c) for c in batch.columns], batch.num_rows,
               batch.schema)


def to_arrow(batch: ColumnarBatch) -> pa.Table:
    """Device ColumnarBatch -> host Arrow table (the D2H download).

    Every device component comes back in ONE batched jax.device_get:
    D2H pays a latency round per call, not per buffer, so sequential
    per-column reads would multiply the transfer latency by the column
    count.  The batch is first SHRUNK on device to its live row count
    (padding rows never cross the wire — a 1-row aggregate result in a
    million-row capacity bucket is a 1-row transfer, not a 100MB one)."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch as _CB

    batch = _strip_dict_sidecar(batch)

    if not isinstance(batch.num_rows, int) \
            and _full_fetch_bytes(batch) <= _FUSED_FETCH_BYTES:
        # modest batch with a device-resident row count (aggregate
        # results, limits): fetch the count WITH the components in one
        # D2H round instead of syncing the count first — each round
        # pays full link latency (>=100ms tunneled), so up to the
        # bandwidth-breakeven size, shipping the padding is cheaper
        # than a second round trip.  Columns are pytrees, so one
        # device_get batches every leaf of every column (incl. nested).
        n_host, host_cols = jax.device_get(
            (batch.num_rows, list(batch.columns)))
        n = int(np.asarray(n_host).reshape(()))
    else:
        n = batch.concrete_num_rows()
        shrunk_cap = max(128, -(-n // 128) * 128)
        if shrunk_cap < batch.capacity:
            batch = batch.shrink_to_capacity(shrunk_cap)
            batch = _CB(batch.columns, n, batch.schema)
        host_cols = jax.device_get(list(batch.columns))

    arrays = []
    aschema = schema_to_arrow(batch.schema)
    for f, col, afield in zip(batch.schema.fields, host_cols, aschema):
        arrays.append(_host_col_to_arrow(col, f.dtype, n, afield.type))
    return pa.Table.from_arrays(arrays, schema=aschema)


def _host_col_to_arrow(col, dtype: T.DataType, n: int,
                       atype) -> pa.Array:
    """One HOST-resident (device_get) column -> pa.Array[:n]."""
    if isinstance(col, ListColumn):
        vals, lens = col.values[:n], col.lengths[:n]
        ev, rv = col.elem_validity[:n], col.validity[:n]
        pylist = []
        for i in range(n):
            if not rv[i]:
                pylist.append(None)
            else:
                m = int(lens[i])
                pylist.append([vals[i, j].item() if ev[i, j] else None
                               for j in range(m)])
        return pa.array(pylist, type=atype)
    if isinstance(col, StringColumn):
        chars, lens, valid = col.chars[:n], col.lengths[:n], \
            col.validity[:n]
        pylist = [bytes(chars[i, :lens[i]]).decode("utf-8")
                  if valid[i] else None for i in range(n)]
        return pa.array(pylist, type=atype)
    if isinstance(col, StructColumn):
        valid = np.asarray(col.validity[:n])
        kids = [_host_col_to_arrow(c, f.dtype, n, atype.field(i).type)
                for i, (c, f) in enumerate(zip(col.children,
                                               dtype.fields))]
        mask = pa.array(~valid) if (~valid).any() else None
        return pa.StructArray.from_arrays(
            kids, fields=list(atype), mask=mask)
    if isinstance(col, MapColumn):
        keys, vals = col.keys[:n], col.values[:n]
        ev, lens, rv = col.entry_validity[:n], col.lengths[:n], \
            col.validity[:n]
        pylist = []
        for i in range(n):
            if not rv[i]:
                pylist.append(None)
            else:
                m = int(lens[i])
                pylist.append([
                    (keys[i, j].item(),
                     vals[i, j].item() if ev[i, j] else None)
                    for j in range(m)])
        return pa.array(pylist, type=atype)
    # fixed-width
    vals, valid = col.data[:n], col.validity[:n]
    if isinstance(dtype, T.DecimalType):
        import decimal

        pylist = [decimal.Decimal(int(vals[i])).scaleb(-dtype.scale)
                  if valid[i] else None for i in range(n)]
        return pa.array(pylist, type=atype)
    mask = ~valid if (~valid).any() else None
    if isinstance(dtype, T.DateType):
        return pa.array(vals.astype("int32"), pa.int32(),
                        mask=mask).cast(atype)
    if isinstance(dtype, T.TimestampType):
        return pa.array(vals.astype("int64"), pa.int64(),
                        mask=mask).cast(atype)
    return pa.array(vals, type=atype, mask=mask)
