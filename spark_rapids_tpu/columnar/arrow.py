"""Arrow RecordBatch <-> device ColumnarBatch conversion.

TPU analog of the reference's row/columnar transitions and host interop:
HostColumnarToGpu (ref: sql-plugin/.../HostColumnarToGpu.scala) for
host Arrow -> device, and GpuColumnarToRowExec's device -> host path
(ref: GpuColumnarToRowExec.scala:287).  Arrow is the host substrate the
CPU engine and all file formats speak, so this module is the single H2D /
D2H seam of the framework.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (
    AnyColumn,
    Column,
    ListColumn,
    StringColumn,
    all_valid_mask,
    pad_capacity,
    pad_width,
)


def schema_from_arrow(aschema: pa.Schema) -> T.Schema:
    return T.Schema(
        [T.Field(f.name, T.from_arrow_type(f.type), f.nullable)
         for f in aschema]
    )


def schema_to_arrow(schema: T.Schema) -> pa.Schema:
    return pa.schema(
        [pa.field(f.name, T.to_arrow_type(f.dtype), f.nullable)
         for f in schema.fields]
    )


def _fixed_host(arr: pa.Array, dtype: T.DataType, cap: int
                ) -> tuple[np.ndarray, Optional[np.ndarray]]:
    """Decode one fixed-width column to padded host buffers:
    (data[cap], validity[cap] or None when fully valid)."""
    n = len(arr)
    phys = T.to_numpy_dtype(dtype)
    if isinstance(dtype, T.DecimalType):
        np_vals = np.zeros(n, np.int64)
        pylist = arr.to_pylist()
        scale = dtype.scale
        for i, v in enumerate(pylist):
            if v is not None:
                np_vals[i] = int(v.scaleb(scale))
        validity = np.array([v is not None for v in pylist], np.bool_)
    else:
        # zero-copy-ish: fill nulls then view as numpy
        if arr.null_count:
            validity = np.asarray(arr.is_valid())
            arr = arr.fill_null(_zero_value(dtype))
        else:
            validity = None
        if isinstance(dtype, T.DateType):
            np_vals = arr.cast(pa.int32()).to_numpy(zero_copy_only=False)
        elif isinstance(dtype, T.TimestampType):
            np_vals = arr.cast(pa.int64()).to_numpy(zero_copy_only=False)
        else:
            np_vals = arr.to_numpy(zero_copy_only=False)
    if n == cap:
        # exact-fit fast path: use the decoded buffer directly — no host
        # pad-copy (scans with power-of-two batch sizes hit this on every
        # full batch)
        data = np.ascontiguousarray(np_vals.astype(phys, copy=False))
    else:
        data = np.zeros(cap, phys)
        data[:n] = np_vals.astype(phys, copy=False)
    if validity is None and n == cap:
        vhost = None  # fully valid: the device-shared mask stands in
    else:
        vhost = np.zeros(cap, np.bool_)
        vhost[:n] = True if validity is None else validity
    return data, vhost


def _zero_value(dtype: T.DataType):
    if isinstance(dtype, T.BooleanType):
        return False
    if isinstance(dtype, (T.DateType,)):
        import datetime

        return datetime.date(1970, 1, 1)
    if isinstance(dtype, T.TimestampType):
        import datetime

        return datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
    if isinstance(dtype, (T.FloatType, T.DoubleType)):
        return 0.0
    return 0


def _string_host(arr: pa.Array, cap: int
                 ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Decode one string column to (chars[cap,w], lengths[cap],
    validity[cap]) host buffers."""
    n = len(arr)
    sarr = arr.cast(pa.large_string())
    buf_offsets = np.frombuffer(sarr.buffers()[1], dtype=np.int64,
                                count=n + 1, offset=sarr.offset * 8)
    data_buf = sarr.buffers()[2]
    raw = np.frombuffer(data_buf, dtype=np.uint8) if data_buf is not None \
        else np.zeros(0, np.uint8)
    lengths_np = (buf_offsets[1:] - buf_offsets[:-1]).astype(np.int32)
    validity = np.asarray(arr.is_valid()) if arr.null_count else np.ones(
        n, np.bool_)
    lengths_np = np.where(validity, lengths_np, 0).astype(np.int32)
    maxw = int(lengths_np.max()) if n else 0
    w = pad_width(max(maxw, 1))
    chars = np.zeros((cap, w), np.uint8)
    for i in range(n):
        ln = lengths_np[i]
        if ln:
            s = buf_offsets[i]
            chars[i, :ln] = raw[s:s + ln]
    lengths = np.zeros(cap, np.int32)
    lengths[:n] = lengths_np
    valid = np.zeros(cap, np.bool_)
    valid[:n] = validity
    return chars, lengths, valid


def _list_host(arr: pa.Array, dtype: T.ListType, cap: int
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Decode one list<primitive> column to dense host buffers:
    (values[cap, L], lengths[cap], elem_validity[cap, L], validity[cap])."""
    n = len(arr)
    phys = T.to_numpy_dtype(dtype.element)
    larr = arr.cast(pa.large_list(T.to_arrow_type(dtype.element)))
    offsets = np.frombuffer(larr.buffers()[1], dtype=np.int64,
                            count=n + 1, offset=larr.offset * 8)
    flat = larr.values
    if len(flat):
        fv = np.asarray(flat.is_valid()) if flat.null_count \
            else np.ones(len(flat), np.bool_)
        if flat.null_count:
            flat = flat.fill_null(_zero_value(dtype.element))
        if isinstance(dtype.element, T.DateType):
            flat_np = flat.cast(pa.int32()).to_numpy(zero_copy_only=False)
        elif isinstance(dtype.element, T.TimestampType):
            flat_np = flat.cast(pa.int64()).to_numpy(zero_copy_only=False)
        else:
            flat_np = flat.to_numpy(zero_copy_only=False).astype(
                phys, copy=False)
    else:
        flat_np = np.zeros(0, phys)
        fv = np.zeros(0, np.bool_)
    validity = np.asarray(arr.is_valid()) if arr.null_count \
        else np.ones(n, np.bool_)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    lens = np.where(validity, lens, 0).astype(np.int32)
    maxlen = int(lens.max()) if n else 0
    L = pad_width(max(maxlen, 1))
    values = np.zeros((cap, L), phys)
    evalid = np.zeros((cap, L), np.bool_)
    if n:
        idx = offsets[:-1, None] + np.arange(L)[None, :]
        mask = np.arange(L)[None, :] < lens[:, None]
        safe = np.clip(idx, 0, max(len(flat_np) - 1, 0))
        if len(flat_np):
            values[:n] = np.where(mask, flat_np[safe], 0)
            evalid[:n] = mask & fv[safe]
    lengths = np.zeros(cap, np.int32)
    lengths[:n] = lens
    valid = np.zeros(cap, np.bool_)
    valid[:n] = validity
    return values, lengths, evalid, valid


# --------------------------------------------------------------------- #
# Packed upload: one H2D transfer per batch
# --------------------------------------------------------------------- #
# Device links have a per-transfer cost (dispatch + latency; large on
# tunneled/remote PJRT backends), so shipping a scan batch as one packed
# byte buffer + one jitted unpack program beats per-column uploads — the
# single staging-buffer design the reference gets from assembling one
# host buffer per Parquet read (ref: GpuParquetScan.scala:495-560).

_PACKED_UPLOAD = None  # config entry, registered lazily


def _packed_enabled() -> bool:
    global _PACKED_UPLOAD
    if _PACKED_UPLOAD is None:
        from spark_rapids_tpu.config import get_conf, register

        _PACKED_UPLOAD = register(
            "spark.rapids.tpu.sql.scan.packedUpload", True,
            "Ship each scanned batch's column components in one batched "
            "device_put (a single transfer round) instead of one "
            "transfer per component.")
    from spark_rapids_tpu.config import get_conf

    return get_conf().get(_PACKED_UPLOAD)


def _pack_components(comps: list[np.ndarray]) -> tuple[np.ndarray, tuple]:
    layout = []
    total = 0
    for a in comps:
        total = (total + 7) & ~7
        layout.append((total, a.shape, str(a.dtype)))
        total += a.nbytes
    buf = np.zeros(total, np.uint8)
    for a, (off, _, _) in zip(comps, layout):
        buf[off:off + a.nbytes] = np.ascontiguousarray(a).view(
            np.uint8).reshape(-1)
    return buf, tuple(layout)


def _make_unpack(layout: tuple):
    def unpack(buf: jax.Array) -> list[jax.Array]:
        out = []
        for off, shape, dt in layout:
            npdt = np.dtype(dt)
            count = int(np.prod(shape))
            raw = jax.lax.dynamic_slice(buf, (off,),
                                        (count * npdt.itemsize,))
            if npdt == np.uint8:
                col = raw.reshape(shape)
            elif npdt == np.bool_:
                col = (raw.reshape(shape) != 0)
            else:
                col = jax.lax.bitcast_convert_type(
                    raw.reshape(count, npdt.itemsize), npdt).reshape(shape)
            out.append(col)
        return out

    return unpack


def from_arrow(rb: pa.RecordBatch | pa.Table,
               capacity: Optional[int] = None) -> ColumnarBatch:
    """Host Arrow batch -> device ColumnarBatch (the H2D upload)."""
    if isinstance(rb, pa.Table):
        rb = rb.combine_chunks()
        arrays = [
            c.combine_chunks() if isinstance(c, pa.ChunkedArray) else c
            for c in rb.columns
        ]
        arrays = [a.chunk(0) if isinstance(a, pa.ChunkedArray) else a
                  for a in arrays]
        aschema = rb.schema
        n = rb.num_rows
    else:
        arrays = rb.columns
        aschema = rb.schema
        n = rb.num_rows
    schema = schema_from_arrow(aschema)

    if capacity is None and n > 0 and _packed_enabled():
        # encoded single-buffer upload: one device_put + cached unpack
        # program (bias/dict wire encodings, device-side validity synth)
        from spark_rapids_tpu.columnar import transfer

        enc = transfer.encode_for_device(arrays, schema, n)
        if enc is not None:
            comps_list, plan = enc
            cols = transfer.decode_on_device(comps_list, plan, schema)
            return ColumnarBatch(cols, n, schema)

    cap = capacity if capacity is not None else pad_capacity(n)

    # host-decode every column into padded component buffers
    comps: list[np.ndarray] = []
    recipe: list[tuple] = []  # (kind, first-component index, dtype)
    for arr, f in zip(arrays, schema.fields):
        if isinstance(f.dtype, T.StringType):
            chars, lengths, valid = _string_host(arr, cap)
            recipe.append(("str", len(comps), f.dtype))
            comps.extend([chars, lengths, valid])
        elif isinstance(f.dtype, T.ListType):
            values, lengths, evalid, valid = _list_host(arr, f.dtype, cap)
            recipe.append(("list", len(comps), f.dtype))
            comps.extend([values, lengths, evalid, valid])
        else:
            data, vhost = _fixed_host(arr, f.dtype, cap)
            if vhost is None:
                recipe.append(("fixed_shared", len(comps), f.dtype))
                comps.append(data)
            else:
                recipe.append(("fixed", len(comps), f.dtype))
                comps.extend([data, vhost])

    if len(comps) > 1 and _packed_enabled():
        # one batched transfer round for every component (beats a packed
        # staging buffer: no unpack program, and jax batches the copies)
        dev = jax.device_put(comps)
    else:
        dev = [jnp.asarray(a) for a in comps]

    cols: list[AnyColumn] = []
    for kind, i, dtype in recipe:
        if kind == "str":
            cols.append(StringColumn(dev[i], dev[i + 1], dev[i + 2]))
        elif kind == "list":
            cols.append(ListColumn(dev[i], dev[i + 1], dev[i + 2],
                                   dev[i + 3], dtype))
        elif kind == "fixed_shared":
            cols.append(Column(dev[i], all_valid_mask(cap), dtype))
        else:
            cols.append(Column(dev[i], dev[i + 1], dtype))
    return ColumnarBatch(cols, n, schema)


def to_arrow(batch: ColumnarBatch) -> pa.Table:
    """Device ColumnarBatch -> host Arrow table (the D2H download).

    Every device component comes back in ONE batched jax.device_get:
    D2H pays a latency round per call, not per buffer, so sequential
    per-column reads would multiply the transfer latency by the column
    count.  The batch is first SHRUNK on device to its live row count
    (padding rows never cross the wire — a 1-row aggregate result in a
    million-row capacity bucket is a 1-row transfer, not a 100MB one)."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch as _CB

    def _comps_of(b):
        comps: list = []
        for col in b.columns:
            if isinstance(col, ListColumn):
                comps += [col.values, col.lengths, col.elem_validity,
                          col.validity]
            elif isinstance(col, StringColumn):
                comps += [col.chars, col.lengths, col.validity]
            else:
                comps += [col.data, col.validity]
        return comps

    if batch.capacity <= 1024 and not isinstance(batch.num_rows, int):
        # small batch with a device-resident row count (aggregate
        # results, limits): fetch the count WITH the components in one
        # D2H round instead of syncing the count first — each round
        # pays full link latency
        host = jax.device_get([batch.num_rows] + _comps_of(batch))
        n = n_live = int(host[0])
        host = host[1:]
        batch = _CB(batch.columns, n_live, batch.schema)
    else:
        n_live = batch.concrete_num_rows()
        shrunk_cap = max(128, -(-n_live // 128) * 128)
        if shrunk_cap < batch.capacity:
            batch = batch.shrink_to_capacity(shrunk_cap)
        batch = _CB(batch.columns, n_live, batch.schema)
        # ONE batched D2H round for the whole batch
        host = jax.device_get(_comps_of(batch))
        n = n_live

    arrays = []
    ci = 0
    aschema = schema_to_arrow(batch.schema)
    for f, col, afield in zip(batch.schema.fields, batch.columns, aschema):
        if isinstance(col, ListColumn):
            vals, lens, ev, rv = (a[:n] for a in host[ci:ci + 4])
            ci += 4
            pylist = []
            for i in range(n):
                if not rv[i]:
                    pylist.append(None)
                else:
                    m = int(lens[i])
                    pylist.append([
                        vals[i, j].item() if ev[i, j] else None
                        for j in range(m)])
            arrays.append(pa.array(pylist, type=afield.type))
        elif isinstance(col, StringColumn):
            chars, lens, valid = (a[:n] for a in host[ci:ci + 3])
            ci += 3
            pylist = [
                bytes(chars[i, :lens[i]]).decode("utf-8")
                if valid[i] else None
                for i in range(n)
            ]
            arrays.append(pa.array(pylist, type=afield.type))
        else:
            vals = host[ci][:n]
            valid = host[ci + 1][:n]
            ci += 2
            if isinstance(f.dtype, T.DecimalType):
                import decimal

                pylist = [
                    decimal.Decimal(int(vals[i])).scaleb(-f.dtype.scale)
                    if valid[i] else None
                    for i in range(n)
                ]
                arrays.append(pa.array(pylist, type=afield.type))
            else:
                mask = ~valid if (~valid).any() else None
                if isinstance(f.dtype, T.DateType):
                    arrays.append(
                        pa.array(vals.astype("int32"), pa.int32(),
                                 mask=mask).cast(afield.type))
                elif isinstance(f.dtype, T.TimestampType):
                    arrays.append(
                        pa.array(vals.astype("int64"), pa.int64(),
                                 mask=mask).cast(afield.type))
                else:
                    arrays.append(pa.array(vals, type=afield.type, mask=mask))
    return pa.Table.from_arrays(arrays, schema=aschema)
