"""Device-resident columnar batches.

TPU-native counterpart of the reference's Spark `ColumnarBatch` of
GpuColumnVectors (ref: GpuColumnVector.java:571,603) plus the coalescing
machinery of GpuCoalesceBatches (ref: GpuCoalesceBatches.scala:133-455).

Invariants:
- all columns share one static `capacity` (power-of-two bucket);
- valid rows are a *prefix*: rows [0, num_rows) are live, the rest padding;
- `num_rows` may be a Python int (statically known, e.g. straight from a
  scan) or a traced/device int32 scalar (e.g. after a filter).  Operators
  must work with both; host materialization forces a sync.

The prefix-compact invariant is what lets aggregations/sorts/joins run as
fixed-shape XLA programs with a row-activity mask derived from
`arange(capacity) < num_rows`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import (
    AnyColumn,
    Column,
    ListColumn,
    MapColumn,
    StringColumn,
    StructColumn,
    pad_capacity,
    pad_width,
)

RowCount = Union[int, jax.Array]

#: device scalar cache: row counts repeat heavily (full batches, tiny
#: partials) and an eager scalar upload is a full dispatch round trip on
#: high-latency device links, so promote each distinct value once
_DEVICE_INT_CACHE: dict[int, jax.Array] = {}
_DEVICE_INT_LOCK = __import__("threading").Lock()


def _device_int32(v: int) -> jax.Array:
    with _DEVICE_INT_LOCK:
        a = _DEVICE_INT_CACHE.get(v)
        if a is None or a.is_deleted():
            if len(_DEVICE_INT_CACHE) > 4096:
                _DEVICE_INT_CACHE.clear()
            a = _DEVICE_INT_CACHE[v] = jnp.asarray(v, jnp.int32)
        return a


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class ColumnarBatch:
    columns: list[AnyColumn]
    num_rows: RowCount
    schema: T.Schema

    def tree_flatten(self):
        static_rows = self.num_rows if isinstance(self.num_rows, int) else None
        if static_rows is None:
            return (tuple(self.columns), self.num_rows), (None, self.schema)
        return (tuple(self.columns),), (static_rows, self.schema)

    @classmethod
    def tree_unflatten(cls, aux, children):
        static_rows, schema = aux
        if static_rows is None:
            cols, num_rows = children
        else:
            (cols,) = children
            num_rows = static_rows
        return cls(list(cols), num_rows, schema)

    @property
    def capacity(self) -> int:
        if not self.columns:
            return 0
        return self.columns[0].capacity

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def row_mask(self) -> jax.Array:
        """Boolean mask of live rows, shape (capacity,)."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < jnp.asarray(
            self.num_rows, dtype=jnp.int32
        )

    def column(self, i: int) -> AnyColumn:
        return self.columns[i]

    def with_columns(self, columns: Sequence[AnyColumn],
                     schema: T.Schema) -> "ColumnarBatch":
        return ColumnarBatch(list(columns), self.num_rows, schema)

    def concrete_num_rows(self) -> int:
        """Force num_rows to a host int (syncs if it is a device scalar)."""
        n = self.num_rows
        return n if isinstance(n, int) else int(jax.device_get(n))

    def with_device_num_rows(self) -> "ColumnarBatch":
        """Promote a Python-int num_rows to a device scalar so jitted
        pipelines key their compile cache on capacity only (a static int
        lives in pytree aux data and would recompile per distinct ragged
        tail count)."""
        if not isinstance(self.num_rows, int):
            return self
        return ColumnarBatch(self.columns,
                             _device_int32(self.num_rows),
                             self.schema)

    # ------------------------------------------------------------------ #
    # Construction / host interop
    # ------------------------------------------------------------------ #

    @staticmethod
    def empty(schema: T.Schema) -> "ColumnarBatch":
        """Zero-row batch of a schema (minimum capacity bucket)."""
        data = {
            f.name: np.array(
                [], dtype=object if isinstance(f.dtype, T.StringType)
                else T.to_numpy_dtype(f.dtype))
            for f in schema.fields}
        return ColumnarBatch.from_numpy(data, schema)

    @staticmethod
    def from_numpy(data: dict[str, np.ndarray],
                   schema: T.Schema,
                   validity: Optional[dict[str, np.ndarray]] = None,
                   capacity: Optional[int] = None) -> "ColumnarBatch":
        validity = validity or {}
        n = len(next(iter(data.values()))) if data else 0
        cap = capacity if capacity is not None else pad_capacity(n)
        cols: list[AnyColumn] = []
        for f in schema.fields:
            vals = data[f.name]
            if isinstance(f.dtype, T.StringType):
                cols.append(StringColumn.from_list(list(vals), capacity=cap))
                if f.name in validity:
                    sc = cols[-1]
                    v = np.zeros(cap, np.bool_)
                    v[:n] = validity[f.name]
                    cols[-1] = sc.with_validity(jnp.asarray(v))
            else:
                cols.append(
                    Column.from_numpy(vals, f.dtype,
                                      validity.get(f.name), capacity=cap)
                )
        return ColumnarBatch(cols, n, schema)

    def to_pydict(self) -> dict[str, list]:
        """Host materialization (syncs). NULLs become None."""
        n = self.concrete_num_rows()
        out: dict[str, list] = {}
        for f, col in zip(self.schema.fields, self.columns):
            out[f.name] = _col_to_pylist(col, f.dtype, n)
        return out

    # ------------------------------------------------------------------ #
    # Batch surgery
    # ------------------------------------------------------------------ #

    def gather(self, indices: jax.Array, num_rows: RowCount,
               index_valid: Optional[jax.Array] = None) -> "ColumnarBatch":
        cols = [c.gather(indices, index_valid) for c in self.columns]
        return ColumnarBatch(cols, num_rows, self.schema)

    def compact(self, keep: jax.Array) -> "ColumnarBatch":
        """Keep rows where `keep` is True, preserving order; result is
        prefix-compact with a traced num_rows.  This is the XLA equivalent
        of cudf's filter/gather (ref: basicPhysicalOperators.scala:230):
        a cumsum ranks the kept rows and a searchsorted inverts that rank
        into gather indices — O(n) scan + O(n log n) vectorized binary
        search, much cheaper than the full stable argsort it replaces
        (filters are the hottest op in the engine)."""
        keep = keep & self.row_mask()
        csum = jnp.cumsum(keep.astype(jnp.int32))
        n = csum[-1]
        # output slot j takes the row where csum first reaches j+1
        src = jnp.searchsorted(
            csum, jnp.arange(self.capacity, dtype=jnp.int32) + 1,
            side="left").astype(jnp.int32)
        src = jnp.minimum(src, self.capacity - 1)
        cols = [c.gather(src) for c in self.columns]
        # rows past n are garbage; invalidate them so padding stays NULL
        live = jnp.arange(self.capacity, dtype=jnp.int32) < n
        cols = [c.with_validity(c.validity & live) for c in cols]
        return ColumnarBatch(cols, n, self.schema)

    def shrink_to_capacity(self, new_cap: int) -> "ColumnarBatch":
        """Re-bucket to a smaller capacity (cheap device slice).  Callers
        must know num_rows <= new_cap (i.e. after a concrete_num_rows
        sync).  Keeps downstream programs (exchange splits, concats,
        merges) sized to the data instead of the producer's input bucket —
        e.g. a grand-aggregate partial is 1 live row in a million-row
        bucket without this."""
        if not self.columns or new_cap >= self.capacity:
            return self
        cols = [_shrink_col(c, new_cap) for c in self.columns]
        return ColumnarBatch(cols, self.num_rows, self.schema)

    def slice_prefix(self, n: RowCount) -> "ColumnarBatch":
        """Logically truncate to the first n rows (no data movement)."""
        if isinstance(n, int) and isinstance(self.num_rows, int):
            new_n: RowCount = min(n, self.num_rows)
        else:
            new_n = jnp.minimum(jnp.asarray(n, jnp.int32),
                                jnp.asarray(self.num_rows, jnp.int32))
        live = jnp.arange(self.capacity, dtype=jnp.int32) < jnp.asarray(
            new_n, jnp.int32)
        cols = [c.with_validity(c.validity & live) for c in self.columns]
        return ColumnarBatch(cols, new_n, self.schema)


def _col_to_pylist(col, dtype: T.DataType, n: int) -> list:
    """One column -> python values (recursive; host sync per leaf)."""
    if isinstance(col, StringColumn):
        return col.to_list(n)
    if isinstance(col, StructColumn):
        valid = np.asarray(col.validity)[:n]
        kids = [_col_to_pylist(c, f.dtype, n)
                for c, f in zip(col.children, dtype.fields)]
        names = [f.name for f in dtype.fields]
        return [dict(zip(names, vals)) if valid[i] else None
                for i, vals in enumerate(zip(*kids))] if kids else \
            [{} if v else None for v in valid]
    if isinstance(col, MapColumn):
        keys = np.asarray(col.keys)[:n]
        vals = np.asarray(col.values)[:n]
        ev = np.asarray(col.entry_validity)[:n]
        lens = np.asarray(col.lengths)[:n]
        valid = np.asarray(col.validity)[:n]
        out = []
        for i in range(n):
            if not valid[i]:
                out.append(None)
            else:
                m = int(lens[i])
                out.append({keys[i, j].item():
                            (vals[i, j].item() if ev[i, j] else None)
                            for j in range(m)})
        return out
    if isinstance(col, ListColumn):
        vals = np.asarray(col.values)[:n]
        ev = np.asarray(col.elem_validity)[:n]
        lens = np.asarray(col.lengths)[:n]
        valid = np.asarray(col.validity)[:n]
        return [[vals[i, j].item() if ev[i, j] else None
                 for j in range(int(lens[i]))] if valid[i] else None
                for i in range(n)]
    vals = np.asarray(col.data)[:n]
    valid = np.asarray(col.validity)[:n]
    return [(vals[i].item() if valid[i] else None) for i in range(n)]


def _shrink_col(c: AnyColumn, new_cap: int) -> AnyColumn:
    """Slice a column to a smaller capacity (recursive for nesting)."""
    if isinstance(c, StringColumn):
        return StringColumn(
            c.chars[:new_cap], c.lengths[:new_cap], c.validity[:new_cap],
            c.dtype,
            c.codes[:new_cap] if c.codes is not None else None,
            c.dict_chars, c.dict_lens, c.dict_len)
    if isinstance(c, ListColumn):
        return ListColumn(c.values[:new_cap], c.lengths[:new_cap],
                          c.elem_validity[:new_cap],
                          c.validity[:new_cap], c.dtype)
    if isinstance(c, StructColumn):
        return StructColumn(
            tuple(_shrink_col(k, new_cap) for k in c.children),
            c.validity[:new_cap], c.dtype)
    if isinstance(c, MapColumn):
        return MapColumn(c.keys[:new_cap], c.values[:new_cap],
                         c.entry_validity[:new_cap], c.lengths[:new_cap],
                         c.validity[:new_cap], c.dtype)
    return Column(c.data[:new_cap], c.validity[:new_cap], c.dtype,
                  c.codes[:new_cap] if c.codes is not None else None,
                  c.dict_values, c.dict_len)


def concat_batches(batches: Sequence[ColumnarBatch]) -> ColumnarBatch:
    """Concatenate batches of one schema into a single larger batch.

    TPU analog of GpuCoalesceBatches' cudf Table.concatenate
    (ref: GpuCoalesceBatches.scala:340).  Row counts must be concrete
    (host-side sizing decision, like the reference's coalesce goal
    logic), but the data never leaves the device: each part is packed
    into the output with dynamic_update_slice — no host round trip."""
    assert batches, "concat of zero batches"
    schema = batches[0].schema
    ns = [b.concrete_num_rows() for b in batches]
    total = sum(ns)
    cap = pad_capacity(total)
    out_cols: list[AnyColumn] = []
    for ci, f in enumerate(schema.fields):
        parts = [b.columns[ci] for b in batches]
        out_cols.append(_concat_cols(parts, ns, cap, f.dtype))
    return ColumnarBatch(out_cols, total, schema)


def concat_batches_traced(batches: Sequence[ColumnarBatch]
                          ) -> Optional[ColumnarBatch]:
    """Concatenate small batches WITHOUT host row counts: stack every
    part at full capacity, then compact the dead interior rows inside
    the program, yielding a prefix-compact batch with a traced total.

    This is the sizing-sync-free sibling of concat_batches: on
    high-latency device links each host sizing fetch costs a full D2H
    round trip, which dominates small-partial pipelines (aggregate
    partials are a few hundred rows in <=4K-capacity buckets).  The
    compact pays O(total_cap log total_cap) device work — trivial at
    these sizes, never worth it for scan-sized batches.

    Returns None when a column kind has no stacked form yet (nested
    types) — callers fall back to the host-pinned path."""
    schema = batches[0].schema
    caps = [b.capacity for b in batches]
    out_cols: list[AnyColumn] = []
    for ci, f in enumerate(schema.fields):
        parts = [b.columns[ci] for b in batches]
        if isinstance(f.dtype, T.StringType):
            w = pad_width(max(p.width for p in parts))
            chars = jnp.concatenate(
                [jnp.pad(p.chars, ((0, 0), (0, w - p.width)))
                 if p.width < w else p.chars for p in parts])
            lengths = jnp.concatenate(
                [p.lengths.astype(jnp.int32) for p in parts])
            valid = jnp.concatenate([p.validity for p in parts])
            out_cols.append(StringColumn(chars, lengths, valid))
        elif isinstance(f.dtype, (T.ListType, T.StructType, T.MapType)):
            return None
        else:
            phys = T.to_numpy_dtype(f.dtype)
            data = jnp.concatenate(
                [p.data.astype(phys) for p in parts])
            valid = jnp.concatenate([p.validity for p in parts])
            out_cols.append(Column(data, valid, f.dtype))
    keep = jnp.concatenate([b.row_mask() for b in batches])
    stacked = ColumnarBatch(out_cols, sum(caps), schema)
    return stacked.compact(keep)


def _concat_cols(parts: list, ns: list[int], cap: int,
                 dtype: T.DataType) -> AnyColumn:
    """Concatenate column parts into one capacity-`cap` column
    (recursive for nested types)."""
    f = T.Field("_", dtype)
    if isinstance(f.dtype, T.StructType):
        valid = jnp.zeros(cap, jnp.bool_)
        off = 0
        for p, n in zip(parts, ns):
            if n == 0:
                continue
            valid = jax.lax.dynamic_update_slice(
                valid, p.validity[:n], (off,))
            off += n
        kids = tuple(
            _concat_cols([p.children[i] for p in parts], ns, cap,
                         cf.dtype)
            for i, cf in enumerate(f.dtype.fields))
        return StructColumn(kids, valid, f.dtype)
    if isinstance(f.dtype, T.MapType):
        kphys = T.to_numpy_dtype(f.dtype.key)
        vphys = T.to_numpy_dtype(f.dtype.value)
        L = max(p.max_len for p in parts)
        keys = jnp.zeros((cap, L), kphys)
        values = jnp.zeros((cap, L), vphys)
        evalid = jnp.zeros((cap, L), jnp.bool_)
        lengths = jnp.zeros(cap, jnp.int32)
        valid = jnp.zeros(cap, jnp.bool_)
        off = 0
        for p, n in zip(parts, ns):
            if n == 0:
                continue
            pk, pv, pe = p.keys[:n], p.values[:n], \
                p.entry_validity[:n]
            if p.max_len < L:
                pad = ((0, 0), (0, L - p.max_len))
                pk, pv, pe = (jnp.pad(x, pad) for x in (pk, pv, pe))
            keys = jax.lax.dynamic_update_slice(keys, pk, (off, 0))
            values = jax.lax.dynamic_update_slice(values, pv,
                                                  (off, 0))
            evalid = jax.lax.dynamic_update_slice(evalid, pe,
                                                  (off, 0))
            lengths = jax.lax.dynamic_update_slice(
                lengths, p.lengths[:n].astype(jnp.int32), (off,))
            valid = jax.lax.dynamic_update_slice(
                valid, p.validity[:n], (off,))
            off += n
        return MapColumn(keys, values, evalid, lengths, valid,
                         f.dtype)
    if isinstance(f.dtype, T.ListType):
        phys = T.to_numpy_dtype(f.dtype.element)
        L = max(p.max_len for p in parts)  # type: ignore[union-attr]
        values = jnp.zeros((cap, L), phys)
        lengths = jnp.zeros(cap, jnp.int32)
        evalid = jnp.zeros((cap, L), jnp.bool_)
        valid = jnp.zeros(cap, jnp.bool_)
        off = 0
        for p, n in zip(parts, ns):
            if n == 0:
                continue
            pv, pe = p.values[:n], p.elem_validity[:n]
            if p.max_len < L:
                pv = jnp.pad(pv, ((0, 0), (0, L - p.max_len)))
                pe = jnp.pad(pe, ((0, 0), (0, L - p.max_len)))
            values = jax.lax.dynamic_update_slice(values, pv, (off, 0))
            evalid = jax.lax.dynamic_update_slice(evalid, pe, (off, 0))
            lengths = jax.lax.dynamic_update_slice(
                lengths, p.lengths[:n].astype(jnp.int32), (off,))
            valid = jax.lax.dynamic_update_slice(
                valid, p.validity[:n], (off,))
            off += n
        return ListColumn(values, lengths, evalid, valid, f.dtype)
    if isinstance(f.dtype, T.StringType):
        w = pad_width(max(p.width for p in parts))  # type: ignore[union-attr]
        chars = jnp.zeros((cap, w), jnp.uint8)
        lengths = jnp.zeros(cap, jnp.int32)
        valid = jnp.zeros(cap, jnp.bool_)
        off = 0
        for p, n in zip(parts, ns):
            if n == 0:
                continue
            pc = p.chars[:n]
            if p.width < w:
                pc = jnp.pad(pc, ((0, 0), (0, w - p.width)))
            chars = jax.lax.dynamic_update_slice(chars, pc, (off, 0))
            lengths = jax.lax.dynamic_update_slice(
                lengths, p.lengths[:n].astype(jnp.int32), (off,))
            valid = jax.lax.dynamic_update_slice(
                valid, p.validity[:n], (off,))
            off += n
        return StringColumn(chars, lengths, valid)
    phys = T.to_numpy_dtype(f.dtype)
    data = jnp.zeros(cap, phys)
    valid = jnp.zeros(cap, jnp.bool_)
    off = 0
    for p, n in zip(parts, ns):
        if n == 0:
            continue
        data = jax.lax.dynamic_update_slice(
            data, p.data[:n].astype(phys), (off,))
        valid = jax.lax.dynamic_update_slice(
            valid, p.validity[:n], (off,))
        off += n
    return Column(data, valid, f.dtype)
