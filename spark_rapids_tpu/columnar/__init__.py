from spark_rapids_tpu.columnar.column import Column, StringColumn, pad_capacity
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar import arrow as arrow_interop  # noqa: F401

__all__ = ["Column", "StringColumn", "ColumnarBatch", "pad_capacity"]
