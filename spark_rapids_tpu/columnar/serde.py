"""Batch serializer with optional compression.

TPU analog of the reference's batch serialization layer
(GpuColumnarBatchSerializer.scala + the nvcomp codec integration,
RapidsConf.scala spark.rapids.shuffle.compression.codec): host-side
column component dicts <-> a single framed byte stream, used by the
disk spill tier and any future network shuffle transport.

Format: MAGIC | version | codec | json header (names, dtypes, shapes)
| concatenated (possibly compressed) buffers.  Codecs resolve through
the shared wire-codec registry (columnar/compression/ — byte codecs:
none, zlib; zstd/lz4 are not in this image, zlib is the stdlib
stand-in), so TCP shuffle and the spill tiers report through the same
per-codec stats surface as the H2D tunnel."""

from __future__ import annotations

import json
import struct

import numpy as np

from spark_rapids_tpu.config import get_conf, register

_MAGIC = b"TPUB"
_VERSION = 1

SHUFFLE_COMPRESSION = register(
    "spark.rapids.tpu.shuffle.compression.codec", "none",
    "Codec for shuffle payloads crossing the TCP block transport: "
    "'none' or 'zlib' (ref: the reference compresses shuffle buffers "
    "on device via nvcomp, NvcompLZ4CompressionCodec.scala:25, conf "
    "spark.rapids.shuffle.compression.codec RapidsConf.scala:905; "
    "this engine's transport is host-side, so the codec runs on the "
    "serialized frame).")

SPILL_COMPRESSION = register(
    "spark.rapids.tpu.memory.spill.compression.codec", "none",
    "Codec for the disk spill tier: 'none' or 'zlib' (ref: "
    "spark.rapids.shuffle.compression.codec, RapidsConf.scala:905).")


def serialize_arrays(arrays: dict, codec: str = "none") -> bytes:
    """Host component dict (str -> np.ndarray) -> framed bytes.  The
    codec resolves through the shared registry (byte form), which also
    accounts raw-vs-wire bytes per codec."""
    from spark_rapids_tpu.columnar import compression as WC
    from spark_rapids_tpu.memory.device_manager import HostBufferPool

    bytes_codec = WC.get_bytes_codec(codec)

    header = []
    items = []
    total = 0
    for name, a in arrays.items():
        a = np.ascontiguousarray(a)
        header.append({"name": name, "dtype": a.dtype.str,
                       "shape": list(a.shape), "nbytes": a.nbytes})
        items.append(a)
        total += a.nbytes
    # one recycled staging buffer instead of a tobytes() copy per
    # array (the pinned-host-pool analog; spill writes are synchronous
    # so the buffer can return to the pool immediately)
    pool = HostBufferPool.get()
    staging = pool.take(max(total, 1))
    off = 0
    for a in items:
        staging[off: off + a.nbytes] = a.view(np.uint8).reshape(-1)
        off += a.nbytes
    body = bytes(staging[:total])
    pool.give(staging)
    body = bytes_codec.compress_bytes(body)
    WC.record_compress(codec, total, len(body))
    hjson = json.dumps({"cols": header, "codec": codec}).encode()
    return b"".join([
        _MAGIC, struct.pack("<HH", _VERSION, 0),  # version, reserved
        struct.pack("<I", len(hjson)), hjson, body,
    ])


def deserialize_arrays(data: bytes) -> dict:
    """Framed bytes -> host component dict."""
    if data[:4] != _MAGIC:
        raise ValueError("not a serialized batch (bad magic)")
    (version, _), = [struct.unpack("<HH", data[4:8])]
    if version != _VERSION:
        raise ValueError(f"unsupported batch version {version}")
    (hlen,) = struct.unpack("<I", data[8:12])
    meta = json.loads(data[12:12 + hlen].decode())
    body = data[12 + hlen:]
    from spark_rapids_tpu.columnar import compression as WC

    body = WC.get_bytes_codec(meta["codec"]).decompress_bytes(body)
    WC.record_decompress(meta["codec"])
    out = {}
    off = 0
    for c in meta["cols"]:
        n = c["nbytes"]
        a = np.frombuffer(body, dtype=np.dtype(c["dtype"]),
                          count=n // np.dtype(c["dtype"]).itemsize,
                          offset=off).reshape(c["shape"])
        out[c["name"]] = a
        off += n
    return out


def spill_codec() -> str:
    """Read ONLY at store construction: spills run on worker threads
    whose thread-local conf is not the user's session conf."""
    return get_conf().get(SPILL_COMPRESSION)


def write_spill_file(path: str, arrays: dict,
                     codec: str = "none") -> None:
    with open(path, "wb") as f:
        f.write(serialize_arrays(arrays, codec))


def read_spill_file(path: str) -> dict:
    with open(path, "rb") as f:
        return deserialize_arrays(f.read())
