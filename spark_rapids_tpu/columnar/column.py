"""Device-resident columns.

TPU-native counterpart of the reference's GpuColumnVector
(ref: sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java).
cudf stores variable-length row counts and offset-encoded strings; XLA wants
static shapes, so the design here is different by construction:

- every column in a batch is padded to the batch *capacity* (a power-of-two
  bucket) so the per-operator XLA programs are compiled once per bucket and
  reused (the reference instead re-launches dynamically-shaped kernels);
- SQL NULLs are a boolean `validity` array (True = valid), matching the
  semantics (not the bit-packing) of Arrow/cudf validity buffers;
- strings are a fixed-width `(capacity, width)` uint8 byte matrix plus an
  int32 `lengths` array.  `width` is the max byte length in the batch,
  rounded up to a small bucket for compile-cache stability.

Columns are registered as JAX pytrees so whole batches can flow through
`jax.jit` / `shard_map` directly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import get_conf, register

ArrayLike = Union[jax.Array, np.ndarray]

#: minimum capacity bucket; keeps tiny test batches from fragmenting the
#: compile cache.
MIN_CAPACITY = 8

#: string width buckets (bytes)
_WIDTH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)

CAPACITY_POLICY = register(
    "spark.rapids.tpu.sql.capacity.policy", "pow2",
    "Capacity bucket policy.  'pow2' (default) rounds row counts up to "
    "the next power of two; 'pow2x3' additionally admits 3*pow2/2 "
    "intermediate buckets (12, 24, 48, ...) when the pow2 bucket's "
    "live-row ratio would fall below "
    "spark.rapids.tpu.sql.capacity.liveRatioFloor, halving worst-case "
    "pad waste from ~2x to ~4/3x.  At most one extra bucket per octave, "
    "so the compile-cache key space stays bounded.  Results are "
    "bit-identical under either policy: capacity only controls how many "
    "pad rows a program carries.",
    check=lambda v: v in ("pow2", "pow2x3"))
CAPACITY_LIVE_RATIO_FLOOR = register(
    "spark.rapids.tpu.sql.capacity.liveRatioFloor", 0.75,
    "Under capacity.policy=pow2x3: a batch whose live/capacity ratio in "
    "its pow2 bucket would be below this floor drops to the 3*pow2/2 "
    "bucket instead (when it fits).  0.75 re-buckets every batch that "
    "fits the intermediate bucket; lower values re-bucket only sparser "
    "batches; values below 0.5 disable re-bucketing (pow2 buckets "
    "already guarantee ratio > 1/2).",
    check=lambda v: 0.0 <= v <= 1.0)


def pad_capacity(n: int) -> int:
    """Round a row count up to its capacity bucket.

    Default policy is next-power-of-two.  Under capacity.policy=pow2x3
    an intermediate 3*pow2/2 bucket (12, 24, 48, ...) is chosen when the
    pow2 bucket would leave the live ratio below the configured floor —
    e.g. 5 of 8 rows live (0.625) re-buckets to 6 (0.83 live).  The
    policy is read per call (host-side, thread-local dict get) so tests
    can flip it; programs are keyed by the resulting capacity either
    way, so mixing policies in one process is safe, just cache-wasteful.
    """
    c = MIN_CAPACITY
    while c < n:
        c <<= 1
    if c > MIN_CAPACITY and n > 0:
        conf = get_conf()
        if conf.get(CAPACITY_POLICY) == "pow2x3":
            mid = 3 * (c >> 2)  # the 3*pow2/2 bucket between c/2 and c
            if n <= mid and n / c <= conf.get(CAPACITY_LIVE_RATIO_FLOOR):
                return mid
    return c


def pad_width(w: int) -> int:
    for b in _WIDTH_BUCKETS:
        if w <= b:
            return b
    return ((w + 4095) // 4096) * 4096


#: process-shared device all-True masks, one per capacity bucket.  Fully
#: valid columns reference these instead of uploading per-batch bool
#: arrays; the spill store must never .delete() them (is_shared_array).
_SHARED_MASKS: dict[int, jax.Array] = {}
_SHARED_LOCK = __import__("threading").Lock()


def all_valid_mask(cap: int) -> jax.Array:
    with _SHARED_LOCK:
        m = _SHARED_MASKS.get(cap)
        if m is None or m.is_deleted():
            m = _SHARED_MASKS[cap] = jnp.ones(cap, jnp.bool_)
        return m


#: device arrays CURRENTLY shared across consumers (the work-sharing
#: tier's shared scan batches, serving/work_share.py): id -> weakref.
#: Identity-keyed with a GC callback, so a recycled id can never alias
#: a dead shared array onto a fresh private one.  Spilling a shared
#: array copies it to host but must never .delete() the device copy —
#: another query may be mid-compute over the same buffers; release
#: defers to the last Python reference instead.
_SHARED_ARRAYS: dict[int, object] = {}


def mark_shared_array(a) -> None:
    """Register one device array as cross-consumer shared (see
    _SHARED_ARRAYS).  Idempotent; non-arrays are ignored."""
    import weakref as _weakref

    if not isinstance(a, jax.Array):
        return
    key = id(a)
    try:
        ref = _weakref.ref(
            a, lambda _r, _k=key: _SHARED_ARRAYS.pop(_k, None))
    except TypeError:
        return
    with _SHARED_LOCK:
        _SHARED_ARRAYS[key] = ref


def is_shared_array(a) -> bool:
    """True for process-shared immortal arrays (spill must not delete)."""
    with _SHARED_LOCK:
        if any(m is a for m in _SHARED_MASKS.values()):
            return True
        ref = _SHARED_ARRAYS.get(id(a))
        return ref is not None and ref() is a


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Column:
    """A fixed-width device column: `data[capacity]` + `validity[capacity]`.

    Rows past the owning batch's `num_rows` are padding with arbitrary data
    and validity False.
    """

    data: ArrayLike
    validity: ArrayLike
    dtype: T.DataType

    #: Optional dictionary sidecar, populated when the wire encoder
    #: shipped this fixed-width column dict-encoded
    #: (columnar/transfer.py "dict"): `codes[capacity]` (0 on
    #: null/padding rows) indexing the device-resident
    #: `dict_values[k]`.  Mirrors StringColumn's sidecar: the coded
    #: group-by (ops/groupby.py) uses codes as dense group ids for
    #: low-cardinality INTEGER/FLOAT keys, replacing the device
    #: lexsort.  Ops that cannot cheaply preserve it drop it; it is a
    #: hint, never a requirement.
    codes: Optional[ArrayLike] = None
    dict_values: Optional[ArrayLike] = None
    #: tight upper bound on the TRUE dictionary entry count
    #: (`dict_values` is padded to its pow2 capacity bucket by the
    #: wire; this is bucketed to a multiple of 16 so jit treedefs do
    #: not fragment per exact cardinality).  Consumers sizing code
    #: domains must use this, not the padded shape.  Static aux data:
    #: it survives jit boundaries alongside dtype.
    dict_len: Optional[int] = None

    def tree_flatten(self):
        return (self.data, self.validity, self.codes,
                self.dict_values), (self.dtype, self.dict_len)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, validity, codes, dvals = children
        return cls(data, validity, aux[0], codes, dvals, aux[1])

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    def with_validity(self, validity: ArrayLike) -> "Column":
        # codes describe data, not validity: the sidecar survives
        return dataclasses.replace(self, validity=validity)

    def gather(self, indices: ArrayLike, index_valid: Optional[ArrayLike] = None
               ) -> "Column":
        """Take rows by index; out-of-range/invalid indices produce NULLs.
        A dictionary sidecar rides along (codes gather like data)."""
        idx = jnp.clip(indices, 0, self.capacity - 1)
        data = jnp.take(self.data, idx, axis=0)
        validity = jnp.take(self.validity, idx, axis=0)
        if index_valid is not None:
            validity = validity & index_valid
        codes = None if self.codes is None \
            else jnp.take(self.codes, idx, axis=0)
        return Column(data, validity, self.dtype, codes,
                      self.dict_values, self.dict_len)

    @staticmethod
    def from_numpy(values: np.ndarray, dtype: T.DataType,
                   validity: Optional[np.ndarray] = None,
                   capacity: Optional[int] = None) -> "Column":
        n = len(values)
        cap = capacity if capacity is not None else pad_capacity(n)
        phys = T.to_numpy_dtype(dtype)
        data = np.zeros(cap, dtype=phys)
        data[:n] = values.astype(phys, copy=False)
        valid = np.zeros(cap, dtype=np.bool_)
        if validity is None:
            valid[:n] = True
        else:
            valid[:n] = validity
        return Column(jnp.asarray(data), jnp.asarray(valid), dtype)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class StringColumn:
    """Fixed-width string column: `chars[capacity, width]` uint8 +
    `lengths[capacity]` int32 + `validity[capacity]`.

    Bytes past a row's length are zero.  This is the TPU answer to cudf's
    offset+chars layout: every string op becomes a dense 2-D vectorized op
    on the MXU/VPU instead of a ragged traversal.
    """

    chars: ArrayLike
    lengths: ArrayLike
    validity: ArrayLike

    dtype: T.DataType = dataclasses.field(default_factory=lambda: T.STRING)

    #: Optional dictionary sidecar, populated when the wire encoder
    #: shipped this column dict-encoded (columnar/transfer.py "sdict"):
    #: `codes[capacity]` int32 (0 on null/padding rows), plus the
    #: device-resident dictionary `dict_chars[k, w]` / `dict_lens[k]`.
    #: The group-by coded fast path (ops/groupby.py) uses the codes as
    #: dense group ids, skipping the O(n log n) lexsort entirely.  Ops
    #: that cannot cheaply preserve the sidecar (concat, expression
    #: results) drop it; consumers must treat it as a hint, never a
    #: requirement.
    codes: Optional[ArrayLike] = None
    dict_chars: Optional[ArrayLike] = None
    dict_lens: Optional[ArrayLike] = None
    #: tight (16-bucketed) upper bound on the TRUE dictionary entry
    #: count (dict_chars/dict_lens are padded to their pow2 capacity
    #: bucket by the wire); domain sizing must use this.
    dict_len: Optional[int] = None

    def tree_flatten(self):
        return (self.chars, self.lengths, self.validity, self.codes,
                self.dict_chars, self.dict_lens), (self.dtype,
                                                   self.dict_len)

    @classmethod
    def tree_unflatten(cls, aux, children):
        chars, lengths, validity, codes, dchars, dlens = children
        return cls(chars, lengths, validity, aux[0], codes, dchars,
                   dlens, aux[1])

    @property
    def capacity(self) -> int:
        return int(self.chars.shape[0])

    @property
    def width(self) -> int:
        return int(self.chars.shape[1])

    def with_validity(self, validity: ArrayLike) -> "StringColumn":
        return dataclasses.replace(self, validity=validity)

    def gather(self, indices: ArrayLike, index_valid: Optional[ArrayLike] = None
               ) -> "StringColumn":
        idx = jnp.clip(indices, 0, self.capacity - 1)
        chars = jnp.take(self.chars, idx, axis=0)
        lengths = jnp.take(self.lengths, idx, axis=0)
        validity = jnp.take(self.validity, idx, axis=0)
        if index_valid is not None:
            validity = validity & index_valid
        # per-row codes follow the gather; the dictionary is row-invariant
        codes = (jnp.take(self.codes, idx, axis=0)
                 if self.codes is not None else None)
        return StringColumn(chars, lengths, validity, self.dtype,
                            codes, self.dict_chars, self.dict_lens,
                            self.dict_len)

    @staticmethod
    def from_list(values: list[Optional[str]],
                  capacity: Optional[int] = None,
                  width: Optional[int] = None) -> "StringColumn":
        n = len(values)
        cap = capacity if capacity is not None else pad_capacity(n)
        encoded = [v.encode("utf-8") if v is not None else b"" for v in values]
        maxw = max((len(b) for b in encoded), default=0)
        w = width if width is not None else pad_width(max(maxw, 1))
        chars = np.zeros((cap, w), dtype=np.uint8)
        lengths = np.zeros(cap, dtype=np.int32)
        valid = np.zeros(cap, dtype=np.bool_)
        for i, (b, v) in enumerate(zip(encoded, values)):
            chars[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
            lengths[i] = len(b)
            valid[i] = v is not None
        return StringColumn(jnp.asarray(chars), jnp.asarray(lengths),
                            jnp.asarray(valid))

    def to_list(self, num_rows: int) -> list[Optional[str]]:
        chars = np.asarray(self.chars)[:num_rows]
        lengths = np.asarray(self.lengths)[:num_rows]
        valid = np.asarray(self.validity)[:num_rows]
        out: list[Optional[str]] = []
        for i in range(num_rows):
            if not valid[i]:
                out.append(None)
            else:
                out.append(bytes(chars[i, : lengths[i]]).decode("utf-8"))
        return out


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class ListColumn:
    """Fixed-width list column: `values[capacity, max_len]` +
    `lengths[capacity]` int32 + per-element `elem_validity` + per-row
    `validity`.

    The dense-matrix answer to ragged arrays (the StringColumn pattern
    applied to list<primitive>): cudf's offsets+child layout is a ragged
    traversal, XLA wants one static 2-D shape — explode becomes a
    flatten+compact, element access a column gather."""

    values: ArrayLike          # (capacity, max_len) element physical type
    lengths: ArrayLike         # (capacity,) int32
    elem_validity: ArrayLike   # (capacity, max_len) bool
    validity: ArrayLike        # (capacity,) bool — row-level NULL
    dtype: T.DataType = dataclasses.field(
        default_factory=lambda: T.ListType(T.LONG))

    def tree_flatten(self):
        return ((self.values, self.lengths, self.elem_validity,
                 self.validity), (self.dtype,))

    @classmethod
    def tree_unflatten(cls, aux, children):
        values, lengths, elem_validity, validity = children
        return cls(values, lengths, elem_validity, validity, aux[0])

    @property
    def capacity(self) -> int:
        return int(self.values.shape[0])

    @property
    def max_len(self) -> int:
        return int(self.values.shape[1])

    def with_validity(self, validity: ArrayLike) -> "ListColumn":
        return ListColumn(self.values, self.lengths, self.elem_validity,
                          validity, self.dtype)

    def gather(self, indices: ArrayLike,
               index_valid: Optional[ArrayLike] = None) -> "ListColumn":
        idx = jnp.clip(indices, 0, self.capacity - 1)
        validity = jnp.take(self.validity, idx, axis=0)
        if index_valid is not None:
            validity = validity & index_valid
        return ListColumn(jnp.take(self.values, idx, axis=0),
                          jnp.take(self.lengths, idx, axis=0),
                          jnp.take(self.elem_validity, idx, axis=0),
                          validity, self.dtype)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class StructColumn:
    """Struct-of-columns: one child AnyColumn per field + row validity.

    The TPU answer to cudf's nested column hierarchy (ref:
    GpuColumnVector's nested support + TypeChecks.scala:129): children
    recurse through the same column protocol, so gather/concat/spill
    machinery needs no special cases beyond dispatch."""

    children: tuple   # of AnyColumn, one per struct field
    validity: ArrayLike
    dtype: T.DataType = dataclasses.field(
        default_factory=lambda: T.StructType([]))

    def tree_flatten(self):
        return (tuple(self.children), self.validity), (self.dtype,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        kids, validity = children
        return cls(tuple(kids), validity, aux[0])

    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    def with_validity(self, validity: ArrayLike) -> "StructColumn":
        return StructColumn(self.children, validity, self.dtype)

    def gather(self, indices: ArrayLike,
               index_valid: Optional[ArrayLike] = None) -> "StructColumn":
        validity = jnp.take(self.validity,
                            jnp.clip(indices, 0, self.capacity - 1),
                            axis=0)
        if index_valid is not None:
            validity = validity & index_valid
        return StructColumn(
            tuple(c.gather(indices, index_valid) for c in self.children),
            validity, self.dtype)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class MapColumn:
    """map<k,v> as two aligned dense list matrices sharing lengths:
    `keys[capacity, max_len]`, `values[capacity, max_len]`, per-slot
    `entry_validity` for values (map keys are non-null by SQL rules),
    `lengths[capacity]`, row `validity` (ref: GpuGetMapValue,
    complexTypeExtractors.scala — cudf walks list<struct<k,v>>; the
    dense twin-matrix form makes lookup one vectorized compare)."""

    keys: ArrayLike            # (capacity, max_len) key physical type
    values: ArrayLike          # (capacity, max_len) value physical type
    entry_validity: ArrayLike  # (capacity, max_len) value-slot validity
    lengths: ArrayLike         # (capacity,) int32
    validity: ArrayLike        # (capacity,) bool
    dtype: T.DataType = dataclasses.field(
        default_factory=lambda: T.MapType(T.LONG, T.LONG))

    def tree_flatten(self):
        return ((self.keys, self.values, self.entry_validity,
                 self.lengths, self.validity), (self.dtype,))

    @classmethod
    def tree_unflatten(cls, aux, children):
        keys, values, ev, lengths, validity = children
        return cls(keys, values, ev, lengths, validity, aux[0])

    @property
    def capacity(self) -> int:
        return int(self.lengths.shape[0])

    @property
    def max_len(self) -> int:
        return int(self.keys.shape[1])

    def with_validity(self, validity: ArrayLike) -> "MapColumn":
        return MapColumn(self.keys, self.values, self.entry_validity,
                         self.lengths, validity, self.dtype)

    def gather(self, indices: ArrayLike,
               index_valid: Optional[ArrayLike] = None) -> "MapColumn":
        idx = jnp.clip(indices, 0, self.capacity - 1)
        validity = jnp.take(self.validity, idx, axis=0)
        if index_valid is not None:
            validity = validity & index_valid
        return MapColumn(jnp.take(self.keys, idx, axis=0),
                         jnp.take(self.values, idx, axis=0),
                         jnp.take(self.entry_validity, idx, axis=0),
                         jnp.take(self.lengths, idx, axis=0),
                         validity, self.dtype)


AnyColumn = Union[Column, StringColumn, ListColumn, StructColumn,
                  MapColumn]


def column_to_numpy(col: AnyColumn, num_rows: int
                    ) -> tuple[np.ndarray, np.ndarray]:
    """Return (values, validity) trimmed to num_rows (host copies)."""
    if isinstance(col, StringColumn):
        vals = np.array(col.to_list(num_rows), dtype=object)
        return vals, np.asarray(col.validity)[:num_rows].copy()
    return (np.asarray(col.data)[:num_rows].copy(),
            np.asarray(col.validity)[:num_rows].copy())
