"""Encoded H2D transfer: compact wire encodings + device-side decode.

The host-device link pays limited sustained bandwidth (and, on
tunneled PJRT backends, orders of magnitude less than PCIe), so the
bytes crossing the wire — not device compute — bound scan-heavy
queries.  The reference sidesteps host bandwidth by decoding Parquet ON
the accelerator (ref: GpuParquetScan.scala:495-560 assembles one device
buffer and launches device decode kernels).  The TPU analog:

- the host (scan prefetch thread) re-encodes each decoded column into a
  compact wire form: bias-packed integers (uint8/uint16 deltas from a
  per-batch base), dictionary-encoded low-cardinality floats/strings
  (codes + values), raw bytes otherwise;
- all components upload in ONE batched `jax.device_put` call;
- a cached, jitted *decode program* (keyed by the static wire plan)
  reconstructs full-width padded device columns: gathers for dictionary
  decode, base adds for bias decode, and validity-mask synthesis
  (`iota < n_live`) so all-valid columns ship zero validity bytes.

Decode work thus moves from the wire to the VPU, where a gather over a
few million rows is microseconds.  Everything is astype/gather/compare —
deliberately NO bitcast_convert_type: the TPU X64 rewriter cannot
compile 64-bit bitcasts, so 64-bit columns ride the list as native
arrays and only sub-32-bit codes get widened on device.

Wire row counts bucket to <=8 sizes per capacity (compile-cache
stability) and live row count rides as a dynamic scalar, so one
compiled decode program serves every batch of the same plan.

When `spark.rapids.tpu.sql.wireCompression.enabled` is on, data-plane
components additionally ride COMPRESSED (columnar/compression/): the
host packs them through the codec chooser during scan-prefetch encode
and the decode program decompresses in HBM — shift/mask unpacking,
per-block cumsums, searchsorted run expansion — fused into the same
XLA program as the rest of the decode.  Off (the default) is
bit-for-bit the uncompressed wire format above.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import (
    Column,
    StringColumn,
    pad_capacity,
    pad_width,
)

#: tapped H2D accounting: bytes actually crossing the wire through
#: THE batched upload below (compressed components count their packed
#: size) — the counter the wire-codec acceptance gate and bench.py's
#: q*_upload_bytes_wire / q*_upload_ratio fields read.
_upload_lock = threading.Lock()
_UPLOAD_STATS = {"batches": 0, "wire_bytes": 0}


def upload_stats() -> dict:
    with _upload_lock:
        return dict(_UPLOAD_STATS)


def reset_upload_stats() -> None:
    with _upload_lock:
        _UPLOAD_STATS["batches"] = 0
        _UPLOAD_STATS["wire_bytes"] = 0


def upload_components(comps):
    """THE batched H2D upload (one ``jax.device_put`` for the whole
    component list) with the ``transfer.upload`` fault seam in front
    and in-place recovery behind it: a retryable failure (injected, or
    a real device-side allocation failure materializing the upload)
    spills every unpinned store buffer and re-uploads once — the
    upload is restartable by construction (host components are still
    in hand).  A second failure propagates to the batch
    split-and-retry ladder / task retry."""
    from spark_rapids_tpu.execs.retry import absorb_once
    from spark_rapids_tpu.robustness import faults as _faults

    def attempt():
        _faults.fault_point("transfer.upload", n_comps=len(comps))
        return jax.device_put(comps)

    out = absorb_once(attempt, action="upload_retry")
    # count HOST array leaves only (tree_leaves: nested column pytrees
    # from the arrow.py fallback path count too): device-resident
    # components handed back through here (decode_now re-running a
    # wire-form batch) are a device_put no-op, and crediting them
    # would double-count bytes that never crossed the link
    host_bytes = sum(
        int(a.nbytes) for a in jax.tree_util.tree_leaves(comps)
        if isinstance(a, np.ndarray))
    if host_bytes:
        with _upload_lock:
            _UPLOAD_STATS["batches"] += 1
            _UPLOAD_STATS["wire_bytes"] += host_bytes
    return out


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _wire_rows(n: int, cap: int) -> int:
    # <= 8 distinct wire lengths per capacity bucket (compile-cache
    # stability) at <= 12.5% padding waste on the wire
    return min(cap, _round_up(n, max(64, cap // 8)))


# ------------------------------------------------------------------ #
# Host-side encoding
# ------------------------------------------------------------------ #

_INT_KINDS = "iu"


def _decode_fixed_host(arr: pa.Array, dtype: T.DataType
                       ) -> tuple[np.ndarray, Optional[np.ndarray]]:
    """One fixed-width pa.Array -> (values[n], validity[n] or None)."""
    from spark_rapids_tpu.columnar.arrow import _zero_value

    phys = T.to_numpy_dtype(dtype)
    if arr.null_count:
        validity = np.asarray(arr.is_valid())
        arr = arr.fill_null(_zero_value(dtype))
    else:
        validity = None
    if isinstance(dtype, T.DateType):
        vals = arr.cast(pa.int32()).to_numpy(zero_copy_only=False)
    elif isinstance(dtype, T.TimestampType):
        vals = arr.cast(pa.int64()).to_numpy(zero_copy_only=False)
    else:
        vals = arr.to_numpy(zero_copy_only=False)
    return np.ascontiguousarray(vals.astype(phys, copy=False)), validity


def _sample_low_cardinality(vals: np.ndarray, limit: int = 1024) -> bool:
    """Cheap gate: does a strided sample look low-cardinality?"""
    n = len(vals)
    if n <= 8192:
        return True
    s = vals[:: max(1, n // 4096)]
    return len(np.unique(s)) <= min(limit, len(s) // 2)


def _try_dict(vals: np.ndarray) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """(codes, values) when dictionary encoding pays off, else None."""
    if vals.dtype.kind == "f" and np.isnan(vals).any():
        return None  # NaN payload bits would not round-trip the dict
    if not _sample_low_cardinality(vals):
        return None
    d = pa.array(vals).dictionary_encode()
    nvals = len(d.dictionary)
    if nvals > 0xFFFF or nvals * 2 > max(len(vals), 1):
        return None
    codes = d.indices.to_numpy(zero_copy_only=False)
    values = d.dictionary.to_numpy(zero_copy_only=False).astype(
        vals.dtype, copy=False)
    # bit-exactness gate (the contract is byte-identical round-trips):
    # Arrow's dictionary_encode unifies -0.0 with +0.0, which flips
    # sign bits downstream (1/x: -inf vs +inf) — verify reconstruction
    if vals.dtype.kind == "f":
        bits = np.int32 if vals.dtype.itemsize == 4 else np.int64
        if not np.array_equal(values[codes].view(bits),
                              vals.view(bits)):
            return None
    return codes, values


def _try_scaled(vals: np.ndarray) -> Optional[np.ndarray]:
    """int32 cents for decimal-valued doubles (prices, rates): data that
    entered the file as 2-decimal values reconstructs BIT-EXACTLY via
    round(v*100)/100.0, verified here before committing to the wire
    format — int32 halves the dominant float column's bytes."""
    if len(vals) == 0:
        return None
    lib = _native()
    if lib is not None:
        v = np.ascontiguousarray(vals)
        out = np.empty(len(v), np.int32)
        ok = lib.scaled_check_encode(v.ctypes.data, len(v),
                                     out.ctypes.data)
        return out if ok else None
    if not np.isfinite(vals).all():
        return None
    s = np.rint(vals * 100.0)
    if (np.abs(s) >= 2**31).any():
        return None
    s32 = s.astype(np.int32)
    r = s32.astype(np.float64) / 100.0
    if not np.array_equal(r.view(np.int64), vals.view(np.int64)):
        return None
    return s32


def _native():
    from spark_rapids_tpu import native

    return native.load()


def _int_range(vals: np.ndarray, phys: np.dtype):
    """(min, range, encode8, encode16) for an integer column, using the
    native codec's single-pass kernels for the common i32/i64 cases."""
    lib = _native()
    if lib is not None and phys in (np.dtype(np.int64),
                                    np.dtype(np.int32)):
        v = np.ascontiguousarray(vals)
        mnb = np.empty(1, np.int64)
        mxb = np.empty(1, np.int64)
        scan = lib.minmax_i64 if phys.itemsize == 8 else lib.minmax_i32
        scan(v.ctypes.data, len(v), mnb.ctypes.data, mxb.ctypes.data)
        mn = int(mnb[0])
        e8 = lib.bias_encode8_i64 if phys.itemsize == 8 \
            else lib.bias_encode8_i32
        e16 = lib.bias_encode16_i64 if phys.itemsize == 8 \
            else lib.bias_encode16_i32

        def enc8(x, base, _f=e8):
            x = np.ascontiguousarray(x)
            out = np.empty(len(x), np.uint8)
            _f(x.ctypes.data, len(x), base, out.ctypes.data)
            return out

        def enc16(x, base, _f=e16):
            x = np.ascontiguousarray(x)
            out = np.empty(len(x), np.uint16)
            _f(x.ctypes.data, len(x), base, out.ctypes.data)
            return out

        return mn, int(mxb[0]) - mn, enc8, enc16
    mn = int(vals.min())
    rng = int(vals.max()) - mn

    def enc8_np(x, base):
        return (x.astype(np.int64) - base).astype(np.uint8)

    def enc16_np(x, base):
        return (x.astype(np.int64) - base).astype(np.uint16)

    return mn, rng, enc8_np, enc16_np


def _padded(a: np.ndarray, wire: int) -> np.ndarray:
    """Zero-pad a 1-D/2-D per-row array to `wire` rows (zero-copy when
    it already fits exactly)."""
    if len(a) == wire:
        return np.ascontiguousarray(a)
    out = np.zeros((wire,) + a.shape[1:], a.dtype)
    out[: len(a)] = a
    return out


class _Comps:
    """Component accumulator producing the physical upload list.

    Each component rides as its OWN array in one batched
    ``jax.device_put`` call (PJRT moves the whole list in one transfer
    round, measured at parity with a single staging buffer on the
    tunneled backend).  An earlier design packed all sub-4-byte
    components into one uint8 buffer recovered with device slices +
    bitcast_convert_type; that was abandoned after XLA:TPU's layout
    pass was observed taking 100-500 SECONDS to compile decode programs
    whose big slices did not exactly tile the staging buffer (the
    multi-megabyte slice-of-uint8 copies defeat the bitcast-view
    recognition and send tiling assignment into a pathological search).
    Separate typed arrays compile in ~2s, need zero bitcasts, and make
    the X64-rewriter caveat moot.

    add() returns an opaque ref the plan stores; the decode program
    resolves refs against the uploaded list.  add_wire() is the
    data-plane variant: when wire compression is configured it routes
    the component through the codec chooser and returns a "comp" ref
    carrying the codec name + static meta — the decode program
    resolves those by running the codec's device decompress before
    (fused with) the rest of the decode.  With compression off,
    add_wire IS add, so the disabled wire format is bit-for-bit the
    historical one.
    """

    def __init__(self, wire_cfg: Optional[tuple] = None):
        self.arrays: list[np.ndarray] = []
        self.wire_cfg = wire_cfg  # (codec names, min_ratio, block_rows)

    def add(self, a: np.ndarray):
        self.arrays.append(np.ascontiguousarray(a))
        return ("arr", len(self.arrays) - 1)

    def add_wire(self, a: np.ndarray):
        a = np.ascontiguousarray(a)
        if self.wire_cfg is not None:
            from spark_rapids_tpu import trace as _trace
            from spark_rapids_tpu.columnar import compression as WC

            with _trace.span("wire.compress", nbytes=a.nbytes,
                             dtype=str(a.dtype)):
                enc = WC.choose_and_encode(a.reshape(-1),
                                           *self.wire_cfg)
            if enc is not None:
                name, arrays, meta = enc
                refs = tuple(self.add(x) for x in arrays)
                return ("comp", name, refs, meta, str(a.dtype),
                        a.shape)
        return self.add(a)

    def finish(self) -> list[np.ndarray]:
        return self.arrays


def encode_for_device(arrays: Sequence[pa.Array], schema: T.Schema,
                      n: int) -> Optional[tuple[list, tuple]]:
    """Encode decoded host Arrow columns into (components, plan).

    Returns None when a column type has no wire encoding yet (decimal,
    list) — callers fall back to the per-component padded upload path.
    """
    for f in schema.fields:
        if isinstance(f.dtype, (T.DecimalType, T.ListType,
                                T.StructType, T.MapType)):
            return None
    if n == 0:
        return None

    cap = pad_capacity(n)
    wire = _wire_rows(n, cap)
    from spark_rapids_tpu.columnar.compression import wire_codec_config

    comps = _Comps(wire_codec_config())
    n_ref = comps.add(np.asarray(n, np.int32))  # dynamic live row count
    entries: list[tuple] = []

    for arr, f in zip(arrays, schema.fields):
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if isinstance(arr, pa.DictionaryArray):
            # dictionary came straight from the Parquet page (fastpar):
            # ship codes + values with no re-encode and, for strings,
            # no full-column materialization at all
            e = _encode_dict_direct(comps, arr, f.dtype, wire)
            if e is not None:
                entries.append(e)
                continue
            arr = arr.cast(arr.type.value_type)
        if isinstance(f.dtype, T.StringType):
            entries.append(_encode_string(comps, arr, wire))
            continue
        vals, validity = _decode_fixed_host(arr, f.dtype)
        if validity is not None and not validity.any():
            # all-NULL column: nothing crosses the wire at all (real
            # all-null data, and the scan's filter-only column
            # suppression which nulls columns no operator above the
            # elided filter reads)
            entries.append(("fixed", "null", -1, str(vals.dtype), (),
                            None, None))
            continue
        vref = None
        if validity is not None:
            vref = comps.add_wire(_padded(validity, wire))
        phys = vals.dtype
        kind = "raw"
        extra: tuple = ()
        dict_n = None  # bucketed dictionary entry bound, dict entries
        if phys.kind in _INT_KINDS and phys.itemsize > 1:
            mn, rng, enc8, enc16 = _int_range(vals, phys)
            if rng <= 0xFF:
                kind = "bias"
                extra = (comps.add(np.asarray(mn, np.int64)),)
                vals = enc8(vals, mn)
            elif phys.itemsize > 2 and rng <= 0xFFFF:
                kind = "bias"
                extra = (comps.add(np.asarray(mn, np.int64)),)
                vals = enc16(vals, mn)
            elif phys.itemsize > 4 and rng <= 0xFFFFFFFF:
                # 64-bit ints with a 32-bit range (join/order keys)
                # halve the dominant upload; base + zero-extended u32
                # round-trips exactly (vals-mn <= rng, no overflow)
                kind = "bias"
                extra = (comps.add(np.asarray(mn, np.int64)),)
                vals = (vals - mn).astype(np.uint32)
        elif phys.kind == "f":
            enc = _try_dict(vals)
            if enc is not None:
                codes, dvals = enc
                code_dt = np.uint8 if len(dvals) <= 0x100 else np.uint16
                nvp = max(8, pad_capacity(len(dvals)))
                kind = "dict"
                dict_n = _dict_len_bound(len(dvals), nvp)
                extra = (comps.add_wire(_padded(dvals, nvp)),)
                vals = codes.astype(code_dt)
            elif phys.itemsize == 8:
                scaled = _try_scaled(vals)
                if scaled is not None:
                    kind = "scaled"
                    # divisor rides as a RUNTIME scalar: a literal
                    # constant lets XLA strength-reduce /100.0 into
                    # *(1/100.0), which breaks the bit-exactness the
                    # host encoder verified
                    extra = (comps.add(np.asarray(100.0, np.float64)),)
                    vals = scaled
        dref = comps.add_wire(_padded(vals, wire))
        entries.append(("fixed", kind, dref, str(phys), extra, vref,
                        dict_n))

    plan = (cap, wire, n_ref, tuple(entries))
    return comps.finish(), plan


def _dict_len_bound(n: int, nvp: int) -> int:
    """Tight upper bound on a dictionary's true entry count, bucketed
    to a multiple of 16 (min 8) and clamped to the padded capacity.
    The bound rides in pytree aux data / the wire plan, both of which
    key jit compile caches — an EXACT per-row-group cardinality would
    mint a distinct program per dictionary size, while the full padded
    capacity (pow2) overestimates coded-key domains (compounding per
    group key).  The bucket keeps domains within 16 of tight and the
    program-variant count small."""
    return min(nvp, max(8, -(-n // 16) * 16))


def _encode_dict_direct(comps: _Comps, arr: pa.DictionaryArray,
                        dtype: T.DataType, wire: int) -> Optional[tuple]:
    """A pre-dictionary-encoded column -> wire dict/sdict entry, trusting
    the source dictionary (values came FROM it, so the round trip is
    exact by construction).  None = no dict wire form for this type."""
    dvals = arr.dictionary
    nvals = len(dvals)
    if nvals > 0xFFFF or dvals.null_count:
        # a null INSIDE the dictionary hides row nulls from
        # arr.is_valid() (index-level only): take the plain path,
        # which decodes through the value type and keeps the nulls
        return None
    validity = np.asarray(arr.is_valid()) if arr.null_count else None
    codes = arr.indices.to_numpy(zero_copy_only=False)
    if validity is not None:
        codes = np.where(validity, codes, 0)
    if isinstance(dtype, T.StringType):
        return _sdict_entry(comps, codes, dvals, validity, wire)
    if isinstance(dtype, (T.DecimalType, T.ListType, T.StructType,
                          T.MapType)):
        return None
    dnp, dvalid = _decode_fixed_host(dvals, dtype)
    if dvalid is not None:
        return None
    code_dt = np.uint8 if nvals <= 0x100 else np.uint16
    nvp = max(8, pad_capacity(max(nvals, 1)))
    vref = comps.add_wire(_padded(validity, wire)) \
        if validity is not None else None
    cref = comps.add_wire(_padded(codes.astype(code_dt), wire))
    extra = (comps.add_wire(_padded(dnp, nvp)),)
    return ("fixed", "dict", cref, str(dnp.dtype), extra, vref,
            _dict_len_bound(nvals, nvp))


def _sdict_entry(comps: _Comps, codes: np.ndarray, dvals: pa.Array,
                 validity: Optional[np.ndarray],
                 wire: int) -> Optional[tuple]:
    """Assemble one string-dictionary wire entry (shared by the direct
    DictionaryArray path and the host re-encode path); None when the
    dictionary exceeds the wire's uint16 length/size format."""
    nvals = len(dvals)
    if nvals > 0xFFFF:
        return None
    dchars, dlens = _chars_matrix(dvals.cast(pa.large_string()))
    if dlens.size and int(dlens.max()) > 0xFFFF:
        return None
    code_dt = np.uint8 if nvals <= 0x100 else np.uint16
    nvp = max(8, pad_capacity(max(nvals, 1)))
    vref = comps.add_wire(_padded(validity, wire)) \
        if validity is not None else None
    cref = comps.add_wire(_padded(codes.astype(code_dt), wire))
    dcref = comps.add_wire(_padded(dchars, nvp))
    dlref = comps.add_wire(_padded(dlens.astype(np.uint16), nvp))
    return ("sdict", cref, dcref, dlref, vref,
            _dict_len_bound(nvals, nvp))


def _encode_string(comps: _Comps, arr: pa.Array, wire: int) -> tuple:
    """Encode one string column; returns its plan entry."""
    sarr = arr.cast(pa.large_string())
    n = len(sarr)
    offsets = np.frombuffer(sarr.buffers()[1], dtype=np.int64,
                            count=n + 1, offset=sarr.offset * 8)
    validity = (np.asarray(arr.is_valid()) if arr.null_count
                else None)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    if validity is not None:
        lens = np.where(validity, lens, 0).astype(np.int32)

    # dictionary attempt: low-cardinality string columns ship codes only
    if _string_dict_gate(sarr):
        d = sarr.dictionary_encode()
        dvals = d.dictionary
        if (len(dvals) * 2 <= max(n, 1)
                and not dvals.null_count):
            codes = d.indices.to_numpy(zero_copy_only=False)
            if validity is not None:
                codes = np.where(validity, codes, 0)
            e = _sdict_entry(comps, codes, dvals, validity, wire)
            if e is not None:
                return e
            # >=64KB dictionary values would wrap the uint16 length
            # wire format: fall through to the raw layout (int32 lens)

    vref = None
    if validity is not None:
        vref = comps.add_wire(_padded(validity, wire))
    chars, _ = _chars_matrix(sarr, lens)
    cref = comps.add_wire(_padded(chars, wire))
    # lengths >= 64KiB would wrap uint16: widen the wire type (the
    # decode side reads whatever dtype the ref carries)
    len_dt = np.uint16 if (not lens.size or int(lens.max()) <= 0xFFFF) \
        else np.int32
    lref = comps.add_wire(_padded(lens.astype(len_dt), wire))
    return ("sraw", cref, lref, vref)


def _string_dict_gate(sarr: pa.Array) -> bool:
    n = len(sarr)
    if n <= 8192:
        return True
    d = sarr.slice(0, 4096).dictionary_encode()
    return len(d.dictionary) <= 1024


def _chars_matrix(sarr: pa.Array,
                  lens: Optional[np.ndarray] = None
                  ) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized fixed-width chars matrix for a large_string array:
    (chars[n, w], lengths[n])."""
    n = len(sarr)
    offsets = np.frombuffer(sarr.buffers()[1], dtype=np.int64,
                            count=n + 1, offset=sarr.offset * 8)
    data_buf = sarr.buffers()[2]
    raw = (np.frombuffer(data_buf, dtype=np.uint8)
           if data_buf is not None else np.zeros(1, np.uint8))
    if lens is None:
        lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    maxw = int(lens.max()) if n else 0
    w = pad_width(max(maxw, 1))
    if n == 0:
        return np.zeros((0, w), np.uint8), lens
    lib = _native()
    if lib is not None:
        chars = np.zeros((n, w), np.uint8)
        off = np.ascontiguousarray(offsets)
        cl = np.ascontiguousarray(np.minimum(lens, w).astype(np.int32))
        rb = np.ascontiguousarray(raw)
        lib.chars_fill(rb.ctypes.data, off.ctypes.data, cl.ctypes.data,
                       n, w, chars.ctypes.data)
        return chars, lens
    idx = offsets[:-1, None] + np.arange(w)[None, :]
    mask = np.arange(w)[None, :] < lens[:, None]
    safe = np.clip(idx, 0, max(len(raw) - 1, 0))
    chars = np.where(mask, raw[safe], 0).astype(np.uint8)
    return chars, lens


# ------------------------------------------------------------------ #
# Device-side decode program
# ------------------------------------------------------------------ #


def _make_decode(plan: tuple):
    cap, wire, n_ref, entries = plan
    pad = cap - wire

    def grow(a):
        if pad == 0:
            return a
        z = jnp.zeros((pad,) + a.shape[1:], a.dtype)
        return jnp.concatenate([a, z], axis=0)

    def decode(xs):
        def read(ref):
            if ref[0] == "arr":
                return xs[ref[1]]  # one typed array per component
            # ("comp", codec, refs, meta, dtype, shape): run the
            # codec's device decompress — it traces into THIS program,
            # so decompress+decode(+consumer transform) is one fused
            # XLA execution per batch
            from spark_rapids_tpu.columnar.compression import get_codec

            _, name, refs, meta, dt, shape = ref
            out = get_codec(name).decode_array(
                [xs[r[1]] for r in refs], meta, np.dtype(dt))
            return out.reshape(shape) if len(shape) > 1 else out

        n_live = read(n_ref)
        live_mask = jnp.arange(cap, dtype=jnp.int32) < n_live

        def validity_of(vref):
            if vref is None:
                return live_mask
            return grow(read(vref)) & live_mask

        out = []
        for e in entries:
            if e[0] == "fixed":
                _, kind, dref, physdt, extra, vref, _dict_n = e
                phys = np.dtype(physdt)
                if kind == "null":
                    out.append((jnp.zeros((cap,), phys),
                                jnp.zeros((cap,), jnp.bool_)))
                    continue
                vals = read(dref)
                if kind == "bias":
                    base = read(extra[0])
                    vals = (vals.astype(jnp.int64) + base).astype(phys)
                elif kind == "dict":
                    codes = vals.astype(jnp.int32)
                    vals = jnp.take(read(extra[0]), codes, axis=0)
                    # codes + dictionary ride along as the column's
                    # sidecar (grow pads dead rows with code 0): the
                    # coded group-by uses them as dense group ids for
                    # low-cardinality numeric keys, skipping the sort
                    out.append((grow(vals), validity_of(vref),
                                grow(codes), read(extra[0])))
                    continue
                elif kind == "scaled":
                    # same op the host exactness check performed
                    vals = vals.astype(phys) / read(extra[0])
                out.append((grow(vals), validity_of(vref)))
            elif e[0] == "sraw":
                _, cref, lref, vref = e
                v = validity_of(vref)
                out.append((grow(read(cref)),
                            grow(read(lref).astype(jnp.int32))
                            * v.astype(jnp.int32), v))
            elif e[0] == "sdict":
                _, cref, dcref, dlref, vref, _dict_n = e
                codes = read(cref).astype(jnp.int32)
                v = validity_of(vref)
                # invariant shared with every string kernel: chars are
                # zero for null rows and beyond each row's length — a
                # gathered dict[0] payload on null/padding rows would
                # break byte-wise comparators
                chars = grow(jnp.take(read(dcref), codes, axis=0)) \
                    * v[:, None].astype(jnp.uint8)
                lens = grow(jnp.take(read(dlref).astype(jnp.int32),
                                     codes, axis=0)) \
                    * v.astype(jnp.int32)
                # codes + dictionary ride along as the column's dict
                # sidecar: the group-by coded fast path uses codes as
                # dense group ids (no sort).  grow() pads dead rows
                # with code 0; consumers gate on validity/row masks.
                out.append((chars, lens, v, grow(codes), read(dcref),
                            read(dlref).astype(jnp.int32)))
        return out

    return decode


def _wrap_cols(parts, schema: T.Schema, entries=None):
    """Decode-program outputs -> AnyColumn list (traceable).  `entries`
    (the plan's per-column entry tuples) supplies the bucketed
    dictionary entry bound for dict-encoded columns — the device
    arrays are padded to pow2 capacity buckets, so consumers sizing
    code domains need the tighter bound carried separately."""
    cols = []
    for i, (f, p) in enumerate(zip(schema.fields, parts)):
        e = entries[i] if entries is not None else None
        dict_n = e[-1] if e is not None and e[0] in ("fixed",
                                                     "sdict") else None
        if isinstance(f.dtype, T.StringType):
            if len(p) == 6:  # sdict: dictionary sidecar rides along
                chars, lens, valid, codes, dchars, dlens = p
                cols.append(StringColumn(chars, lens, valid, f.dtype,
                                         codes, dchars, dlens, dict_n))
                continue
            chars, lens, valid = p
            cols.append(StringColumn(chars, lens, valid))
        else:
            if len(p) == 4:  # dict: numeric dictionary sidecar
                data, valid, codes, dvals = p
                cols.append(Column(data, valid, f.dtype, codes, dvals,
                                   dict_n))
                continue
            data, valid = p
            cols.append(Column(data, valid, f.dtype))
    return cols


def plan_codecs(plan: tuple) -> tuple:
    """Codec names appearing in a wire plan's comp refs (empty when the
    plan is uncompressed) — the host-side view the decompress stats and
    the wire.decompress span key off."""
    names = []
    for e in plan[3]:
        for ref in e:
            if isinstance(ref, tuple) and ref and ref[0] == "comp":
                names.append(ref[1])
            elif isinstance(ref, tuple) and ref and \
                    isinstance(ref[0], tuple):  # extra refs tuple
                names.extend(r[1] for r in ref if r[0] == "comp")
    return tuple(names)


def _record_decompress(names: tuple) -> None:
    """Bump the per-codec decompress stats for one wire-form batch
    (``names`` = plan_codecs(plan), computed once by the caller)."""
    if not names:
        return
    from spark_rapids_tpu.columnar import compression as WC

    for name in set(names):
        WC.record_decompress(name, names.count(name))


def decode_on_device(comps: list, plan: tuple, schema: T.Schema,
                     record: bool = True):
    """Upload the component list (one batched transfer round) and run
    the cached decode program.  Returns device columns in schema
    order.  The program is compiled through cached_jit under
    op="WireDecode", so the device ledger attributes decode (and
    decompress) device-time per program.

    ``record=False`` skips the per-codec decompress stat bump: callers
    whose batch was ALREADY counted at encode_batch (decode_now on a
    wire-form batch) must not count it twice — every encoded batch
    contributes exactly one decompress per codec use, whether its
    decode runs here eagerly or fused inside a consumer program."""
    from spark_rapids_tpu import trace as _trace
    from spark_rapids_tpu.execs.jit_cache import cached_jit

    # the compiled decode ignores dict_n (it is applied by _wrap_cols
    # OUTSIDE the program here): strip it from the cache key so row
    # groups differing only in dictionary cardinality bucket share one
    # program (the fused EncodedBatch path legitimately keys on it)
    key = ("wire.decode",) + plan[:3] + (tuple(
        e[:-1] if e[0] in ("fixed", "sdict") else e for e in plan[3]),)
    fn = cached_jit(key, lambda: _make_decode(plan), op="WireDecode")
    dev = upload_components(comps)
    codecs = plan_codecs(plan)
    if codecs:
        if record:
            _record_decompress(codecs)
        with _trace.span("wire.decompress", components=len(codecs),
                         codecs=",".join(sorted(set(codecs)))):
            parts = fn(dev)
    else:
        parts = fn(dev)
    return _wrap_cols(parts, schema, plan[3])


class ConsumedBatchError(RuntimeError):
    """A donated (consumed) batch was asked for its device buffers
    again.  Deliberately NON-retryable (no retryable marker in the
    text): re-running over freed HBM cannot succeed, so the failure
    must fail fast instead of burning the spill/split ladder —
    donation's contract is that consumers resume from the memoized
    program output (run_consuming), never re-execute."""


def run_consuming(fn, eb: "EncodedBatch"):
    """Execute a DONATING fused program over a wire-form batch exactly
    once.  The batch is marked consumed BEFORE the call (a failure
    mid-execution leaves device state unknown — conservatively gone)
    and the output is memoized on the batch, so a retry-ladder re-run
    of the same unit (e.g. a retire-side OOM after a successful
    update dispatch) RESUMES from the already-produced output instead
    of re-executing over donated buffers.  A re-run that finds the
    batch consumed with no memoized output (the program itself died)
    — or a memoized output whose buffers were since freed (spilled
    while registered, and the rollback's repair_donated_memo could
    not restore it) — raises ConsumedBatchError, non-retryable by
    design."""
    if eb.consumed:
        if eb.donated_out is None:
            raise ConsumedBatchError(
                "donated program died mid-execution; input buffers "
                "are gone and no output was memoized")
        if memo_is_dead(eb.donated_out):
            raise ConsumedBatchError(
                "memoized donated output was spilled and its device "
                "buffers freed before the re-run; input buffers are "
                "gone too, so the unit cannot be recovered")
        return eb.donated_out
    eb.consumed = True
    out = fn(eb)
    eb.donated_out = out
    return out


def memo_is_dead(out) -> bool:
    """True if any device-array leaf of a memoized program output has
    been deleted.  The spill store's device→host spill deletes the
    device arrays of the batch it holds (`_batch_to_host(delete=True)`)
    and restores into a NEW batch object — a raw reference memoized
    before the spill (EncodedBatch.donated_out) is not updated, so it
    must be liveness-checked before the resume path hands it
    downstream."""
    for x in jax.tree_util.tree_leaves(out):
        if isinstance(x, jax.Array):
            try:
                if x.is_deleted():
                    return True
            except Exception:
                return True
    return False


def repair_donated_memo(eb: "EncodedBatch", handle) -> bool:
    """Rollback seam for a donated unit (docs/fusion.md): retire
    registers the memoized update output with the spill store UNPINNED,
    so pressure may spill it — deleting the very device arrays
    ``eb.donated_out`` references.  A retry-ladder rollback about to
    close that registration (dropping the only surviving copy) calls
    this first: if the memo is dead, re-materialize through the handle
    and re-memoize, so the re-run's resume hands downstream a live
    batch instead of freed buffers — the recovery the memo exists for.
    Best-effort: a failed restore (e.g. OOM during the rollback
    itself) leaves the memo dead and run_consuming fails fast with
    ConsumedBatchError instead of an opaque deleted-array crash.
    Returns True when the memo was repaired."""
    out = eb.donated_out
    if out is None or not memo_is_dead(out):
        return False
    try:
        restored = handle.get()  # re-materialize on device (pins)
        handle.unpin()
    except Exception:
        return False  # rollback must proceed; resume will fail fast
    eb.donated_out = restored
    return True


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class EncodedBatch:
    """A scan batch still in WIRE form: uploaded components + static
    decode plan.  Consumers that jit their per-batch work (the fusable
    pipeline driver, the hash aggregate's update phase) decode INSIDE
    their own program, so scan->filter->aggregate is one program
    execution per batch — on the tunneled backend every execution pays
    a link round trip once any D2H fetch has happened, so collapsing
    decode+transform+update into one program is a direct latency win
    (the reference gets the same effect by chaining cudf kernels inside
    one task, GpuParquetScan.scala:495-560 -> GpuFilterExec).

    `num_rows` is the host-known live count for metrics/accumulation
    bookkeeping; it deliberately does NOT survive tracing (the decode
    derives the traced count from the wire components), so one compiled
    consumer program serves every ragged tail.

    `consumed` / `donated_out`: donation bookkeeping
    (docs/fusion.md).  A consumer that donates the wire components
    into its fused program (cached_jit's `donate=`) marks the batch
    consumed FIRST and memoizes the program output — the retry/split
    ladder's re-run path then resumes from the memoized output instead
    of re-executing over donated (freed) buffers, and
    `retry.bisect_batch`/`_batch_rows` refuse to decode or split a
    consumed batch.  Neither field rides the pytree (flatten drops
    them): tracing sees only the wire components.
    """

    comps: list
    plan: tuple
    schema: T.Schema
    num_rows: Optional[int] = None
    consumed: bool = False
    donated_out: Optional[object] = None

    def tree_flatten(self):
        return (tuple(self.comps),), (self.plan, self.schema)

    @classmethod
    def tree_unflatten(cls, aux, children):
        (comps,) = children
        return cls(list(comps), aux[0], aux[1], None)

    @property
    def capacity(self) -> int:
        return self.plan[0]

    @property
    def live_count(self):
        """The wire `n` component: a device scalar holding the live
        row count (the one place the plan's n-ref layout is decoded —
        consumers must not index comps/plan themselves)."""
        return self.comps[self.plan[2][1]]

    def decode(self):
        """Traceable: wire components -> ColumnarBatch with a traced
        live-row count (read off the wire's n component)."""
        from spark_rapids_tpu.columnar.batch import ColumnarBatch

        decode = _make_decode(self.plan)
        cols = _wrap_cols(decode(self.comps), self.schema, self.plan[3])
        return ColumnarBatch(cols,
                             jnp.asarray(self.live_count, jnp.int32),
                             self.schema)

    def decode_now(self):
        """Eager fallback for consumers that do not fuse the decode."""
        from spark_rapids_tpu.columnar.batch import ColumnarBatch

        if self.consumed:
            raise ConsumedBatchError(
                "wire components were donated into a fused program; "
                "the batch cannot be decoded again")
        # record=False: this batch's decompress was counted when
        # encode_batch shipped it
        cols = decode_on_device(self.comps, self.plan, self.schema,
                                record=False)
        n = self.num_rows
        if n is None:
            from spark_rapids_tpu.parallel.pipeline import device_read_int

            n = device_read_int(self.live_count, tag="transfer.decode")
        return ColumnarBatch(cols, n, self.schema)


def encode_batch(arrays: Sequence[pa.Array], schema: T.Schema,
                 n: int) -> Optional[EncodedBatch]:
    """Host Arrow columns -> EncodedBatch (one batched H2D upload), or
    None when a column type has no wire encoding."""
    enc = encode_for_device(arrays, schema, n)
    if enc is None:
        return None
    comps, plan = enc
    # a wire-form batch is decoded (decompressed) exactly once —
    # fused inside a consumer program or via decode_now — so the
    # per-codec decompress stat is counted HERE, where every such
    # batch passes once on the host (trace-time counting inside the
    # fused program would undercount on compile-cache hits)
    _record_decompress(plan_codecs(plan))
    return EncodedBatch(upload_components(comps), plan, schema, n)
