"""Encoded single-buffer H2D / D2H transfer.

The interconnect between host and TPU pays (a) a per-transfer latency
and (b) limited sustained bandwidth — on tunneled PJRT backends both are
orders of magnitude worse than PCIe.  The reference sidesteps host
bandwidth by decoding Parquet ON the accelerator (ref:
GpuParquetScan.scala:495-560 assembles one device buffer and launches
device decode kernels).  The TPU analog implemented here:

- the host (scan prefetch thread) re-encodes each decoded column into a
  compact wire form: bias-packed integers (uint8/uint16 deltas from a
  per-batch base), dictionary-encoded low-cardinality floats/strings
  (codes + values), raw bytes otherwise;
- every component is packed into ONE contiguous uint8 staging buffer —
  a single `jax.device_put` per batch regardless of column count;
- a cached, jitted *unpack program* (keyed by the static wire plan)
  reconstructs full-width padded device columns: bitcasts, gathers for
  dictionary decode, base adds for bias decode, and validity-mask
  synthesis (`iota < n_live`) so all-valid columns ship zero validity
  bytes.

Decode work thus moves from the wire to the VPU, where a gather over a
few million rows is microseconds.  The same trick in reverse —
`fetch_packed` — returns any set of device arrays in one D2H round.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import (
    Column,
    StringColumn,
    pad_capacity,
    pad_width,
)

_ALIGN = 8
_WIRE_BUCKET = 1 << 16  # wire row counts round up to this (compile-cache)

_unpack_cache: dict = {}
_pack_cache: dict = {}
_cache_lock = threading.Lock()


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _wire_rows(n: int, cap: int) -> int:
    # <= 8 distinct wire lengths per capacity bucket (compile-cache
    # stability) at <= 12.5% padding waste on the wire
    return min(cap, _round_up(n, max(64, cap // 8)))


# ------------------------------------------------------------------ #
# Host-side encoding
# ------------------------------------------------------------------ #

_INT_KINDS = "iu"


def _decode_fixed_host(arr: pa.Array, dtype: T.DataType
                       ) -> tuple[np.ndarray, Optional[np.ndarray]]:
    """One fixed-width pa.Array -> (values[n], validity[n] or None)."""
    from spark_rapids_tpu.columnar.arrow import _zero_value

    phys = T.to_numpy_dtype(dtype)
    if arr.null_count:
        validity = np.asarray(arr.is_valid())
        arr = arr.fill_null(_zero_value(dtype))
    else:
        validity = None
    if isinstance(dtype, T.DateType):
        vals = arr.cast(pa.int32()).to_numpy(zero_copy_only=False)
    elif isinstance(dtype, T.TimestampType):
        vals = arr.cast(pa.int64()).to_numpy(zero_copy_only=False)
    else:
        vals = arr.to_numpy(zero_copy_only=False)
    return np.ascontiguousarray(vals.astype(phys, copy=False)), validity


def _sample_low_cardinality(vals: np.ndarray, limit: int = 1024) -> bool:
    """Cheap gate: does a strided sample look low-cardinality?"""
    n = len(vals)
    if n <= 8192:
        return True
    s = vals[:: max(1, n // 4096)]
    return len(np.unique(s)) <= min(limit, len(s) // 2)


def _try_dict(vals: np.ndarray) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """(codes, values) when dictionary encoding pays off, else None."""
    if vals.dtype.kind == "f" and np.isnan(vals).any():
        return None  # NaN payload bits would not round-trip the dict
    if not _sample_low_cardinality(vals):
        return None
    d = pa.array(vals).dictionary_encode()
    nvals = len(d.dictionary)
    if nvals > 0xFFFF or nvals * 2 > max(len(vals), 1):
        return None
    codes = d.indices.to_numpy(zero_copy_only=False)
    values = d.dictionary.to_numpy(zero_copy_only=False).astype(
        vals.dtype, copy=False)
    return codes, values


class _Builder:
    """Accumulates aligned regions of the staging buffer."""

    def __init__(self, n_header_slots: int):
        self.chunks: list[tuple[int, np.ndarray]] = []
        self.off = n_header_slots * 8
        self.header = np.zeros(n_header_slots, np.int64)

    def add(self, a: np.ndarray) -> int:
        a = np.ascontiguousarray(a)
        off = _round_up(self.off, _ALIGN)
        self.chunks.append((off, a))
        self.off = off + a.nbytes
        return off

    def finish(self) -> np.ndarray:
        total = _round_up(self.off, _ALIGN)
        buf = np.zeros(total, np.uint8)
        buf[: len(self.header) * 8] = self.header.view(np.uint8)
        for off, a in self.chunks:
            buf[off: off + a.nbytes] = a.view(np.uint8).reshape(-1)
        return buf


def _padded(a: np.ndarray, wire: int) -> np.ndarray:
    """Zero-pad a 1-D/2-D per-row array to `wire` rows."""
    if len(a) == wire:
        return a
    out = np.zeros((wire,) + a.shape[1:], a.dtype)
    out[: len(a)] = a
    return out


def encode_for_device(arrays: Sequence[pa.Array], schema: T.Schema,
                      n: int) -> Optional[tuple[np.ndarray, tuple]]:
    """Encode decoded host Arrow columns into (staging_buffer, plan).

    Returns None when a column type has no wire encoding yet (decimal,
    list) — callers fall back to the per-component upload path.
    """
    for f in schema.fields:
        if isinstance(f.dtype, (T.DecimalType, T.ListType)):
            return None
    if n == 0:
        return None

    cap = pad_capacity(n)
    wire = _wire_rows(n, cap)
    # header: slot 0 = n_live; one base slot per column (bias encodings)
    b = _Builder(1 + len(schema.fields))
    b.header[0] = n
    entries: list[tuple] = []

    for ci, (arr, f) in enumerate(zip(arrays, schema.fields)):
        if isinstance(f.dtype, T.StringType):
            entries.append(_encode_string(b, arr, wire))
            continue
        vals, validity = _decode_fixed_host(arr, f.dtype)
        voff = -1
        if validity is not None:
            voff = b.add(_padded(validity.astype(np.uint8), wire))
        phys = vals.dtype
        kind = "raw"
        extra: tuple = ()
        if phys.kind in _INT_KINDS and phys.itemsize > 1 and n > 0:
            mn = int(vals.min())
            rng = int(vals.max()) - mn
            if rng <= 0xFF:
                kind, extra = "bias8", ()
                b.header[1 + ci] = mn
                vals = (vals.astype(np.int64) - mn).astype(np.uint8)
            elif phys.itemsize > 2 and rng <= 0xFFFF:
                kind, extra = "bias16", ()
                b.header[1 + ci] = mn
                vals = (vals.astype(np.int64) - mn).astype(np.uint16)
        elif phys.kind == "f":
            enc = _try_dict(vals)
            if enc is not None:
                codes, dvals = enc
                code_dt = np.uint8 if len(dvals) <= 0x100 else np.uint16
                nvp = max(8, pad_capacity(len(dvals)))
                kind = "dict"
                doff = b.add(_padded(dvals, nvp))
                extra = (doff, nvp, str(code_dt.__name__)
                         if hasattr(code_dt, "__name__") else str(code_dt))
                vals = codes.astype(code_dt)
        if phys == np.bool_:
            vals = vals.astype(np.uint8)
        off = b.add(_padded(vals, wire))
        entries.append(("fixed", kind, off, str(vals.dtype), str(phys),
                        extra, voff))

    plan = (cap, wire, tuple(entries))
    return b.finish(), plan


def _encode_string(b: _Builder, arr: pa.Array, wire: int) -> tuple:
    """Encode one string column; returns its plan entry."""
    sarr = arr.cast(pa.large_string())
    n = len(sarr)
    offsets = np.frombuffer(sarr.buffers()[1], dtype=np.int64,
                            count=n + 1, offset=sarr.offset * 8)
    validity = (np.asarray(arr.is_valid()) if arr.null_count
                else None)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    if validity is not None:
        lens = np.where(validity, lens, 0).astype(np.int32)
    voff = -1
    if validity is not None:
        voff = b.add(_padded(validity.astype(np.uint8), wire))

    # dictionary attempt: low-cardinality string columns ship codes only
    if _string_dict_gate(sarr):
        d = sarr.dictionary_encode()
        dvals = d.dictionary
        if len(dvals) <= 0xFFFF and len(dvals) * 2 <= max(n, 1):
            codes = d.indices.to_numpy(zero_copy_only=False)
            if validity is not None:
                codes = np.where(validity, codes, 0)
            code_dt = np.uint8 if len(dvals) <= 0x100 else np.uint16
            nvp = max(8, pad_capacity(len(dvals)))
            dchars, dlens = _chars_matrix(dvals.cast(pa.large_string()))
            if not dlens.size or int(dlens.max()) <= 0xFFFF:
                w = dchars.shape[1] if dchars.size else 1
                dcoff = b.add(_padded(dchars, nvp))
                dloff = b.add(_padded(dlens.astype(np.uint16), nvp))
                coff = b.add(_padded(codes.astype(code_dt), wire))
                return ("sdict", coff, str(code_dt.__name__), dcoff,
                        dloff, nvp, w, voff)
            # >=64KB dictionary values would wrap the uint16 length
            # wire format: fall through to the raw layout (int32 lens)

    chars, _ = _chars_matrix(sarr, lens)
    w = chars.shape[1] if chars.size else 1
    coff = b.add(_padded(chars, wire))
    loff = b.add(_padded(lens.astype(np.int32), wire))
    return ("sraw", coff, loff, w, voff)


def _string_dict_gate(sarr: pa.Array) -> bool:
    n = len(sarr)
    if n <= 8192:
        return True
    d = sarr.slice(0, 4096).dictionary_encode()
    return len(d.dictionary) <= 1024


def _chars_matrix(sarr: pa.Array,
                  lens: Optional[np.ndarray] = None
                  ) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized fixed-width chars matrix for a large_string array:
    (chars[n, w], lengths[n])."""
    n = len(sarr)
    offsets = np.frombuffer(sarr.buffers()[1], dtype=np.int64,
                            count=n + 1, offset=sarr.offset * 8)
    data_buf = sarr.buffers()[2]
    raw = (np.frombuffer(data_buf, dtype=np.uint8)
           if data_buf is not None else np.zeros(1, np.uint8))
    if lens is None:
        lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    maxw = int(lens.max()) if n else 0
    w = pad_width(max(maxw, 1))
    if n == 0:
        return np.zeros((0, w), np.uint8), lens
    idx = offsets[:-1, None] + np.arange(w)[None, :]
    mask = np.arange(w)[None, :] < lens[:, None]
    safe = np.clip(idx, 0, max(len(raw) - 1, 0))
    chars = np.where(mask, raw[safe], 0).astype(np.uint8)
    return chars, lens


# ------------------------------------------------------------------ #
# Device-side unpack program
# ------------------------------------------------------------------ #


def _bitcast_from_u8(raw: jax.Array, npdt: np.dtype, count: int):
    if npdt == np.uint8:
        return raw
    if npdt.itemsize == 1:
        return jax.lax.bitcast_convert_type(raw, jnp.dtype(npdt))
    return jax.lax.bitcast_convert_type(
        raw.reshape(count, npdt.itemsize), jnp.dtype(npdt))


def _make_unpack(plan: tuple):
    cap, wire, entries = plan

    def unpack(buf: jax.Array):
        n_live = jax.lax.bitcast_convert_type(buf[0:8], jnp.int64)
        n_live = n_live.reshape(())
        live_mask = jnp.arange(cap, dtype=jnp.int64) < n_live
        pad = cap - wire

        def grow(a):
            if pad == 0:
                return a
            z = jnp.zeros((pad,) + a.shape[1:], a.dtype)
            return jnp.concatenate([a, z], axis=0)

        def read(off, npdt, count):
            raw = jax.lax.slice(buf, (off,),
                                (off + count * npdt.itemsize,))
            return _bitcast_from_u8(raw, npdt, count)

        def validity_of(voff):
            if voff < 0:
                return live_mask
            return grow(read(voff, np.dtype(np.uint8), wire) != 0) \
                & live_mask

        out = []
        for ci, e in enumerate(entries):
            if e[0] == "fixed":
                _, kind, off, wiredt, physdt, extra, voff = e
                npw, npp = np.dtype(wiredt), np.dtype(physdt)
                vals = read(off, npw, wire)
                if kind.startswith("bias"):
                    base = jax.lax.bitcast_convert_type(
                        buf[(1 + ci) * 8:(1 + ci) * 8 + 8],
                        jnp.int64).reshape(())
                    vals = (vals.astype(jnp.int64) + base).astype(
                        jnp.dtype(npp))
                elif kind == "dict":
                    doff, nvp, _ = extra
                    dvals = read(doff, npp, nvp)
                    vals = jnp.take(dvals, vals.astype(jnp.int32), axis=0)
                elif npp == np.bool_:
                    vals = vals != 0
                else:
                    vals = vals.astype(jnp.dtype(npp)) \
                        if npw != npp else vals
                out.append((grow(vals), validity_of(voff)))
            elif e[0] == "sraw":
                _, coff, loff, w, voff = e
                chars = read(coff, np.dtype(np.uint8),
                             wire * w).reshape(wire, w)
                lens = read(loff, np.dtype(np.int32), wire)
                v = validity_of(voff)
                out.append((grow(chars), grow(lens) * v.astype(jnp.int32),
                            v))
            elif e[0] == "sdict":
                _, coff, codedt, dcoff, dloff, nvp, w, voff = e
                codes = read(coff, np.dtype(codedt), wire).astype(
                    jnp.int32)
                dchars = read(dcoff, np.dtype(np.uint8),
                              nvp * w).reshape(nvp, w)
                dlens = read(dloff, np.dtype(np.uint16), nvp).astype(
                    jnp.int32)
                v = validity_of(voff)
                # invariant shared with every string kernel: chars are
                # zero for null rows and beyond each row's length — a
                # gathered dict[0] payload on null/padding rows would
                # break byte-wise comparators
                chars = grow(jnp.take(dchars, codes, axis=0)) \
                    * v[:, None].astype(jnp.uint8)
                lens = grow(jnp.take(dlens, codes, axis=0)) \
                    * v.astype(jnp.int32)
                out.append((chars, lens, v))
        return out

    return unpack


def decode_on_device(staging: np.ndarray, plan: tuple,
                     schema: T.Schema):
    """Upload one staging buffer and run the cached unpack program.

    Returns the list of device columns (order = schema order)."""
    with _cache_lock:
        fn = _unpack_cache.get(plan)
        if fn is None:
            fn = _unpack_cache[plan] = jax.jit(_make_unpack(plan))
            while len(_unpack_cache) > 256:
                _unpack_cache.pop(next(iter(_unpack_cache)))
    dev = jax.device_put(staging)
    parts = fn(dev)
    cols = []
    for f, p in zip(schema.fields, parts):
        if isinstance(f.dtype, T.StringType):
            chars, lens, valid = p
            cols.append(StringColumn(chars, lens, valid))
        else:
            data, valid = p
            cols.append(Column(data, valid, f.dtype))
    return cols


# ------------------------------------------------------------------ #
# Packed D2H fetch
# ------------------------------------------------------------------ #


def fetch_packed(comps: Sequence[jax.Array]) -> list[np.ndarray]:
    """Return host copies of device arrays in ONE D2H transfer.

    A cached jitted pack program bitcasts every component to uint8 and
    concatenates (8-aligned) into a single buffer; the host slices views
    back out.  D2H on tunneled links pays a full latency round per
    transfer, so one packed round beats per-array gets by ~column-count.
    """
    comps = list(comps)
    if not comps:
        return []
    layout = []
    off = 0
    for a in comps:
        npdt = np.dtype(a.dtype)
        count = int(np.prod(a.shape)) if a.ndim else 1
        off = _round_up(off, _ALIGN)
        layout.append((off, tuple(a.shape), str(npdt), count))
        off += count * npdt.itemsize
    total = _round_up(max(off, _ALIGN), _ALIGN)
    key = (total, tuple(layout))

    with _cache_lock:
        fn = _pack_cache.get(key)
        if fn is None:
            def make(layout=tuple(layout), total=total):
                def pack(xs):
                    buf = jnp.zeros(total, jnp.uint8)
                    for a, (o, shape, dt, count) in zip(xs, layout):
                        npdt = np.dtype(dt)
                        flat = a.reshape(count) if a.ndim != 1 else a
                        if npdt == np.bool_:
                            rawb = flat.astype(jnp.uint8)
                        elif npdt.itemsize == 1:
                            rawb = jax.lax.bitcast_convert_type(
                                flat, jnp.uint8)
                        else:
                            rawb = jax.lax.bitcast_convert_type(
                                flat, jnp.uint8).reshape(
                                    count * npdt.itemsize)
                        buf = jax.lax.dynamic_update_slice(
                            buf, rawb, (o,))
                    return buf
                return pack
            fn = _pack_cache[key] = jax.jit(make())
            while len(_pack_cache) > 256:
                _pack_cache.pop(next(iter(_pack_cache)))
    host = np.asarray(jax.device_get(fn(comps)))
    out = []
    for o, shape, dt, count in layout:
        npdt = np.dtype(dt)
        if npdt == np.bool_:
            a = host[o: o + count] != 0
        else:
            a = host[o: o + count * npdt.itemsize].view(npdt)[:count]
        out.append(a.reshape(shape))
    return out
