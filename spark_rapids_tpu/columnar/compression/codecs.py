"""The built-in wire codecs.

Array codecs are XLA-static-shape by construction: every packed
layout is fully determined by the static meta tuple (block size, lane
width, padded length, run capacity) that rides the wire plan — so one
compiled decode program serves every batch of the same plan, and the
decompress composes into whatever jitted program reads the component
(shifts/masks for bitpack lanes, segment cumsum for delta,
cumsum+searchsorted gather for RLE; deliberately no
bitcast_convert_type — see columnar/transfer.py's X64-rewriter
caveat).

Host packing is vectorized numpy: k-bit lanes fold into uint32 words
by a reshape + shift + or-reduce, so the scan-prefetch thread pays a
few passes over the column, not a Python loop.
"""

from __future__ import annotations

import zlib
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.compression.registry import (
    Codec,
    register_codec,
)

#: packed lane widths: sub-byte and sub-word powers of two, so each
#: uint32 word holds exactly 32/k lanes and shifts are static masks.
#: k=0 is the degenerate pure-frame-of-reference form (constant
#: blocks: only the per-block references ride the wire).
_KBITS = (0, 1, 2, 4, 8, 16)


def _pack_words(lanes: np.ndarray, k: int) -> np.ndarray:
    """k-bit lanes (uint32, values < 2**k, length a multiple of 32/k)
    -> packed uint32 words, little-endian lane order within a word."""
    vpw = 32 // k
    m = lanes.reshape(-1, vpw)
    shifts = (np.arange(vpw, dtype=np.uint32) * np.uint32(k))
    return np.bitwise_or.reduce(m << shifts, axis=1).astype(np.uint32)


def _unpack_words(words, k: int, n: int):
    """Traceable inverse of _pack_words: n k-bit lanes as uint32."""
    vpw = 32 // k
    i = jnp.arange(n, dtype=jnp.int32)
    w = jnp.take(words, i // vpw, axis=0)
    sh = ((i % vpw) * k).astype(jnp.uint32)
    return (w >> sh) & jnp.uint32((1 << k) - 1)


def _pad_to_blocks(v: np.ndarray, block: int) -> np.ndarray:
    """Pad to a whole number of blocks with the LAST value (keeps the
    tail block's range — zero padding could widen it; the decode
    slices the pad back off, so the fill never surfaces)."""
    n = len(v)
    padded = -(-n // block) * block
    if padded == n:
        return v
    return np.concatenate([v, np.full(padded - n, v[-1], v.dtype)])


def _range_guard(v64: np.ndarray) -> bool:
    """True when (v - blockmin) arithmetic cannot overflow int64.  A
    spread past 2**62 is incompressible for these codecs anyway."""
    return int(v64.max()) - int(v64.min()) < (1 << 62)


def _sample_blocks(n: int, block: int, take: int = 16) -> np.ndarray:
    nb = max(1, n // block)
    return np.unique(np.linspace(0, nb - 1, min(nb, take)).astype(int))


def _choose_k(ranges: np.ndarray, block: int, itemsize: int,
              padded: int) -> tuple[int, int]:
    """(k, nexc) minimizing total packed cost.  Blocks whose range
    exceeds 2**k - 1 become EXCEPTIONS shipped raw and scatter-patched
    on device (patched frame-of-reference) — so one outlier block (a
    value spike, or the mixed live/zero-pad block at the wire tail)
    cannot poison the lane width of the whole column."""
    best_cost = best_k = best_exc = None
    for k in _KBITS:
        lim = (1 << k) - 1
        nexc = int(np.count_nonzero(ranges > lim))
        cost = (padded * k) // 8 + nexc * block * itemsize \
            + len(ranges) * 8
        if best_cost is None or cost < best_cost:
            best_cost, best_k, best_exc = cost, k, nexc
    return best_k, best_exc


def _exception_comps(a_padded: np.ndarray, exc_blocks: np.ndarray,
                     block: int) -> tuple[list[np.ndarray], int]:
    """([block indices (int32), raw block values (wire dtype)], cap)
    for the exception blocks.  The count buckets to a power of two —
    padded with REPEATS of the last exception block, so the duplicate
    device scatter rewrites the same rows with the same values
    (idempotent) — because the cap lands in the static meta that keys
    the compiled decode program: an exact per-batch count would mint a
    fresh XLA program per outlier population (the same reason RLE
    buckets its run capacity)."""
    idx = np.flatnonzero(exc_blocks).astype(np.int32)
    cap = 1
    while cap < len(idx):
        cap <<= 1
    pad = cap - len(idx)
    if pad:
        idx = np.concatenate([idx, np.full(pad, idx[-1], np.int32)])
    vals = a_padded.reshape(-1, block)[idx].reshape(-1)
    return [idx, np.ascontiguousarray(vals)], cap


def _patch_exceptions(out, arrays: Sequence, nexc: int, block: int):
    """Traceable: overwrite the exception blocks of the reconstructed
    (padded-length) array with their raw values."""
    if nexc == 0:
        return out
    exc_idx, exc_vals = arrays[-2], arrays[-1]
    rows = (exc_idx[:, None].astype(jnp.int32) * block
            + jnp.arange(block, dtype=jnp.int32)[None, :]).reshape(-1)
    return out.at[rows].set(exc_vals.astype(out.dtype))


class BitpackCodec(Codec):
    """Block frame-of-reference + sub-byte bitpacking: per pow2 block
    the host subtracts the block minimum and packs the deltas as k-bit
    lanes into uint32 words (k the smallest of 1/2/4/8/16 covering the
    widest block range); the device unpacks with shifts/masks and adds
    the gathered block reference back.  The workhorse for dict codes,
    dates, validity masks and clustered integer keys."""

    name = "bitpack"
    decoder_program_key = "device:wire.decode.bitpack"
    supports_arrays = True

    def estimate(self, vals: np.ndarray,
                 block_rows: int) -> Optional[float]:
        v = vals.astype(np.int64, copy=False)
        ranges = []
        for b in _sample_blocks(len(v), block_rows):
            blk = v[b * block_rows:(b + 1) * block_rows]
            if not _range_guard(blk):
                return None
            ranges.append(int(blk.max()) - int(blk.min()))
        k, nexc = _choose_k(np.asarray(ranges, np.int64), block_rows,
                            vals.dtype.itemsize,
                            len(ranges) * block_rows)
        exc_frac = nexc / max(len(ranges), 1)
        return vals.dtype.itemsize / (
            k / 8 + 8.0 / block_rows
            + exc_frac * vals.dtype.itemsize)

    def encode_array(self, vals: np.ndarray, block_rows: int
                     ) -> Optional[tuple[list[np.ndarray], tuple]]:
        n = len(vals)
        v64 = _pad_to_blocks(vals.astype(np.int64), block_rows)
        if not _range_guard(v64):
            return None
        a_padded = _pad_to_blocks(np.asarray(vals), block_rows) \
            if len(v64) != n else np.asarray(vals)
        blocks = v64.reshape(-1, block_rows)
        refs = blocks.min(axis=1)
        delta = blocks - refs[:, None]
        ranges = delta.max(axis=1)
        k, nexc = _choose_k(ranges, block_rows,
                            vals.dtype.itemsize, len(v64))
        exc_blocks = ranges > ((1 << k) - 1)
        comps: list[np.ndarray] = []
        if k > 0:
            lanes = np.where(exc_blocks[:, None], 0, delta)
            comps.append(_pack_words(
                lanes.reshape(-1).astype(np.uint32), k))
        comps.append(refs)
        exc_cap = 0
        if nexc:
            exc, exc_cap = _exception_comps(a_padded, exc_blocks,
                                            block_rows)
            comps += exc
        return comps, ("bitpack", block_rows, k, exc_cap, len(v64), n)

    def decode_array(self, arrays: Sequence, meta: tuple,
                     out_dtype: np.dtype):
        _, block, k, nexc, padded, n = meta
        i = jnp.arange(padded, dtype=jnp.int32)
        if k == 0:
            refs = arrays[0]
            out = jnp.take(refs, i // block, axis=0)
        else:
            words, refs = arrays[0], arrays[1]
            d = _unpack_words(words, k, padded).astype(jnp.int64)
            out = jnp.take(refs, i // block, axis=0) + d
        out = _patch_exceptions(out.astype(out_dtype), arrays, nexc,
                                block)
        return out[:n]


class DeltaCodec(Codec):
    """Delta + zigzag + bitpack for sorted/clustered columns (shipdates
    out of a time-ordered file, monotone keys): per block the host
    stores the first value and packs zigzagged successive differences;
    the device unpacks and reconstructs with a per-block cumulative
    sum."""

    name = "delta"
    decoder_program_key = "device:wire.decode.delta"
    supports_arrays = True

    @staticmethod
    def _zigzag_bits(d: np.ndarray) -> int:
        z = (d << 1) ^ (d >> 63)
        return int(z.max()).bit_length() if len(z) else 0

    def estimate(self, vals: np.ndarray,
                 block_rows: int) -> Optional[float]:
        v = vals.astype(np.int64, copy=False)
        ranges = []
        for b in _sample_blocks(len(v), block_rows, take=8):
            blk = v[b * block_rows:(b + 1) * block_rows]
            if len(blk) < 2:
                continue
            if not _range_guard(blk):
                return None
            ranges.append(self._zigzag_bits(np.diff(blk)))
        if not ranges:
            return None
        zr = np.asarray([(1 << b) - 1 for b in ranges], np.int64)
        k, nexc = _choose_k(zr, block_rows, vals.dtype.itemsize,
                            len(zr) * block_rows)
        exc_frac = nexc / len(zr)
        return vals.dtype.itemsize / (
            k / 8 + 8.0 / block_rows
            + exc_frac * vals.dtype.itemsize)

    def encode_array(self, vals: np.ndarray, block_rows: int
                     ) -> Optional[tuple[list[np.ndarray], tuple]]:
        n = len(vals)
        v64 = _pad_to_blocks(vals.astype(np.int64), block_rows)
        if not _range_guard(v64):
            return None
        a_padded = _pad_to_blocks(np.asarray(vals), block_rows) \
            if len(v64) != n else np.asarray(vals)
        blocks = v64.reshape(-1, block_rows)
        refs = np.ascontiguousarray(blocks[:, 0])
        d = np.diff(blocks, axis=1, prepend=blocks[:, :1])
        z = (d << 1) ^ (d >> 63)
        ranges = z.max(axis=1)
        k, nexc = _choose_k(ranges, block_rows,
                            vals.dtype.itemsize, len(v64))
        exc_blocks = ranges > ((1 << k) - 1)
        comps: list[np.ndarray] = []
        if k > 0:
            lanes = np.where(exc_blocks[:, None], 0, z)
            comps.append(_pack_words(
                lanes.reshape(-1).astype(np.uint32), k))
        comps.append(refs)
        exc_cap = 0
        if nexc:
            exc, exc_cap = _exception_comps(a_padded, exc_blocks,
                                            block_rows)
            comps += exc
        return comps, ("delta", block_rows, k, exc_cap, len(v64), n)

    def decode_array(self, arrays: Sequence, meta: tuple,
                     out_dtype: np.dtype):
        _, block, k, nexc, padded, n = meta
        if k == 0:
            refs = arrays[0]
            i = jnp.arange(padded, dtype=jnp.int32)
            out = jnp.take(refs, i // block, axis=0)
        else:
            words, refs = arrays[0], arrays[1]
            z = _unpack_words(words, k, padded).astype(jnp.int64)
            d = (z >> 1) ^ -(z & 1)  # un-zigzag
            out = (jnp.cumsum(d.reshape(-1, block), axis=1)
                   + refs[:, None]).reshape(-1)
        out = _patch_exceptions(out.astype(out_dtype), arrays, nexc,
                                block)
        return out[:n]


class RleCodec(Codec):
    """Block run-length encoding, expanded on device via a cumulative
    sum over run lengths and a searchsorted gather — heavy-repeat
    columns (status flags, low-cardinality codes in clustered order,
    zero-padded char tails) collapse to (values, lengths) pairs.  Run
    capacity buckets to a power of two so program variants stay
    bounded."""

    name = "rle"
    decoder_program_key = "device:wire.decode.rle"
    supports_arrays = True

    def estimate(self, vals: np.ndarray,
                 block_rows: int) -> Optional[float]:
        n = len(vals)
        win = min(n, 2048)
        changes = 0
        sampled = 0
        for start in {0, max(0, n // 2 - win // 2), max(0, n - win)}:
            w = vals[start:start + win]
            if len(w) > 1:
                changes += int(np.count_nonzero(w[1:] != w[:-1]))
                sampled += len(w) - 1
        if sampled == 0:
            return None
        est_runs = max(1.0, (changes / sampled) * n + 3)
        return (n * vals.dtype.itemsize) \
            / (est_runs * (vals.dtype.itemsize + 4))

    def encode_array(self, vals: np.ndarray, block_rows: int
                     ) -> Optional[tuple[list[np.ndarray], tuple]]:
        n = len(vals)
        change = np.flatnonzero(vals[1:] != vals[:-1]) + 1
        starts = np.concatenate([np.zeros(1, np.int64), change])
        r = len(starts)
        cap = 8
        while cap < r:
            cap <<= 1
        values = np.empty(cap, vals.dtype)
        values[:r] = vals[starts]
        values[r:] = vals[-1]
        lens = np.zeros(cap, np.int32)
        lens[:r] = np.diff(np.concatenate(
            [starts, np.asarray([n], np.int64)])).astype(np.int32)
        return [values, lens], ("rle", cap, n)

    def decode_array(self, arrays: Sequence, meta: tuple,
                     out_dtype: np.dtype):
        values, lens = arrays
        _, _cap, n = meta
        ends = jnp.cumsum(lens)
        idx = jnp.searchsorted(ends, jnp.arange(n, dtype=ends.dtype),
                               side="right")
        return jnp.take(values, idx, axis=0).astype(out_dtype)


class NoneCodec(Codec):
    """Identity byte codec: frames ship as serialized."""

    name = "none"
    decoder_program_key = "host:identity"
    supports_bytes = True

    def compress_bytes(self, body: bytes) -> bytes:
        return body

    def decompress_bytes(self, body: bytes) -> bytes:
        return body


class ZlibCodec(Codec):
    """Host-side zlib for serde frames (TCP shuffle, spill files) —
    the stdlib stand-in for nvcomp's host path (zstd/lz4 are not in
    this image)."""

    name = "zlib"
    decoder_program_key = "host:zlib.decompress"
    supports_bytes = True

    def compress_bytes(self, body: bytes) -> bytes:
        return zlib.compress(body, 1)

    def decompress_bytes(self, body: bytes) -> bytes:
        return zlib.decompress(body)


register_codec(BitpackCodec())
register_codec(DeltaCodec())
register_codec(RleCodec())
register_codec(NoneCodec())
register_codec(ZlibCodec())
