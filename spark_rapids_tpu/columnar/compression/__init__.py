"""Wire-codec subsystem: host-side compression + device-side
decompression for the H2D tunnel, unified with the TCP shuffle and
spill tiers through one codec registry and one per-codec stats
surface.  See registry.py for the architecture and
docs/wire_compression.md for the operator view."""

from spark_rapids_tpu.columnar.compression.registry import (  # noqa: F401
    MIN_COMPRESS_BYTES,
    WIRE_BLOCK_ROWS,
    WIRE_CODECS,
    WIRE_ENABLED,
    WIRE_MIN_RATIO,
    Codec,
    choose_and_encode,
    get_bytes_codec,
    get_codec,
    record_compress,
    record_decompress,
    register_codec,
    registry_items,
    reset_stats,
    stats,
    unregister_codec,
    wire_codec_config,
)
from spark_rapids_tpu.columnar.compression import codecs  # noqa: F401,E402
