"""Wire-codec registry: the shared codec surface for the H2D tunnel,
the TCP shuffle tier, and the spill tiers.

The reference compresses shuffle slices ON DEVICE via nvcomp before
they touch the wire (RapidsShuffleManager + NvcompLZ4CompressionCodec;
conf spark.rapids.shuffle.compression.codec) and decompresses on the
GPU.  The TPU mirror splits the work across the link the same way but
with XLA-friendly primitives: the HOST compresses wire components
during scan-prefetch encode, and a jitted DEVICE program decompresses
them in HBM — so compressed bytes, not raw, cross the ~13 MB/s
tunneled H2D link that bounds the losing BASELINE milestones.

Two codec kinds share one registry and one per-codec stats surface:

- ARRAY codecs (bitpack, delta, rle): host ``encode_array`` packs a
  1-D integer/bool component into smaller typed arrays + a static
  meta tuple; device ``decode_array`` reconstructs the exact original
  inside whatever jitted program reads the component (the scan decode,
  or a fused consumer program).  Everything is shift/mask/gather/
  cumsum — XLA-static shapes, no bitcasts, so the decode composes
  into the existing wire-decode program as one fused XLA program.
- BYTE codecs (none, zlib): host-side framed-bytes compression for
  the serde tier (TCP shuffle frames, disk/host spill files) — the
  stdlib stand-in for nvcomp's host path.

Every codec declares a ``decoder_program_key`` naming the program (or
host routine) that undoes it; tpulint REG007 hard-fails a registered
codec without one, or one missing from the round-trip test matrix.

Compression is LOSSLESS RE-ENCODING, never approximation: a codec
must round-trip bit-exactly or refuse (return None) — the chooser
additionally refuses when the measured ratio does not clear
``wireCompression.minRatio``, mirroring the ``_try_dict`` /
``_try_scaled`` pays-for-itself gates in columnar/transfer.py.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from spark_rapids_tpu.config import get_conf, register

WIRE_ENABLED = register(
    "spark.rapids.tpu.sql.wireCompression.enabled", False,
    "Compress wire components on the host during scan-prefetch encode "
    "and decompress them on device inside the jitted wire-decode "
    "program, so compressed bytes (not raw) cross the H2D link (the "
    "TPU mirror of the reference's nvcomp device-side shuffle "
    "compression, RapidsConf.scala:905).  Off is bit-for-bit "
    "identical to the uncompressed wire format.")

WIRE_CODECS = register(
    "spark.rapids.tpu.sql.wireCompression.codecs", "bitpack,delta,rle",
    "Comma-separated array codecs the per-column chooser may pick "
    "from, in no particular order (the chooser ranks by estimated "
    "ratio): bitpack (block frame-of-reference + sub-byte bitpacking "
    "for integers/dict-codes/dates/validity), delta (delta + zigzag + "
    "bitpack for sorted/clustered columns), rle (block run-length, "
    "expanded on device via cumsum/searchsorted gather).")

WIRE_MIN_RATIO = register(
    "spark.rapids.tpu.sql.wireCompression.minRatio", 1.3,
    "Minimum measured compression ratio (raw bytes / packed bytes) a "
    "codec must achieve on a component before it rides the wire "
    "compressed; below this the component ships raw (compression "
    "must pay for its decode gathers).",
    check=lambda v: v >= 1.0)

WIRE_BLOCK_ROWS = register(
    "spark.rapids.tpu.sql.wireCompression.blockRows", 256,
    "Frame-of-reference / delta block size in rows (power of two, "
    ">= 32 so packed lanes tile uint32 words exactly).  Smaller "
    "blocks track local value ranges tighter at more per-block "
    "reference overhead.",
    check=lambda v: v >= 32 and (v & (v - 1)) == 0)

#: components smaller than this ship raw — a packed scalar or a tiny
#: dictionary would spend a decode gather to save nothing measurable
MIN_COMPRESS_BYTES = 1024


class Codec:
    """One registered codec.  Array codecs implement ``estimate`` /
    ``encode_array`` / ``decode_array``; byte codecs implement
    ``compress_bytes`` / ``decompress_bytes``.  ``decoder_program_key``
    names the decode program (device) or routine (host) that undoes
    the encode — REG007 requires it and a round-trip test matrix row
    for every registered codec."""

    name: str = ""
    decoder_program_key: str = ""
    supports_arrays: bool = False
    supports_bytes: bool = False

    # -- array side (host pack -> device unpack) ------------------------ #

    def estimate(self, vals: np.ndarray,
                 block_rows: int) -> Optional[float]:
        """Cheap sampled ratio estimate (host), or None when the codec
        cannot apply.  Never exact — the chooser re-checks the real
        ratio after ``encode_array``."""
        return None

    def encode_array(self, vals: np.ndarray, block_rows: int
                     ) -> Optional[tuple[list[np.ndarray], tuple]]:
        """vals (1-D, int/uint/bool) -> (component arrays, static meta)
        or None when the codec does not apply.  The meta tuple must be
        hashable: it rides the wire plan and keys the compiled decode
        program."""
        raise NotImplementedError(self.name)

    def decode_array(self, arrays: Sequence, meta: tuple,
                     out_dtype: np.dtype):
        """TRACEABLE device decompress: the uploaded component arrays
        + meta -> the exact original 1-D array (dtype ``out_dtype``).
        Runs inside whatever jitted program reads the component."""
        raise NotImplementedError(self.name)

    # -- byte side (serde frames: shuffle + spill) ---------------------- #

    def compress_bytes(self, body: bytes) -> bytes:
        raise NotImplementedError(self.name)

    def decompress_bytes(self, body: bytes) -> bytes:
        raise NotImplementedError(self.name)


_REG_LOCK = threading.Lock()
_REGISTRY: dict[str, Codec] = {}


def register_codec(codec: Codec) -> Codec:
    if not codec.name:
        raise ValueError("codec must declare a name")
    with _REG_LOCK:
        _REGISTRY[codec.name] = codec
    return codec


def unregister_codec(name: str) -> None:
    """Test hook: remove a codec registered by a fixture."""
    with _REG_LOCK:
        _REGISTRY.pop(name, None)


def get_codec(name: str) -> Codec:
    with _REG_LOCK:
        c = _REGISTRY.get(name)
    if c is None:
        raise ValueError(f"unknown codec {name!r}")
    return c


def get_bytes_codec(name: str) -> Codec:
    c = get_codec(name)
    if not c.supports_bytes:
        raise ValueError(
            f"codec {name!r} has no byte-stream form (array-only)")
    return c


def registry_items() -> list[tuple[str, Codec]]:
    with _REG_LOCK:
        return sorted(_REGISTRY.items())


# ------------------------------------------------------------------ #
# Per-codec stats: THE shared observability surface (H2D tunnel,
# TCP shuffle and spill all report here)
# ------------------------------------------------------------------ #

_STATS_LOCK = threading.Lock()
_STATS: dict[str, dict] = {}


def _stat_entry(name: str) -> dict:
    e = _STATS.get(name)
    if e is None:
        e = _STATS[name] = {"compress_calls": 0, "decompress_calls": 0,
                            "raw_bytes": 0, "wire_bytes": 0}
    return e


def record_compress(name: str, raw: int, wire: int) -> None:
    with _STATS_LOCK:
        e = _stat_entry(name)
        e["compress_calls"] += 1
        e["raw_bytes"] += int(raw)
        e["wire_bytes"] += int(wire)


def record_decompress(name: str, count: int = 1) -> None:
    with _STATS_LOCK:
        _stat_entry(name)["decompress_calls"] += int(count)


def stats() -> dict:
    """{codec: {compress_calls, decompress_calls, raw_bytes,
    wire_bytes, ratio}} — one surface per codec regardless of which
    tier (H2D wire, shuffle frame, spill file) drove it."""
    with _STATS_LOCK:
        out = {}
        for name, e in sorted(_STATS.items()):
            d = dict(e)
            d["ratio"] = round(e["raw_bytes"] / e["wire_bytes"], 3) \
                if e["wire_bytes"] else 0.0
            out[name] = d
        return out


def reset_stats() -> None:
    with _STATS_LOCK:
        _STATS.clear()


# ------------------------------------------------------------------ #
# The chooser
# ------------------------------------------------------------------ #


def wire_codec_config(conf=None) -> Optional[tuple]:
    """(codec names, min_ratio, block_rows) when wire compression is
    enabled, else None — disabled is ONE conf read and the encode path
    is byte-identical to the uncompressed wire format."""
    conf = conf or get_conf()
    if not conf.get_bool(WIRE_ENABLED.key):
        return None
    names = tuple(n.strip() for n in
                  str(conf.get(WIRE_CODECS)).split(",") if n.strip())
    return names, float(conf.get(WIRE_MIN_RATIO)), \
        int(conf.get(WIRE_BLOCK_ROWS))


def choose_and_encode(vals: np.ndarray, names: Sequence[str],
                      min_ratio: float, block_rows: int
                      ) -> Optional[tuple[str, list[np.ndarray], tuple]]:
    """Pick the best-paying codec for one 1-D wire component, or None
    to ship raw.  Cheap sampled estimates rank the candidates
    (mirroring the _try_dict/_try_scaled entropy gates); the winner's
    REAL ratio is re-checked against ``min_ratio`` before committing —
    estimates may flatter, the wire never lies."""
    if vals.ndim != 1 or vals.dtype.kind not in "iub" \
            or vals.nbytes < MIN_COMPRESS_BYTES or len(vals) == 0:
        return None
    ranked = []
    for name in names:
        with _REG_LOCK:
            c = _REGISTRY.get(name)
        if c is None or not c.supports_arrays:
            continue
        est = c.estimate(vals, block_rows)
        if est is not None and est >= min_ratio:
            ranked.append((est, name, c))
    ranked.sort(key=lambda t: t[0], reverse=True)
    for _est, name, c in ranked:
        enc = c.encode_array(vals, block_rows)
        if enc is None:
            continue
        arrays, meta = enc
        wire = sum(int(a.nbytes) for a in arrays)
        if wire == 0 or vals.nbytes / wire < min_ratio:
            continue
        record_compress(name, vals.nbytes, wire)
        return name, arrays, meta
    return None
