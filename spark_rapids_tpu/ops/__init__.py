"""Shared device kernels (sort, segmented aggregation, partitioning).

This package plays the role cudf's C++ kernels play for the reference
(L0 in SURVEY.md): dense, fixed-shape primitives the operator library
calls into.  Here they are jax.numpy/XLA programs (Pallas where it pays).
"""
