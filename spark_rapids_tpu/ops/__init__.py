"""Shared device kernels (sort, segmented aggregation, partitioning).

This package plays the role cudf's C++ kernels play for the reference
(L0 in SURVEY.md): dense, fixed-shape primitives the operator library
calls into.  Here they are jax.numpy/XLA programs (Pallas where it pays).
"""

# eager conf registration: the pallas.enabled entry must exist before
# any TpuConf snapshot (env-var overrides, conf.set conversion, docs)
from spark_rapids_tpu.ops import pallas_kernels  # noqa: E402,F401
