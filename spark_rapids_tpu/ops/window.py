"""Segmented window kernels over sort-partitioned batches.

TPU re-design of the reference's window machinery (ref: GpuWindowExec.scala
:27,92 and GpuWindowExpression.scala:174,207-296 — cudf rolling/group
windows).  cudf evaluates each window aggregation with a dedicated
windowed kernel; the XLA-idiomatic design computes every window column
from a handful of *segmented scan* primitives over the batch sorted by
(partition keys, order keys):

    segment starts -> per-row segment start/end positions (cummax /
    reversed cummax) -> prefix sums invert into ANY rows-frame aggregate
    (sum/count/avg over [lo, hi] = c[hi] - c[lo-1]); ranking functions
    are arithmetic on start positions and order-key-change flags; lead/
    lag are clamped gathers.

Everything is one fused fixed-shape XLA program; there is no per-frame
kernel dispatch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import AnyColumn, Column, StringColumn


def _idx(cap: int) -> jax.Array:
    return jnp.arange(cap, dtype=jnp.int32)


def segment_positions(is_start: jax.Array, live: jax.Array):
    """Per-row segment start and end positions (inclusive), given start
    flags over a live-prefix batch.  Dead rows get degenerate [i, i]."""
    cap = is_start.shape[0]
    idx = _idx(cap)
    start_idx = jax.lax.cummax(jnp.where(is_start, idx, 0))
    # a row is a segment end if the next row starts a new segment (or is
    # dead / off the end)
    nxt_start = jnp.concatenate(
        [is_start[1:], jnp.ones((1,), is_start.dtype)])
    nxt_live = jnp.concatenate([live[1:], jnp.zeros((1,), live.dtype)])
    is_end = live & (nxt_start | ~nxt_live)
    end_idx = jnp.flip(jax.lax.cummin(
        jnp.flip(jnp.where(is_end, idx, cap - 1))))
    start_idx = jnp.where(live, start_idx, idx)
    end_idx = jnp.where(live, end_idx, idx)
    return start_idx, end_idx


def prefix_at(c: jax.Array, pos: jax.Array) -> jax.Array:
    """c is an inclusive prefix sum; sum over [0, pos] with pos possibly
    -1 (empty -> 0)."""
    v = jnp.take(c, jnp.clip(pos, 0, c.shape[0] - 1), axis=0)
    return jnp.where(pos < 0, jnp.zeros_like(v), v)


def range_sum(c: jax.Array, lo: jax.Array, hi: jax.Array) -> jax.Array:
    """Sum over rows [lo, hi] given inclusive prefix sums c; empty
    (hi < lo) -> 0."""
    s = prefix_at(c, hi) - prefix_at(c, lo - 1)
    return jnp.where(hi < lo, jnp.zeros_like(s), s)


def frame_bounds(start_idx: jax.Array, end_idx: jax.Array,
                 lo_off, hi_off, cap: int):
    """Resolve a ROWS frame (offsets relative to current row; None =
    unbounded) into absolute [lo, hi] positions clamped to the segment."""
    idx = _idx(cap)
    lo = start_idx if lo_off is None else jnp.clip(
        idx + jnp.int32(lo_off), start_idx, end_idx + 1)
    hi = end_idx if hi_off is None else jnp.clip(
        idx + jnp.int32(hi_off), start_idx - 1, end_idx)
    return lo, hi


def bounded_bisect(keys: jax.Array, targets: jax.Array,
                   lo_b: jax.Array, hi_b: jax.Array, side: str,
                   cap: int, key_cls=None, target_cls=None) -> jax.Array:
    """Vectorized per-row binary search over a segment-sorted key array:
    for each row, the insertion point of `targets` within
    [lo_b, hi_b + 1) of `keys` (side='left' -> first key >= target,
    'right' -> first key > target).  log2(cap) gather/compare rounds —
    the whole batch searches in lockstep on the VPU (no per-row loops),
    which is how value-based RANGE frames (ref:
    GpuWindowExpression.scala:207-296 bounded RangeFrame) map to TPU.

    `key_cls`/`target_cls` (int8) make the comparison LEXICOGRAPHIC on
    (class, key): null/NaN/padding rows get their own ordering class so
    their float sentinels can never collide with genuine +-inf keys."""
    lo = lo_b.astype(jnp.int32)
    hi = (hi_b + 1).astype(jnp.int32)
    for _ in range(max(cap, 2).bit_length() + 1):
        cont = lo < hi
        mid = (lo + hi) // 2
        midc = jnp.clip(mid, 0, cap - 1)
        mv = jnp.take(keys, midc)
        kv_lt = (mv < targets) if side == "left" else (mv <= targets)
        if key_cls is not None:
            mc = jnp.take(key_cls, midc)
            pred = (mc < target_cls) | ((mc == target_cls) & kv_lt)
        else:
            pred = kv_lt
        lo = jnp.where(cont & pred, mid + 1, lo)
        hi = jnp.where(cont & ~pred, mid, hi)
    return lo


def range_frame_bounds(okey: Column, descending: bool,
                       nulls_first_sorted: bool, fstart, fend,
                       start_idx, end_idx, peer_start, peer_end,
                       live, cap: int):
    """Per-row [lo, hi] for a bounded value-based RANGE frame over ONE
    numeric order key (Spark semantics, GpuWindowExpression.scala:207):
    ascending, `s PRECEDING .. e FOLLOWING` covers rows whose key lies
    in [v+s, v+e] (s negative); descending measures distance the other
    way, handled by negating the working key.  Null-key rows form their
    own frame (their peer group); null/padding slots get +-inf
    sentinels consistent with their sorted position so finite targets
    never include them."""
    data = okey.data
    if jnp.issubdtype(data.dtype, jnp.integer):
        w = data.astype(jnp.int64)
        big = jnp.asarray(jnp.iinfo(jnp.int64).max, jnp.int64)
        small = jnp.asarray(jnp.iinfo(jnp.int64).min, jnp.int64)
    else:
        w = data.astype(jnp.float64)
        big = jnp.asarray(jnp.inf, jnp.float64)
        small = jnp.asarray(-jnp.inf, jnp.float64)
    if descending:
        w = -w
    # ordering CLASSES keep special rows bisectable without sentinel
    # collisions (a real +-inf key must not capture NaN/null rows).
    # Classes mirror the SORTED layout: nulls at -2 or +4 per the sort
    # key's null placement, NaN (greatest VALUE in Spark's total order)
    # at +2 ascending / -1 descending, finite values at 1, padding +5.
    cls = jnp.ones((cap,), jnp.int8)
    if jnp.issubdtype(data.dtype, jnp.floating):
        isnan_key = okey.validity & jnp.isnan(data)
        nan_cls = jnp.int8(-1) if descending else jnp.int8(2)
        cls = jnp.where(isnan_key, nan_cls, cls)
        w = jnp.where(isnan_key, big, w)  # value irrelevant: own class
    else:
        isnan_key = jnp.zeros((cap,), bool)
    null_cls = jnp.int8(-2) if nulls_first_sorted else jnp.int8(4)
    cls = jnp.where(okey.validity, cls, null_cls)
    w = jnp.where(okey.validity, w, big)
    cls = jnp.where(live, cls, jnp.int8(5))  # padding at the back
    cur = jnp.where(okey.validity & live, w, 0)
    tcls = jnp.ones((cap,), jnp.int8)  # finite targets: class 1
    lo = start_idx if fstart is None else bounded_bisect(
        w, cur + fstart, start_idx, end_idx, "left", cap,
        key_cls=cls, target_cls=tcls)
    hi = end_idx if fend is None else bounded_bisect(
        w, cur + fend, start_idx, end_idx, "right", cap,
        key_cls=cls, target_cls=tcls) - 1
    # null-key and NaN-key rows: the peer block is the frame
    first_peer = jax.lax.cummax(jnp.where(peer_start, _idx(cap), 0))
    special = live & (~okey.validity | isnan_key)
    lo = jnp.where(special, first_peer, lo)
    hi = jnp.where(special, peer_end, hi)
    return lo, hi


def windowed_sum_count(col: Column, lo: jax.Array, hi: jax.Array,
                       live: jax.Array, out_dtype: T.DataType):
    """(sum over frame, non-null count over frame) for a value column."""
    phys = T.to_numpy_dtype(out_dtype)
    valid = col.validity & live
    vals = jnp.where(valid, col.data.astype(phys), jnp.asarray(0, phys))
    csum = jnp.cumsum(vals)
    ccnt = jnp.cumsum(valid.astype(jnp.int64))
    s = range_sum(csum, lo, hi)
    n = range_sum(ccnt, lo, hi)
    return s, n


def segmented_cummin_cummax(vals: jax.Array, is_start: jax.Array,
                            op: str) -> jax.Array:
    """Running min/max within segments via an associative segmented scan:
    combine((a, fa), (b, fb)) = (b if fb else op(a, b), fa | fb)."""
    f = jnp.minimum if op == "min" else jnp.maximum

    def combine(x, y):
        av, af = x
        bv, bf = y
        return jnp.where(bf, bv, f(av, bv)), af | bf

    out, _ = jax.lax.associative_scan(combine, (vals, is_start))
    return out


def minmax_sentinel(phys, op: str):
    if jnp.issubdtype(phys, jnp.floating):
        return jnp.asarray(jnp.inf if op == "min" else -jnp.inf, phys)
    info = jnp.iinfo(phys)
    return jnp.asarray(info.max if op == "min" else info.min, phys)


def windowed_minmax(col: Column, op: str, is_start: jax.Array,
                    live: jax.Array, lo: jax.Array, hi: jax.Array,
                    anchored_start: bool, cap: int):
    """min/max over frames with one side unbounded.  Frames starting at
    the partition edge read the forward running scan at position hi
    (min over [start, hi] == running_min[hi]); frames ending at the edge
    read the reversed running scan at lo.  Bounded-both-sides min/max
    needs a different structure; the planner falls back for those.
    Returns (values, non-empty-frame mask)."""
    valid = col.validity & live
    sent = minmax_sentinel(col.data.dtype, op)
    vals = jnp.where(valid, col.data, sent)
    is_float = jnp.issubdtype(col.data.dtype, jnp.floating)
    if is_float and op == "min":
        # Spark float total order: NaN is greatest, so MIN ignores NaN
        # unless the whole frame is NaN (handled after the scan); MAX
        # keeps IEEE NaN propagation, which already realizes it
        isnan = valid & jnp.isnan(col.data)
        vals = jnp.where(isnan, sent, vals)
        cnan = jnp.cumsum(isnan.astype(jnp.int32))
    ccnt = jnp.cumsum(valid.astype(jnp.int32))
    if anchored_start:
        run = segmented_cummin_cummax(vals, is_start, op)
        out = jnp.take(run, jnp.clip(hi, 0, cap - 1))
    else:
        # reversed scan: segment starts in reversed order are the ends
        nxt_start = jnp.concatenate(
            [is_start[1:], jnp.ones((1,), is_start.dtype)])
        nxt_live = jnp.concatenate([live[1:], jnp.zeros((1,), live.dtype)])
        is_end = live & (nxt_start | ~nxt_live)
        rev = lambda x: jnp.flip(x, axis=0)  # noqa: E731
        run = rev(segmented_cummin_cummax(rev(vals), rev(is_end), op))
        out = jnp.take(run, jnp.clip(lo, 0, cap - 1))
    n = range_sum(ccnt, lo, hi)
    if is_float and op == "min":
        n_nan = range_sum(cnan, lo, hi)
        out = jnp.where((n > 0) & (n_nan == n),
                        jnp.asarray(jnp.nan, out.dtype), out)
    return out, n > 0


def gather_in_segment(col: AnyColumn, offset: int, start_idx: jax.Array,
                      end_idx: jax.Array, live: jax.Array, cap: int):
    """lead/lag: value at (current + offset) if inside the segment, else
    marker (returned mask False)."""
    idx = _idx(cap)
    src = idx + jnp.int32(offset)
    ok = live & (src >= start_idx) & (src <= end_idx)
    src_c = jnp.clip(src, 0, cap - 1)
    g = col.gather(src_c)
    return g, ok
