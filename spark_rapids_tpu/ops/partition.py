"""Output partitioners.

TPU counterparts of the reference's four partitioning strategies
(ref: GpuHashPartitioning.scala, GpuRoundRobinPartitioning.scala,
GpuSinglePartitioning.scala, GpuRangePartitioning.scala; base mechanics
in GpuPartitioning.scala:45-73 — cudf Table.partition + contiguousSplit).

Here a partitioner produces per-row partition ids on device; the split
into per-partition sub-batches reuses the stable-argsort compaction: one
sort by pid groups rows, a sizing sync reads the per-partition counts,
and each sub-batch is a sliced gather of the grouped batch.  Hash
partitioning is murmur3-pmod, bit-for-bit Spark-compatible (the parity
requirement the reference calls out), so a row lands on the same
partition index as it would under Spark CPU."""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exprs.base import EvalContext, Expression, bind_references
from spark_rapids_tpu.exprs.hashing import partition_ids


class Partitioning:
    """Computes per-row partition ids for a batch (traceable)."""

    num_partitions: int

    def bind(self, schema) -> "Partitioning":
        return self

    def partition_ids(self, batch: ColumnarBatch) -> jax.Array:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


@dataclasses.dataclass
class HashPartitioning(Partitioning):
    exprs: Sequence[Expression]
    num_partitions: int

    def bind(self, schema) -> "HashPartitioning":
        return HashPartitioning(
            [bind_references(e, schema) for e in self.exprs],
            self.num_partitions)

    def partition_ids(self, batch: ColumnarBatch) -> jax.Array:
        ctx = EvalContext.for_batch(batch)
        cols = [e.eval(ctx) for e in self.exprs]
        return partition_ids(cols, batch.capacity, self.num_partitions)

    def describe(self) -> str:
        return (f"hashpartitioning({', '.join(e.name for e in self.exprs)},"
                f" {self.num_partitions})")


@dataclasses.dataclass
class RangePartitioning(Partitioning):
    """Range partitioning for distributed ORDER BY (ref:
    GpuRangePartitioning.scala + GpuRangePartitioner.scala:30,167).
    Bounds are sampled at exchange map time (two-pass map stage); rows
    compare to bounds via the total-order lexicographic keys of
    ops.range_partition, so partition index order IS the sort order."""

    keys: Sequence  # of execs.sort.SortKey
    num_partitions: int

    def bind(self, schema) -> "RangePartitioning":
        from spark_rapids_tpu.execs.sort import SortKey

        return RangePartitioning(
            [SortKey(bind_references(k.expr, schema), k.descending,
                     k.nulls_last) for k in self.keys],
            self.num_partitions)

    def key_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Evaluate the sort-key expressions into a key-column batch
        (traceable); both samples and bounds live in this layout."""
        from spark_rapids_tpu import types as T

        ctx = EvalContext.for_batch(batch)
        cols = [k.expr.eval(ctx) for k in self.keys]
        schema = T.Schema([T.Field(f"__rk{i}", k.expr.dtype)
                           for i, k in enumerate(self.keys)])
        return ColumnarBatch(cols, batch.num_rows, schema)

    def key_orders(self):
        from spark_rapids_tpu.ops.sort import SortOrder

        return [SortOrder(i, k.descending, k.nulls_last)
                for i, k in enumerate(self.keys)]

    def partition_ids_with_bounds(self, batch: ColumnarBatch,
                                  bounds: ColumnarBatch) -> jax.Array:
        """Traceable; `bounds` is a key-layout batch of
        num_partitions-1 rows."""
        from spark_rapids_tpu.ops.range_partition import bucket_ids

        return bucket_ids(self.key_batch(batch), bounds,
                          self.key_orders(), self.num_partitions - 1)

    def partition_ids(self, batch: ColumnarBatch) -> jax.Array:
        raise TypeError("RangePartitioning needs sampled bounds; the "
                        "exchange runs its two-pass map stage")

    def describe(self) -> str:
        ks = ", ".join(
            f"{k.expr.name}{' DESC' if k.descending else ''}"
            for k in self.keys)
        return f"rangepartitioning({ks}, {self.num_partitions})"


@dataclasses.dataclass
class RoundRobinPartitioning(Partitioning):
    num_partitions: int
    start: int = 0

    def partition_ids(self, batch: ColumnarBatch) -> jax.Array:
        idx = jnp.arange(batch.capacity, dtype=jnp.int32)
        return (idx + jnp.int32(self.start)) % jnp.int32(self.num_partitions)

    def describe(self) -> str:
        return f"roundrobin({self.num_partitions})"


@dataclasses.dataclass
class SinglePartitioning(Partitioning):
    num_partitions: int = 1

    def partition_ids(self, batch: ColumnarBatch) -> jax.Array:
        return jnp.zeros((batch.capacity,), jnp.int32)

    def describe(self) -> str:
        return "single"


def split_batch_dispatch(batch: ColumnarBatch, pids: jax.Array,
                         n_parts: int):
    """Device half of split_batch, NO sync: group rows by partition id
    and count them.  Returns (grouped_batch, device_counts) — the
    sizing readback is the caller's, so a pipelined map loop can
    dispatch batch k+1's sort while batch k's counts are in flight."""
    live = batch.row_mask()
    key = jnp.where(live, pids, jnp.int32(n_parts))
    order = jnp.argsort(key, stable=True)
    grouped = batch.gather(order, batch.num_rows)
    counts = jax.ops.segment_sum(live.astype(jnp.int32), key,
                                 num_segments=n_parts)
    return grouped, counts


def split_batch_finish(grouped: ColumnarBatch, counts_np,
                       n_parts: int) -> list[ColumnarBatch]:
    """Slice the per-partition batches once the counts are host-side.
    `counts_np` is any host array-like — typically the harvested value
    of a `device_read`/`device_read_async` on split_batch_dispatch's
    counts (already host memory; the asarray below is a view, not a
    device sync)."""
    counts_np = np.asarray(counts_np)
    offsets = np.concatenate([[0], np.cumsum(counts_np)])
    out = []
    cap = grouped.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    for p in range(n_parts):
        off, cnt = int(offsets[p]), int(counts_np[p])
        take = jnp.clip(idx + off, 0, cap - 1)
        sub = grouped.gather(take, cnt)
        live_p = idx < cnt
        cols = [c.with_validity(c.validity & live_p) for c in sub.columns]
        out.append(ColumnarBatch(cols, cnt, grouped.schema))
    return out


def split_batch(batch: ColumnarBatch, pids: jax.Array, n_parts: int
                ) -> list[ColumnarBatch]:
    """Group rows by partition id and slice out per-partition batches.
    One device sort + one sizing sync per input batch (the analog of
    cudf's Table.partition returning parts + offsets)."""
    if n_parts == 1:
        # single destination: the batch IS the slice (grand-aggregate
        # exchanges hit this constantly)
        return [batch]
    from spark_rapids_tpu.parallel.pipeline import device_read

    grouped, counts = split_batch_dispatch(batch, pids, n_parts)
    counts_np = device_read(counts, tag="exchange.split")
    return split_batch_finish(grouped, counts_np, n_parts)
