"""Equi-join kernels.

TPU re-design of the reference's hash-join core (ref: sql-plugin/.../sql/
rapids/execution/GpuHashJoin.scala:62,190 and JoinGatherer.scala:55 —
cudf builds device hash tables and emits gather maps).  XLA has no
device hash table, and join output size is data-dependent, so the design
here is different by construction:

1. **Dense key ranks instead of a hash table**: build-side and
   stream-side key columns are concatenated and run through the same
   lexsort + boundary machinery as group-by, yielding a dense int32
   `gid` per row where equal SQL keys (any column mix, incl. strings)
   share a gid.  Equality then reduces to integer equality — no
   collisions, no probing.
2. **Counting + offset expansion instead of gather-map growth**: per
   stream row the number of build matches is `counts[gid]`; an
   exclusive scan gives each stream row its output offset, and the
   output pair table of static capacity is filled by a vectorized
   searchsorted over the scan (the JoinGatherer chunking analog: the
   caller sizes the output from the returned total and can re-invoke
   with a bigger bucket).

NULL join keys never match (SQL equality), are excluded from counts,
and surface only through the outer-join unmatched paths."""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import AnyColumn, Column, StringColumn
from spark_rapids_tpu.ops.groupby import _keys_equal_adjacent
from spark_rapids_tpu.ops.sort import SortOrder, sort_permutation


def _pad_string_widths(a: StringColumn, b: StringColumn
                       ) -> tuple[StringColumn, StringColumn]:
    w = max(a.width, b.width)
    pa_ = jnp.pad(a.chars, ((0, 0), (0, w - a.width)))
    pb = jnp.pad(b.chars, ((0, 0), (0, w - b.width)))
    return (StringColumn(pa_, a.lengths, a.validity),
            StringColumn(pb, b.lengths, b.validity))


def _concat_key_cols(build: list[AnyColumn], stream: list[AnyColumn]
                     ) -> list[AnyColumn]:
    out = []
    for cb, cs in zip(build, stream):
        if isinstance(cb, StringColumn):
            cb, cs = _pad_string_widths(cb, cs)
            out.append(StringColumn(
                jnp.concatenate([cb.chars, cs.chars]),
                jnp.concatenate([cb.lengths, cs.lengths]),
                jnp.concatenate([cb.validity, cs.validity])))
        else:
            out.append(Column(jnp.concatenate([cb.data, cs.data]),
                              jnp.concatenate([cb.validity, cs.validity]),
                              cb.dtype))
    return out


def compute_gids(build_keys: list[AnyColumn], stream_keys: list[AnyColumn],
                 live_b: jax.Array, live_s: jax.Array):
    """Dense rank over the union of both sides' keys.

    Returns (gid_b, gid_s, null_b, null_s, n_combined_capacity)."""
    cap_b = live_b.shape[0]
    cap_s = live_s.shape[0]
    capc = cap_b + cap_s
    combined = _concat_key_cols(build_keys, stream_keys)
    live = jnp.concatenate([live_b, live_s])
    schema = T.Schema([T.Field(f"k{i}", c.dtype) for i, c in
                       enumerate(combined)])
    orders = [SortOrder(i) for i in range(len(combined))]
    keys_batch = ColumnarBatch(list(combined), capc, schema)
    perm = sort_permutation(keys_batch, orders)
    # dead rows must not pollute groups: push them last by re-sorting on
    # (dead, key) — emulate by stable argsort on dead flag after key sort
    dead_sorted = jnp.take(~live, perm)
    perm = jnp.take(perm, jnp.argsort(dead_sorted, stable=True))

    sorted_cols = [c.gather(perm) for c in combined]
    live_sorted = jnp.take(live, perm)
    same = jnp.ones((capc,), bool)
    for c in sorted_cols:
        same = same & _keys_equal_adjacent(c)
    idx = jnp.arange(capc, dtype=jnp.int32)
    is_start = live_sorted & ((idx == 0) | ~same)
    gid_sorted = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    gid_sorted = jnp.where(live_sorted, gid_sorted, capc - 1)
    # invert permutation
    gid = jnp.zeros((capc,), jnp.int32).at[perm].set(gid_sorted)
    null_flags = jnp.zeros((capc,), bool)
    for c in combined:
        null_flags = null_flags | ~c.validity
    return (gid[:cap_b], gid[cap_b:], null_flags[:cap_b],
            null_flags[cap_b:], capc)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class JoinState:
    """Traceable intermediate state shared by sizing and expansion."""

    gid_s: jax.Array
    cnt_s: jax.Array  # matches per stream row (outer rows forced to >=1)
    matched_s: jax.Array
    cum_excl: jax.Array
    start_by_gid: jax.Array
    build_rows_sorted: jax.Array
    live_s: jax.Array
    matched_b: jax.Array  # per build row (for full outer)
    live_b: jax.Array


def join_state(build: ColumnarBatch, stream: ColumnarBatch,
               build_key_cols: list[AnyColumn],
               stream_key_cols: list[AnyColumn],
               join_type: str) -> JoinState:
    live_b = build.row_mask()
    live_s = stream.row_mask()
    gid_b, gid_s, null_b, null_s, capc = compute_gids(
        build_key_cols, stream_key_cols, live_b, live_s)

    joinable_b = live_b & ~null_b
    joinable_s = live_s & ~null_s
    counts = jax.ops.segment_sum(
        joinable_b.astype(jnp.int32),
        jnp.where(joinable_b, gid_b, capc), num_segments=capc)
    starts = jnp.cumsum(counts) - counts
    # stable order of build rows by gid: row at starts[g]+j is the j-th
    # build row with gid g
    build_sort = jnp.argsort(jnp.where(joinable_b, gid_b, capc),
                             stable=True)

    cnt = jnp.where(joinable_s, jnp.take(counts, gid_s), 0)
    matched_s = cnt > 0
    if join_type in ("left_outer", "full_outer"):
        cnt_eff = jnp.where(live_s & ~matched_s, 1, cnt)
    else:
        cnt_eff = cnt
    cum = jnp.cumsum(cnt_eff) - cnt_eff

    stream_counts = jax.ops.segment_sum(
        joinable_s.astype(jnp.int32),
        jnp.where(joinable_s, gid_s, capc), num_segments=capc)
    matched_b = joinable_b & (jnp.take(stream_counts, gid_b) > 0)

    return JoinState(gid_s=gid_s, cnt_s=cnt_eff, matched_s=matched_s,
                     cum_excl=cum, start_by_gid=starts,
                     build_rows_sorted=build_sort, live_s=live_s,
                     matched_b=matched_b, live_b=live_b)


def expand_pairs(state: JoinState, out_cap: int, offset=0
                 ) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Produce (stream_idx, build_idx, pair_live, build_matched) arrays
    of static length out_cap for output pairs [offset, offset+out_cap)
    — the JoinGatherer chunk window (ref: JoinGatherer.scala:55
    gatherNext(n)); offset may be a traced scalar so ONE compiled
    program serves every chunk."""
    total = jnp.sum(state.cnt_s).astype(jnp.int32)
    i = jnp.arange(out_cap, dtype=jnp.int32) + jnp.asarray(
        offset, jnp.int32)
    s = jnp.searchsorted(state.cum_excl, i, side="right").astype(
        jnp.int32) - 1
    s = jnp.clip(s, 0, state.cum_excl.shape[0] - 1)
    j = i - jnp.take(state.cum_excl, s)
    pair_live = i < total
    matched = jnp.take(state.matched_s, s)
    gid = jnp.take(state.gid_s, s)
    pos = jnp.take(state.start_by_gid, gid) + j
    pos = jnp.clip(pos, 0, state.build_rows_sorted.shape[0] - 1)
    b = jnp.take(state.build_rows_sorted, pos)
    return s, b, pair_live, matched


def gather_joined(build: ColumnarBatch, stream: ColumnarBatch,
                  s_idx: jax.Array, b_idx: jax.Array, pair_live: jax.Array,
                  matched: jax.Array, num_rows,
                  out_schema: T.Schema,
                  stream_first: bool = True) -> ColumnarBatch:
    scols = [c.gather(s_idx, pair_live) for c in stream.columns]
    bcols = [c.gather(b_idx, pair_live & matched) for c in build.columns]
    cols = scols + bcols if stream_first else bcols + scols
    return ColumnarBatch(cols, num_rows, out_schema)
