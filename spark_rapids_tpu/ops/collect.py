"""collect_list / collect_set device kernels.

TPU shape of the reference's collect aggregations (ref:
AggregateFunctions.scala GpuCollectList/GpuCollectSet over cudf
collect_list): cudf emits ragged lists; XLA wants static shapes, so the
result is the dense ListColumn layout (values[groups, L] + lengths)
and L is discovered with ONE host sync between two compiled phases:

  phase 1 (traced): sort rows by (keys, value), segment them, count
     each group's kept elements (non-null; first-of-run for sets) —
     returns the sorted batch plus (num_groups, max_kept) scalars;
  phase 2 (traced, static L/out_cap from the sync): scatter each kept
     element to (group, position) in one 2-D scatter, compact the key
     rows, synthesize lengths/validities.

Spark semantics: nulls are skipped, all-null groups produce EMPTY
lists (never NULL), set dedup uses the total order (NaN == NaN)."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, ListColumn, StringColumn
from spark_rapids_tpu.ops.groupby import _keys_equal_adjacent
from spark_rapids_tpu.ops.sort import SortOrder, sort_permutation


def collect_phase1(batch: ColumnarBatch, n_keys: int,
                   kinds: Sequence[str]):
    """Sort/segment the (keys ++ values) batch.  Returns
    (sorted_batch, num_groups, max_kept) — the last two are 0-d arrays
    the driver syncs to size phase 2."""
    cap = batch.capacity
    live = batch.row_mask()
    n_vals = len(kinds)
    orders = [SortOrder(o) for o in range(n_keys + n_vals)]
    perm = sort_permutation(batch, orders)
    sb = batch.gather(perm, batch.num_rows)
    live_s = jnp.take(live, perm)

    is_start, seg_id, num_groups = _segments(sb, n_keys, live_s, cap)
    max_kept = jnp.zeros((), jnp.int32)
    for vi, kind in enumerate(kinds):
        kept = _kept_mask(sb.columns[n_keys + vi], kind, is_start,
                          live_s)
        counts = jax.ops.segment_sum(kept.astype(jnp.int32), seg_id,
                                     num_segments=cap)
        max_kept = jnp.maximum(max_kept, jnp.max(counts))
    return sb, live_s, num_groups, max_kept


def _segments(sb: ColumnarBatch, n_keys: int, live_s, cap: int):
    same = jnp.ones((cap,), bool)
    for kc in sb.columns[:n_keys]:
        same = same & _keys_equal_adjacent(kc)
    idx = jnp.arange(cap, dtype=jnp.int32)
    is_start = live_s & ((idx == 0) | ~same)
    seg_id = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    seg_id = jnp.where(live_s, seg_id, cap)
    return is_start, seg_id, jnp.sum(is_start.astype(jnp.int32))


def _kept_mask(vc, kind: str, is_start, live_s):
    kept = vc.validity & live_s
    if kind == "set":
        # rows are value-sorted within each segment: keep the first of
        # each run of equal values (total-order equality: NaN == NaN)
        same_val = _keys_equal_adjacent(vc)
        prev_valid = jnp.concatenate(
            [jnp.zeros((1,), bool), vc.validity[:-1]])
        kept = kept & (is_start | ~same_val | ~prev_valid)
    return kept


def collect_phase2(sb: ColumnarBatch, live_s, n_keys: int,
                   kinds: Sequence[str], L: int, out_cap: int,
                   out_schema: T.Schema) -> ColumnarBatch:
    """Assemble the output batch: compact keys ++ one ListColumn per
    collect (L and out_cap are static, from the phase-1 sync)."""
    cap = sb.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    is_start, seg_id, num_groups = _segments(sb, n_keys, live_s, cap)
    group_live = jnp.arange(out_cap, dtype=jnp.int32) < num_groups
    start_dest = jnp.where(is_start, seg_id, out_cap)

    out_cols = []
    for kc in sb.columns[:n_keys]:
        if isinstance(kc, StringColumn):
            chars = jnp.zeros((out_cap,) + kc.chars.shape[1:],
                              kc.chars.dtype).at[start_dest].set(
                kc.chars, mode="drop")
            lengths = jnp.zeros(out_cap, jnp.int32).at[start_dest].set(
                kc.lengths, mode="drop")
            valid = jnp.zeros(out_cap, bool).at[start_dest].set(
                kc.validity, mode="drop") & group_live
            out_cols.append(StringColumn(chars, lengths, valid))
        else:
            data = jnp.zeros(out_cap, kc.data.dtype).at[start_dest].set(
                kc.data, mode="drop")
            valid = jnp.zeros(out_cap, bool).at[start_dest].set(
                kc.validity, mode="drop") & group_live
            out_cols.append(Column(data, valid, kc.dtype))

    for vi, kind in enumerate(kinds):
        vc = sb.columns[n_keys + vi]
        kept = _kept_mask(vc, kind, is_start, live_s)
        # position within the group among kept elements: inclusive
        # running count minus the count at the segment's entry (the
        # cummax trick works because the running count never decreases)
        run = jnp.cumsum(kept.astype(jnp.int32))
        seg_base = jax.lax.cummax(
            jnp.where(is_start, run - kept.astype(jnp.int32), 0))
        pos = run - 1 - seg_base
        row_dest = jnp.where(kept, seg_id, out_cap)
        col_dest = jnp.where(kept, pos, 0)
        values = jnp.zeros((out_cap, L), vc.data.dtype).at[
            row_dest, col_dest].set(vc.data, mode="drop")
        lengths = jax.ops.segment_sum(
            kept.astype(jnp.int32), seg_id,
            num_segments=out_cap).astype(jnp.int32)
        # grand collect over empty input still emits one EMPTY list
        # (Spark: collect over no rows is [], never NULL)
        row_valid = group_live if n_keys else group_live | (
            jnp.arange(out_cap, dtype=jnp.int32) == 0)
        lengths = jnp.where(group_live, lengths, 0)
        evalid = jnp.arange(L, dtype=jnp.int32)[None, :] < lengths[:, None]
        elem_dtype = out_schema.fields[n_keys + vi].dtype.element
        out_cols.append(ListColumn(values, lengths, evalid, row_valid,
                                   T.ListType(elem_dtype)))

    n_rows = num_groups if n_keys else jnp.maximum(num_groups, 1)
    return ColumnarBatch(out_cols, n_rows, out_schema)
