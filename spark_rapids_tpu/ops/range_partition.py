"""Range partitioning: sample -> bounds -> per-row bucket search.

TPU counterpart of GpuRangePartitioning/GpuRangePartitioner
(ref: GpuRangePartitioning.scala, GpuRangePartitioner.scala:30 `sketch`
reservoir sampling, :77 `determineBounds`, :167 device upper-bound
search).  The same mechanism drives BOTH:
- the distributed ORDER BY exchange (range-partitioned shuffle), and
- the local out-of-core sort (sample-split sort: split oversized input
  into key-range buckets that each fit on device, sort buckets
  independently, emit in bound order) — the TPU-idiomatic replacement
  for the reference's cursor-based GpuOutOfCoreSortIterator merge
  (GpuSortExec.scala:213), chosen because it is two streaming passes of
  fixed-shape device programs with no per-round host round trips.

Multi-column ordering reuses the total-order integer key transforms of
ops.sort (floats via IEEE total-order bits, strings via big-endian words,
NULL placement flags), so a "row < bound" test is a short vectorized
lexicographic compare and bucket ids are `sum_i [bound_i < row]`.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.ops.sort import SortOrder, column_sort_keys


def row_lex_keys(batch: ColumnarBatch,
                 orders: Sequence[SortOrder]) -> list[jax.Array]:
    """Major-first integer key arrays realizing the SQL ORDER BY as plain
    ascending lexicographic order (padding/live flags NOT included)."""
    keys: list[jax.Array] = []
    for o in orders:
        col = batch.columns[o.ordinal]
        minor_first = column_sort_keys(col, o.descending, o.nulls_last)
        # column_sort_keys returns [value minor..major, null_flag]; the
        # null flag is most significant
        keys.extend(reversed(minor_first))
    return keys


def _lex_less(a_keys: Sequence[jax.Array],
              b_keys: Sequence[jax.Array]) -> jax.Array:
    """Elementwise `a < b` over parallel major-first key arrays."""
    lt = jnp.zeros(a_keys[0].shape, bool)
    decided = jnp.zeros(a_keys[0].shape, bool)
    for a, b in zip(a_keys, b_keys):
        lt = lt | (~decided & (a < b))
        decided = decided | (a != b)
    return lt


def choose_bounds(samples: ColumnarBatch, orders: Sequence[SortOrder],
                  n_parts: int, n_live: int) -> ColumnarBatch:
    """Sort the pooled sample and take n_parts-1 evenly spaced rows as
    range bounds (ref: GpuRangePartitioner.determineBounds).  Returns a
    small device batch of bound rows.  Traceable when n_live is static
    (fixed-size sampling makes it so)."""
    from spark_rapids_tpu.ops.sort import sort_batch

    assert n_parts >= 1
    s = sort_batch(samples, orders)
    n_bounds = n_parts - 1
    if n_live == 0 or n_bounds == 0:
        return s.slice_prefix(0)
    # evenly spaced ranks, clipped to live rows
    ranks = np.minimum(
        ((np.arange(1, n_bounds + 1) * n_live) // n_parts).astype(np.int32),
        n_live - 1)
    picked = s.gather(jnp.asarray(ranks, jnp.int32), n_bounds)
    return ColumnarBatch(picked.columns, n_bounds, s.schema)


def choose_bounds_dynamic(samples: ColumnarBatch,
                          orders: Sequence[SortOrder],
                          n_parts: int) -> ColumnarBatch:
    """choose_bounds with a TRACED live-sample count: sort the pooled
    sample (dead rows last), then gather n_parts-1 evenly spaced ranks
    computed from the in-program `num_rows` scalar.  This is the form
    the SPMD sort stage needs — the host never learns how many samples
    each shard contributed (that would be a per-round sync), so the
    rank arithmetic happens on device.  With zero live samples the
    picked bounds are dead padding rows, which is harmless: every data
    row routed against them is itself dead."""
    from spark_rapids_tpu.ops.sort import sort_batch

    assert n_parts >= 1
    n_bounds = n_parts - 1
    s = sort_batch(samples, orders)
    if n_bounds == 0:
        return s.slice_prefix(0)
    n_live = jnp.asarray(s.num_rows, jnp.int32)
    ranks = jnp.minimum(
        (jnp.arange(1, n_parts, dtype=jnp.int32) * n_live) // n_parts,
        jnp.maximum(n_live - 1, 0))
    picked = s.gather(ranks, n_bounds)
    return ColumnarBatch(picked.columns, n_bounds, s.schema)


def bucket_ids(batch: ColumnarBatch, bounds: ColumnarBatch,
               orders: Sequence[SortOrder], n_bounds: int) -> jax.Array:
    """Per-row partition id in [0, n_bounds]: number of bounds strictly
    less than the row (rows equal to a bound share its left bucket).
    Traceable; program size O(n_bounds * n_keys)."""
    if n_bounds == 0:
        return jnp.zeros((batch.capacity,), jnp.int32)
    row_keys = row_lex_keys(batch, orders)
    bound_keys = row_lex_keys(bounds, orders)
    pid = jnp.zeros((batch.capacity,), jnp.int32)
    for i in range(n_bounds):
        bi = [bk[i] for bk in bound_keys]
        pid = pid + _lex_less(bi, row_keys).astype(jnp.int32)
    return pid
