"""Total-order sort keys and batch sorting.

TPU counterpart of cudf's `Table.orderBy` as used by GpuSortExec
(ref: sql-plugin/.../GpuSortExec.scala) — but instead of a comparator
kernel, every SQL sort key is mapped to one or more *integer key arrays*
whose ascending lexicographic order equals the SQL order, then a single
stable `jnp.lexsort` produces the permutation.  This keeps the whole sort
one fused XLA op (bitonic/radix under the hood) with no dynamic shapes.

Key transforms:
- integers: identity (descending = bitwise NOT, which is monotone-reversing
  and overflow-free, unlike negation at INT_MIN);
- floats: IEEE-754 total-order trick (sign-magnitude -> two's complement);
  NaN's canonical bit pattern sorts above +inf, matching Spark;
- strings: the fixed-width byte matrix is already lexicographic because
  padding bytes are zero; bytes become uint8 key columns (chunked into
  int32 words, 4 bytes per word, to cut lexsort key count 4x);
- NULLs: a leading null-flag key implements NULLS FIRST/LAST;
- dead padding rows always sort last via a most-significant live flag.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import AnyColumn, Column, StringColumn


@dataclasses.dataclass(frozen=True)
class SortOrder:
    """One sort key: column (by ordinal at this layer), direction, null
    placement (Spark default: ascending, nulls first)."""

    ordinal: int
    descending: bool = False
    nulls_last: bool = False


def float_total_order_bits(x: jax.Array) -> jax.Array:
    """Map a FLOAT32 array to ints whose ascending order is IEEE total
    order (with canonical NaN > +inf, as Spark sorts NaN largest).
    float64 has no bitcast form on TPU (the X64 rewriter cannot compile
    64-bit bitcast-convert) — use float64_order_keys instead."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.int32)
    bits = jnp.where(jnp.isnan(x), jnp.int32(0x7FC00000), bits)
    return jnp.where(bits < 0, bits ^ jnp.int32(2**31 - 1), bits)


def float64_order_keys(x: jax.Array, descending: bool) -> list:
    """float64 total order WITHOUT a 64-bit bitcast (which the TPU X64
    rewriter cannot compile): sort by the value itself with NaN
    canonicalized to +inf, break the +inf tie with an is-NaN flag (NaN
    strictly above +inf), and break the IEEE ±0.0 tie with the sign bit
    (-0.0 strictly below 0.0, matching the bit-order the CPU oracle
    sorts by).  Returned minor-first (flags are tiebreakers)."""
    isnan = jnp.isnan(x)
    vals = jnp.where(isnan, jnp.inf, x)
    flag = isnan.astype(jnp.int32)
    # sign of zero WITHOUT jnp.signbit (it lowers to a 64-bit bitcast
    # the TPU X64 rewriter rejects): 1/-0.0 = -inf < 0; the tiebreak
    # only matters on the ±0.0 value tie, so nonzero rows can take any
    # constant
    neg_zero = (x == 0) & (1.0 / x < 0)
    zkey = jnp.where(isnan | ~neg_zero, 1, 0)
    if descending:
        vals = -vals
        flag = 1 - flag
        zkey = 1 - zkey
    # one combined tiebreak: among value-ties only ±0 (zkey) and
    # inf-vs-NaN (flag) need ordering, and zkey outranks flag — every
    # sort operand is a whole bitonic pass, so fold them
    return [zkey * 2 + flag, vals]


def _string_word_keys(col: StringColumn) -> list[jax.Array]:
    """Big-endian 4-byte words over the byte matrix: ascending word order
    == ascending byte-lexicographic order (zero padding sorts prefixes
    first)."""
    n, width = col.chars.shape
    c = col.chars.astype(jnp.uint32)
    words: list[jax.Array] = []
    for j in range(0, width, 4):

        def byte(off):
            if j + off < width:
                return c[:, j + off]
            return jnp.zeros((n,), jnp.uint32)

        w = (byte(0) << 24) | (byte(1) << 16) | (byte(2) << 8) | byte(3)
        words.append(w.astype(jnp.int64))  # zero-extended, order-preserving
    return words


def column_sort_keys(col: AnyColumn, descending: bool,
                     nulls_last: bool) -> list[jax.Array]:
    """Minor-to-major int key arrays for one SQL sort key.  Returned
    minor-first (callers feed jnp.lexsort, whose LAST key is primary).

    Value keys are neutralized to a constant under NULL: the slot data
    beneath a null is decoder garbage (fastpar leaves the previous
    value), and if it leaked into the key, NULL rows would order by
    garbage instead of falling through to the next SQL sort key — a
    divergence from Spark that only bites multi-key sorts."""
    if isinstance(col, StringColumn):
        vals = [jnp.where(col.validity, v, 0)
                for v in _string_word_keys(col)]
        if descending:
            vals = [~v for v in vals]
        vals = list(reversed(vals))  # minor-first
    elif isinstance(col.dtype, T.DoubleType):
        vals = float64_order_keys(
            jnp.where(col.validity, col.data, 0.0), descending)
    else:
        d = jnp.where(col.validity, col.data,
                      jnp.zeros((), col.data.dtype))
        if isinstance(col.dtype, T.FloatType):
            k = float_total_order_bits(d)
        elif col.dtype == T.BOOLEAN:
            k = d.astype(jnp.int32)
        else:
            k = d
        if descending:
            k = ~k
        if jnp.dtype(k.dtype).itemsize <= 4:
            # pack the null flag INTO the key: every lexsort operand is
            # a whole extra bitonic pass over the batch, and 32-bit
            # keys have the headroom ((flag << 32) | zero-extended key)
            null_flag = col.validity.astype(jnp.int64)  # 0 = null
            if nulls_last:
                null_flag = 1 - null_flag
            u = k.astype(jnp.int64) + jnp.int64(2 ** 31)
            return [(null_flag << 32) | u]
        vals = [k]
    null_flag = col.validity.astype(jnp.int32)  # 0 = null
    if nulls_last:
        null_flag = 1 - null_flag
    # null flag is more significant than the value keys
    return vals + [null_flag]


def sort_permutation(batch: ColumnarBatch,
                     orders: Sequence[SortOrder],
                     live=None) -> jax.Array:
    """Stable permutation realizing the SQL ORDER BY; padding rows last.
    `live` overrides the default prefix liveness (masked-filter callers
    mark additional rows dead without compacting first)."""
    keys: list[jax.Array] = []
    for o in reversed(orders):  # minor keys first for lexsort
        col = batch.columns[o.ordinal]
        keys.extend(column_sort_keys(col, o.descending, o.nulls_last))
    if live is None:
        live = batch.row_mask()
    keys.append(live.astype(jnp.int32) * -1)  # live rows first
    return jnp.lexsort(keys)


def sort_batch(batch: ColumnarBatch,
               orders: Sequence[SortOrder]) -> ColumnarBatch:
    perm = sort_permutation(batch, orders)
    return batch.gather(perm, batch.num_rows)
